"""Non-interactive CLI — the counterpart of the reference's numbered menus
(automated_multimodal_collection.sh:845-888, run_all_experiments.sh:601-638)
as flags instead of prompts.

Subcommands grow with the framework; `list` and `synth` are available from
day one so every experiment the reference menus offer is addressable by name.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _probe_backend(args) -> None:
    """Dead-tunnel guard for the jax-heavy subcommands: probe the device
    backend out-of-process and fall back to CPU instead of hanging at the
    first backend touch.  Called AFTER each subcommand's cheap flag
    validation so usage errors stay instant; ANOMOD_PLATFORM=cpu skips it
    by pinning up front, ANOMOD_SKIP_PROBE=1 skips it trusting the
    backend.  A process where pin_cpu already ran (the test suite calling
    main() in-process, any embedder) skips too — via the process-local
    pin flag, NOT the JAX_PLATFORMS env var, which the container's
    sitecustomize renders non-binding (a user exporting it with a dead
    tunnel still needs the probe to pin for real)."""
    from anomod.utils.platform import (ensure_live_backend, env_number,
                                       is_pinned)
    if os.environ.get("ANOMOD_PLATFORM", "").strip().lower() == "cpu" \
            or is_pinned():
        return
    # the fallback mesh must be large enough for an explicitly requested
    # virtual device count (replay --devices N)
    n_fallback = max(env_number("ANOMOD_CPU_DEVICES", 1),
                     getattr(args, "devices", None) or 1)
    note = ensure_live_backend(n_fallback)
    if "unavailable" in note:
        print(f"[anomod] {note}", file=sys.stderr)


def main(argv=None) -> int:
    # Pre-init platform pin: ANOMOD_PLATFORM=cpu makes every subcommand
    # usable with a dead device tunnel (the container's sitecustomize
    # eagerly probes the TPU backend, so even JAX_PLATFORMS=cpu in the
    # environment hangs forever; only the pre-init jax.config pin sticks —
    # see anomod.utils.platform).
    if os.environ.get("ANOMOD_PLATFORM", "").strip().lower() == "cpu":
        from anomod.utils.platform import env_number, pin_cpu
        pin_cpu(env_number("ANOMOD_CPU_DEVICES", 1))
    parser = argparse.ArgumentParser(
        prog="anomod",
        description="TPU-native anomaly-detection & RCA framework (AnoMod capabilities)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_list = sub.add_parser("list", help="list experiments + fault labels")
    p_list.add_argument("--testbed", choices=["SN", "TT"], default=None)

    p_synth = sub.add_parser("synth", help="generate a synthetic experiment summary")
    p_synth.add_argument("experiment")
    p_synth.add_argument("--traces", type=int, default=100)

    p_detect = sub.add_parser(
        "detect", help="run the z-score detector + RCA ranking over a corpus")
    p_detect.add_argument("--testbed", choices=["SN", "TT"], default="TT")
    p_detect.add_argument("--backend", choices=["cpu", "jax"], default="cpu")
    p_detect.add_argument("--traces", type=int, default=100)
    p_detect.add_argument("--from-data", action="store_true",
                          help="load from the data root (LFS stubs -> synth)")

    p_rca = sub.add_parser("rca", help="train a GNN RCA model on chaos labels")
    p_rca.add_argument("--testbed", choices=["SN", "TT"], default="TT")
    p_rca.add_argument("--model",
                       choices=["gcn", "gat", "sage", "temporal", "lru",
                                "transformer", "moe", "linegraph"],
                       default="gcn")
    p_rca.add_argument("--epochs", type=int, default=300)
    p_rca.add_argument("--train-seeds", type=int, default=6)
    p_rca.add_argument("--eval-seeds", type=int, default=2)
    p_rca.add_argument("--checkpoint-dir", default=None,
                       help="persist params/opt_state every 50 epochs "
                            "(orbax, pickle fallback)")
    p_rca.add_argument("--resume", action="store_true",
                       help="continue from the epoch saved in "
                            "--checkpoint-dir")

    p_camp = sub.add_parser(
        "campaign", help="run the full 13-experiment collection campaign "
        "and archive a reference-shaped dataset tree")
    p_camp.add_argument("--testbed", choices=["SN", "TT"], default="TT")
    p_camp.add_argument("--out", required=True)
    p_camp.add_argument("--traces", type=int, default=200)
    p_camp.add_argument("--experiments", nargs="*", default=None)

    p_coll = sub.add_parser(
        "collect", help="live-transport collection: pull from a running "
        "Prometheus / Jaeger / SkyWalking / Elasticsearch endpoint "
        "(anomod.io.live) or through kubectl/docker exec transports "
        "(anomod.io.live_exec) and write loader-compatible artifacts")
    p_coll.add_argument("kind", choices=["prometheus", "jaeger",
                                         "skywalking", "es", "kube-logs",
                                         "docker-logs", "jacoco", "gcov"])
    p_coll.add_argument("--url",
                        help="base URL (prometheus/jaeger/es) or the "
                             "GraphQL endpoint (skywalking); unused by "
                             "the exec transports")
    p_coll.add_argument("--namespace", default="default",
                        help="kube-logs/jacoco: kubernetes namespace")
    p_coll.add_argument("--tail", type=int, default=1000,
                        help="kube-logs: lines per pod")
    p_coll.add_argument("--since", default=None,
                        help="docker-logs: docker logs --since window "
                             "(default: full history, the collect_log.sh "
                             "default)")
    p_coll.add_argument("--report-dir", default=None,
                        help="jacoco: coverage_report output tree "
                             "(default: <out>/../coverage_report)")
    p_coll.add_argument("--mount-root", default="./coverage-reports",
                        help="gcov: the compose-mounted coverage-reports "
                             "dir the in-container collect scripts write "
                             "into (collect_all_data.sh:535)")
    p_coll.add_argument("--out", required=True,
                        help="output dir (prometheus) or artifact file "
                             "path (jaeger/skywalking/es)")
    p_coll.add_argument("--testbed", choices=["SN", "TT"], default="SN",
                        help="prometheus only: SN = per-query CSV dir from "
                             "the SN catalog; TT = one long CSV from the "
                             "TT catalog")
    p_coll.add_argument("--hours-back", type=float, default=1.0)
    p_coll.add_argument("--step", default="15s",
                        help="prometheus query_range step")
    p_coll.add_argument("--limit", type=int, default=1000,
                        help="jaeger: traces per service; skywalking: "
                             "total trace budget; es: segment budget")
    p_coll.add_argument("--experiment", default="live",
                        help="skywalking: experiment name stamped into "
                             "the artifact metadata; gcov: the "
                             "EXPERIMENT_BASE_NAME forwarded to the "
                             "in-container collect scripts")
    p_coll.add_argument("--timeout", type=float, default=30.0)
    p_coll.add_argument("--retries", type=int, default=3)

    p_gold = sub.add_parser(
        "golden", help="golden run over the REAL reference dataset trees: "
        "loadability census + coverage-modality detection on the non-LFS "
        "artifacts (anomod.golden)")
    p_gold.add_argument("--markdown", action="store_true",
                        help="emit the docs/GOLDEN_REPORT.md body instead "
                             "of JSON")

    p_ing = sub.add_parser(
        "ingest", help="ingest-cache management (anomod.io.cache): warm the "
        "content-addressed corpus cache before driver benches, report its "
        "state, or clear it")
    p_ing.add_argument("--warm-cache", action="store_true",
                       help="load the full corpus (and the bench.py span "
                            "corpus) through the cache so later runs are "
                            "warm")
    p_ing.add_argument("--testbed", choices=["SN", "TT", "both"],
                       default="TT")
    p_ing.add_argument("--traces", type=int, default=200,
                       help="n_synth_traces for the corpus loaders")
    p_ing.add_argument("--bench-traces", type=int, default=2_000,
                       help="n_traces of the bench.py replay corpus to warm "
                            "(0 skips it; 2000 is bench.py's default)")
    p_ing.add_argument("--workers", type=int, default=None,
                       help="process-pool size for the corpus load "
                            "(default: ANOMOD_INGEST_WORKERS)")
    p_ing.add_argument("--cache-dir", default=None,
                       help="override ANOMOD_CACHE_DIR for this invocation")
    p_ing.add_argument("--data-root", default=None,
                       help="override ANOMOD_DATA_ROOT for this invocation")
    p_ing.add_argument("--clear", action="store_true",
                       help="delete every cache entry first")

    p_val = sub.add_parser("validate", help="data-quality validation report "
                           "over a corpus (reference-style embedded checks)")
    p_val.add_argument("--testbed", choices=["SN", "TT"], default="TT")
    p_val.add_argument("--traces", type=int, default=60)
    p_val.add_argument("--from-data", action="store_true")

    p_lint = sub.add_parser(
        "lint", help="contract-checking static analysis "
        "(anomod.analysis): AST lint of the determinism / env-contract "
        "/ seam / lock contracts plus the parity-surface audit "
        "(ServeReport fields and flight-record keys vs their declared "
        "variant lists).  Pure stdlib ast — never touches the backend. "
        "Catalog: docs/CONTRACTS.md")
    p_lint.add_argument("--root", default=None,
                        help="repo root to scan (default: this checkout)")
    p_lint.add_argument("--json", action="store_true",
                        help="machine output only (one JSON document, "
                             "findings inlined)")
    p_lint.add_argument("--baseline", default=None,
                        help="baseline file (default: "
                             "scripts/lint_baseline.json)")
    p_lint.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline to exactly the "
                             "current findings (the ratchet only "
                             "shrinks unless you run this)")
    p_lint.add_argument("--no-parity", action="store_true",
                        help="skip the parity-surface audit (AST rule "
                             "families only)")
    p_lint.add_argument("--show-suppressed", action="store_true",
                        help="also list suppressed findings with their "
                             "reasons")
    p_lint.add_argument("--rules", action="store_true",
                        help="print the rule catalog and exit")

    p_chaos = sub.add_parser(
        "chaos", help="render the fault-injection plan for an experiment "
        "(Chaos Mesh CRD YAML / ChaosBlade argv / docker argv)")
    p_chaos.add_argument("experiment")
    p_chaos.add_argument("--format", choices=["yaml", "json"], default="yaml")

    p_scen = sub.add_parser(
        "scenario", help="drive the TT user-journey workload against the "
        "synthetic SUT (optionally under an injected fault)")
    p_scen.add_argument("--iterations", type=int, default=1)
    p_scen.add_argument("--seed", type=int, default=0)
    p_scen.add_argument("--chaos", default=None,
                        help="experiment name to inject during the run")

    p_deploy = sub.add_parser(
        "deploy", help="render the deployment plan (helm/kubectl action "
        "list for TT, compose lifecycle for SN)")
    p_deploy.add_argument("--testbed", choices=["SN", "TT"], default="TT")
    # the deploy.sh argument surface, as real flags
    p_deploy.add_argument("--all", action="store_true", dest="deploy_all")
    p_deploy.add_argument("--independent-db", action="store_true")
    p_deploy.add_argument("--with-monitoring", action="store_true")
    p_deploy.add_argument("--with-tracing", action="store_true")
    p_deploy.add_argument("--down", action="store_true",
                          help="SN only: render the teardown instead")
    p_deploy.add_argument("--secrets", action="store_true",
                          help="TT only: print the 27 per-service DB secrets")

    p_mon = sub.add_parser(
        "monitor", help="SN API-response monitor over the synthetic SUT "
        "(active: 12 wrk2-api endpoints; passive: GET-only fallback)")
    p_mon.add_argument("--mode", choices=["active", "passive"],
                       default="active")
    p_mon.add_argument("--cycles", type=int, default=10)
    p_mon.add_argument("--seed", type=int, default=0)
    p_mon.add_argument("--chaos", default=None,
                       help="experiment name to inject during the capture")
    p_mon.add_argument("--out", default=None,
                       help="materialize the api_responses artifact family")
    p_mon.add_argument("--wrk2-requests", type=int, default=0,
                       help="interleave N wrk2 mixed-workload requests "
                            "(full compose content model) with the capture")

    p_logscan = sub.add_parser(
        "logscan", help="per-file log summary sweep over a directory "
        "(collect_log.sh summary pass; native thread-pool when built)")
    p_logscan.add_argument("dir")
    p_logscan.add_argument("--glob", default="**/*.log")

    p_replay = sub.add_parser("replay", help="measure span replay throughput")
    p_replay.add_argument("--testbed", choices=["SN", "TT"], default="TT")
    p_replay.add_argument("--traces", type=int, default=2000)
    p_replay.add_argument("--replicate", type=int, default=1)
    p_replay.add_argument("--kernel",
                          choices=["xla", "pallas", "pallas-sorted", "numpy"],
                          default="xla",
                          help="aggregation path: XLA scan (default; runs "
                               "anywhere), the fused pallas kernel (the "
                               "TPU fast path; interpret-mode off-TPU), its "
                               "sorted-window variant (128-lane one-hot via "
                               "host pre-sort; single-chip only), or "
                               "the numpy cpu-backend engine (fastest on a "
                               "host core; single-chip only)")
    p_replay.add_argument("--percentiles", action="store_true",
                          help="also report corpus-wide p50/p95/p99 from the "
                               "per-segment t-digest plane (XLA build on "
                               "TPU, host build elsewhere; "
                               "ANOMOD_TDIGEST_ENGINE=pallas opts into the "
                               "Mosaic kernel)")
    p_replay.add_argument("--edge-percentiles", action="store_true",
                          help="also report the slowest call-graph edges by "
                               "p99 from the PER-EDGE t-digest plane "
                               "(caller->callee keyed segments; the "
                               "per-edge featurization view)")
    p_replay.add_argument("--devices", type=int, default=0,
                          help="shard the stream over an N-device 1-D mesh "
                               "(shard_map + psum merge over ICI) instead of "
                               "the single-chip path; requires >= N attached "
                               "devices (use ANOMOD_PLATFORM=cpu + "
                               "ANOMOD_CPU_DEVICES=N for a virtual mesh). "
                               "--percentiles still computes its digest "
                               "plane in a separate single-chip pass")

    p_stream = sub.add_parser(
        "stream", help="online detection: replay an experiment's spans in "
        "arrival order through the incremental replay state and report the "
        "alert timeline + detection latency (streaming analog of `detect`)")
    p_stream.add_argument("experiment", nargs="?", default=None)
    p_stream.add_argument("--all", action="store_true",
                          help="run every experiment of --testbed and "
                               "report the taxonomy-wide quality table "
                               "(localization + detection latency); "
                               "writes a bench_runs/ provenance record")
    p_stream.add_argument("--testbed", choices=["SN", "TT"], default="TT",
                          help="with --all: which taxonomy to run; "
                               "single-experiment mode infers the testbed "
                               "from the name")
    p_stream.add_argument("--traces", type=int, default=400)
    p_stream.add_argument("--seed", type=int, default=0)
    p_stream.add_argument("--slice-seconds", type=float, default=60.0,
                          help="micro-batch width of the simulated feed")
    p_stream.add_argument("--threshold", type=float, default=4.0,
                          help="z-score alert threshold")
    p_stream.add_argument("--baseline-windows", type=int, default=8)
    p_stream.add_argument("--consecutive", type=int, default=1,
                          help="windows above threshold before alerting")
    p_stream.add_argument("--multimodal", action="store_true",
                          help="fuse the log/metric/api planes with the "
                               "span stream (streaming counterpart of the "
                               "offline five-modality detector)")
    p_stream.add_argument("--devices", type=int, default=0,
                          help="shard the streaming replay plane (incl. "
                               "the edge-attribution id space) over an "
                               "N-device mesh (use ANOMOD_PLATFORM=cpu + "
                               "ANOMOD_CPU_DEVICES=N for a virtual mesh)")
    p_stream.add_argument("--severity", type=float, default=1.0,
                          help="de-saturate the fault effects "
                               "(synth.HardMode) — the streaming "
                               "degradation-curve knob")
    p_stream.add_argument("--noise", type=float, default=0.0,
                          help="widen baseline distributions (HardMode)")
    p_stream.add_argument("--confounders", type=int, default=0,
                          help="decoy services per experiment (--all only; "
                               "same corpus builder as the quality sweep)")
    p_stream.add_argument("--shift", default="in-dist",
                          choices=["in-dist", "additive", "tail-only",
                                   "bursty", "partial-window", "edge-locus"],
                          help="--all only: evaluate under a shifted "
                               "generator (quality.SHIFTS axes)")
    p_stream.add_argument("--from-data", action="store_true",
                          help="replay the experiment from the archived "
                               "dataset tree (io.dataset loaders; LFS "
                               "stubs -> synth) instead of generating — "
                               "single-experiment mode only")
    p_stream.add_argument("--no-edge-attribution", action="store_true",
                          help="disable the out-edge attribution plane "
                               "(default on): skips the per-push span-batch "
                               "duplication and the 3x replay-plane rows, "
                               "restoring pre-edge-plane throughput (and "
                               "spans_per_sec comparability with those "
                               "records) at the cost of edge-locus RCA")

    p_serve = sub.add_parser(
        "serve", help="multi-tenant serving plane: admission control + "
        "dynamic micro-batching + SLO-aware load shedding over the "
        "streaming detectors, driven by a seeded power-law tenant fleet "
        "on a deterministic virtual clock (anomod.serve)")
    p_serve.add_argument("--tenants", type=int, default=200)
    p_serve.add_argument("--services", type=int, default=8)
    p_serve.add_argument("--duration", type=float, default=120.0,
                         help="virtual seconds to serve")
    p_serve.add_argument("--tick", type=float, default=1.0,
                         help="virtual scheduler tick (seconds)")
    p_serve.add_argument("--capacity", type=float, default=20_000.0,
                         help="serving capacity in spans/sec")
    p_serve.add_argument("--overload", type=float, default=1.0,
                         help="offered load as a multiple of capacity "
                              "(2.0 = the bench's shed regime)")
    p_serve.add_argument("--alpha", type=float, default=1.2,
                         help="power-law exponent of the tenant rate "
                              "distribution (0 = equal rates)")
    p_serve.add_argument("--seed", type=int, default=0)
    p_serve.add_argument("--window-seconds", type=float, default=5.0,
                         help="detector window width on the virtual clock")
    p_serve.add_argument("--baseline-windows", type=int, default=4)
    p_serve.add_argument("--threshold", type=float, default=4.0)
    p_serve.add_argument("--shards", type=int, default=None,
                         help="tenant-sharded engine workers (default: "
                              "ANOMOD_SERVE_SHARDS, 1 = the single-"
                              "threaded engine; N-shard output is "
                              "identical to 1-shard on the same seed)")
    p_serve.add_argument("--pipeline", type=int, default=None,
                         help="in-flight fused dispatches per shard "
                              "(default: ANOMOD_SERVE_PIPELINE; 1 = "
                              "synchronous, >1 = async double-buffered "
                              "staging — bit-identical at any depth)")
    p_serve.add_argument("--no-fuse", action="store_true",
                         help="disable tenant-fused (lane-stacked) "
                              "dispatch: one dispatch per tenant "
                              "micro-batch, as before ANOMOD_SERVE_FUSE")
    p_serve.add_argument("--lane-buckets", default=None,
                         help="comma-separated fused-dispatch lane "
                              "counts (default ANOMOD_SERVE_LANE_BUCKETS)")
    p_serve.add_argument("--buckets", default=None,
                         help="comma-separated micro-batch bucket widths "
                              "(default: ANOMOD_SERVE_BUCKETS)")
    p_serve.add_argument("--max-backlog", type=int, default=None,
                         help="global backlog bound in spans "
                              "(default: ANOMOD_SERVE_MAX_BACKLOG)")
    p_serve.add_argument("--fault-tenants", type=int, default=2,
                         help="tenants given a scripted latency fault at "
                              "mid-run (alert latency under load)")
    p_serve.add_argument("--rca", action="store_true",
                         help="online root-cause inference in the serve "
                              "tick: a firing detector queues GNN culprit "
                              "inference over the tenant's live service "
                              "graph (anomod.serve.rca; default: "
                              "ANOMOD_SERVE_RCA)")
    p_serve.add_argument("--state", choices=["auto", "host", "device"],
                         default=None,
                         help="tenant replay state residency: device = "
                              "shard-owned device pool, on-device scatter "
                              "fold + fused score gather (bit-identical); "
                              "host = the per-tenant numpy seam "
                              "(default: ANOMOD_SERVE_STATE, auto=device)")
    p_serve.add_argument("--no-native", action="store_true",
                         help="disable the GIL-free C++ lane staging for "
                              "this run: the interpreter fill, as before "
                              "ANOMOD_NATIVE (byte-identical output)")
    p_serve.add_argument("--perf", action="store_true",
                         help="dispatch-lifecycle timeline + overlap-"
                              "bubble accounting (anomod.obs.perf; "
                              "default: ANOMOD_PERF — pure read-side, "
                              "decisions byte-identical either way)")
    p_serve.add_argument("--async-commit", action="store_true",
                         help="deferred-commit tick: issue the fold/"
                              "score dispatches without waiting, run "
                              "the next tick's admission/drain/shed/SLO "
                              "under the in-flight XLA work, commit at "
                              "the next barrier — states/alerts/SLO/"
                              "shed and the canonical flight journal "
                              "byte-identical to the synchronous "
                              "engine (default: "
                              "ANOMOD_SERVE_ASYNC_COMMIT)")
    p_serve.add_argument("--no-async-commit", action="store_true",
                         help="force the synchronous tick (the parity "
                              "oracle) even when "
                              "ANOMOD_SERVE_ASYNC_COMMIT is on")
    p_serve.add_argument("--worker", choices=["thread", "process"],
                         default=None,
                         help="shard worker engine: thread = in-process "
                              "shard threads (the byte-parity oracle); "
                              "process = spawn-context worker processes "
                              "owning their shard's detectors/replays/"
                              "runner — escapes the GIL; states/alerts/"
                              "SLO/shed and the canonical flight journal "
                              "byte-identical to the thread engine "
                              "(default: ANOMOD_SERVE_WORKER)")
    p_serve.add_argument("--fold", choices=["dense", "sparse"],
                         default=None,
                         help="per-tick cross-shard registry barrier "
                              "fold: sparse = touched-key deltas "
                              "combined through a deterministic binary "
                              "fold tree; dense = full-walk snapshots "
                              "(the parity oracle) — scrape output "
                              "byte-identical either way (default: "
                              "ANOMOD_SERVE_FOLD)")
    p_serve.add_argument("--native-drain",
                         choices=["auto", "on", "off"], default=None,
                         help="columnar SFQ drain/shed engine for the "
                              "admission hot loop: auto = native C++ "
                              "kernels when the toolchain has them, "
                              "NumPy-columnar otherwise; off = the "
                              "Python heap loop (the byte-parity "
                              "oracle); on = require the native "
                              "kernels (default: "
                              "ANOMOD_SERVE_NATIVE_DRAIN)")
    p_serve.add_argument("--no-score", action="store_true",
                         help="replay-plane only (skip per-tenant window "
                              "scoring) — isolates the serving overhead")
    p_serve.add_argument("--chaos", default=None,
                         help="scripted serve-plane fault injection, "
                              "e.g. 'crash@5:shard=1;stall@8:ms=20' "
                              "(anomod.serve.chaos; default: "
                              "ANOMOD_SERVE_CHAOS, empty = off)")
    p_serve.add_argument("--ckpt-every", type=int, default=None,
                         help="shard-checkpoint cadence in ticks for "
                              "supervised no-score-gap recovery "
                              "(default: ANOMOD_SERVE_CKPT_EVERY; "
                              "0 disables supervision)")
    p_serve.add_argument("--policy", choices=["off", "auto", "script"],
                         default=None,
                         help="elastic scaling policy "
                              "(anomod.serve.policy): auto = signal-fed "
                              "autoscaler at every tick boundary, "
                              "script = fixed schedule from "
                              "--policy-script; scaling episodes are "
                              "seed-deterministic and leave tenant "
                              "states/alerts/SLO/shed byte-identical to "
                              "a static run (default: "
                              "ANOMOD_SERVE_POLICY)")
    p_serve.add_argument("--policy-script", default=None,
                         help="scaling schedule for --policy script, "
                              "e.g. 'up@10;rebalance@25:k=2;down@40' "
                              "(default: ANOMOD_SERVE_POLICY_SCRIPT)")
    p_serve.add_argument("--min-shards", type=int, default=None,
                         help="elastic scale-down floor (default: "
                              "ANOMOD_SERVE_POLICY_MIN_SHARDS)")
    p_serve.add_argument("--max-shards", type=int, default=None,
                         help="elastic scale-up ceiling (default: "
                              "ANOMOD_SERVE_POLICY_MAX_SHARDS; past it "
                              "sustained overload climbs the brownout "
                              "ladder)")
    p_serve.add_argument("--devices", type=int, default=0,
                         help="serve over an N-device mesh plane "
                              "(ShardedStreamReplay per tenant; use "
                              "ANOMOD_PLATFORM=cpu + ANOMOD_CPU_DEVICES=N "
                              "for a virtual mesh)")
    p_serve.add_argument("--trace-out", default=None,
                         help="dump the engine's own Jaeger-shaped trace "
                              "(anomod.utils.tracing.Tracer)")
    p_serve.add_argument("--from-live", default=None, metavar="URL",
                         help="drive the tick from a LIVE Prometheus "
                              "text-exposition endpoint instead of the "
                              "synthetic fleet (anomod.serve.feed); "
                              "'self' starts the embedded /metrics "
                              "endpoint (anomod.obs.http) and scrapes "
                              "this process's OWN registry — the "
                              "dogfood closed loop")
    p_serve.add_argument("--live-replay", default=None, metavar="JOURNAL",
                         help="re-run a recorded live-feed wire journal "
                              "(ANOMOD_FEED_JOURNAL) through the replay "
                              "transport: byte-identical planes, no "
                              "network; the feed shape comes from the "
                              "journal header (--tenants/--services are "
                              "ignored)")
    p_serve.add_argument("--feed-lag", type=float, default=None,
                         help="live-feed wall->virtual lag budget in "
                              "seconds (default: ANOMOD_SERVE_FEED_LAG_S)")
    p_serve.add_argument("--feed-journal", default=None,
                         help="record the live feed's wire journal to "
                              "this path (default: ANOMOD_FEED_JOURNAL)")

    p_obs = sub.add_parser(
        "obs", help="self-scraping telemetry plane (anomod.obs): snapshot "
        "the metrics registry, export it (Prometheus text / the "
        "framework's own TT metric CSV), or score a self-scrape capture "
        "through the framework's own OnlineDetector stack")
    p_obs.add_argument("action", choices=["snapshot", "export", "score"])
    p_obs.add_argument("--from", dest="from_path", default=None,
                       help="score: TT-CSV self-scrape capture to load "
                            "(default: run the self-exercise and score "
                            "its own telemetry)")
    p_obs.add_argument("--out", default=None,
                       help="export: output file path (required)")
    p_obs.add_argument("--format", choices=["json", "prom", "tt-csv",
                                            "chrome", "jaeger"],
                       default=None,
                       help="snapshot: json (default) or prom; "
                            "export: tt-csv (default), prom, or the "
                            "self-exercise engine's own SPAN trace as "
                            "chrome (trace-event array, loads in "
                            "chrome://tracing / Perfetto) or jaeger")
    p_obs.add_argument("--serve-seconds", type=float, default=20.0,
                       help="virtual seconds of the seeded self-exercise "
                            "serve run that populates the registry")
    p_obs.add_argument("--tenants", type=int, default=24)
    p_obs.add_argument("--capacity", type=float, default=4000.0,
                       help="self-exercise serving capacity (spans/sec)")
    p_obs.add_argument("--seed", type=int, default=0)
    p_obs.add_argument("--window-seconds", type=float, default=5.0,
                       help="score: detector window width")
    p_obs.add_argument("--baseline-windows", type=int, default=4)
    p_obs.add_argument("--threshold", type=float, default=4.0)

    p_audit = sub.add_parser(
        "audit", help="black-box flight-recorder forensics (anomod.obs."
        "flight): `record` runs seeded traffic with the tick journal on "
        "and dumps it, `replay` re-executes a journal from its header's "
        "seed+config (optionally at a different shard count / pipeline "
        "depth / state residency — the determinism contracts under "
        "test), `diff` compares two journals tick-aligned and reports "
        "the first divergent tick and which plane (admission / dispatch "
        "/ fold / score / rca) diverged, exiting nonzero")
    p_audit.add_argument("action", choices=["record", "replay", "diff"])
    p_audit.add_argument("journals", nargs="*",
                         help="replay: the journal to re-execute; diff: "
                              "the two journals to compare")
    p_audit.add_argument("--out", default=None,
                         help="record/replay: journal output path "
                              "(required)")
    # record-run shape flags default to None so the replay/diff branches
    # can tell "passed" from "absent" without a second copy of the
    # defaults; the record branch resolves the real defaults below
    p_audit.add_argument("--tenants", type=int, default=None,
                         help="record only (default 24)")
    p_audit.add_argument("--services", type=int, default=None,
                         help="record only (default 8)")
    p_audit.add_argument("--duration", type=float, default=None,
                         help="record: virtual seconds to serve "
                              "(default 30)")
    p_audit.add_argument("--tick", type=float, default=None,
                         help="record only (default 0.5)")
    p_audit.add_argument("--capacity", type=float, default=None,
                         help="record only (default 4000)")
    p_audit.add_argument("--overload", type=float, default=None,
                         help="record only (default 1.5)")
    p_audit.add_argument("--seed", type=int, default=None,
                         help="record only (default 0)")
    p_audit.add_argument("--window-seconds", type=float, default=None,
                         help="record only (default 5.0)")
    p_audit.add_argument("--baseline-windows", type=int, default=None,
                         help="record only (default 2)")
    p_audit.add_argument("--threshold", type=float, default=None,
                         help="record only (default 4.0)")
    p_audit.add_argument("--fault-tenants", type=int, default=None,
                         help="record only (default 1)")
    p_audit.add_argument("--rca", action="store_true",
                         help="record: journal the online-RCA verdict "
                              "plane too")
    p_audit.add_argument("--digest-every", type=int, default=None,
                         help="record: tenant-state digest cadence in "
                              "ticks (default: ANOMOD_FLIGHT_DIGEST_"
                              "EVERY)")
    p_audit.add_argument("--shards", type=int, default=None,
                         help="record: engine shard count; replay: "
                              "OVERRIDE the recorded shard count (the "
                              "N-way-pinned-to-1-way forensic replay)")
    p_audit.add_argument("--pipeline", type=int, default=None,
                         help="record: dispatch pipeline depth; replay: "
                              "override the recorded depth")
    p_audit.add_argument("--state", choices=["auto", "host", "device"],
                         default=None,
                         help="record: tenant-state residency; replay: "
                              "override the recorded residency")

    p_perf = sub.add_parser(
        "perf", help="performance observatory (anomod.obs.perf): "
        "`record` runs seeded traffic with the dispatch-lifecycle "
        "timeline on and dumps the event timeline + overlap-bubble "
        "analysis (--chrome adds a Chrome/Perfetto trace, one lane "
        "per shard/scratch-slot), `diff` compares two bench captures "
        "— decision metrics byte-exact, wall metrics by bootstrap "
        "confidence intervals over their raw_wall_s samples against "
        "the explicit box noise model (ANOMOD_PERF_NOISE_FLOOR) — "
        "exiting nonzero naming the first statistically significant "
        "wall regression or decision drift, and `history` indexes a "
        "bench_runs/ directory into a trajectory table")
    p_perf.add_argument("action", choices=["record", "diff", "history"])
    p_perf.add_argument("paths", nargs="*",
                        help="diff: the two capture JSONs (A then B); "
                             "history: the runs directory "
                             "(default bench_runs/)")
    p_perf.add_argument("--out", default=None,
                        help="record: timeline JSON output path "
                             "(required)")
    p_perf.add_argument("--chrome", default=None,
                        help="record: also dump the timeline as a "
                             "Chrome trace-event array (loads in "
                             "chrome://tracing / Perfetto; lanes group "
                             "by shard, shard/slot tags in args)")
    p_perf.add_argument("--tenants", type=int, default=24,
                        help="record only (default 24)")
    p_perf.add_argument("--duration", type=float, default=30.0,
                        help="record: virtual seconds to serve")
    p_perf.add_argument("--tick", type=float, default=0.5)
    p_perf.add_argument("--capacity", type=float, default=4000.0)
    p_perf.add_argument("--overload", type=float, default=1.5)
    p_perf.add_argument("--seed", type=int, default=0)
    p_perf.add_argument("--shards", type=int, default=None,
                        help="record: engine shard count (default: "
                             "ANOMOD_SERVE_SHARDS)")
    p_perf.add_argument("--pipeline", type=int, default=None,
                        help="record: dispatch pipeline depth (default: "
                             "ANOMOD_SERVE_PIPELINE)")
    p_perf.add_argument("--noise-floor", type=float, default=None,
                        help="diff: box noise fraction the wall-ratio "
                             "CIs must clear (default: "
                             "ANOMOD_PERF_NOISE_FLOOR, 0.35)")

    p_cen = sub.add_parser(
        "census", help="fleet census observatory (anomod.obs.census): "
        "`record` runs seeded traffic with the deterministic resident-"
        "bytes + hot-set/Zipf census on and dumps the census timeline, "
        "`probe` sweeps registered-fleet sizes at fixed hot traffic and "
        "fits the O(registered) per-tick wall and resident-bytes "
        "slopes (the baseline the million-tenant tiering refactor must "
        "flatten), and `diff` compares two bench captures' census "
        "blocks — byte counts exact (deterministic, so every delta is "
        "real), wall slopes within the explicit box noise tolerance — "
        "exiting nonzero on a regression: the tiering PR's "
        "before/after judge")
    p_cen.add_argument("action", choices=["record", "probe", "diff"])
    p_cen.add_argument("paths", nargs="*",
                       help="diff: the two capture JSONs (A then B)")
    p_cen.add_argument("--out", default=None,
                       help="record: census-timeline JSON output path "
                            "(required); probe: optional sweep output "
                            "path")
    # every shape flag defaults to None so the other actions can tell
    # "passed" from "absent" and refuse it loudly (the audit-branch
    # discipline: a silently ignored flag makes the user believe they
    # parameterized the run); each action resolves its real defaults
    p_cen.add_argument("--tenants", type=int, default=None,
                       help="record only (default 24)")
    p_cen.add_argument("--duration", type=float, default=None,
                       help="record: virtual seconds to serve "
                            "(default 30)")
    p_cen.add_argument("--tick", type=float, default=None,
                       help="record only (default 0.5)")
    p_cen.add_argument("--capacity", type=float, default=None,
                       help="record only (default 4000)")
    p_cen.add_argument("--overload", type=float, default=None,
                       help="record only (default 1.5)")
    p_cen.add_argument("--seed", type=int, default=None,
                       help="record/probe (default 0)")
    p_cen.add_argument("--shards", type=int, default=None,
                       help="record: engine shard count (default: "
                            "ANOMOD_SERVE_SHARDS)")
    p_cen.add_argument("--every", type=int, default=None,
                       help="record: census cadence in ticks "
                            "(default: ANOMOD_CENSUS_EVERY)")
    p_cen.add_argument("--sizes", default=None,
                       help="probe: comma-separated registered-fleet "
                            "sizes (default: ANOMOD_CENSUS_SWEEP)")
    p_cen.add_argument("--hot", type=int, default=None,
                       help="probe: fixed hot-traffic tenant count "
                            "(default 1000)")
    p_cen.add_argument("--ticks", type=int, default=None,
                       help="probe: measured ticks per sweep size "
                            "(default 8)")
    p_cen.add_argument("--tolerance", type=float, default=None,
                       help="diff: wall-slope noise tolerance the B/A "
                            "ratio must clear (default: "
                            "ANOMOD_PERF_NOISE_FLOOR)")

    p_q = sub.add_parser(
        "quality", help="de-saturated quality sweep: degradation curves over "
        "fault severity with noise + confounders (HardMode)")
    p_q.add_argument("--testbed", choices=["SN", "TT"], default="TT")
    p_q.add_argument("--models", nargs="*",
                     default=["zscore", "gcn", "gat", "sage", "temporal",
                              "lru", "transformer", "moe"])
    p_q.add_argument("--severities", nargs="*", type=float,
                     default=[1.0, 0.4, 0.2, 0.1, 0.05])
    p_q.add_argument("--train-seeds", type=int, default=6)
    p_q.add_argument("--eval-seeds", type=int, default=3)
    p_q.add_argument("--traces", type=int, default=60)
    p_q.add_argument("--epochs", type=int, default=120)
    p_q.add_argument("--noise", type=float, default=0.5)
    p_q.add_argument("--confounders", type=int, default=2)
    p_q.add_argument("--sweep", choices=["severity", "shift"],
                     default="severity",
                     help="severity: degradation curves; shift: train on the "
                          "default effect model, eval under shifted "
                          "generators (effect shape / fault timing / locus)")
    p_q.add_argument("--shift-severity", type=float, default=0.3,
                     help="fixed fault severity for the shift sweep")
    p_q.add_argument("--edge-aware", action="store_true",
                     help="--sweep shift only: out-edge feature blocks + "
                          "node+edge mixed-locus training (the supervised "
                          "counterpart of the streaming out-edge plane; "
                          "the canonical table keeps node features and "
                          "node-locus training)")
    p_q.add_argument("--json", action="store_true",
                     help="emit one JSON object per sweep point")

    args = parser.parse_args(argv)

    if args.cmd == "lint":
        # backend-free by design (pure ast over source): the contract
        # gate must run in milliseconds and can never hang on a dead
        # device tunnel, so no _probe_backend here
        import dataclasses as _dc

        from anomod.analysis import lint as _lint
        if args.rules:
            print(json.dumps({rid: _dc.asdict(r) for rid, r
                              in sorted(_lint.RULES.items())}, indent=2))
            return 0
        root = _lint.repo_root() if args.root is None else args.root
        bpath = args.baseline or _lint.baseline_path(root)
        doc, findings = _lint.run_gate(
            root, include_parity=not args.no_parity,
            baseline_file=bpath)
        if args.update_baseline:
            _lint.save_baseline(
                bpath, [f.key for f in findings if not f.suppressed])
            doc, findings = _lint.run_gate(
                root, include_parity=not args.no_parity,
                baseline_file=bpath)
        if args.json:
            if args.show_suppressed:
                doc["suppressed_findings"] = [
                    {"finding": f.render(), "reason": f.reason}
                    for f in findings if f.suppressed]
            print(json.dumps(doc))
        else:
            for line in doc["new"]:
                print(line, file=sys.stderr)
            if args.show_suppressed:
                for f in findings:
                    if f.suppressed:
                        print(f"{f.render()} [suppressed: {f.reason}]",
                              file=sys.stderr)
            print(json.dumps({k: v for k, v in doc.items()
                              if k != "new"}))
        return 0 if doc["status"] == "ok" else 1

    if args.cmd == "list":
        from anomod import labels
        rows = labels.ALL_LABELS if args.testbed is None else \
            labels.labels_for_testbed(args.testbed)
        for l in rows:
            print(f"{l.testbed}  {l.experiment:40s} {l.anomaly_level:12s} "
                  f"{l.anomaly_type:28s} {l.target_service}")
        return 0

    if args.cmd == "synth":
        from anomod import synth
        exp = synth.generate_experiment(args.experiment, n_traces=args.traces)
        print(json.dumps({
            "experiment": exp.name, "testbed": exp.testbed,
            "spans": exp.spans.n_spans, "traces": exp.spans.n_traces,
            "services": exp.spans.n_services,
            "metric_samples": exp.metrics.n_samples,
            "log_lines": exp.logs.n_lines,
            "api_records": exp.api.n_records,
        }))
        return 0

    if args.cmd == "detect":
        if args.backend == "jax":
            _probe_backend(args)
        from anomod import detect, labels, synth
        from anomod.io import dataset
        if args.from_data:
            corpus = dataset.load_corpus(args.testbed,
                                         n_synth_traces=args.traces)
        else:
            corpus = [synth.generate_experiment(l, n_traces=args.traces)
                      for l in labels.labels_for_testbed(args.testbed)]
        s = detect.evaluate_corpus(corpus, backend=args.backend)
        print(json.dumps({
            "testbed": args.testbed, "backend": args.backend,
            "top1": s.top1, "top3": s.top3, "top5": s.top5,
            "detection_accuracy": s.detection_accuracy,
            "n_rca_cases": s.n_rca_cases,
            "per_level": detect.per_level_breakdown(s),
            "per_experiment": {r.experiment: {
                "score": round(r.score, 4),
                "top3": r.ranked_services[:3],
                "target": r.target_service} for r in s.results},
        }, indent=2))
        return 0

    if args.cmd == "stream":
        import dataclasses as _dc

        from anomod import labels, synth
        from anomod.stream import stream_experiment
        if bool(args.experiment) == bool(args.all):
            parser.error("give an experiment name OR --all")
        if args.all and args.from_data:
            parser.error("--from-data is single-experiment only; --all "
                         "sweeps the generator taxonomy")
        if args.all:
            _probe_backend(args)
            from anomod.stream import stream_quality
            mesh_kw = {}
            if args.devices:
                from anomod.parallel import make_mesh
                mesh_kw["mesh"] = make_mesh(args.devices)
            if args.no_edge_attribution:
                mesh_kw["edge_attribution"] = False
            rows = stream_quality(
                args.testbed, n_traces=args.traces, seed=args.seed,
                multimodal=args.multimodal,
                severity=args.severity, noise=args.noise,
                n_confounders=args.confounders, shift=args.shift,
                slice_s=args.slice_seconds, z_threshold=args.threshold,
                baseline_windows=args.baseline_windows,
                consecutive=args.consecutive, **mesh_kw)
            for r in rows:
                print(json.dumps(r))
            import statistics
            rca_rows = [r for r in rows if "top1_hit" in r]
            lats = [r["detection_latency_windows"] for r in rca_rows
                    if r.get("detection_latency_windows") is not None]
            summary = {
                "testbed": args.testbed, "n_experiments": len(rows),
                "top1": (sum(r["top1_hit"] for r in rca_rows)
                         / len(rca_rows)) if rca_rows else None,
                "top3": (sum(r["top3_hit"] for r in rca_rows)
                         / len(rca_rows)) if rca_rows else None,
                "median_detection_latency_windows":
                    (statistics.median(lats) if lats else None),
            }
            print(json.dumps({"summary": summary}))
            try:
                import jax

                from anomod.provenance import capture_record, write_capture
                rec = capture_record(
                    "stream_quality", float(len(rows)), "experiments",
                    device=str(jax.devices()[0]), testbed=args.testbed,
                    params=dict(n_traces=args.traces, seed=args.seed,
                                multimodal=args.multimodal,
                                severity=args.severity, noise=args.noise,
                                confounders=args.confounders,
                                shift=args.shift,
                                slice_seconds=args.slice_seconds,
                                threshold=args.threshold,
                                baseline_windows=args.baseline_windows,
                                consecutive=args.consecutive,
                                edge_attribution=not
                                args.no_edge_attribution),
                    summary=summary, rows=rows)
                path = write_capture(rec)
                if path:
                    print(f"capture: {path}", file=sys.stderr)
            except Exception:
                pass
            return 0
        label = labels.label_for(args.experiment)
        if label is None:
            parser.error(f"unknown experiment {args.experiment!r}")
        # a non-default --testbed that contradicts the experiment's own
        # testbed must not be silently dropped (same contract as the
        # quality subcommand's cross-mode flag checks); the TT default
        # can't be told apart from an explicit --testbed TT, hence only
        # the detectable mismatch errors
        if args.testbed != "TT" and label.testbed != args.testbed:
            parser.error(f"{label.experiment} is a {label.testbed} "
                         f"experiment; --testbed {args.testbed} "
                         "contradicts it")
        if args.confounders:
            parser.error("--confounders applies to --all (the corpus "
                         "builder picks per-experiment decoys); it would "
                         "be silently ignored here")
        if args.shift != "in-dist":
            parser.error("--shift applies to --all; it would be silently "
                         "ignored here")
        if args.from_data and (args.severity != 1.0 or args.noise != 0.0
                               or args.seed != 0):
            parser.error("--severity/--noise/--seed shape the GENERATOR; "
                         "with --from-data the archived experiment is what "
                         "it is")
        _probe_backend(args)
        if args.from_data:
            from anomod.io import dataset
            # load only what the detector consumes (coverage is not
            # time-resolved and never streams)
            mods = (["traces", "metrics", "logs", "api"]
                    if args.multimodal else ["traces"])
            exp = dataset.load_experiment(label.experiment,
                                          modalities=mods,
                                          n_synth_traces=args.traces)
        else:
            exp = synth.generate_experiment(
                label, n_traces=args.traces, seed=args.seed,
                hard=synth.HardMode(severity=args.severity,
                                    noise=args.noise))
        _kw = dict(slice_s=args.slice_seconds, z_threshold=args.threshold,
                   baseline_windows=args.baseline_windows,
                   consecutive=args.consecutive)
        if args.no_edge_attribution:
            _kw["edge_attribution"] = False
        if args.devices:
            from anomod.parallel import make_mesh
            _kw["mesh"] = make_mesh(args.devices)
        if args.multimodal:
            from anomod.stream import stream_experiment_multimodal
            det = stream_experiment_multimodal(exp, **_kw)
        else:
            det = stream_experiment(exp.spans, **_kw)
        ranked = det.ranked_services()
        win_s = det.replay.cfg.window_us / 1e6
        out = {
            "experiment": label.experiment, "testbed": label.testbed,
            "target_service": label.target_service,
            "n_spans": det.n_spans_in,
            "window_seconds": win_s,
            "n_alerts": len(det.alerts),
            "ranked_services": ranked[:5],
            # steady pipeline cost of the simulated live feed (staging +
            # jitted chunk steps + modality planes + window scoring);
            # one-time jit compilation is warmed in the constructor and
            # reported separately
            "push_wall_s": round(det.push_wall_s, 4),
            "compile_s": round(det.replay.compile_s, 3),
            "spans_per_sec": round(det.n_spans_in
                                   / max(det.push_wall_s, 1e-9), 1),
            "alerts": [_dc.asdict(a) for a in det.alerts[:50]],
        }
        # onset/latency report only when the corpus satisfies the synth
        # fault-window invariant (onset 600 s).  Generated corpora always
        # do; --from-data corpora may mix real archived artifacts (whose
        # fault timing is arbitrary) with synth fallbacks, so no latency
        # claim is made for them — localization fields still report.
        if label.is_anomaly and not args.from_data:
            # synth faults activate in the middle third: onset 600 s
            onset_w = int(600.0 // win_s)
            fw = det.first_alert_window(label.target_service
                                        or (ranked[0] if ranked else None))
            out["fault_onset_window"] = onset_w
            out["first_culprit_alert_window"] = fw
            # signed: negative = the culprit alerted BEFORE the fault
            # (a pre-onset false positive must not read as instant
            # detection)
            out["detection_latency_windows"] = \
                None if fw is None else fw - onset_w
            if label.target_service:
                out["top1_hit"] = bool(ranked) and \
                    ranked[0] == label.target_service
        print(json.dumps(out, indent=2))
        return 0

    if args.cmd == "obs":
        if args.action == "export" and not args.out:
            parser.error("obs export needs --out")
        if args.action != "score" and args.from_path:
            parser.error("--from applies to obs score")
        if args.action == "snapshot" and args.format in ("tt-csv", "chrome",
                                                         "jaeger"):
            parser.error("snapshot prints point-in-time state; the time "
                         "series export is `obs export` (tt-csv), the "
                         "span trace is `obs export --format "
                         "chrome|jaeger`")
        if args.action == "export" and args.format == "json":
            parser.error("obs export writes prom, tt-csv, chrome or "
                         "jaeger; `obs snapshot` is the JSON view")
        if args.action == "score" and args.format in ("chrome", "jaeger"):
            parser.error("--format chrome/jaeger applies to obs export")
        from anomod.obs.selfscrape import score_self_scrape
        if args.action == "score" and args.from_path:
            # scoring an existing capture needs jax (the detector stack)
            # but no serve run
            _probe_backend(args)
            print(json.dumps(score_self_scrape(
                args.from_path, window_s=args.window_seconds,
                baseline_windows=args.baseline_windows,
                z_threshold=args.threshold), indent=2))
            return 0
        _probe_backend(args)
        from anomod.obs.selfscrape import self_exercise
        tracer = None
        if args.action == "export" and args.format in ("chrome", "jaeger"):
            # the span exporters dump the self-exercise ENGINE's own
            # trace (the Tracer rides the run), not the metric registry
            from anomod.utils.tracing import Tracer
            tracer = Tracer("anomod-serve")
        reg = self_exercise(duration_s=args.serve_seconds,
                            n_tenants=args.tenants,
                            capacity_spans_per_s=args.capacity,
                            seed=args.seed, tracer=tracer)
        if tracer is not None:
            from pathlib import Path as _P
            if args.format == "chrome":
                tracer.dump_chrome(_P(args.out))
            else:
                tracer.dump(_P(args.out))
            print(json.dumps({"out": args.out, "format": args.format,
                              "spans": tracer.n_spans}))
            return 0
        if args.action == "snapshot":
            if args.format == "prom":
                from anomod.obs.export import to_prometheus_text
                print(to_prometheus_text(reg), end="")
            else:
                print(json.dumps({"n_journal_samples": reg.n_samples,
                                  "metrics": reg.snapshot()}, indent=2))
            return 0
        if args.action == "export":
            if args.format == "prom":
                from anomod.obs.export import export_prometheus_text
                n = export_prometheus_text(reg, args.out)
                # prom is a point-in-time view: count METRICS, not the
                # journal's time-series samples
                print(json.dumps({"out": args.out, "format": "prom",
                                  "metrics": n}))
            else:
                from anomod.obs.export import export_tt_csv
                n = export_tt_csv(reg, args.out)
                print(json.dumps({"out": args.out, "format": "tt-csv",
                                  "samples": n}))
            return 0
        # score the self-exercise's own telemetry (registry -> MetricBatch
        # -> detector), no file round trip
        from anomod.obs.export import to_metric_batch
        print(json.dumps(score_self_scrape(
            to_metric_batch(reg), window_s=args.window_seconds,
            baseline_windows=args.baseline_windows,
            z_threshold=args.threshold), indent=2))
        return 0

    if args.cmd == "serve":
        if args.tenants < 1:
            parser.error("--tenants must be >= 1")
        if args.services < 1:
            parser.error("--services must be >= 1")
        if args.capacity <= 0:
            parser.error("--capacity must be positive")
        if args.tick <= 0:
            parser.error("--tick must be positive")
        if args.window_seconds <= 0:
            parser.error("--window-seconds must be positive")
        if args.overload <= 0:
            parser.error("--overload must be positive")
        if args.fault_tenants < 0:
            parser.error("--fault-tenants must be >= 0")
        if args.shards is not None and args.shards < 1:
            parser.error("--shards must be >= 1")
        if args.pipeline is not None and args.pipeline < 1:
            parser.error("--pipeline must be >= 1")
        if args.rca and args.no_score:
            parser.error("--rca consumes the detectors' alert stream; "
                         "it cannot combine with --no-score")
        if args.ckpt_every is not None and args.ckpt_every < 0:
            parser.error("--ckpt-every must be >= 0 (0 = supervision "
                         "off)")
        if args.devices and args.ckpt_every:
            parser.error("shard supervision cannot checkpoint the mesh "
                         "plane's sharded state; --devices runs with "
                         "--ckpt-every 0")
        from anomod.config import get_config
        policy_mode = (args.policy if args.policy is not None
                       else get_config().serve_policy)
        if args.policy_script is not None:
            from anomod.config import validate_policy_script
            try:
                validate_policy_script(args.policy_script)
            except ValueError as e:
                parser.error(f"--policy-script: {e}")
            if policy_mode != "script":
                parser.error("--policy-script applies to --policy "
                             "script (it would be silently ignored)")
        for flag, val in (("--min-shards", args.min_shards),
                          ("--max-shards", args.max_shards)):
            if val is not None:
                if policy_mode == "off":
                    parser.error(f"{flag} applies to an elastic policy "
                                 "(--policy auto|script)")
                if val < 1:
                    parser.error(f"{flag} must be >= 1")
        if args.devices and args.policy is not None \
                and args.policy != "off":
            # only an EXPLICIT --policy conflicts hard; an env-sourced
            # ANOMOD_SERVE_POLICY=auto degrades to off at the engine
            # (the mesh plane is outside the migration seams — the
            # supervision idiom), so existing --devices workflows keep
            # working under a globally exported policy
            parser.error("the elastic policy migrates tenants through "
                         "the bucket-runner state seams; --devices "
                         "runs with --policy off")
        if args.async_commit and args.no_async_commit:
            parser.error("--async-commit contradicts --no-async-commit")
        if args.devices and args.async_commit:
            # only an EXPLICIT --async-commit conflicts hard; an
            # env-sourced ANOMOD_SERVE_ASYNC_COMMIT=1 degrades to the
            # synchronous tick at the engine (the mesh plane manages
            # its own sharded dispatch), so existing --devices
            # workflows keep working under a globally exported knob
            parser.error("the deferred-commit tick splits the bucket-"
                         "runner issue/commit seam; --devices runs "
                         "with the synchronous tick "
                         "(drop --async-commit)")
        if args.devices and args.worker == "process":
            # only an EXPLICIT --worker process conflicts hard; an
            # env-sourced ANOMOD_SERVE_WORKER=process degrades to the
            # thread engine at the engine (the mesh plane owns its own
            # device-sharded dispatch), so existing --devices workflows
            # keep working under a globally exported knob
            parser.error("the mesh plane shards across devices inside "
                         "one process; --devices runs with the thread "
                         "worker engine (drop --worker process)")
        if args.chaos:
            from anomod.config import validate_chaos_script
            try:
                faults = validate_chaos_script(args.chaos)
            except ValueError as e:
                parser.error(f"--chaos: {e}")
            n_sh = (args.shards if args.shards is not None
                    else get_config().serve_shards)
            if policy_mode != "off":
                # an elastic run can legitimately target any shard id
                # the scale-up ceiling makes reachable
                n_sh = max(n_sh, args.max_shards
                           if args.max_shards is not None
                           else get_config().serve_policy_max_shards)
            bad = sorted({f["shard"] for f in faults
                          if f["kind"] != "surge" and f["shard"] >= n_sh})
            if bad:
                parser.error(
                    f"--chaos targets shard(s) {bad} but the run has "
                    f"{n_sh} reachable shard(s) (ids 0..{n_sh - 1}) — "
                    "the fault(s) could never fire")
        _probe_backend(args)
        from anomod.serve.batcher import validate_buckets
        from anomod.serve.engine import run_power_law
        buckets = None
        if args.buckets is not None:
            try:
                buckets = validate_buckets(
                    [p.strip() for p in args.buckets.split(",")
                     if p.strip()])
            except ValueError as e:
                parser.error(f"--buckets: {e}")
        lane_buckets = None
        if args.lane_buckets is not None:
            from anomod.config import validate_lane_buckets
            try:
                lane_buckets = validate_lane_buckets(
                    [p.strip() for p in args.lane_buckets.split(",")
                     if p.strip()])
            except ValueError as e:
                parser.error(f"--lane-buckets: {e}")
        if args.from_live or args.live_replay:
            if args.from_live and args.live_replay:
                parser.error("--from-live contradicts --live-replay")
            for flag, bad in (("--devices", args.devices),
                              ("--chaos", args.chaos),
                              ("--rca", args.rca),
                              ("--policy", args.policy),
                              ("--policy-script", args.policy_script),
                              ("--async-commit", args.async_commit),
                              ("--worker", args.worker),
                              ("--fold", args.fold),
                              ("--state", args.state),
                              ("--ckpt-every", args.ckpt_every),
                              ("--trace-out", args.trace_out),
                              ("--perf", args.perf)):
                if bad:
                    parser.error(f"{flag} is not supported on the "
                                 "live-feed path")
            from anomod.serve.feed import run_live_feed
            endpoint = None
            scrape_url = args.from_live
            if scrape_url and scrape_url.strip().lower() == "self":
                # the dogfood closed loop: serve this process's own
                # registry over real HTTP and point the feed at it
                from anomod.obs.http import ObsHttpServer
                endpoint = ObsHttpServer(
                    port=get_config().obs_http_port).start()
                scrape_url = f"{endpoint.url}/metrics"
            elif scrape_url and "://" not in scrape_url:
                parser.error("--from-live takes a URL (or 'self')")
            try:
                if args.live_replay:
                    _, report, _ = run_live_feed(
                        replay=args.live_replay,
                        capacity_spans_per_s=args.capacity,
                        duration_s=args.duration, tick_s=args.tick,
                        lag_s=args.feed_lag,
                        window_s=args.window_seconds,
                        baseline_windows=args.baseline_windows,
                        z_threshold=args.threshold, buckets=buckets,
                        lane_buckets=lane_buckets,
                        max_backlog=args.max_backlog,
                        score=not args.no_score,
                        fuse=False if args.no_fuse else None,
                        shards=args.shards, pipeline=args.pipeline)
                else:
                    _, report, _ = run_live_feed(
                        scrape_url=scrape_url,
                        n_tenants=args.tenants,
                        n_services=args.services,
                        capacity_spans_per_s=args.capacity,
                        duration_s=args.duration, tick_s=args.tick,
                        lag_s=args.feed_lag,
                        window_s=args.window_seconds,
                        baseline_windows=args.baseline_windows,
                        z_threshold=args.threshold, buckets=buckets,
                        lane_buckets=lane_buckets,
                        max_backlog=args.max_backlog,
                        score=not args.no_score,
                        fuse=False if args.no_fuse else None,
                        shards=args.shards, pipeline=args.pipeline,
                        journal=args.feed_journal)
            finally:
                if endpoint is not None:
                    endpoint.stop()
            print(json.dumps(report.to_dict(), indent=2))
            return 0
        mesh = None
        if args.devices:
            from anomod.parallel import make_mesh
            mesh = make_mesh(args.devices)
        tracer = None
        if args.trace_out:
            from anomod.utils.tracing import Tracer
            tracer = Tracer("anomod-serve")
        # the endpoint plane rides any serve run when ANOMOD_OBS_HTTP is
        # on: pure registry reads, decisions byte-identical either way
        from anomod.obs.http import maybe_serve
        _endpoint = maybe_serve()
        _, report = run_power_law(
            n_tenants=args.tenants, n_services=args.services,
            capacity_spans_per_s=args.capacity, overload=args.overload,
            duration_s=args.duration, tick_s=args.tick, seed=args.seed,
            alpha=args.alpha, window_s=args.window_seconds,
            baseline_windows=args.baseline_windows,
            z_threshold=args.threshold, buckets=buckets,
            max_backlog=args.max_backlog,
            fault_tenants=args.fault_tenants, score=not args.no_score,
            mesh=mesh, tracer=tracer,
            fuse=False if args.no_fuse else None,
            lane_buckets=lane_buckets, shards=args.shards,
            pipeline=args.pipeline,
            native=False if args.no_native else None,
            state=args.state, chaos=args.chaos,
            perf=True if args.perf else None,
            ckpt_every=args.ckpt_every,
            policy=args.policy, policy_script=args.policy_script,
            min_shards=args.min_shards, max_shards=args.max_shards,
            async_commit=(True if args.async_commit
                          else (False if args.no_async_commit
                                else None)),
            worker=args.worker, fold=args.fold,
            native_drain=args.native_drain,
            # --no-score forces RCA off even when ANOMOD_SERVE_RCA=1
            # (the explicit CLI ask wins over the env default; the
            # --rca + --no-score combination already parser.error'd)
            rca=True if args.rca else (False if args.no_score else None))
        if _endpoint is not None:
            _endpoint.stop()
        if tracer is not None:
            from pathlib import Path as _P
            tracer.dump(_P(args.trace_out))
        print(json.dumps(report.to_dict(), indent=2))
        return 0

    if args.cmd == "perf":
        from pathlib import Path as _P
        if args.action == "history":
            if len(args.paths) > 1:
                parser.error("perf history takes at most one runs "
                             "directory")
            # mode-mismatched flags fail loud, never silently ignored
            # (the audit-branch discipline)
            for flag, val in (("--out", args.out),
                              ("--chrome", args.chrome),
                              ("--noise-floor", args.noise_floor)):
                if val is not None:
                    parser.error(f"{flag} applies to perf "
                                 + ("diff" if flag == "--noise-floor"
                                    else "record")
                                 + ", not history")
            from anomod.obs.perf import capture_history
            rows = capture_history(args.paths[0] if args.paths
                                   else "bench_runs")
            print(json.dumps({"check": "anomod_perf_history",
                              "n_captures": len(rows), "runs": rows},
                             indent=2))
            return 0
        if args.action == "diff":
            if len(args.paths) != 2:
                parser.error("perf diff takes exactly two capture "
                             "paths (A then B)")
            if args.out or args.chrome:
                parser.error("--out/--chrome apply to perf record")
            from anomod.obs.perf import diff_captures
            try:
                a = json.loads(_P(args.paths[0]).read_text())
                b = json.loads(_P(args.paths[1]).read_text())
            except (OSError, ValueError) as e:
                parser.error(f"cannot load capture: {e}")
            doc = diff_captures(a, b, noise_floor=args.noise_floor)
            print(json.dumps(doc, indent=2))
            if doc["decision_mismatches"]:
                m = doc["decision_mismatches"][0]
                print(f"perf diff: decision drift at {m['path']} "
                      f"(a={m['a']!r}, b={m['b']!r}) — decision "
                      "metrics are byte-exact across same-seed "
                      "captures; this is not noise", file=sys.stderr)
                return 2
            if doc["status"] == "decision-coverage-gap":
                print("perf diff: the two captures share NO decision "
                      "metrics (truncated or foreign capture?) — "
                      "nothing was actually compared byte-exact, so "
                      "this verdict must not pass a gate",
                      file=sys.stderr)
                return 2
            if doc["regressions"]:
                r = doc["regressions"][0]
                print(f"perf diff: statistically significant wall "
                      f"regression at {r['path']}: B/A mean ratio "
                      f"{r['ratio']} (95% CI {r['ci95']}) clears the "
                      f"1+{doc['noise_model']['floor_fraction']} "
                      "noise floor", file=sys.stderr)
                return 1
            return 0
        # record
        if not args.out:
            parser.error("perf record needs --out")
        if args.paths:
            parser.error("perf record takes no positional paths")
        if args.noise_floor is not None:
            parser.error("--noise-floor applies to perf diff")
        _probe_backend(args)
        from anomod.obs.perf import (PERF_FORMAT, analyze_events,
                                     perf_tracer, round_events)
        from anomod.serve.engine import run_power_law
        eng, rep = run_power_law(
            n_tenants=args.tenants, n_services=8,
            capacity_spans_per_s=args.capacity, overload=args.overload,
            duration_s=args.duration, tick_s=args.tick, seed=args.seed,
            shards=args.shards, pipeline=args.pipeline, perf=True)
        stats = analyze_events(eng.perf_events, eng.pipeline)
        from anomod.obs.flight import _atomic_write_json
        _atomic_write_json(args.out, {
            "perf_format": PERF_FORMAT,
            "engine": {"shards": rep.shards, "pipeline": rep.pipeline,
                       "seed": args.seed, "tick_s": args.tick},
            "report": {
                "perf_events_recorded": rep.perf_events_recorded,
                "events_dropped": eng.perf_events_dropped,
                "overlap_headroom_s": rep.overlap_headroom_s,
                "fold_wait_s": rep.fold_wait_s,
                "bubble_fractions": rep.bubble_fractions,
                "stage_wall_s": rep.stage_wall_s,
                "dispatch_wall_s": rep.dispatch_wall_s,
                "fold_wall_s": rep.fold_wall_s,
                "score_wall_s": rep.score_wall_s,
                "serve_wall_s": rep.serve_wall_s},
            "raw_wall_s": [round(t, 6) for t in eng.tick_walls],
            "events": round_events(eng.perf_events)})
        out = {"action": "record", "out": args.out,
               "events": rep.perf_events_recorded,
               "overlap_headroom_s": rep.overlap_headroom_s,
               "fold_wait_s": rep.fold_wait_s,
               "fold_wall_s": rep.fold_wall_s,
               "headroom_of_fold":
                   rep.bubble_fractions.get("headroom_of_fold"),
               "analysis": {k: round(v, 6) if isinstance(v, float)
                            else v for k, v in stats.items()}}
        if args.chrome:
            tr = perf_tracer(eng.perf_events)
            tr.dump_chrome(_P(args.chrome))
            out["chrome"] = {"out": args.chrome, "spans": tr.n_spans}
        print(json.dumps(out, indent=2))
        return 0

    if args.cmd == "census":
        from pathlib import Path as _P
        # mode-mismatched flags fail loud, never silently ignored
        # (the audit/perf-branch discipline): record-only and
        # probe-only flags are refused by the other actions
        _record_only = (("--tenants", args.tenants),
                        ("--duration", args.duration),
                        ("--tick", args.tick),
                        ("--capacity", args.capacity),
                        ("--overload", args.overload),
                        ("--shards", args.shards),
                        ("--every", args.every))
        _probe_only = (("--sizes", args.sizes), ("--hot", args.hot),
                       ("--ticks", args.ticks))
        if args.action != "record":
            for flag, got in _record_only:
                if got is not None:
                    parser.error(f"{flag} applies to census record, "
                                 f"not {args.action}")
        if args.action != "probe":
            for flag, got in _probe_only:
                if got is not None:
                    parser.error(f"{flag} applies to census probe, "
                                 f"not {args.action}")
        if args.action == "diff":
            if len(args.paths) != 2:
                parser.error("census diff takes exactly two capture "
                             "paths (A then B)")
            if args.out:
                parser.error("--out applies to census record/probe")
            if args.seed is not None:
                parser.error("--seed applies to census record/probe")
            from anomod.obs.census import diff_census
            try:
                a = json.loads(_P(args.paths[0]).read_text())
                b = json.loads(_P(args.paths[1]).read_text())
            except (OSError, ValueError) as e:
                parser.error(f"cannot load capture: {e}")
            doc = diff_census(a, b, tolerance=args.tolerance)
            print(json.dumps(doc, indent=2))
            if doc["status"] == "census-missing":
                print("census diff: capture(s) carry no census block "
                      f"(missing in {doc['missing_in']}) — nothing was "
                      "compared, so this verdict must not pass a gate",
                      file=sys.stderr)
                return 2
            if doc["status"] == "bytes-regression":
                r = doc["bytes_regressions"][0]
                print(f"census diff: resident bytes grew on the "
                      f"{r['plane']!r} plane ({r['a']} -> {r['b']}) — "
                      "byte counts are deterministic; this is real "
                      "growth, not noise", file=sys.stderr)
                return 1
            if doc["status"] == "slope-regression":
                r = doc["slope_regressions"][0]
                if r["exact"]:
                    # the bytes slope is deterministic — the verdict
                    # is exact growth, never a tolerance breach
                    print(f"census diff: the {r['slope']} baseline "
                          f"grew (a={r['a']}, b={r['b']}) — this "
                          "slope is deterministic; any growth is "
                          "real, not noise", file=sys.stderr)
                else:
                    print(f"census diff: the {r['slope']} baseline "
                          f"regressed (a={r['a']}, b={r['b']}) past "
                          f"the 1+{doc['tolerance']} noise tolerance",
                          file=sys.stderr)
                return 1
            return 0
        if args.tolerance is not None:
            parser.error("--tolerance applies to census diff")
        if args.paths:
            parser.error(f"census {args.action} takes no positional "
                         "paths")
        if args.action == "probe":
            sizes = None
            if args.sizes is not None:
                try:
                    sizes = tuple(int(p.strip())
                                  for p in args.sizes.split(",")
                                  if p.strip())
                    if len(sizes) < 2 or any(s < 1 for s in sizes) \
                            or any(a >= b for a, b
                                   in zip(sizes, sizes[1:])):
                        raise ValueError(
                            "need >= 2 strictly ascending positive "
                            "sizes")
                except ValueError as e:
                    parser.error(f"--sizes: {e}")
            if args.ticks is not None and args.ticks < 1:
                parser.error("--ticks must be >= 1")
            if args.hot is not None and args.hot < 1:
                parser.error("--hot must be >= 1")
            _probe_backend(args)
            from anomod.obs.census import CENSUS_FORMAT, fleet_probe
            doc = {"census_format": CENSUS_FORMAT,
                   "sweep": fleet_probe(
                       sizes=sizes,
                       hot=1000 if args.hot is None else args.hot,
                       ticks=8 if args.ticks is None else args.ticks,
                       seed=0 if args.seed is None else args.seed)}
            if args.out:
                from anomod.obs.flight import _atomic_write_json
                _atomic_write_json(args.out, doc)
                doc["out"] = args.out
            print(json.dumps(doc, indent=2))
            return 0
        # record
        if not args.out:
            parser.error("census record needs --out")

        def _or(v, default):
            return default if v is None else v

        _probe_backend(args)
        from anomod.obs.census import CENSUS_FORMAT
        from anomod.obs.flight import _atomic_write_json
        from anomod.serve.engine import run_power_law
        eng, rep = run_power_law(
            n_tenants=_or(args.tenants, 24), n_services=8,
            capacity_spans_per_s=_or(args.capacity, 4000.0),
            overload=_or(args.overload, 1.5),
            duration_s=_or(args.duration, 30.0),
            tick_s=_or(args.tick, 0.5), seed=_or(args.seed, 0),
            shards=args.shards, census=True, census_every=args.every,
            flight=True)
        stream = [rec["census"]
                  for rec in eng.flight_recorder.records()
                  if rec["census"]["planes"]]
        _atomic_write_json(args.out, {
            "census_format": CENSUS_FORMAT,
            "engine": {"shards": rep.shards, "seed": _or(args.seed, 0),
                       "tick_s": _or(args.tick, 0.5),
                       "census_every": eng.census_every},
            "report": {
                "census_ticks": rep.census_ticks,
                "census_hot_set": rep.census_hot_set,
                "census_resident_bytes": rep.census_resident_bytes},
            "stream": stream})
        print(json.dumps({
            "action": "record", "out": args.out,
            "census_ticks": rep.census_ticks,
            "resident_bytes":
                rep.census_resident_bytes.get("total"),
            "pool_reconciled":
                rep.census_resident_bytes.get("pool_reconciled"),
            "hot_set": rep.census_hot_set}, indent=2))
        return 0

    if args.cmd == "audit":
        from anomod.obs.flight import diff_journals, load_journal
        # record-only flags must not be silently ignored by replay/diff
        # (replay takes its run from the journal header; an operator
        # passing --seed or --duration there would draw forensic
        # conclusions from a run they did not ask for)
        if args.action != "record":
            _record_only = (("--tenants", args.tenants),
                            ("--services", args.services),
                            ("--duration", args.duration),
                            ("--tick", args.tick),
                            ("--capacity", args.capacity),
                            ("--overload", args.overload),
                            ("--seed", args.seed),
                            ("--window-seconds", args.window_seconds),
                            ("--baseline-windows", args.baseline_windows),
                            ("--threshold", args.threshold),
                            ("--fault-tenants", args.fault_tenants),
                            ("--rca", args.rca or None))
            for flag, got in _record_only:
                if got is not None:
                    parser.error(
                        f"{flag} applies to audit record; "
                        f"{args.action} takes its run from the journal "
                        "header" + (" (--shards/--pipeline/--state/"
                                    "--digest-every override)"
                                    if args.action == "replay" else ""))
        if args.action == "diff":
            for flag, val in (("--shards", args.shards),
                              ("--pipeline", args.pipeline),
                              ("--state", args.state),
                              ("--digest-every", args.digest_every)):
                if val is not None:
                    parser.error(f"{flag} applies to audit record/replay")
            if len(args.journals) != 2:
                parser.error("audit diff takes exactly two journal paths")
            if args.out:
                parser.error("--out applies to audit record/replay")
            a = load_journal(args.journals[0])
            b = load_journal(args.journals[1])
            d = diff_journals(a, b)
            out = {"action": "diff",
                   "a": args.journals[0], "b": args.journals[1],
                   "ticks_a": len(a["ticks"]), "ticks_b": len(b["ticks"]),
                   "identical": d is None}
            if d is not None:
                out["divergence"] = d
            print(json.dumps(out, indent=2))
            if d is not None:
                print(f"audit diff: first divergence at tick "
                      f"{d['tick']} in the {d['plane']} plane",
                      file=sys.stderr)
                return 1
            return 0
        if not args.out:
            parser.error(f"audit {args.action} needs --out")
        if args.action == "record":
            if args.journals:
                parser.error("audit record takes no journal arguments")

            def _or(v, default):
                return default if v is None else v

            kw = dict(n_tenants=_or(args.tenants, 24),
                      n_services=_or(args.services, 8),
                      capacity_spans_per_s=_or(args.capacity, 4000.0),
                      overload=_or(args.overload, 1.5),
                      duration_s=_or(args.duration, 30.0),
                      tick_s=_or(args.tick, 0.5),
                      seed=_or(args.seed, 0),
                      window_s=_or(args.window_seconds, 5.0),
                      baseline_windows=_or(args.baseline_windows, 2),
                      z_threshold=_or(args.threshold, 4.0),
                      fault_tenants=_or(args.fault_tenants, 1),
                      shards=args.shards, pipeline=args.pipeline,
                      state=args.state,
                      rca=True if args.rca else None,
                      flight=True,
                      flight_digest_every=args.digest_every)
        else:
            if len(args.journals) != 1:
                parser.error("audit replay takes exactly one journal path")
            header = load_journal(args.journals[0]).get("header", {})
            run = header.get("run")
            if not run:
                parser.error("journal header carries no run parameters "
                             "(not recorded through `anomod audit "
                             "record` / run_power_law) — cannot replay")
            kw = dict(run)
            kw["buckets"] = tuple(kw["buckets"]) if kw.get("buckets") \
                else None
            kw["lane_buckets"] = tuple(kw["lane_buckets"]) \
                if kw.get("lane_buckets") else None
            # the forensic overrides: replay the SAME decisions at a
            # different shard count / pipeline depth / residency — diff
            # against the original is the determinism contract's probe
            for name, val in (("shards", args.shards),
                              ("pipeline", args.pipeline),
                              ("state", args.state),
                              ("flight_digest_every", args.digest_every)):
                if val is not None:
                    kw[name] = val
            kw["flight"] = True
        _probe_backend(args)
        if kw.pop("traffic", None) == "live_feed":
            # a live-feed run replays through its WIRE journal (the
            # response sequence is the ground truth), not by re-polling
            if args.state is not None:
                parser.error("--state applies to power-law journals; "
                             "live-feed replays take the engine shape "
                             "from the journal header")
            from pathlib import Path as _P
            feed_journal = kw.pop("feed_journal", "")
            if not feed_journal or not _P(feed_journal).exists():
                parser.error(
                    "the run's wire journal is missing "
                    f"({feed_journal or 'not recorded'}) — record live "
                    "runs with ANOMOD_FEED_JOURNAL/--feed-journal to "
                    "make them replayable")
            from anomod.serve.feed import run_live_feed
            eng, rep, _ = run_live_feed(replay=feed_journal, **kw)
        else:
            from anomod.serve.engine import run_power_law
            # pre-tiering journals (recorded before the state-tiering
            # PR) carry no tier geometry: replay them tiering-OFF, never
            # under the replaying process's env knobs — env drift must
            # not masquerade as plane divergence
            kw.setdefault("tier_hot", 0)
            # likewise pre-procshard journals carry no worker/fold
            # keys: replay them on the thread engine with the dense
            # fold, never under the replaying process's env knobs
            kw.setdefault("worker", "thread")
            kw.setdefault("fold", "dense")
            eng, rep = run_power_law(**kw)
        doc = eng.flight_recorder.dump(args.out)
        print(json.dumps({
            "action": args.action, "out": args.out,
            "ticks": doc["n_recorded"], "dropped": doc["n_dropped"],
            "seed": doc["header"]["run"].get("seed"),
            "shards": doc["header"]["engine"]["shards"],
            "serve_state": doc["header"]["engine"]["serve_state"],
            "digest_every": doc["header"]["digest_every"],
            "served_spans": rep.served_spans,
            "n_alerts": rep.n_alerts,
        }))
        return 0

    if args.cmd == "quality":
        import dataclasses as _dc

        from anomod.quality import (render_markdown, render_shift_markdown,
                                    severity_sweep, shift_sweep)
        # a flag belonging to the other sweep kind must not be silently
        # dropped (defaults come from the parser, so a non-default value
        # means the user passed it)
        if args.sweep == "shift" and args.severities != [1.0, 0.4, 0.2, 0.1,
                                                         0.05]:
            parser.error("--severities applies to --sweep severity; "
                         "use --shift-severity for the shift sweep")
        if args.sweep == "severity" and args.shift_severity != 0.3:
            parser.error("--shift-severity applies to --sweep shift")
        if args.sweep == "severity" and args.edge_aware:
            parser.error("--edge-aware applies to --sweep shift")
        _probe_backend(args)
        common = dict(
            testbed=args.testbed, model_names=args.models,
            train_seeds=range(args.train_seeds),
            eval_seeds=range(100, 100 + args.eval_seeds),
            n_traces=args.traces, epochs=args.epochs, noise=args.noise,
            n_confounders=args.confounders, verbose=not args.json)
        if args.sweep == "shift":
            pts = shift_sweep(severity=args.shift_severity,
                              edge_aware=args.edge_aware, **common)
            render = render_shift_markdown
        else:
            pts = severity_sweep(severities=args.severities, **common)
            render = render_markdown
        # committed provenance trail (same contract as bench.py): every
        # sweep leaves a bench_runs/ record with the full table + device
        # string + git SHA, so docs tables cite re-checkable artifacts
        try:
            import jax

            from anomod import quality as _q
            from anomod.provenance import capture_record, write_capture
            # a sweep that lost its device mid-run and finished on the CPU
            # failover backend is labeled so (the device string alone would
            # already read cpu, but the note records *why*)
            failover = ({"device_failover": _q.LAST_FAILOVER}
                        if _q.LAST_FAILOVER else {})
            rec = capture_record(
                f"quality_{args.sweep}_sweep", float(len(pts)), "points",
                device=str(jax.devices()[0]), testbed=args.testbed,
                models=list(args.models),
                params={**{k: (list(v) if isinstance(v, range) else v)
                           for k, v in common.items()
                           if k not in ("verbose", "testbed", "model_names")},
                        **({"shift_severity": args.shift_severity,
                            "edge_aware": bool(args.edge_aware)}
                           if args.sweep == "shift"
                           else {"severities": args.severities})},
                points=[_dc.asdict(p) for p in pts], **failover)
            capture_path = write_capture(rec)
        except Exception:
            capture_path = None
        if args.json:
            # one QualityPoint per stdout line (stream stays homogeneous);
            # the capture path goes to stderr
            for p in pts:
                print(json.dumps(_dc.asdict(p)))
            if capture_path:
                print(f"capture: {capture_path}", file=sys.stderr)
        else:
            print(render(pts))
            if capture_path:
                print(f"\ncapture: {capture_path}")
        return 0

    if args.cmd == "rca":
        if args.resume and not args.checkpoint_dir:
            parser.error("--resume requires --checkpoint-dir")
        _probe_backend(args)
        from anomod.rca import train_rca_resilient
        r, failover = train_rca_resilient(
            args.testbed, args.model,
            train_seeds=range(args.train_seeds),
            eval_seeds=range(100, 100 + args.eval_seeds),
            epochs=args.epochs,
            checkpoint_dir=args.checkpoint_dir,
            resume=args.resume)
        if failover:
            print(f"[anomod] {failover}", file=sys.stderr)
        out = {
            "testbed": args.testbed, "model": r.model_name,
            "top1": r.top1, "top3": r.top3,
            "detection_auc": r.detection_auc, "n_eval": r.n_eval,
        }
        if failover:
            out["device_failover"] = failover
        print(json.dumps(out))
        return 0

    if args.cmd == "collect":
        import time as _time

        from anomod.io.live import (ElasticsearchClient, HttpTransport,
                                    JaegerClient, PrometheusClient,
                                    SkyWalkingClient)
        if args.kind in ("kube-logs", "docker-logs", "jacoco", "gcov"):
            from pathlib import Path as _P

            from anomod.io.live_exec import (DockerLogCollector, ExecRunner,
                                             GcovCoverageCollector,
                                             JacocoCoverageCollector,
                                             KubeLogCollector)
            runner = ExecRunner(timeout=args.timeout)
            stamp = _time.strftime("%Y%m%d_%H%M%S")
            if args.kind == "kube-logs":
                rep = KubeLogCollector(
                    runner=runner, namespace=args.namespace).collect(
                    _P(args.out), stamp=stamp, tail=args.tail)
            elif args.kind == "docker-logs":
                rep = DockerLogCollector(runner=runner).collect(
                    _P(args.out), stamp=stamp, time_range=args.since)
            elif args.kind == "gcov":
                out = _P(args.out)
                rep = GcovCoverageCollector(runner=runner).collect(
                    _P(args.mount_root), out,
                    base=args.experiment, stamp=stamp)
            else:
                out = _P(args.out)
                report = (_P(args.report_dir) if args.report_dir
                          else out.parent / "coverage_report")
                rep = JacocoCoverageCollector(
                    runner=runner, namespace=args.namespace).collect(
                    out, report)
            print(json.dumps(rep.to_json()))
            return 0
        if not args.url:
            parser.error(f"--url is required for kind {args.kind}")
        tp = HttpTransport(timeout=args.timeout, max_retries=args.retries)
        now = _time.time()
        start = now - args.hours_back * 3600.0
        if args.kind == "prometheus":
            client = PrometheusClient(args.url, transport=tp)
            if args.testbed == "SN":
                # catalog names double as identity queries against a stub
                # or relabeling proxy; a real deployment maps names to the
                # recorded PromQL (collect_metric.sh's query table)
                from anomod.metrics_catalog import SN_METRIC_FILES
                rep = client.collect_sn({n: n for n in SN_METRIC_FILES},
                                        args.out, start, now,
                                        step=args.step)
            else:
                from anomod.metrics_catalog import TT_ALL_QUERIES
                rep = client.collect_tt(TT_ALL_QUERIES, args.out,
                                        start, now, step=args.step)
        elif args.kind == "jaeger":
            rep = JaegerClient(args.url, transport=tp).collect_all(
                args.out, limit=args.limit,
                lookback_ms=int(args.hours_back * 3_600_000))
        elif args.kind == "skywalking":
            rep = SkyWalkingClient(args.url, transport=tp).collect(
                args.out, experiment=args.experiment, limit=args.limit,
                hours_back=args.hours_back)
        else:
            rep = ElasticsearchClient(args.url, transport=tp).collect(
                args.out, size=args.limit, hours_back=args.hours_back)
        print(json.dumps(rep.to_json()))
        return 0

    if args.cmd == "golden":
        from anomod.golden import format_markdown, golden_report
        report = golden_report()
        print(format_markdown(report) if args.markdown
              else json.dumps(report, indent=1))
        return 0

    if args.cmd == "ingest":
        import dataclasses as _dc
        import time as _time

        from anomod.config import get_config
        from anomod.io import cache as ingest_cache
        from anomod.io import dataset
        cfg = get_config()
        from pathlib import Path as _P
        if args.cache_dir is not None:
            cfg = _dc.replace(cfg, cache_dir=_P(args.cache_dir))
        if args.data_root is not None:
            cfg = _dc.replace(cfg, data_root=_P(args.data_root))
        root = ingest_cache.cache_root(cfg)
        out = {"cache_dir": str(root) if root else None}
        if root is None:
            print(json.dumps({**out, "error":
                              "caching disabled (ANOMOD_CACHE_DIR=off)"}))
            return 1
        if args.clear:
            out["cleared"] = ingest_cache.clear(root)
        if args.warm_cache:
            ingest_cache.reset_stats()
            testbeds = (["SN", "TT"] if args.testbed == "both"
                        else [args.testbed])
            t0 = _time.perf_counter()
            for tb in testbeds:
                dataset.load_corpus(tb, cfg=cfg,
                                    n_synth_traces=args.traces,
                                    workers=args.workers)
                if args.bench_traces:
                    dataset.load_bench_corpus(tb, args.bench_traces, cfg)
            out.update(warmed=testbeds,
                       wall_s=round(_time.perf_counter() - t0, 3),
                       **ingest_cache.stats().to_dict())
        out["entries"] = ingest_cache.entry_count(root)
        print(json.dumps(out))
        return 0

    if args.cmd == "validate":
        from anomod import labels, synth
        from anomod.io import cache as ingest_cache
        from anomod.io import dataset
        from anomod.validate import corpus_summary, validate_experiment
        ingest_cache.reset_stats()
        if args.from_data:
            corpus = dataset.load_corpus(args.testbed, n_synth_traces=args.traces)
        else:
            corpus = [synth.generate_experiment(l, n_traces=args.traces)
                      for l in labels.labels_for_testbed(args.testbed)]
        reports = [validate_experiment(e) for e in corpus]
        cache_stats = None
        if args.from_data:
            # a fresh/empty cache dir (or one the counters can't be read
            # from) must degrade to zero counters, never crash the
            # validation report — the counters are a quality SIGNAL, not
            # a load-bearing dependency
            try:
                cache_stats = ingest_cache.stats().to_dict()
            except Exception:
                cache_stats = ingest_cache.CacheStats().to_dict()
        summary = corpus_summary(args.testbed, reports,
                                 cache_stats=cache_stats)
        # native-runtime health rides the validation document: the knob
        # value, availability, and — the part a silent fallback hides —
        # the recorded build-failure reason when the .so is unusable
        from anomod.io import native as native_io
        summary["native"] = native_io.status()
        # contract health rides the validation document too (the
        # static-analysis twin of the native block): rule inventory,
        # live finding counts and baseline size — an operator sees a
        # violated determinism/parity contract next to an unusable
        # native runtime, not in a separate tool
        from anomod.analysis import status_block as _lint_status
        summary["lint"] = _lint_status()
        print(json.dumps(summary, indent=2))
        return 0

    if args.cmd == "campaign":
        from anomod.campaign import run_campaign
        done = run_campaign(args.testbed, args.out,
                            experiments=args.experiments,
                            n_traces=args.traces)
        print(json.dumps({"testbed": args.testbed, "out": args.out,
                          "experiments": done}))
        return 0

    if args.cmd == "chaos":
        from anomod import chaos, labels
        label = labels.label_for(args.experiment)
        if label is None:
            print(f"unknown experiment: {args.experiment}", file=sys.stderr)
            return 1
        plan = {"experiment": label.experiment, "tool": label.chaos_tool}
        if label.chaos_tool == "chaosmesh":
            if args.format == "yaml":
                print(chaos.mesh_crd_yaml(label))
                return 0
            plan["crd"] = chaos.build_mesh_crd(label)
        elif label.chaos_tool == "chaosblade":
            cmd = chaos.blade_create_command(label)
            if cmd is not None:
                plan["blade"] = list(cmd.args)
                plan["needs_sudo"] = cmd.needs_sudo
            dc = chaos.docker_command(label)
            if dc is not None:
                plan["docker"] = list(dc)
        if args.format == "yaml":
            import yaml
            print(yaml.safe_dump(plan, sort_keys=False), end="")
        else:
            print(json.dumps(plan, indent=2))
        return 0

    if args.cmd == "scenario":
        import numpy as np

        from anomod import labels, scenario
        from anomod.chaos import ChaosController
        if args.iterations < 1:
            print("--iterations must be >= 1", file=sys.stderr)
            return 1
        ctl = None
        if args.chaos:
            label = labels.label_for(args.chaos)
            if label is None:
                print(f"unknown experiment: {args.chaos}", file=sys.stderr)
                return 1
            if label.testbed != "TT":
                print(f"{label.experiment} is an {label.testbed} fault; the "
                      "scenario workload drives the TT testbed", file=sys.stderr)
                return 1
            ctl = ChaosController()
            ctl.create(label)
        batch = scenario.run_scenario(iterations=args.iterations,
                                      seed=args.seed, controller=ctl)
        by_status = {str(c): int((batch.status == c).sum())
                     for c in np.unique(batch.status)}
        print(json.dumps({
            "requests": batch.n_records,
            "endpoints": len(batch.endpoints),
            "status_codes": by_status,
            "error_rate": round(float((batch.status >= 500).mean()), 4),
            "avg_latency_ms": round(float(batch.latency_ms.mean()), 2),
            "p99_latency_ms": round(float(np.percentile(batch.latency_ms, 99)), 2),
            "chaos": args.chaos,
        }))
        return 0

    if args.cmd == "deploy":
        from anomod import deploy
        if args.testbed == "SN":
            print(deploy.render_plan(deploy.sn_compose_plan(up=not args.down)),
                  end="")
            return 0
        flags = deploy.DeployFlags(
            all=args.deploy_all, independent_db=args.independent_db,
            with_monitoring=args.with_monitoring,
            with_tracing=args.with_tracing)
        if args.secrets:
            import yaml
            host = None if flags.independent_db else "tsdb-mysql-leader"
            print(yaml.safe_dump_all(deploy.gen_mysql_secrets(host),
                                     sort_keys=False), end="")
            return 0
        print(deploy.render_plan(deploy.tt_deploy_plan(flags)), end="")
        return 0

    if args.cmd == "monitor":
        import numpy as np

        from anomod.monitor import capture_openapi_responses
        report = capture_openapi_responses(
            args.out, mode=args.mode, cycles=args.cycles,
            seed=args.seed, chaos=args.chaos,
            wrk2_requests=args.wrk2_requests)
        b = report.batch
        print(json.dumps({
            "mode": report.mode, "cycles": report.n_cycles,
            "requests": b.n_records, "endpoints": len(b.endpoints),
            "reachable": sum(report.connectivity.values()),
            "status_codes": {str(c): int((b.status == c).sum())
                             for c in np.unique(b.status)},
            "error_rate": round(float((b.status >= 500).mean()), 4),
            "p99_latency_ms": round(float(np.percentile(b.latency_ms, 99)), 2),
            "out": args.out, "chaos": args.chaos,
        }))
        return 0

    if args.cmd == "logscan":
        from pathlib import Path

        from anomod.io import native
        from anomod.io.lfs import is_lfs_pointer
        from anomod.io.logs import summarize_log_files
        root = Path(args.dir)
        if not root.is_dir():
            print(f"not a directory: {root}", file=sys.stderr)
            return 1
        candidates = sorted(root.glob(args.glob))
        paths = [p for p in candidates if not is_lfs_pointer(p)]
        summaries = summarize_log_files(paths)
        print(json.dumps({
            "dir": str(root), "n_files": len(paths),
            "n_lfs_stubs": len(candidates) - len(paths),
            "native": native.enabled(),
            "totals": {
                "lines": sum(s.n_lines for s in summaries),
                "errors": sum(s.n_error for s in summaries),
                "warnings": sum(s.n_warn for s in summaries),
                "bytes": sum(s.size_bytes for s in summaries),
            },
            "files": [{
                "path": str(p.relative_to(root)), "service": s.service,
                "lines": s.n_lines, "errors": s.n_error,
                "warnings": s.n_warn, "info": s.n_info,
                "bytes": s.size_bytes,
            } for p, s in zip(paths, summaries)],
        }, indent=2))
        return 0

    if args.cmd == "replay":
        if args.devices and args.replicate != 1:
            parser.error("--replicate is not supported with --devices")
        if args.devices and args.kernel == "numpy":
            parser.error("--kernel numpy is the single-chip host engine; "
                         "the sharded path needs a device kernel")
        if args.devices and args.kernel == "pallas-sorted":
            parser.error("--kernel pallas-sorted stages on the host for one "
                         "chip; the sharded path uses 'xla' or 'pallas'")
        # a pure-host run (numpy engine, no mesh, no digest plane) touches
        # no jax — don't pay the backend probe for it
        if args.kernel != "numpy" or args.devices or args.percentiles \
                or args.edge_percentiles:
            _probe_backend(args)
        from anomod import labels, synth
        from anomod.replay import ReplayConfig, measure_throughput
        from anomod.schemas import concat_span_batches
        batch = concat_span_batches([
            synth.generate_spans(l, n_traces=args.traces)
            for l in labels.labels_for_testbed(args.testbed)])
        cfg = ReplayConfig(n_services=batch.n_services)
        if args.devices:
            from anomod.parallel import make_mesh, sharded_throughput
            mesh = make_mesh(args.devices)
            r = sharded_throughput(batch, mesh, cfg, kernel=args.kernel)
        else:
            r = measure_throughput(batch, cfg, replicate=args.replicate,
                                   kernel=args.kernel)
        out = {
            "n_spans": r.n_spans, "wall_s": round(r.wall_s, 4),
            "spans_per_sec": round(r.spans_per_sec, 1),
            "compile_s": round(r.compile_s, 2),
            "kernel": r.kernel,
        }
        if args.devices:
            out["devices"] = int(mesh.devices.size)
        if args.percentiles:
            import numpy as np

            from anomod.ops.tdigest import tdigest_build, tdigest_quantile
            from anomod.replay import replay_digests
            # per-segment digest plane, merged (weighted rebuild) into ONE
            # corpus digest so the reported tail is the true corpus-wide
            # p99, not a median across segments
            d = replay_digests(batch, cfg)
            corpus = tdigest_build(d.mean.reshape(-1), k=64,
                                   weights=d.weight.reshape(-1))
            out["latency_us"] = {
                name: round(float(np.expm1(tdigest_quantile(corpus, q))), 1)
                for name, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99))
            } if float(d.weight.sum()) > 0 else {}
        if args.edge_percentiles:
            import numpy as np

            from anomod.replay import replay_edge_features
            pct, distinct, table = replay_edge_features(batch, cfg)
            W = cfg.n_windows
            # per-edge p99 = worst window's p99 with traffic; rank the
            # cross edges (self-edges are the node view)
            p99 = np.nan_to_num(pct[:, -1].reshape(len(table), W))
            worst = p99.max(axis=1)
            rows = sorted(
                ((float(worst[i]), i, a, b)
                 for i, (a, b) in enumerate(table)
                 if a != b and worst[i] > 0), reverse=True)
            out["edge_p99_us_top"] = [
                {"edge": f"{batch.services[a]}->{batch.services[b]}",
                 "p99_us": round(v, 1),
                 "distinct_traces": round(float(distinct[i]), 1)}
                for v, i, a, b in rows[:5]]
        print(json.dumps(out))
        return 0

    return 1


if __name__ == "__main__":
    sys.exit(main())
