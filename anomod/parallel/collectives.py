"""Explicit collective patterns over the device mesh.

XLA inserts collectives from sharding annotations for the main paths; this
module provides the explicit shard_map building blocks for state merging:

  - ``ring_allreduce``: ppermute-based ring all-reduce (the bandwidth-optimal
    ICI pattern, written out instead of ``psum`` where overlap with compute
    matters or where the reduction isn't a plain sum).
  - ``allgather_merge_tdigests``: t-digest shard states are NOT sum-mergeable,
    so shards all-gather their centroid sets over the mesh axis and rebuild —
    the sketch-state analog of gradient synchronization.
  - ``pmax_merge_hll``: HLL registers merge exactly with an elementwise max.
"""

from __future__ import annotations

from typing import Optional


def ring_allreduce(x, axis: str):
    """Ring all-reduce via ppermute (call inside shard_map over ``axis``)."""
    import jax

    # axis_size is a newer lax addition; psum(1) is the portable spelling
    n = (jax.lax.axis_size(axis) if hasattr(jax.lax, "axis_size")
         else int(jax.lax.psum(1, axis)))
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(i, carry):
        acc, buf = carry
        buf = jax.lax.ppermute(buf, axis, perm)
        return acc + buf, buf

    acc, _ = jax.lax.fori_loop(0, n - 1, body, (x, x))
    return acc


def reduce_scatter_state(x, axis: str):
    """psum_scatter: merge shard states AND leave each shard holding only
    its slice of the result — half the ICI traffic of psum when the
    consumer is itself sharded over the same axis (the pod-scale pattern
    for huge [S*W, F] aggregate states: merge once, keep 1/D locally).
    Call inside shard_map; the axis size must divide the leading dim."""
    import jax
    return jax.lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)


def pmax_merge_hll(registers, axis: str):
    """Exact HLL merge across shards (call inside shard_map)."""
    import jax
    return jax.lax.pmax(registers, axis)


def allgather_merge_tdigests(mean, weight, axis: str, k: Optional[int] = None):
    """Merge per-shard t-digests: all_gather centroids, weighted rebuild.

    mean/weight: [..., K] per-shard centroid arrays inside shard_map.
    Returns a merged digest replicated on every shard.
    """
    import jax
    import jax.numpy as jnp

    from anomod.ops.tdigest import tdigest_build

    k = k or mean.shape[-1]
    all_mean = jax.lax.all_gather(mean, axis, axis=-1, tiled=True)
    all_weight = jax.lax.all_gather(weight, axis, axis=-1, tiled=True)
    d = tdigest_build(all_mean, k=k, weights=all_weight, xp=jnp)
    return d.mean, d.weight
