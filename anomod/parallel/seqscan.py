"""Sequence-parallel temporal scan: the long-context plane for time series.

GRUs are non-associative, so they cannot shard over time.  For long window
streams (days of 15 s samples — far beyond one device's comfortable scan
length) the temporal recurrence is expressed as a **linear recurrence**

    h_t = a ⊙ h_{t-1} + x_t,   a ∈ (0,1)^C  (per-channel decay)

whose composition law ``(a1,b1)∘(a2,b2) = (a1·a2, a2·b1 + b2)`` is
associative.  Within a device it runs as ``lax.associative_scan`` (log-depth,
VPU-friendly); across devices the window axis is sharded and the classic
block-scan applies: local scan → all_gather of the [D] block aggregates over
ICI → exclusive prefix (computed redundantly per device, D is tiny) → local
correction.  Exact to floating-point reassociation, verified against the
single-device scan on the CPU mesh.
"""

from __future__ import annotations


def linear_recurrence(xs, decay):
    """Single-device reference: h_t = decay ⊙ h_{t-1} + xs_t over axis 0.

    xs: [T, ...]; decay: broadcastable to xs[0].  Returns all states [T, ...].
    """
    import jax
    import jax.numpy as jnp

    a = jnp.broadcast_to(decay, xs.shape[1:])
    a_seq = jnp.broadcast_to(a, xs.shape)

    def combine(left, right):
        a1, b1 = left
        a2, b2 = right
        return a1 * a2, a2 * b1 + b2

    _, h = jax.lax.associative_scan(combine, (a_seq, xs), axis=0)
    return h


def make_seqpar_recurrence(mesh, axis: str = "data"):
    """Sequence-parallel linear recurrence: window axis sharded over ``axis``.

    Returns fn(xs [T, ...], decay) -> [T, ...] with T % mesh_size == 0;
    xs arrives sharded on axis 0, output leaves sharded the same way.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from anomod.parallel.mesh import shard_map_compat

    n_dev = mesh.shape[axis]

    def body(xs_local, decay):
        # decay is replicated (P()) hence device-invariant; mark it varying so
        # every derived carry/aggregate has consistent vma annotations
        from anomod.parallel.mesh import pvary_compat
        decay = pvary_compat(decay, (axis,))
        # local block scan
        h_local = linear_recurrence(xs_local, decay)             # [T/D, ...]
        t_local = xs_local.shape[0]
        a = jnp.broadcast_to(decay, xs_local.shape[1:])
        block_a = a ** t_local                                   # decay^T/D
        block_b = h_local[-1]
        # gather all block aggregates: [D, ...]
        all_a = jax.lax.all_gather(block_a, axis)
        all_b = jax.lax.all_gather(block_b, axis)
        # exclusive prefix over blocks (serial over D — D is the mesh size)
        idx = jax.lax.axis_index(axis)

        def step(carry, ab):
            a_i, b_i = ab
            new = (carry[0] * a_i, a_i * carry[1] + b_i)
            return new, carry[1]          # emit EXCLUSIVE prefix state

        init = (jnp.ones_like(block_a), jnp.zeros_like(block_b))
        _, prefix_states = jax.lax.scan(step, init, (all_a, all_b))
        carry_in = prefix_states[idx]                            # [...]
        # correction: h_t += a^(t+1) * carry_in within the local block
        t_idx = jnp.arange(1, t_local + 1).reshape(
            (t_local,) + (1,) * (xs_local.ndim - 1))
        corr = (a[None] ** t_idx) * carry_in[None]
        return h_local + corr

    fn = shard_map_compat(body, mesh=mesh,
                   in_specs=(P(axis), P()),
                   out_specs=P(axis))
    return jax.jit(fn)
