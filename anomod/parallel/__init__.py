"""Device-mesh parallelism: sharded replay, collectives, mesh helpers,
dp×tp training, pipeline (pp) stages, expert (ep) sharding, ring attention
(sp)."""

from anomod.parallel.mesh import make_mesh, shard_chunks
from anomod.parallel.replay import make_sharded_replay_fn, sharded_throughput

__all__ = ["make_mesh", "shard_chunks", "make_sharded_replay_fn",
           "sharded_throughput"]
