"""Device-mesh parallelism: sharded replay, collectives, mesh helpers,
dp×tp training, pipeline (pp) stages, expert (ep) sharding, and the two
sequence-parallel attention planes (ring + Ulysses all-to-all)."""

from anomod.parallel.mesh import make_mesh, shard_chunks
from anomod.parallel.replay import (make_sharded_replay_fn, stage_sharded,
                                    sharded_throughput)
from anomod.parallel.ring_attention import make_ring_attention
from anomod.parallel.sp_transformer import make_sp_transformer
from anomod.parallel.ulysses import make_ulysses_attention

__all__ = ["make_mesh", "shard_chunks", "make_sharded_replay_fn",
           "stage_sharded", "sharded_throughput", "make_ring_attention",
           "make_sp_transformer", "make_ulysses_attention"]
