"""Pod-sharded ONLINE replay: the streaming plane over the device mesh.

A live feed hot enough to saturate one chip shards the same way the batch
replay does: each push's chunks spread over the mesh's data axis, every
device scans its chunk with the shared chunk step, and the per-push state
delta psum-merges over ICI before folding into the running ring
(anomod.parallel.replay.make_sharded_replay_fn is reused wholesale — one
definition of the sharded aggregation for batch and stream).

:class:`ShardedStreamReplay` duck-types :class:`anomod.stream.StreamReplay`
(push / agg_plane / ring roll / compile bookkeeping), so
``OnlineDetector(..., replay=...)`` runs the full alerting stack over the
mesh unchanged.  Pushes are processed in fixed groups of ``n_dev`` chunks
(the last group padded with dead chunks), so the shard_map compiles ONCE
regardless of micro-batch size.

The plane is id-space agnostic — it scans whatever ``batch.service``
holds against ``cfg.sw`` — so EDGE ATTRIBUTION runs over the mesh too:
construct it on the combined id space
(``ShardedStreamReplay(stream.edge_combined_cfg(cfg, S), t0, mesh)``)
and pass ``edge_attribution=True``; the detector's doubled span rows
(node id + caller-keyed edge slot) shard across devices like any other
rows, and the alert stream matches the single-chip edge detector
(parity-tested on the 8-device CPU mesh).
"""

from __future__ import annotations

import time

import numpy as np

from anomod.parallel.replay import make_sharded_replay_fn
from anomod.replay import N_FEATS, ReplayConfig, ReplayState, stage_columns
from anomod.schemas import SpanBatch
from anomod.stream import plane_view, roll_ring_state


class ShardedStreamReplay:
    """Mesh-sharded drop-in for the single-chip StreamReplay."""

    def __init__(self, cfg: ReplayConfig, t0_us: int, mesh,
                 axis: str = "data"):
        import jax.numpy as jnp

        self.cfg = cfg
        self.t0_us = int(t0_us)
        self.window_offset = 0
        self.n_spans = 0
        self.mesh = mesh
        self.axis = axis
        self.n_dev = int(mesh.shape[axis])
        self._fn = make_sharded_replay_fn(cfg, mesh, axis=axis)
        self.state = ReplayState(
            agg=jnp.zeros((cfg.sw, N_FEATS), jnp.float32),
            hist=jnp.zeros((cfg.sw, cfg.n_hist_buckets), jnp.float32))
        self.compile_s = 0.0
        self._warmed = False

    # -- ring maintenance (the one shared definition) ---------------------

    def _roll(self, k: int) -> None:
        self.state = roll_ring_state(self.state, self.cfg, k)
        self.t0_us += k * self.cfg.window_us
        self.window_offset += k

    # -- push -------------------------------------------------------------

    def _dead_chunk(self) -> dict:
        from anomod.replay import dead_chunk
        return {k: v[None] for k, v in dead_chunk(self.cfg, xp=np).items()}

    def _run_group(self, group: dict) -> ReplayState:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        sharding = NamedSharding(self.mesh, P(self.axis))
        dev = {k: jax.device_put(v, sharding) for k, v in group.items()}
        return self._fn(dev)

    def _warm(self) -> None:
        from anomod import obs
        t0 = time.perf_counter()
        dead = self._dead_chunk()
        group = {k: np.repeat(v, self.n_dev, axis=0)
                 for k, v in dead.items()}
        np.asarray(self._run_group(group).agg)     # compile barrier
        self.compile_s = time.perf_counter() - t0
        obs.counter("anomod_stream_compile_total", plane="sharded").inc()
        obs.counter("anomod_stream_compile_seconds_total",
                    plane="sharded").inc(self.compile_s)
        self._warmed = True

    def push(self, batch: SpanBatch) -> int:
        """Same contract as StreamReplay.push: fold, return the newest
        ABSOLUTE window binned (-1 for empty)."""
        import jax.numpy as jnp
        if batch.n_spans == 0:
            return -1
        if not self._warmed:
            self._warm()
        from anomod import obs
        t_push = time.perf_counter()
        w_need = int((int(batch.start_us.max()) - self.t0_us)
                     // self.cfg.window_us)
        if w_need > self.cfg.n_windows - 1:
            self._roll(w_need - (self.cfg.n_windows - 1))
            w_need = self.cfg.n_windows - 1
        chunks, n = stage_columns(batch, self.cfg, t0_us=self.t0_us)
        n_chunks = chunks["sid"].shape[0]
        dead = self._dead_chunk()
        for lo in range(0, n_chunks, self.n_dev):
            group = {k: v[lo:lo + self.n_dev] for k, v in chunks.items()}
            short = self.n_dev - group["sid"].shape[0]
            if short:
                group = {k: np.concatenate(
                    [v, np.repeat(dead[k], short, axis=0)])
                    for k, v in group.items()}
            delta = self._run_group(group)
            self.state = ReplayState(
                agg=self.state.agg + delta.agg,
                hist=self.state.hist + jnp.asarray(delta.hist))
        self.n_spans += n
        obs.histogram("anomod_stream_push_seconds",
                      plane="sharded").observe(
            time.perf_counter() - t_push)
        return self.window_offset + max(w_need, 0)

    def agg_plane(self) -> np.ndarray:
        return plane_view(self.state, self.cfg)
