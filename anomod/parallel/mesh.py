"""Mesh construction + data sharding helpers.

The reference's only "distributed" machinery is kubectl/HTTP fan-out and
thread pools (SURVEY.md §2.4).  Here distribution is first-class: a
``jax.sharding.Mesh`` over however many chips exist (one axis ``data`` for
stream sharding; model axes come with the GNN), XLA collectives over ICI/DCN.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def pvary_compat(x, axis_names: Sequence[str]):
    """Mark ``x`` device-varying over the named mesh axes (shard_map vma
    checking requires loop carries to match varying outputs).  Single home
    for the pcast/pvary API shim: ``jax.lax.pcast(..., to="varying")``
    replaced the deprecated ``pvary``.  No-op when already varying."""
    from jax import lax
    vma = getattr(getattr(x, "aval", None), "vma", frozenset())
    if all(a in vma for a in axis_names):
        return x
    if hasattr(lax, "pcast"):
        return lax.pcast(x, tuple(axis_names), to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, tuple(axis_names))
    return x  # pre-vma jax: nothing to annotate


def shard_map_compat(f, **kwargs):
    """``jax.shard_map`` across the API migration — single home for the
    shim: newer jax exports it at top level with ``check_vma``; older
    releases have ``jax.experimental.shard_map.shard_map`` with the same
    knob spelled ``check_rep``."""
    import jax
    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    if "check_vma" in kwargs:
        import inspect
        try:
            params = inspect.signature(sm).parameters
        except (TypeError, ValueError):
            params = {}
        if "check_vma" not in params and "check_rep" in params:
            kwargs["check_rep"] = kwargs.pop("check_vma")
    return sm(f, **kwargs)


def make_mesh(n_devices: Optional[int] = None, axis: str = "data"):
    """1-D device mesh over the first n devices (defaults to all).

    Requesting more devices than are attached is an error, not a silent
    shrink — a throughput record labeled "8 devices" must have run on 8.
    """
    import jax
    from jax.sharding import Mesh
    devs = jax.devices()
    if n_devices is not None:
        if not 0 < n_devices <= len(devs):
            raise ValueError(
                f"requested a {n_devices}-device mesh but "
                f"{len(devs)} device(s) are attached")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def shard_chunks(chunks: dict, n_shards: int, dead_sid: int) -> dict:
    """Split the leading (chunk) dim across shards: [N, C] -> [D, N/D, C].

    Pads the chunk count to a multiple of n_shards with dead chunks
    (sid = ``dead_sid``, valid = 0) so every shard gets identical shapes.
    ``dead_sid`` must be the config's padding id (``cfg.sw``) — inferring
    it from the data (the old ``sid.max()`` heuristic) silently picked a
    REAL segment whenever the corpus length was an exact chunk multiple,
    and the HLL plane then counted the fill rows' phantom trace id.
    """
    out = {}
    n_chunks = next(iter(chunks.values())).shape[0]
    pad = (-n_chunks) % n_shards
    for k, v in chunks.items():
        if pad:
            fill = np.zeros((pad,) + v.shape[1:], v.dtype)
            if k == "sid":
                fill[:] = dead_sid
            v = np.concatenate([v, fill], axis=0)
        out[k] = v.reshape(n_shards, -1, *v.shape[1:])
    return out
