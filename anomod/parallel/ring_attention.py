"""Ring attention — sequence-parallel exact attention over a mesh axis.

Long-context is first-class in this framework: a full experiment corpus is a
span sequence far larger than one chip's HBM wants to hold at attention
granularity.  Ring attention shards the sequence across the mesh's data axis
and rotates K/V blocks around the ring with ``jax.lax.ppermute`` (ICI
neighbor exchange — each step overlaps a block's worth of compute with a
block transfer), accumulating the exact softmax with the online
(max/denominator-carrying) recurrence.  After P steps every query block has
attended to every key block: numerically identical to full attention, with
per-chip memory O(L/P · L/P) instead of O(L²).

No reference counterpart (SURVEY.md §5: long-context/sequence parallelism
absent there); the design follows the public blockwise-attention recipe, on
XLA collectives instead of NCCL.
"""

from __future__ import annotations

import functools

import numpy as np


def full_attention(q, k, v):
    """Reference dense softmax attention.  [L, H, D] -> [L, H, D]."""
    import jax.numpy as jnp
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("qhd,khd->hqk", q, k) * scale
    p = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    p = p / p.sum(axis=-1, keepdims=True)
    return jnp.einsum("hqk,khd->qhd", p, v)


def ring_attention_local(q, k, v, axis_name: str):
    """Exact attention over the ring — call inside shard_map.

    Args are the *local* blocks [L/P, H, D]; the full sequence is the
    concatenation over the ``axis_name`` mesh axis.  Returns the local output
    block [L/P, H, D].
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = lax.psum(1, axis_name)            # ring size
    perm = [(i, (i + 1) % n) for i in range(n)]
    scale = 1.0 / np.sqrt(q.shape[-1])
    Lq, H, D = q.shape

    def block(q, kb, vb, num, den, m):
        """One online-softmax accumulation step against K/V block (kb, vb)."""
        scores = jnp.einsum("qhd,khd->qhk", q, kb) * scale   # [Lq, H, Lk]
        m_new = jnp.maximum(m, scores.max(axis=-1))          # [Lq, H]
        p = jnp.exp(scores - m_new[..., None])
        correction = jnp.exp(m - m_new)
        num = num * correction[..., None] + jnp.einsum("qhk,khd->qhd", p, vb)
        den = den * correction + p.sum(axis=-1)
        return num, den, m_new

    def body(_, carry):
        kb, vb, num, den, m = carry
        num, den, m = block(q, kb, vb, num, den, m)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return kb, vb, num, den, m

    def _varying(x):
        # fresh constants are unvarying over the mesh axis; the loop carry
        # must match the varying outputs (shard_map vma checking)
        from anomod.parallel.mesh import pvary_compat
        return pvary_compat(x, (axis_name,))

    num0 = jnp.zeros_like(q)
    den0 = _varying(jnp.zeros((Lq, H), q.dtype))
    m0 = _varying(jnp.full((Lq, H), -jnp.inf, q.dtype))
    _, _, num, den, _ = lax.fori_loop(0, n, body, (k, v, num0, den0, m0))
    return num / den[..., None]


def make_sharded_attention(local_fn, mesh, axis: str = "data"):
    """Shared jit/shard_map wrapper for every sequence-parallel attention
    plane: q/k/v [L, H, D] sharded on L over ``axis``, output sharded the
    same way, ``local_fn(q, k, v, axis_name)`` runs on the local blocks.
    One copy so a shard_map/sharding API migration lands everywhere."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P(axis, None, None)

    @functools.partial(jax.jit, out_shardings=NamedSharding(mesh, spec))
    def attend(q, k, v):
        from anomod.parallel.mesh import shard_map_compat
        fn = shard_map_compat(
            functools.partial(local_fn, axis_name=axis),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        return fn(q, k, v)

    return attend


def make_ring_attention(mesh, axis: str = "data"):
    """Jitted global-array form: q/k/v [L, H, D] sharded on L over ``axis``.

    L must divide evenly by the mesh axis size (pad upstream; static shapes
    keep XLA on one compiled program).
    """
    return make_sharded_attention(ring_attention_local, mesh, axis)
