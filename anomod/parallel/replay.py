"""Pod-sharded span replay: shard_map over the data axis, psum state merge.

Each chip scans its shard of the span stream with the single-chip replay
kernel (anomod.replay); the tiny per-chip state ([S*W, F] aggregates +
[S*W, H] histograms) is ``psum``-merged over ICI at the end — the TPU-native
version of the reference's per-worker collection + host-side merge
(trace_collector.py:519-547's ThreadPoolExecutor + list append).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from anomod.replay import (N_FEATS, ReplayConfig, ReplayState, ThroughputResult)
from anomod.schemas import SpanBatch


def make_sharded_replay_fn(cfg: ReplayConfig, mesh, axis: str = "data"):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    SW, H = cfg.sw, cfg.n_hist_buckets

    def shard_body(chunks):  # runs per-device on its [N/D, C] shard
        # the carry is device-varying from step 1 on, so the initial zeros
        # must be cast to varying over the data axis too
        from anomod.parallel.mesh import pvary_compat
        state = ReplayState(
            agg=pvary_compat(jnp.zeros((SW, N_FEATS), jnp.float32), (axis,)),
            hist=pvary_compat(jnp.zeros((SW, H), jnp.float32), (axis,)))

        def step(state, chunk):
            sid = chunk["sid"]
            # same split-precision pattern as the single-chip kernel
            onehot16 = jax.nn.one_hot(sid, SW + 1, dtype=jnp.bfloat16)
            exact = jnp.stack([chunk["valid"], chunk["err"], chunk["s5"]],
                              axis=1).astype(jnp.bfloat16)
            durs = jnp.stack([chunk["dur_raw"], chunk["dur"],
                              chunk["dur"] * chunk["dur"]], axis=1)
            a_exact = jnp.matmul(onehot16.T, exact,
                                 preferred_element_type=jnp.float32)[:SW]
            a_dur = jnp.matmul(onehot16.astype(jnp.float32).T, durs,
                               precision=jax.lax.Precision.HIGHEST)[:SW]
            agg = state.agg + jnp.concatenate([a_exact, a_dur], axis=1)
            bucket = jnp.clip(chunk["dur"].astype(jnp.int32), 0, H - 1)
            bucket_oh = (jax.nn.one_hot(bucket, H, dtype=jnp.bfloat16)
                         * chunk["valid"][:, None].astype(jnp.bfloat16))
            hist = state.hist + jnp.matmul(
                onehot16.T, bucket_oh, preferred_element_type=jnp.float32)[:SW]
            return ReplayState(agg=agg, hist=hist), None

        state, _ = jax.lax.scan(step, state, chunks)
        # merge shard states over ICI
        return ReplayState(agg=jax.lax.psum(state.agg, axis),
                           hist=jax.lax.psum(state.hist, axis))

    from jax import shard_map
    fn = shard_map(shard_body, mesh=mesh,
                   in_specs=({k: P(axis) for k in
                              ("sid", "dur", "dur_raw", "err", "s5", "valid",
                               "tid")},),
                   out_specs=ReplayState(agg=P(), hist=P()))
    return jax.jit(fn)


def sharded_throughput(batch: SpanBatch, mesh,
                       cfg: Optional[ReplayConfig] = None,
                       repeats: int = 3) -> ThroughputResult:
    """Stage, shard, compile, and time the multi-chip replay."""
    import jax
    from anomod.replay import stage_columns
    from anomod.parallel.mesh import shard_chunks

    cfg = cfg or ReplayConfig(n_services=len(batch.services))
    n_dev = mesh.devices.size
    chunks_np, n = stage_columns(batch, cfg)
    sharded = shard_chunks(chunks_np, n_dev)
    # flatten back to [N_total, C] with device-major order for sharding
    flat = {k: v.reshape(-1, v.shape[-1]) for k, v in sharded.items()}
    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(mesh, P("data"))
    dev_chunks = {k: jax.device_put(v, sharding) for k, v in flat.items()}
    fn = make_sharded_replay_fn(cfg, mesh)
    t0 = time.perf_counter()
    out = fn(dev_chunks)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn(dev_chunks)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return ThroughputResult(n_spans=n, wall_s=best,
                            spans_per_sec=n / best, compile_s=compile_s)
