"""Pod-sharded span replay: shard_map over the data axis, psum state merge.

Each chip scans its shard of the span stream with the single-chip replay
kernel (anomod.replay); the tiny per-chip state ([S*W, F] aggregates +
[S*W, H] histograms) is ``psum``-merged over ICI at the end — the TPU-native
version of the reference's per-worker collection + host-side merge
(trace_collector.py:519-547's ThreadPoolExecutor + list append).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from anomod.replay import (N_FEATS, ReplayConfig, ReplayState,
                           ThroughputResult, make_chunk_step, pallas_block)
from anomod.schemas import SpanBatch


def make_sharded_replay_fn(cfg: ReplayConfig, mesh, axis: str = "data",
                           kernel: str = "xla", with_hll: bool = False,
                           merge: str = "replicated"):
    """Pod-sharded replay over the mesh's data axis.

    ``kernel`` selects the per-shard aggregation: "xla" scans chunks with
    the shared :func:`anomod.replay.make_chunk_step` (identical
    split-precision scheme to the single-chip path), "pallas" flattens the
    shard and runs the fused kernel (anomod.ops.pallas_replay — the
    single-chip fast path, composed with shard_map + psum; interpret mode
    off-TPU).

    ``with_hll`` adds the per-service distinct-trace HLL plane: each shard
    scatter-maxes its trace ids into [n_services, 2^p] registers, merged
    over ICI with one ``pmax`` (register-exact — the sketch-state
    allreduce BASELINE.json mandates, in the production replay path).

    ``merge`` selects the agg/hist reduction: "replicated" (one ``psum``,
    every device holds the full merged state) or "scattered"
    (``psum_scatter``: half the ICI traffic, each device keeps only its
    SW/D slice of the segment axis — the pod-scale mode for aggregate
    states too large to replicate; requires SW % n_devices == 0).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    if kernel not in ("xla", "pallas"):
        raise ValueError(f"unknown replay kernel {kernel!r}")
    if merge not in ("replicated", "scattered"):
        raise ValueError(f"unknown merge mode {merge!r}")
    SW, H = cfg.sw, cfg.n_hist_buckets
    n_dev = int(mesh.shape[axis])
    if merge == "scattered" and SW % n_dev != 0:
        raise ValueError(
            f"merge='scattered' needs SW ({SW}) divisible by the "
            f"{axis} axis size ({n_dev})")
    if kernel == "pallas":
        from anomod.ops.pallas_replay import make_pallas_replay_fn
        interpret = mesh.devices.ravel()[0].platform != "tpu"
        pfn = make_pallas_replay_fn(cfg.sw, cfg.n_hist_buckets,
                                    block=pallas_block(cfg.chunk_size),
                                    interpret=interpret)

    def _shard_hll(chunks):
        # whole-shard register build: one scatter-max over the flat shard
        # through the shared plane definition (anomod.replay)
        from anomod.replay import hll_scatter_update
        regs = jnp.zeros((cfg.n_services, cfg.hll_m), jnp.int32)
        return hll_scatter_update(regs, chunks["sid"].reshape(-1),
                                  chunks["tid"].reshape(-1), cfg)

    def shard_body(chunks):  # runs per-device on its [N/D, C] shard
        if kernel == "pallas":
            from anomod.replay import stage_pallas_planes
            sid, planes = stage_pallas_planes(chunks, xp=jnp)
            acc = pfn(sid, planes)
            state = ReplayState(agg=acc[:, :N_FEATS], hist=acc[:, N_FEATS:])
        else:
            # the carry is device-varying from step 1 on, so the initial
            # zeros must be cast to varying over the data axis too
            from anomod.parallel.mesh import pvary_compat
            state = ReplayState(
                agg=pvary_compat(jnp.zeros((SW, N_FEATS), jnp.float32),
                                 (axis,)),
                hist=pvary_compat(jnp.zeros((SW, H), jnp.float32), (axis,)))
            state, _ = jax.lax.scan(make_chunk_step(cfg), state, chunks)
        hll = None
        if with_hll:
            from anomod.parallel.collectives import pmax_merge_hll
            hll = pmax_merge_hll(_shard_hll(chunks), axis)
        # merge shard states over ICI
        if merge == "scattered":
            from anomod.parallel.collectives import reduce_scatter_state
            return ReplayState(agg=reduce_scatter_state(state.agg, axis),
                               hist=reduce_scatter_state(state.hist, axis),
                               hll=hll)
        return ReplayState(agg=jax.lax.psum(state.agg, axis),
                           hist=jax.lax.psum(state.hist, axis),
                           hll=hll)

    from anomod.parallel.mesh import shard_map_compat
    # the pallas kernel's internal constants (iota tiles, zero-init) carry
    # no mesh varying-axes metadata, so shard_map's static vma checker
    # rejects the mix unconditionally (interpret or compiled, with or
    # without a declared output vma); JAX's documented workaround is
    # check_vma=False — psum merge semantics are unchanged, only the
    # static checker is off for this variant
    kwargs = {"check_vma": False} if kernel == "pallas" else {}
    state_spec = P(axis) if merge == "scattered" else P()
    fn = shard_map_compat(
        shard_body, mesh=mesh,
        in_specs=({k: P(axis) for k in
                   ("sid", "dur", "dur_raw", "err", "s5", "valid",
                    "tid")},),
        out_specs=ReplayState(agg=state_spec, hist=state_spec,
                              hll=P() if with_hll else None),
        **kwargs)
    return jax.jit(fn)


def stage_sharded(batch: SpanBatch, mesh, cfg: ReplayConfig):
    """Stage + device-put the span columns sharded over the mesh's data
    axis; returns (dev_chunks, n_real_spans)."""
    import jax
    from anomod.replay import stage_columns
    from anomod.parallel.mesh import shard_chunks

    n_dev = mesh.devices.size
    chunks_np, n = stage_columns(batch, cfg)
    sharded = shard_chunks(chunks_np, n_dev, dead_sid=cfg.sw)
    # flatten back to [N_total, C] with device-major order for sharding
    flat = {k: v.reshape(-1, v.shape[-1]) for k, v in sharded.items()}
    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(mesh, P("data"))
    return {k: jax.device_put(v, sharding) for k, v in flat.items()}, n


def sharded_throughput(batch: SpanBatch, mesh,
                       cfg: Optional[ReplayConfig] = None,
                       repeats: int = 3,
                       kernel: str = "xla") -> ThroughputResult:
    """Stage, shard, compile, and time the multi-chip replay."""
    import jax

    cfg = cfg or ReplayConfig(n_services=len(batch.services))
    dev_chunks, n = stage_sharded(batch, mesh, cfg)
    fn = make_sharded_replay_fn(cfg, mesh, kernel=kernel)
    t0 = time.perf_counter()
    out = fn(dev_chunks)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn(dev_chunks)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    # same wall_s contract as the single-chip path: median of the raw
    # per-repeat walls, with the full trail on raw_wall_s
    wall = sorted(times)[len(times) // 2]
    return ThroughputResult(n_spans=n, wall_s=wall,
                            spans_per_sec=n / wall, compile_s=compile_s,
                            kernel=kernel, raw_wall_s=tuple(times))
