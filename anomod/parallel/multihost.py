"""Multi-host (pod-scale) mesh construction: ICI within a slice, DCN across.

Single-host meshes come from ``make_mesh``/``make_mesh2d``; at pod scale the
recipe is: ``jax.distributed.initialize`` on every host, then a hybrid mesh
whose inner axes map to ICI (fast, within-slice) and outer axis to DCN
(across hosts).  Shardings are unchanged — the same ``PartitionSpec``s used
on the CPU test mesh drive ICI collectives within a slice and DCN transfers
across, which is the whole point of keeping the replay/train paths expressed
as shardings + psum instead of explicit sends.

The multi-process path is exercised for real by ``tests/test_multihost.py``,
which launches two coordinator-connected CPU processes (4 virtual devices
each), builds the hybrid (dcn=2, data=4) mesh, and runs psum + HLL
register-merge collectives AND a dp-sharded GCN training step (each
process stages only its half of the batch; the gradient psum crosses the
process boundary; both replicas must agree bit-for-bit post-update)
across the process boundary.
"""

from __future__ import annotations

from typing import Optional, Sequence


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """jax.distributed.initialize wrapper; no-op for single-process runs."""
    import jax
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def make_hybrid_mesh(ici_axes: Sequence[int] = (),
                     axis_names: Sequence[str] = ("dcn", "data")):
    """(dcn, data) mesh: outer axis = hosts (DCN), inner = local chips (ICI).

    With one process this degenerates to (1, n_local_chips) — same program,
    same shardings, so code tested on the CPU mesh runs unchanged at pod
    scale.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    n_hosts = jax.process_count()
    local = jax.local_device_count()
    devs = np.asarray(jax.devices()).reshape(n_hosts, local)
    return Mesh(devs, tuple(axis_names))


def dcn_data_parallel_spec(mesh):
    """PartitionSpec sharding the batch/stream axis over both dcn and data —
    gradient/state psums then reduce over ICI first, DCN once per host."""
    from jax.sharding import PartitionSpec as P
    return P(tuple(mesh.axis_names))


def process_local_array(mesh, spec, local):
    """Assemble a global sharded array from this process's local shard
    (each host stages only its slice of the corpus into its own devices;
    the mesh makes it one logical array)."""
    import jax
    from jax.sharding import NamedSharding
    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), local)


def replicated_value(out):
    """Host value of a fully-replicated global array.  Multi-process global
    arrays are not fully addressable, so plain ``np.asarray`` raises; every
    process holds a replica, so the first addressable shard is the value."""
    import numpy as np
    return np.asarray(out.addressable_data(0))
