"""Ulysses-style all-to-all sequence parallelism — the second long-context
plane, complementing ring attention.

Where ring attention keeps the sequence sharded and rotates K/V blocks
around the ICI ring in P ``ppermute`` steps (compute/transfer overlap,
per-chip memory O((L/P)²)), the all-to-all layout swap redistributes
activations exactly twice per attention call:

  [L/P, H, D]  --all_to_all-->  [L, H/P, D]   (heads sharded, sequence whole)
       ... dense per-head attention locally ...
  [L, H/P, D]  --all_to_all-->  [L/P, H, D]

Each device then runs *unmodified* dense attention over the full sequence
for its head subset — trivially exact, two collective hops regardless of
ring size, but it requires n_heads % P == 0 and holds full-L scores
locally, so it suits moderate L with many heads while the ring suits
extreme L.  Both planes ride the same (data,) mesh axis and compose with
the dp/tp/pp/ep shardings in anomod.parallel.train.

No reference counterpart (SURVEY.md §5: long-context parallelism absent
there); the layout-swap recipe is the public DeepSpeed-Ulysses pattern on
XLA's ``all_to_all`` instead of NCCL.
"""

from __future__ import annotations


def ulysses_attention_local(q, k, v, axis_name: str):
    """Exact attention via head-scatter/sequence-gather — call inside
    shard_map.  Args are local sequence blocks [L/P, H, D]; requires
    H % P == 0.  Returns the local output block [L/P, H, D]."""
    from jax import lax

    from anomod.parallel.ring_attention import full_attention

    n = lax.psum(1, axis_name)
    if q.shape[1] % n:
        raise ValueError(
            f"ulysses attention needs n_heads divisible by the mesh axis: "
            f"{q.shape[1]} heads over {n} devices")

    def seq_gather(x):      # [L/P, H, D] -> [L, H/P, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=0,
                              tiled=True)

    out = full_attention(seq_gather(q), seq_gather(k), seq_gather(v))
    # head-gather / sequence-scatter back to the resident layout
    return lax.all_to_all(out, axis_name, split_axis=0, concat_axis=1,
                          tiled=True)


def make_ulysses_attention(mesh, axis: str = "data"):
    """Jitted global-array form: q/k/v [L, H, D] sharded on L over ``axis``.

    L and H must both divide by the mesh axis size (static shapes; pad
    upstream).  Output sharding matches the inputs, so ring and ulysses
    are drop-in interchangeable per layer.
    """
    from anomod.parallel.ring_attention import make_sharded_attention
    return make_sharded_attention(ulysses_attention_local, mesh, axis)
