"""Sequence-parallel TraceTransformer forward: the RCA scorer's attention
core swapped for a mesh-distributed plane, same params.

The single-chip TraceTransformer already computes its attention through
:func:`anomod.parallel.ring_attention.full_attention`; this builder
instantiates the SAME module with that core replaced by a ring
(ppermute K/V rotation) or Ulysses (all_to_all head-scatter) plane over a
1-D mesh — the long-context path for experiments whose S·W token sequence
outgrows one chip.  The param tree is identical, so params trained
single-chip score sequence-parallel unchanged (and vice versa).

Constraints come from the planes: the mesh size must divide the S·W token
count; Ulysses additionally needs n_heads % n_devices == 0.
"""

from __future__ import annotations


def make_sp_transformer(mesh, model=None, plane: str = "ring"):
    """Returns ``(sp_model, apply_fn)`` where ``apply_fn(params, x_swf,
    adj_counts)`` runs the sequence-parallel forward over ``mesh``.

    ``model`` is the single-chip TraceTransformer whose hyperparameters
    (and trained params) to reuse; defaults to the zoo configuration.
    """
    import jax

    from anomod.models.transformer import TraceTransformer
    from anomod.parallel.ring_attention import make_ring_attention
    from anomod.parallel.ulysses import make_ulysses_attention

    if plane == "ring":
        attn = make_ring_attention(mesh)
    elif plane == "ulysses":
        attn = make_ulysses_attention(mesh)
    else:
        raise ValueError(f"unknown sequence-parallel plane {plane!r}")
    model = model or TraceTransformer()
    sp_model = model.clone(attention_fn=attn)
    return sp_model, jax.jit(sp_model.apply)
