"""Pipeline parallelism (pp): stage-sharded transformer over a ``pipe`` axis.

The TraceTransformer block stack is partitioned into one stage per device
along a 1-D ``pipe`` mesh: stage parameters are stacked with a leading
``[n_stages, layers_per_stage, ...]`` axis and sharded ``P('pipe')``, so each
device holds only its own layers' weights.  Microbatches stream through the
ring GPipe-style: every tick each device applies its stage to its activation
buffer and ``ppermute``s the result to the next device, while stage 0 feeds
the next microbatch and the last stage banks finished outputs.  The tick loop
is a ``lax.scan``, so reverse-mode AD derives the backward pipeline schedule
automatically (``ppermute`` transposes to the reverse rotation) — no
hand-written backward pass.

Embedding and head stay replicated outside the pipelined region (they are a
tiny fraction of the FLOPs); the block stack — where a transformer's memory
actually lives — is what pp exists to partition.

No reference counterpart (the reference has no distributed compute,
SURVEY.md §2.4); this is the pp plane of the tp/pp/dp/sp/ep story, next to
:mod:`anomod.parallel.train` (dp×tp), :mod:`anomod.parallel.replay`
(stream/dp), and :mod:`anomod.parallel.ring_attention` (sp).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from anomod.models.transformer import AttentionBlock, ScoreHead, TokenEmbed

AXIS = "pipe"


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    n_microbatches: int = 2
    layers_per_stage: int = 1
    d_model: int = 32
    n_heads: int = 2
    mlp_hidden: int = 64
    hidden: int = 32


def make_pipe_mesh(n_devices=None):
    from anomod.parallel.mesh import make_mesh
    return make_mesh(n_devices, axis=AXIS)


def _modules(cfg: PipelineConfig, S: int, W: int):
    return (TokenEmbed(cfg.d_model),
            AttentionBlock(cfg.d_model, cfg.n_heads, cfg.mlp_hidden),
            ScoreHead(S, W, cfg.hidden))


def pipeline_shardings(mesh, params):
    """Stage stack sharded over ``pipe``; embed/head replicated."""
    rep = NamedSharding(mesh, P())
    stage = NamedSharding(mesh, P(AXIS))
    tree = jax.tree_util.tree_map
    return {"embed": tree(lambda _: rep, params["embed"]),
            "stages": tree(lambda _: stage, params["stages"]),
            "head": tree(lambda _: rep, params["head"])}


def init_pipeline(rng, mesh, cfg: PipelineConfig, S: int, W: int, F: int):
    """Init + place params: ``{embed, stages[P, lps, ...], head}``."""
    n_stages = mesh.shape[AXIS]
    n_layers = n_stages * cfg.layers_per_stage
    embed, block, head = _modules(cfg, S, W)
    r_embed, r_blocks, r_head = jax.random.split(rng, 3)
    x0 = jnp.zeros((S, W, F), jnp.float32)
    p_embed = embed.init(r_embed, x0)
    seq0 = embed.apply(p_embed, x0)
    p_blocks = jax.vmap(lambda r: block.init(r, seq0))(
        jax.random.split(r_blocks, n_layers))
    p_stages = jax.tree_util.tree_map(
        lambda a: a.reshape(n_stages, cfg.layers_per_stage, *a.shape[1:]),
        p_blocks)
    p_head = head.init(r_head, seq0, jnp.zeros((S, S), jnp.float32))
    params = {"embed": p_embed, "stages": p_stages, "head": p_head}
    return jax.device_put(params, pipeline_shardings(mesh, params))


def make_pipeline_forward(mesh, cfg: PipelineConfig, S: int, W: int):
    """Returns ``(forward, reference_forward)``.

    Both map ``(params, x [B, S, W, F], adj [B, S, S]) -> [B, S]`` scores;
    ``forward`` runs the block stack through the stage ring,
    ``reference_forward`` applies the same stacked layers sequentially
    (the single-program oracle the pipeline must match exactly).
    """
    n_stages = mesh.shape[AXIS]
    embed, block, head = _modules(cfg, S, W)
    L, M = S * W, cfg.n_microbatches

    def stage_fwd(stage_params, x):          # [lps, ...] params, [mb, L, d]
        def body(h, p):
            return jax.vmap(lambda s: block.apply(p, s))(h), None
        h, _ = lax.scan(body, x, stage_params)
        return h

    def _varying(x):
        from anomod.parallel.mesh import pvary_compat
        return pvary_compat(x, (AXIS,))

    def pipeline_local(stage_params, micro):
        # stage_params leading [1, lps, ...] (my shard); micro [M, mb, L, d]
        params = jax.tree_util.tree_map(lambda a: a[0], stage_params)
        idx = lax.axis_index(AXIS)
        T = M + n_stages - 1
        micro = _varying(micro)
        state0 = _varying(jnp.zeros(micro.shape[1:], micro.dtype))
        out0 = _varying(jnp.zeros_like(micro))
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            state, out = carry
            # stage 0 ingests microbatch t (clamped in the drain phase,
            # whose outputs never get banked); later stages consume what
            # their predecessor ppermuted over last tick
            inp = jnp.where(idx == 0, micro[jnp.minimum(t, M - 1)], state)
            y = stage_fwd(params, inp)
            j = t - (n_stages - 1)           # microbatch finishing this tick
            jc = jnp.clip(j, 0, M - 1)
            bank = (idx == n_stages - 1) & (j >= 0)
            out = out.at[jc].set(jnp.where(bank, y, out[jc]))
            state = lax.ppermute(y, AXIS, perm)
            return (state, out), None

        (_, out), _ = lax.scan(tick, (state0, out0), jnp.arange(T))
        # finished outputs live on the last stage; psum broadcasts them
        mask = (idx == n_stages - 1).astype(micro.dtype)
        return lax.psum(out * mask, AXIS)

    from anomod.parallel.mesh import shard_map_compat
    pipe = shard_map_compat(pipeline_local, mesh=mesh,
                            in_specs=(P(AXIS), P()), out_specs=P())

    def _embed_all(params, x):
        return jax.vmap(lambda xi: embed.apply(params["embed"], xi))(x)

    def _head_all(params, seq, adj):
        return jax.vmap(lambda s, a: head.apply(params["head"], s, a))(
            seq, adj)

    def forward(params, x, adj):
        seq = _embed_all(params, x)                      # [B, L, d]
        B = seq.shape[0]
        assert B % M == 0, f"batch {B} must divide into {M} microbatches"
        micro = seq.reshape(M, B // M, L, cfg.d_model)
        out = pipe(params["stages"], micro).reshape(B, L, cfg.d_model)
        return _head_all(params, out, adj)

    def reference_forward(params, x, adj):
        seq = _embed_all(params, x)
        flat = jax.tree_util.tree_map(
            lambda a: a.reshape(-1, *a.shape[2:]), params["stages"])
        return _head_all(params, stage_fwd(flat, seq), adj)

    return forward, reference_forward


def make_pipeline_train_step(mesh, cfg: PipelineConfig, sample_batch: dict,
                             lr: float = 1e-3):
    """(params, opt_state, step, put_batch) — pp train step on chaos labels.

    ``sample_batch``: stacked batch from :func:`anomod.rca._stack`; the
    fused (temporal + static) features feed the pipelined transformer, loss
    matches the RCA harness (CE over culprit services + detection BCE).
    """
    import optax

    from anomod.rca import rca_loss

    S, W = sample_batch["x_t"].shape[1:3]
    F = sample_batch["x_t"].shape[3] + sample_batch["x"].shape[2]
    forward, _ = make_pipeline_forward(mesh, cfg, S, W)
    params = init_pipeline(jax.random.PRNGKey(0), mesh, cfg, S, W, F)
    tx = optax.adamw(lr)
    opt_state = tx.init(params)

    def _fused(batch):
        return jnp.concatenate(
            [batch["x_t"],
             jnp.repeat(batch["x"][:, :, None, :], W, axis=2)], axis=-1)

    def loss_fn(params, batch):
        scores = forward(params, _fused(batch), batch["adj"])
        return rca_loss(scores, batch)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    rep = NamedSharding(mesh, P())

    def put_batch(batch_np):
        return {k: jax.device_put(jnp.asarray(v), rep)
                for k, v in batch_np.items()}

    return params, opt_state, step, put_batch
