"""Distributed GNN training step: dp × tp shardings over a 2-D device mesh.

The pjit recipe (pick a mesh → annotate shardings → let XLA insert the
collectives): batch axis sharded over ``data``, Dense kernels whose output
dim divides the ``model`` axis sharded column-wise (tensor parallelism —
all-gathers/reduce-scatters ride ICI), everything else replicated.  Gradient
psums over ``data`` are inserted by XLA from the sharding annotations.

This is the training-step path ``__graft_entry__.dryrun_multichip`` compiles
over N virtual devices.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def make_mesh2d(n_devices: int, model_axis: int = 2):
    """(data, model) mesh; model axis shrinks to 1 if it doesn't divide."""
    import jax
    from jax.sharding import Mesh
    devs = np.array(jax.devices()[:n_devices])
    model = model_axis if n_devices % model_axis == 0 and n_devices > 1 else 1
    return Mesh(devs.reshape(n_devices // model, model), ("data", "model"))


def _param_spec(path, arr, mesh):
    """Sharding rule per param leaf, keyed on its tree path.

    MoE expert params (``MoEBlock`` w1/b1/w2/b2, all with a leading ``[E]``
    axis; the router stays dense) shard their expert axis over ``model`` —
    expert parallelism: each device computes only its own experts and XLA
    psums the gated combine.  Other 2-D kernels whose output dim divides the
    model axis are column-sharded (tensor parallelism).  Everything else is
    replicated."""
    from jax.sharding import PartitionSpec as P
    m = mesh.shape.get("model", 1)  # dp-only meshes (e.g. hybrid (dcn, data))
    if m > 1 and hasattr(arr, "ndim"):
        keys = "/".join(str(getattr(k, "key", k)) for k in path)
        in_expert = "MoEBlock" in keys and "router" not in keys
        if in_expert and arr.ndim >= 2 and arr.shape[0] % m == 0:
            return P("model", *([None] * (arr.ndim - 1)))
        if arr.ndim == 2 and arr.shape[1] % m == 0:
            return P(None, "model")
    return P()


def make_distributed_train_step(model_name: str, sample_batch: dict, mesh,
                                stage: str = "global"):
    """Returns (params, opt_state, step_fn, put_batch) with sharded
    placements.

    ``sample_batch``: stacked numpy batch from anomod.rca._stack; its leading
    (experiment) axis is the dp axis and must divide the product of the
    mesh's dp axes (every axis except ``model`` — a single-host
    ``(data, model)`` mesh and the multi-host hybrid ``(dcn, data)`` mesh
    both work; params shard over ``model`` only when that axis exists).

    ``stage`` selects how ``put_batch`` places data: "global" (every
    process passes the full global batch) or "process-local" (each process
    passes only ITS rows of the dp axis — the multi-host staging pattern,
    via ``jax.make_array_from_process_local_data``).
    """
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if stage not in ("global", "process-local"):
        raise ValueError(f"unknown staging mode {stage!r}")

    from anomod.rca import _apply_model, init_params, make_model, rca_loss

    model = make_model(model_name)
    sample0 = {k: v[0] for k, v in sample_batch.items()}
    params = init_params(model_name, model, sample0, jax.random.PRNGKey(0))

    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)

    param_shardings = jax.tree_util.tree_map_with_path(
        lambda p, a: NamedSharding(mesh, _param_spec(p, a, mesh)), params)
    opt_shardings = jax.tree_util.tree_map_with_path(
        lambda p, a: NamedSharding(mesh, _param_spec(p, a, mesh)), opt_state)
    dp_axes = tuple(a for a in mesh.axis_names if a != "model")
    batch_sharding = {k: NamedSharding(mesh, P(dp_axes))
                      for k in sample_batch}

    params = jax.device_put(params, param_shardings)
    opt_state = jax.device_put(opt_state, opt_shardings)

    def loss_fn(params, batch):
        scores = _apply_model(model_name, model, params, batch)
        return rca_loss(scores, batch)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def put_batch(batch_np: dict):
        if stage == "process-local":
            return {k: jax.make_array_from_process_local_data(
                        batch_sharding[k], np.asarray(v))
                    for k, v in batch_np.items()}
        return {k: jax.device_put(jnp.asarray(v), batch_sharding[k])
                for k, v in batch_np.items()}

    return params, opt_state, step, put_batch
