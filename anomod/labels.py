"""Fault taxonomy + ground-truth labels for both testbeds.

Sources (reference, read-only):
  - SN experiment menu: automated_multimodal_collection.sh:904-916
    (12 anomalies + Normal_Baseline; format ``type:Name``).
  - TT experiment menu: run_all_experiments.sh:661-672
    (format ``name:chaos_type:display``); Normal_case via run_normal_case:437.
  - TT chaos metadata labels (anomaly_level / anomaly_type / target_service):
    chaos-experiments/*.yaml, e.g. Lv_P_CPU_preserve.yaml:6-11.
  - TT JVM (code-level) faults: run_experiment.sh:293-351 — ChaosBlade
    container-jvm against ts-security-service / ts-order-service /
    ts-travel-service.
  - Taxonomy table: chaos-experiments/README.md:23-37.

Four anomaly levels: performance / service / database / code, plus "normal".
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

LEVELS = ("normal", "performance", "service", "database", "code")


@dataclasses.dataclass(frozen=True)
class FaultLabel:
    experiment: str          # canonical experiment base name (no timestamp)
    testbed: str             # "SN" | "TT"
    anomaly_level: str       # one of LEVELS
    anomaly_type: str        # e.g. "cpu_contention"
    target_service: str      # culprit service name ("" for normal/host-level)
    chaos_tool: str          # "chaosblade" | "chaosmesh" | "none"

    @property
    def is_anomaly(self) -> bool:
        return self.anomaly_level != "normal"


# --- SN: 12 anomalies + baseline (ChaosBlade on a single Docker host).
# Host-level performance faults have no single culprit *service*; the
# reference's sanity checks look at host metrics instead
# (SN_collection-scripts/README.md:106).  We record the stressed component.
SN_LABELS: Tuple[FaultLabel, ...] = (
    FaultLabel("Normal_Baseline", "SN", "normal", "baseline", "", "none"),
    FaultLabel("Perf_CPU_Contention", "SN", "performance", "cpu_contention", "", "chaosblade"),
    FaultLabel("Perf_Network_Loss", "SN", "performance", "network_loss", "", "chaosblade"),
    FaultLabel("Perf_Disk_IO_Stress", "SN", "performance", "disk_io_stress", "", "chaosblade"),
    FaultLabel("Svc_Kill_UserTimeline", "SN", "service", "kill_service_instance",
               "user-timeline-service", "chaosblade"),
    FaultLabel("Svc_Kill_Media", "SN", "service", "kill_service_instance",
               "media-service", "chaosblade"),
    FaultLabel("Svc_Kill_SocialGraph", "SN", "service", "kill_service_instance",
               "social-graph-service", "chaosblade"),
    FaultLabel("DB_Redis_CacheLimit_HomeTimeline", "SN", "database", "cache_limit",
               "home-timeline-service", "chaosblade"),
    FaultLabel("DB_Redis_CacheLimit_UserTimeline", "SN", "database", "cache_limit",
               "user-timeline-service", "chaosblade"),
    FaultLabel("DB_Redis_CacheLimit_SocialGraph", "SN", "database", "cache_limit",
               "social-graph-service", "chaosblade"),
    FaultLabel("Code_Stop_UserService", "SN", "code", "process_stop",
               "user-service", "chaosblade"),
    FaultLabel("Code_Stop_TextService", "SN", "code", "process_stop",
               "text-service", "chaosblade"),
    FaultLabel("Code_Stop_MediaService", "SN", "code", "process_stop",
               "media-service", "chaosblade"),
)

# --- TT: 12 anomalies + Normal_case (Chaos Mesh CRDs + ChaosBlade JVM).
TT_LABELS: Tuple[FaultLabel, ...] = (
    FaultLabel("Normal_case", "TT", "normal", "baseline", "", "none"),
    FaultLabel("Lv_P_CPU_preserve", "TT", "performance", "cpu_contention",
               "ts-preserve-service", "chaosmesh"),
    FaultLabel("Lv_P_DISKIO_preserve", "TT", "performance", "disk_io_stress",
               "ts-preserve-service", "chaosmesh"),
    FaultLabel("Lv_P_NETLOSS_preserve", "TT", "performance", "network_loss",
               "ts-preserve-service", "chaosmesh"),
    FaultLabel("Lv_S_DNSFAIL_preserve_no_order", "TT", "service", "dns_failure",
               "ts-preserve-service", "chaosmesh"),
    FaultLabel("Lv_S_HTTPABORT_preserve", "TT", "service", "http_abort",
               "ts-preserve-service", "chaosmesh"),
    FaultLabel("Lv_S_KILLPOD_preserve", "TT", "service", "kill_service_instance",
               "ts-preserve-service", "chaosmesh"),
    FaultLabel("Lv_D_cachelimit", "TT", "database", "cache_limit",
               "ts-order-service", "chaosmesh"),  # MySQL mem stress upstream of order
    FaultLabel("Lv_D_CONNECTION_POOL_exhaustion", "TT", "database", "connection_pool_exhaustion",
               "ts-order-service", "chaosmesh"),
    FaultLabel("Lv_D_TRANSACTION_timeout", "TT", "database", "transaction_timeout",
               "ts-order-service", "chaosmesh"),
    FaultLabel("Lv_C_security_check", "TT", "code", "return_fault",
               "ts-security-service", "chaosblade"),
    FaultLabel("Lv_C_exception_injection", "TT", "code", "throw_exception",
               "ts-order-service", "chaosblade"),
    FaultLabel("Lv_C_travel_detail_failure", "TT", "code", "return_fault",
               "ts-travel-service", "chaosblade"),
)

ALL_LABELS: Tuple[FaultLabel, ...] = SN_LABELS + TT_LABELS

_BY_NAME: Dict[str, FaultLabel] = {l.experiment: l for l in ALL_LABELS}

# Experiment dir names carry timestamps:
#   SN: <Base>_<YYYYMMDD_HHMMSS>[_<modality>_<YYYY-MM-DD_HH-MM-SS>]
#   TT: <Base>_<ISO8601Z>_em   (run_all_experiments.sh:554-555)
_SN_TS = re.compile(r"_\d{8}_\d{6}.*$")
_TT_TS = re.compile(r"_\d{8}T\d{6}Z(_em)?.*$")


def canonical_experiment(dir_name: str) -> str:
    """Strip timestamp/modality suffixes from an experiment directory name.

    Handles both suffix orders: ``<Base>_<ts>_em`` (anomalies) and
    ``Normal_case_em_<ts>`` (run_all_experiments.sh:554-555 vs :447).
    """
    base = _TT_TS.sub("", dir_name)
    base = _SN_TS.sub("", base)
    if base.endswith("_em"):
        base = base[:-3]
    return base


def label_for(dir_or_name: str) -> Optional[FaultLabel]:
    return _BY_NAME.get(canonical_experiment(dir_or_name))


def labels_for_testbed(testbed: str) -> List[FaultLabel]:
    return [l for l in ALL_LABELS if l.testbed == testbed]


def anomalous_labels(testbed: Optional[str] = None) -> List[FaultLabel]:
    return [l for l in ALL_LABELS
            if l.is_anomaly and (testbed is None or l.testbed == testbed)]
