"""The reference's complete metric catalogs, level-keyed, plus window rules.

Two catalogs, kept name-identical to the reference so artifact trees and
detector features line up file-for-file:

- **SN**: the 24 per-query CSV families written by
  ``SN_collection-scripts/Dataset/metric_data/collect_metric.sh:20-125``
  (one ``<name>.csv`` per PromQL range query; 15 s step, 24 h window,
  ``collect_metric.sh:4-5``).
- **TT**: the anomaly-level-keyed metric groups of
  ``TT_collection-scripts/T-Dataset/metric_collector.py:37-104``
  (performance / service / database categories; entries may be raw metric
  names or ``rate(<name>[5m])`` wrappers) plus the TT-specific kube-state
  queries of ``collect_train_ticket_specific_metrics`` (``:283-303``).

Also implements the reference's experiment-window semantics
(``metric_collector.py:480-525``): app start = earliest pod start time,
clamped to 24 h; 2 h safe window when discovery fails; 1 h on error.

The parity tests (tests/test_metrics_catalog.py) parse the reference
scripts and assert these constants match name-for-name.
"""

from __future__ import annotations

import datetime
import re
from typing import Dict, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# SN: per-query CSV families (file stem == CSV name in the artifact tree).
# Grouped exactly like collect_metric.sh's section banners.
# ---------------------------------------------------------------------------

SN_METRIC_FILES: Tuple[str, ...] = (
    # ===== Microservice KPIs (collect_metric.sh:20-41)
    "microservice_request_rate",
    "microservice_latency_p95",
    "microservice_error_rate",
    "post_creation_rate",
    "timeline_read_rate",
    # ===== Container resource usage (:44-59)
    "socialnet_container_cpu",
    "socialnet_container_memory",
    "socialnet_container_network_receive",
    "socialnet_container_network_transmit",
    # ===== Database and cache metrics (:61-73)
    "mongodb_latency_p95",
    "redis_memory_used",
    "redis_command_rate",
    # ===== Jaeger tracing metrics (:75-83)
    "jaeger_spans_rate",
    "jaeger_sampling_rate",
    # ===== Host-level indicators (:85-101)
    "system_cpu_usage",
    "system_memory_usage_percent",
    "system_load1",
    "system_network_errors",
    # ===== Extended performance indicators (:103-125)
    "system_disk_io_time",
    "system_disk_read_bytes",
    "system_disk_write_bytes",
    "system_network_receive_bytes",
    "system_network_transmit_bytes",
    "system_disk_usage_percent",
)

# Families whose PromQL groups by the compose service label — these carry
# per-service fault signal and get one series per service in synth.
SN_PER_SERVICE_FILES: Tuple[str, ...] = (
    "microservice_request_rate", "microservice_latency_p95",
    "microservice_error_rate", "socialnet_container_cpu",
    "socialnet_container_memory", "socialnet_container_network_receive",
    "socialnet_container_network_transmit",
)

# ---------------------------------------------------------------------------
# TT: level-keyed categories — raw entries exactly as the reference lists
# them (metric_collector.py:37-104), including rate() wrappers and the
# deliberate overlaps (node_filesystem_* in performance AND database,
# process_open_fds in service AND database).
# ---------------------------------------------------------------------------

TT_METRIC_CATEGORIES: Dict[str, Tuple[str, ...]] = {
    "performance": (
        "node_cpu_seconds_total",
        "container_cpu_usage_seconds_total",
        "rate(node_cpu_seconds_total[5m])",
        "node_load5",
        "node_memory_MemAvailable_bytes",
        "node_memory_MemTotal_bytes",
        "node_memory_MemFree_bytes",
        "container_memory_usage_bytes",
        "container_memory_working_set_bytes",
        "container_spec_memory_limit_bytes",
        "node_filesystem_avail_bytes",
        "node_filesystem_size_bytes",
        "rate(node_disk_read_bytes_total[5m])",
        "rate(node_disk_written_bytes_total[5m])",
        "node_disk_io_time_seconds_total",
        "node_network_receive_bytes_total",
        "node_network_transmit_bytes_total",
        "node_network_receive_drop_total",
        "node_network_transmit_drop_total",
        "node_network_receive_errs_total",
        "node_network_transmit_errs_total",
        "container_network_receive_errors_total",
        "container_network_transmit_errors_total",
    ),
    "service": (
        "up",
        "http_requests_total",
        "process_open_fds",
        "process_cpu_seconds_total",
        "process_resident_memory_bytes",
        "container_processes",
        "container_memory_failcnt",
        "container_cpu_cfs_throttled_periods_total",
    ),
    "database": (
        "node_filesystem_avail_bytes",
        "node_filesystem_size_bytes",
        "volume_manager_total_volumes",
        "process_open_fds",
        "process_max_fds",
    ),
}

# TT-specific kube-state queries (metric_collector.py:283-303).
TT_SPECIFIC_QUERIES: Tuple[str, ...] = (
    'kube_pod_status_phase{namespace="default"}',
    'rate(container_cpu_usage_seconds_total{namespace="default"}[5m])',
    'container_memory_usage_bytes{namespace="default"}',
    'rate(container_network_receive_bytes_total{namespace="default"}[5m])',
    'rate(container_network_transmit_bytes_total{namespace="default"}[5m])',
    'kube_pod_container_status_restarts_total{namespace="default"}',
    'kubelet_volume_stats_used_bytes{namespace="default"}',
    'up{job="kubernetes-pods"}',
)

_WRAP_RE = re.compile(r"^rate\((?P<name>[A-Za-z_:][\w:]*)"
                      r"(?:\{[^}]*\})?\[[^\]]+\]\)$")
_SELECTOR_RE = re.compile(r"^(?P<name>[A-Za-z_:][\w:]*)(?:\{[^}]*\})?$")


def normalize_metric_name(entry: str) -> str:
    """Catalog entry -> base metric name: strips rate(...[5m]) wrappers and
    {label} selectors, so 'rate(node_cpu_seconds_total[5m])' and
    'node_cpu_seconds_total' key the same long-CSV series family."""
    m = _WRAP_RE.match(entry) or _SELECTOR_RE.match(entry)
    if not m:
        raise ValueError(f"unparseable catalog entry: {entry!r}")
    return m.group("name")


def _dedup(seq) -> Tuple[str, ...]:
    seen: Dict[str, None] = {}
    for s in seq:
        seen.setdefault(s)
    return tuple(seen)


#: Deduped union of base names across the three level groups — what the
#: experiment-mode long CSV carries one series family per
#: (metric_collector.py:400-478 iterates the category lists).
TT_METRIC_NAMES: Tuple[str, ...] = _dedup(
    normalize_metric_name(e)
    for group in TT_METRIC_CATEGORIES.values() for e in group)

#: Base names of the TT-specific kube-state mode.
TT_SPECIFIC_METRICS: Tuple[str, ...] = _dedup(
    normalize_metric_name(q) for q in TT_SPECIFIC_QUERIES)

#: Everything the TT synth/loader plane models: level groups + kube-state.
TT_ALL_METRIC_NAMES: Tuple[str, ...] = _dedup(
    (*TT_METRIC_NAMES, *TT_SPECIFIC_METRICS))

#: Deduped RAW query strings (rate() wrappers and selectors intact) across
#: the level groups + kube-state — what a live collection actually sends to
#: Prometheus (metric_collector.py:421-425 iterates these, and each row's
#: ``metric_name`` is the raw query).
TT_ALL_QUERIES: Tuple[str, ...] = _dedup(
    (*(e for group in TT_METRIC_CATEGORIES.values() for e in group),
     *TT_SPECIFIC_QUERIES))

# Per-service (per-pod/container) TT families — carry per-service series.
TT_PER_SERVICE_METRICS: Tuple[str, ...] = (
    "container_cpu_usage_seconds_total", "container_memory_usage_bytes",
    "container_memory_working_set_bytes", "container_spec_memory_limit_bytes",
    "container_network_receive_errors_total",
    "container_network_transmit_errors_total",
    "up", "http_requests_total", "process_open_fds",
    "process_cpu_seconds_total", "process_resident_memory_bytes",
    "container_processes", "container_memory_failcnt",
    "container_cpu_cfs_throttled_periods_total", "process_max_fds",
    "kube_pod_status_phase", "kube_pod_container_status_restarts_total",
    "container_network_receive_bytes_total",
    "container_network_transmit_bytes_total",
    "kubelet_volume_stats_used_bytes",
)


def metrics_for_level(level: str) -> Tuple[str, ...]:
    """Normalized metric names for one anomaly level ('performance' /
    'service' / 'database') — the level-keyed grouping the detector's
    per-level metric features use."""
    return _dedup(normalize_metric_name(e)
                  for e in TT_METRIC_CATEGORIES[level])


# SN level grouping (by collect_metric.sh section): the detector's per-level
# features need the same keying on SN artifacts.
SN_LEVEL_FILES: Dict[str, Tuple[str, ...]] = {
    "performance": (
        "socialnet_container_cpu", "socialnet_container_memory",
        "system_cpu_usage", "system_memory_usage_percent", "system_load1",
        "system_disk_io_time", "system_disk_read_bytes",
        "system_disk_write_bytes", "system_network_receive_bytes",
        "system_network_transmit_bytes", "system_network_errors",
        "system_disk_usage_percent",
    ),
    "service": (
        "microservice_request_rate", "microservice_latency_p95",
        "microservice_error_rate", "post_creation_rate",
        "timeline_read_rate", "socialnet_container_network_receive",
        "socialnet_container_network_transmit", "jaeger_spans_rate",
        "jaeger_sampling_rate",
    ),
    "database": (
        "mongodb_latency_p95", "redis_memory_used", "redis_command_rate",
    ),
}


def level_metric_names(testbed: str, level: str) -> Tuple[str, ...]:
    return (SN_LEVEL_FILES[level] if testbed == "SN"
            else metrics_for_level(level))


def experiment_window(pod_start_times: Optional[Sequence[float]],
                      now_s: float,
                      discovery_failed: bool = False) -> Tuple[float, float]:
    """(start_s, end_s) of the metric collection window — the reference's
    app-start discovery + clamp semantics (metric_collector.py:480-525):

    - earliest pod start time, clamped to at most 24 h before now;
    - a 2 h "safe window" when discovery returns nothing;
    - a 1 h fallback on discovery error (``discovery_failed=True``).
    """
    if discovery_failed:
        return now_s - 3600.0, now_s
    if not pod_start_times:
        return now_s - 2 * 3600.0, now_s
    start = min(float(t) for t in pod_start_times)
    start = max(start, now_s - 24 * 3600.0)
    return start, now_s


def fmt_window(start_s: float, end_s: float) -> str:
    """Human-readable window line for metadata.txt artifacts."""
    f = lambda t: datetime.datetime.fromtimestamp(t).isoformat()
    return f"{f(start_s)} .. {f(end_s)}"
