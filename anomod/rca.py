"""RCA training/eval harness: GNNs trained on chaos fault labels.

Dataset: synthetic experiment corpora (many seeds per fault label — the
reference ships one run per label; seeds are the augmentation axis), features
relative to the same-seed normal baseline (exactly what an operator has: a
healthy profile of the same deployment).  Targets: the culprit service from
the chaos metadata (anomod.labels).  Eval: top-k hit-rate on held-out seeds,
the metric BASELINE.json ties to the numpy-baseline parity requirement.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from anomod import detect, labels as labels_mod, synth
from anomod.graph import build_service_graph
from anomod.rca_features import (edge_feature_block as _edge_feature_block,
                                 pad_edge_arrays,
                                 windowed_features as _windowed_features)
from anomod.replay import ReplayConfig


@dataclasses.dataclass
class RCASample:
    experiment: str
    x: np.ndarray          # [S, F] baseline-relative features
    x_t: np.ndarray        # [S, W, Ft] windowed temporal features
    adj: np.ndarray        # [S, S] call counts
    edge_src: np.ndarray   # [E_max] int32 (padded)
    edge_dst: np.ndarray   # [E_max] int32
    edge_mask: np.ndarray  # [E_max] bool
    target: int            # culprit service index (-1 if none)
    is_anomaly: bool
    #: [E_max, W, 4] baseline-relative PER-EDGE temporal features aligned
    #: with edge_src/edge_dst (built when edge_features=True) — the
    #: line-graph model's token inputs; None otherwise
    edge_x: Optional[np.ndarray] = None


# _windowed_features / _edge_feature_block moved to anomod.rca_features
# (ONE definition shared with the online serve-tick RCA plane,
# anomod.serve.rca; the underscore aliases keep this module's historical
# names importable).  tests/test_rca_features.py pins bit-exact parity
# between the offline batch path here and the online extraction.


def _edge_x_relative(exp_spans, services, g, cfg,
                     base_edge: Dict[tuple, np.ndarray]) -> np.ndarray:
    """Baseline-relative per-edge features: the normal run's edge set can
    differ, so rows align by (src, dst) pair; edges unseen in the
    baseline keep their raw values (their baseline is zero traffic)."""
    raw = _edge_feature_block(exp_spans, services, g, cfg)
    for i, (a, b) in enumerate(zip(g.edge_src, g.edge_dst)):
        base = base_edge.get((int(a), int(b)))
        if base is not None:
            raw[i] = raw[i] - base
    return raw


def _pick_confounders(label, services: Tuple[str, ...], seed: int,
                      n: int) -> Tuple[str, ...]:
    """Deterministic decoy services for one (label, seed): never the culprit."""
    cands = [s for s in services if s != label.target_service]
    rng = np.random.default_rng(synth._seed_for(label.experiment, 13) + seed)
    return tuple(rng.choice(cands, size=min(n, len(cands)), replace=False))


def experiment_stream(testbed: str, seed: int, n_traces: int = 80,
                      hard: Optional["synth.HardMode"] = None,
                      n_confounders: int = 0,
                      experiments: Optional[Sequence[str]] = None):
    """Yield ``(label, experiment)`` for every label of one seed — THE
    corpus definition for quality evaluation.  ``experiments`` filters by
    name BEFORE generation (a consumer-side filter would still pay the
    synthesis cost of every skipped bundle).

    This is the single builder consumed by both the learned-model dataset
    (:func:`build_dataset`) and the training-free baselines
    (anomod.quality._zscore_eval), so every cell of the quality table
    scores byte-identical experiment bundles; round 2's sweep regenerated
    the zscore corpora separately, which made the model-vs-baseline
    comparison cross-sample noise-coupled.

    Seeds are process-stable per (seed, experiment): Python's ``hash()`` is
    salted per interpreter, which would make every call produce different
    corpora across processes (synth._seed_for is the stable hash).
    """
    svc_list = synth.SN_SERVICES if testbed == "SN" else synth.TT_SERVICES
    services = tuple(svc_list)
    for label in labels_mod.labels_for_testbed(testbed):
        if experiments is not None and label.experiment not in experiments:
            continue
        mode = hard or synth.HardMode()
        if n_confounders and label.is_anomaly:
            mode = dataclasses.replace(
                mode, confounders=_pick_confounders(
                    label, services, seed, n_confounders))
        yield label, synth.generate_experiment(
            label, n_traces=n_traces, hard=mode,
            seed=seed * 1000 + synth._seed_for(label.experiment) % 997)


def build_dataset(testbed: str, seeds: Sequence[int], n_traces: int = 80,
                  n_windows: int = 8,
                  hard: Optional["synth.HardMode"] = None,
                  n_confounders: int = 0,
                  edge_features: bool = False
                  ) -> Tuple[List[RCASample], Tuple[str, ...]]:
    """One sample per (fault label, seed), features relative to the same-seed
    normal baseline.

    ``hard`` applies HardMode difficulty (severity/noise) to the FAULT
    experiments; the normal baseline stays easy (it is the healthy profile).
    ``n_confounders`` > 0 additionally plants that many per-(label, seed)
    decoy services into each fault experiment.  ``edge_features`` doubles
    the windowed block with per-service OUT-EDGE aggregates (opt-in: the
    canonical quality tables use node features; the edge-aware variant
    needs this channel to learn link-fault attribution).
    """
    svc_list = synth.SN_SERVICES if testbed == "SN" else synth.TT_SERVICES
    services = tuple(svc_list)
    cfg = ReplayConfig(n_services=len(services), n_windows=n_windows,
                       chunk_size=2048, window_us=300_000_000)
    samples: List[RCASample] = []
    e_max = 0
    raw: List[tuple] = []
    for seed in seeds:
        normal_label = next(l for l in labels_mod.labels_for_testbed(testbed)
                            if not l.is_anomaly)
        normal = synth.generate_experiment(normal_label, n_traces=n_traces,
                                           seed=seed * 1000)
        base_x = detect.extract_features(normal, services).x
        base_t = _windowed_features(normal.spans, services, cfg,
                                    edge_features=edge_features)
        base_edge: Dict[tuple, np.ndarray] = {}
        if edge_features:
            g_n = build_service_graph(normal.spans, services=services)
            nb = _edge_feature_block(normal.spans, services, g_n, cfg)
            base_edge = {(int(a), int(b)): nb[i] for i, (a, b) in
                         enumerate(zip(g_n.edge_src, g_n.edge_dst))}
        for label, exp in experiment_stream(testbed, seed, n_traces=n_traces,
                                            hard=hard,
                                            n_confounders=n_confounders):
            x = detect.extract_features(exp, services).x - base_x
            x_t = _windowed_features(exp.spans, services, cfg,
                                     edge_features=edge_features) - base_t
            g = build_service_graph(exp.spans, services=services)
            e_max = max(e_max, g.n_edges)
            target = (services.index(label.target_service)
                      if label.target_service in services else -1)
            ex = (_edge_x_relative(exp.spans, services, g, cfg, base_edge)
                  if edge_features else None)
            raw.append((label.experiment, x, x_t, g, target,
                        label.is_anomaly, ex))
    for name, x, x_t, g, target, is_anom, ex in raw:
        E = e_max
        src, dst, mask = pad_edge_arrays(g, E)
        if ex is not None:
            ex = np.pad(ex.astype(np.float32),
                        ((0, E - ex.shape[0]), (0, 0), (0, 0)))
        samples.append(RCASample(name, x.astype(np.float32), x_t, g.adj_counts,
                                 src, dst, mask, target, is_anom, edge_x=ex))
    return samples, services


def _stack(samples: List[RCASample]) -> Dict[str, np.ndarray]:
    out = {
        "x": np.stack([s.x for s in samples]),
        "x_t": np.stack([s.x_t for s in samples]),
        "adj": np.stack([s.adj for s in samples]).astype(np.float32),
        "edge_src": np.stack([s.edge_src for s in samples]),
        "edge_dst": np.stack([s.edge_dst for s in samples]),
        "edge_mask": np.stack([s.edge_mask for s in samples]),
        "target": np.array([s.target for s in samples], np.int32),
        "is_anomaly": np.array([s.is_anomaly for s in samples], np.float32),
    }
    if samples and samples[0].edge_x is not None:
        out["edge_x"] = np.stack([s.edge_x for s in samples])
    return out


def _apply_model(model_name: str, model, params, batch):
    import jax
    if model_name in ("gcn",):
        return jax.vmap(lambda x, a: model.apply(params, x, a))(
            batch["x"], batch["adj"])
    if model_name == "linegraph":
        if "edge_x" not in batch:
            raise ValueError("the linegraph model needs per-edge features "
                             "(build_dataset(edge_features=True) / quality "
                             "sweeps with edge_aware)")
        return jax.vmap(
            lambda x, xt, ex, s, d, m:
            model.apply(params, x, xt, ex, s, d, m))(
            batch["x"], batch["x_t"], batch["edge_x"], batch["edge_src"],
            batch["edge_dst"], batch["edge_mask"])
    if model_name in ("temporal", "lru", "transformer", "moe"):
        import jax.numpy as jnp
        # fuse static multimodal features (logs etc.) into every window
        W = batch["x_t"].shape[2]
        x_full = jnp.concatenate(
            [batch["x_t"],
             jnp.repeat(batch["x"][:, :, None, :], W, axis=2)], axis=-1)
        return jax.vmap(lambda x, a: model.apply(params, x, a))(
            x_full, batch["adj"])
    return jax.vmap(lambda x, s, d, m: model.apply(params, x, s, d, m))(
        batch["x"], batch["edge_src"], batch["edge_dst"], batch["edge_mask"])


def init_params(model_name: str, model, sample0: Dict[str, np.ndarray], rng):
    """Per-model-family parameter init (single source for train_rca, the
    distributed train steps, and the quality sweep)."""
    if model_name == "gcn":
        return model.init(rng, sample0["x"], sample0["adj"])
    if model_name == "linegraph":
        return model.init(rng, sample0["x"], sample0["x_t"],
                          sample0["edge_x"], sample0["edge_src"],
                          sample0["edge_dst"], sample0["edge_mask"])
    if model_name in ("temporal", "lru", "transformer", "moe"):
        W = sample0["x_t"].shape[1]
        fused = np.concatenate(
            [sample0["x_t"],
             np.repeat(sample0["x"][:, None, :], W, axis=1)], axis=-1)
        return model.init(rng, fused, sample0["adj"])
    return model.init(rng, sample0["x"], sample0["edge_src"],
                      sample0["edge_dst"], sample0["edge_mask"])


def standardize_features(train: Dict[str, np.ndarray],
                         evals: Sequence[Dict[str, np.ndarray]]) -> None:
    """Standardize x/x_t (and edge_x when present) on train statistics,
    in place (shared with eval)."""
    for key in ("x", "x_t", "edge_x"):
        if key not in train:
            continue
        axes = tuple(range(train[key].ndim - 1))  # all but the feature axis
        mu = train[key].mean(axis=axes, keepdims=True)
        sd = train[key].std(axis=axes, keepdims=True) + 1e-6
        train[key] = (train[key] - mu) / sd
        for ev in evals:
            if key in ev:
                ev[key] = (ev[key] - mu) / sd


def topk_eval(scores: np.ndarray,
              batch: Dict[str, np.ndarray]) -> Tuple[float, float, float, int]:
    """(top1, top3, detection_auc, n_rca) from [B, S] scores vs labels.
    AUC is rank-based (max score as the experiment-level statistic)."""
    tgt = batch["target"]
    rca_mask = tgt >= 0
    order = np.argsort(-scores, axis=-1)
    rank = np.array([np.where(order[i] == tgt[i])[0][0] if rca_mask[i] else -1
                     for i in range(len(tgt))])
    top1 = float((rank[rca_mask] == 0).mean()) if rca_mask.any() else 0.0
    top3 = float((rank[rca_mask] < 3).mean()) if rca_mask.any() else 0.0
    det = scores.max(axis=-1)
    y = batch["is_anomaly"]
    pos, neg = det[y > 0], det[y == 0]
    auc = float((pos[:, None] > neg[None, :]).mean()) \
        if len(neg) and len(pos) else 1.0
    return top1, top3, auc, int(rca_mask.sum())


def rca_loss(scores, batch):
    """Shared training objective: CE over culprit services (where a chaos
    label names one) + 0.3 × detection BCE on the max score.  Single source
    of truth for the local, dp×tp, and pipeline train steps."""
    import jax
    import jax.numpy as jnp
    import optax
    has_target = batch["target"] >= 0
    logp = jax.nn.log_softmax(scores, axis=-1)
    tgt = jnp.clip(batch["target"], 0, scores.shape[-1] - 1)
    ce = -jnp.take_along_axis(logp, tgt[:, None], axis=1)[:, 0]
    rca = jnp.sum(ce * has_target) / jnp.maximum(has_target.sum(), 1)
    det = optax.sigmoid_binary_cross_entropy(
        scores.max(axis=-1), batch["is_anomaly"]).mean()
    return rca + 0.3 * det


def make_model(model_name: str):
    from anomod.models import GAT, GCN, GraphSAGE, MoERCA, TemporalGCN
    from anomod.models.linegraph import LineGraphRCA
    from anomod.models.lru import TemporalLRU
    from anomod.models.transformer import TraceTransformer
    return {"gcn": GCN(), "gat": GAT(), "sage": GraphSAGE(),
            "temporal": TemporalGCN(), "lru": TemporalLRU(),
            "transformer": TraceTransformer(), "moe": MoERCA(),
            "linegraph": LineGraphRCA()}[model_name]


@dataclasses.dataclass
class TrainResult:
    model_name: str
    top1: float
    top3: float
    detection_auc: float
    n_eval: int
    params: object


def train_rca(testbed: str = "TT", model_name: str = "gcn",
              train_seeds: Sequence[int] = range(8),
              eval_seeds: Sequence[int] = range(100, 104),
              epochs: int = 150, lr: float = 3e-3,
              n_traces: int = 80, verbose: bool = False,
              checkpoint_dir=None, resume: bool = False,
              save_every: int = 50) -> TrainResult:
    """Train a GNN RCA scorer on chaos labels; report held-out top-k.

    ``checkpoint_dir`` persists params + opt_state + epoch counter
    (anomod.utils.checkpoint) every ``save_every`` epochs and at the end;
    with ``resume=True`` training continues from the saved epoch — the
    checkpoint/resume plane the reference lacks (SURVEY.md §5), wired into
    the training entry point so an interrupted run loses at most
    ``save_every`` epochs (``save_every <= 0`` = final save only)."""
    import jax
    import jax.numpy as jnp
    import optax

    # the edge-native model consumes the per-edge feature plane; every
    # other model keeps the lighter node-only dataset
    edge_features = model_name == "linegraph"
    train_samples, services = build_dataset(testbed, train_seeds, n_traces,
                                            edge_features=edge_features)
    eval_samples, _ = build_dataset(testbed, eval_seeds, n_traces,
                                    edge_features=edge_features)
    # pad eval edge arrays to the train E_max (or vice versa)
    E = max(train_samples[0].edge_src.shape[0], eval_samples[0].edge_src.shape[0])
    def repad(samples):
        for s in samples:
            cur = s.edge_src.shape[0]
            if cur < E:
                s.edge_src = np.pad(s.edge_src, (0, E - cur))
                s.edge_dst = np.pad(s.edge_dst, (0, E - cur))
                s.edge_mask = np.pad(s.edge_mask, (0, E - cur))
                if s.edge_x is not None:
                    s.edge_x = np.pad(s.edge_x,
                                      ((0, E - cur), (0, 0), (0, 0)))
    repad(train_samples); repad(eval_samples)
    train = _stack([s for s in train_samples])
    evalb = _stack(eval_samples)

    standardize_features(train, [evalb])

    model = make_model(model_name)
    rng = jax.random.PRNGKey(0)
    sample0 = {k: v[0] for k, v in train.items()}
    params = init_params(model_name, model, sample0, rng)

    tx = optax.adamw(lr, weight_decay=1e-4)
    opt_state = tx.init(params)

    def loss_fn(params, batch):
        scores = _apply_model(model_name, model, params, batch)  # [B, S]
        return rca_loss(scores, batch)

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    start_ep = 0
    if checkpoint_dir is not None and resume:
        from anomod.utils.checkpoint import (has_checkpoint,
                                             restore_train_state)
        # no checkpoint yet = first attempt of an always-pass-resume job:
        # start fresh instead of crashing
        if has_checkpoint(checkpoint_dir):
            params, opt_state, start_ep, meta = \
                restore_train_state(checkpoint_dir)
            for key, want in (("model", model_name), ("testbed", testbed)):
                if meta.get(key) not in (None, want):
                    raise ValueError(
                        f"checkpoint at {checkpoint_dir} was trained with "
                        f"{key}={meta.get(key)!r}, not {want!r}")
            if verbose:
                print(f"resumed from epoch {start_ep}")
        elif verbose:
            print(f"no checkpoint at {checkpoint_dir} yet; starting fresh")

    def _save(completed: int):
        """Persist with step = number of COMPLETED epochs, so resume's
        range(start_ep, epochs) never re-applies a baked-in update."""
        if checkpoint_dir is not None:
            from anomod.utils.checkpoint import save_train_state
            save_train_state(checkpoint_dir, params, opt_state, completed,
                             meta={"model": model_name, "testbed": testbed})

    batch = {k: jnp.asarray(v) for k, v in train.items()}
    last_saved = start_ep
    for ep in range(start_ep, epochs):
        params, opt_state, loss = step(params, opt_state, batch)
        if verbose and ep % 20 == 0:
            print(f"epoch {ep}: loss {float(loss):.4f}")
        if save_every > 0 and (ep + 1) % save_every == 0:
            _save(ep + 1)
            last_saved = ep + 1
    if start_ep < epochs and last_saved != epochs:
        # final save, unless the periodic save just wrote this exact state;
        # a no-op resume must not rewind the counter either
        _save(epochs)

    # eval
    scores = np.asarray(_apply_model(model_name, model, params,
                                     {k: jnp.asarray(v) for k, v in evalb.items()}))
    top1, top3, auc, n_eval = topk_eval(scores, evalb)
    return TrainResult(model_name=model_name, top1=top1, top3=top3,
                       detection_auc=auc, n_eval=n_eval, params=params)


def train_rca_resilient(*args, resume: bool = False, checkpoint_dir=None,
                        **kwargs):
    """:func:`train_rca` with mid-run dead-device failover.

    If training dies with a backend RuntimeError while a device backend is
    active (the tunnel-died-mid-sweep mode), the process is repointed to
    CPU (utils.platform.with_cpu_failover) and training reruns once.  The
    retry resumes ONLY from a checkpoint this invocation itself published
    (checkpoint mtime >= start; a stale same-model checkpoint left from an
    earlier run must not be silently resumed into a "freshly trained"
    result) — with no fresh checkpoint it retrains from scratch.

    Returns ``(result, failover_note)`` where ``failover_note`` is None on
    the clean path and a one-line explanation when the CPU retry ran —
    callers surface it so mixed-backend results are labeled as such.
    """
    import time

    from anomod.utils.checkpoint import checkpoint_mtime
    from anomod.utils.platform import with_cpu_failover

    t_start = time.time()
    tried = []
    note = []

    def _saved_this_run() -> bool:
        if not checkpoint_dir:
            return False
        m = checkpoint_mtime(checkpoint_dir)
        return m is not None and m >= t_start

    def _attempt():
        do_resume = resume if not tried else (resume or _saved_this_run())
        tried.append(1)
        return train_rca(*args, resume=do_resume,
                         checkpoint_dir=checkpoint_dir, **kwargs)

    def _on_failover(exc):
        # the retry actually resumes only when a restorable checkpoint
        # exists at retry time AND the resume gate passes — "--resume with
        # an empty dir, died before the first save" retrains from scratch
        # and must be labeled so
        will_resume = ((resume or _saved_this_run())
                       and checkpoint_dir is not None
                       and checkpoint_mtime(checkpoint_dir) is not None)
        note.append(f"device backend lost mid-train ({type(exc).__name__});"
                    f" retried on the CPU failover backend"
                    + (" from the last checkpoint"
                       if will_resume else " from scratch"))

    result = with_cpu_failover(_attempt, on_failover=_on_failover)
    return result, (note[0] if note else None)
