"""Edge-native RCA: line-graph message passing with edges as tokens.

Every other model in the zoo consumes per-SERVICE aggregates, so a fault
living on a call-graph LINK (anomod.synth fault_locus="edge": the callee
side of one caller's outgoing calls degrades, every node statistic stays
healthy) is architecturally outside their evidence — post-leak-fix, all
node-feature models score ≤0.06 edge-locus top-1 and even the out-edge
feature BLOCK (which sums a caller's callees together) lifts only the
attention models to 0.39 (docs/BENCHMARKS.md).  This model makes edges
first-class: each observed (caller, callee) edge is a token carrying its
own windowed aggregates, messages flow over the LINE graph (edges sharing
an endpoint exchange state through node mailboxes), and service scores
read BOTH the node evidence and each service's incident-edge mailboxes —
the caller's out-mailbox is exactly where a link fault lands.

TPU-first shape discipline: the edge list is padded to a static E_max with
a mask; the edge↔node exchanges are one-hot [E, S] matmuls (MXU) instead
of gather/scatter, and every round is a fixed-depth compact module — no
data-dependent control flow anywhere.

No reference counterpart: the reference ships labeled data for this model
family but no model code (SURVEY.md §0).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class _MLP(nn.Module):
    features: int
    out: int

    @nn.compact
    def __call__(self, h):
        return nn.Dense(self.out)(nn.relu(nn.Dense(self.features)(h)))


class LineGraphRCA(nn.Module):
    """Line-graph edge-token culprit scorer.

    ``__call__(x, x_t, edge_x, src, dst, mask) -> [S]`` scores:
      - ``x``       [S, Fs]     static multimodal features (logs/metrics/
                                api/coverage — the node evidence channel
                                every temporal-family model fuses)
      - ``x_t``     [S, W, Fn]  windowed node features
      - ``edge_x``  [E, W, 4]   windowed PER-EDGE features (padded)
      - ``src/dst`` [E] int32   edge endpoints, ``mask`` [E] bool

    Deliberately LEAN: one weight-shared per-edge scorer, one
    weight-shared per-node scorer, one line-graph exchange round
    (edges read their endpoints' pooled edge state), and a 6-feature
    linear combiner.  The RCA corpus is dozens-to-hundreds of graphs —
    a wide read-out memorizes it in 50 epochs and transfers nothing
    (measured: train 1.0 / eval 0.19); the shared-scorer design is the
    right bias for "a degraded edge looks degraded wherever it sits"."""
    hidden: int = 32

    @nn.compact
    def __call__(self, x, x_t, edge_x, src, dst, mask):
        S = x_t.shape[0]
        E = edge_x.shape[0]
        m = mask.astype(jnp.float32)[:, None]
        # one-hot incidence [E, S]: the edge<->node exchange operator (MXU
        # matmuls; masked rows contribute nothing anywhere)
        inc_src = jnp.eye(S, dtype=jnp.float32)[src] * m
        inc_dst = jnp.eye(S, dtype=jnp.float32)[dst] * m
        deg_out = jnp.maximum(inc_src.sum(axis=0), 1.0)[:, None]
        deg_in = jnp.maximum(inc_dst.sum(axis=0), 1.0)[:, None]

        h_e = nn.relu(nn.Dense(self.hidden)(edge_x.reshape(E, -1))) * m
        # ONE line-graph round: every edge reads the mean state of the
        # edges sharing its endpoints (through the endpoint mailboxes) —
        # enough to tell "my callee is slow because of ITS callee" from
        # "my link itself is the problem"
        out_box = inc_src.T @ h_e / deg_out
        in_box = inc_dst.T @ h_e / deg_in
        ctx = inc_src @ in_box + inc_dst @ out_box      # [E, H]
        edge_logit = nn.Dense(1)(
            nn.relu(nn.Dense(self.hidden)(
                jnp.concatenate([h_e, ctx * m], axis=-1))))[:, 0]
        edge_logit = jnp.where(mask, edge_logit, -1e9)
        # per-service edge evidence: the hottest incident edge, by
        # direction (a link fault is the caller's MAX out-edge; the
        # callee side sees it as its max in-edge)
        def peak(inc):
            v = jnp.where(inc.T > 0, edge_logit[None, :], -1e9).max(axis=1)
            return jnp.where(v < -1e8, 0.0, v)
        out_peak, in_peak = peak(inc_src), peak(inc_dst)
        out_mean = (inc_src.T @ jnp.where(mask, edge_logit, 0.0)[:, None]
                    / deg_out)[:, 0]
        node_in = jnp.concatenate([x_t.reshape(S, -1), x], axis=-1)
        node_logit = nn.Dense(1)(
            nn.relu(nn.Dense(self.hidden)(node_in)))[:, 0]
        feats = jnp.stack([node_logit, out_peak, in_peak, out_mean,
                           out_peak - in_peak,
                           jnp.maximum(out_peak - in_peak, 0.0)], axis=-1)
        return nn.Dense(1)(feats)[:, 0]
