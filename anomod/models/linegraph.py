"""Edge-native RCA: line-graph scoring with edges as tokens.

Every other model in the zoo consumes per-SERVICE aggregates, so a fault
living on call-graph LINKS (anomod.synth fault_locus="edge": the callee
side of the culprit's outgoing calls degrades, the culprit's own spans
stay healthy) is architecturally outside their evidence — post-leak-fix,
all node-feature models score ≤0.06 edge-locus top-1, and even with the
out-edge feature BLOCK the best attention model reaches 0.39
(docs/BENCHMARKS.md).  This model makes edges first-class: each observed
(caller, callee) edge is a token carrying its own windowed aggregates and
explicit CONTRAST features (its deviation from the callee's other
in-edges and the caller's other out-edges — the discriminative pattern
"this link is hot in a way its endpoints' other traffic is not",
hand-built instead of hoped-for from message passing), while the node
channel reuses the zoo's proven sequence backbone (TokenEmbed + attention
+ adjacency-hop pooling, anomod.models.transformer) so edge capability
never taxes in-distribution accuracy.  Service scores combine the node
logit with direction-aware peak/mean readouts of the incident-edge
logits — the caller's out-edge plane is exactly where a link fault lands.

Round-5 redesign notes (committed records in bench_runs/, table in
docs/BENCHMARKS.md):
  - windowed inputs enter POOLED over windows (mean/max/mean-positive):
    the earlier flatten readout memorized window positions (train 1.00 /
    eval 0.42); pooling alone moved in-dist 0.42 -> 0.81.
  - the transformer node backbone restores in-dist to 0.97 across every
    non-edge shift at unchanged edge capability.
  - edge-locus attribution is DATA-limited at the sweep's 6-seed
    training protocol: 0.39 top-1 there (bench_runs/20260731T184051Z)
    vs 0.50 with 24 training seeds (bench_runs/20260731T210351Z, the
    committed data-scaling record; in-dist 0.97 at both protocols —
    see docs/BENCHMARKS.md for the same-protocol comparison against
    the out-edge-block models).

TPU-first shape discipline: the edge list is padded to a static E_max
with a mask; the edge<->node exchanges are one-hot [E, S] matmuls (MXU)
instead of gather/scatter, and every stage is a fixed-depth compact
module — no data-dependent control flow anywhere.

No reference counterpart: the reference ships labeled data for this model
family but no model code (SURVEY.md §0).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


def _pool_windows(t):
    """[..., W, F] -> [..., 3F]: mean / max / mean-positive over windows.

    The anti-memorization stage: a flatten readout lets a small corpus be
    memorized by window position; these order-free summaries keep the
    burst shape (max), the level (mean), and the one-sided heat
    (mean-positive) that fault effects actually live in."""
    return jnp.concatenate([t.mean(axis=-2), t.max(axis=-2),
                            nn.relu(t).mean(axis=-2)], axis=-1)


class LineGraphRCA(nn.Module):
    """Edge-token culprit scorer.

    ``__call__(x, x_t, edge_x, src, dst, mask) -> [S]`` scores:
      - ``x``       [S, Fs]     static multimodal features (logs/metrics/
                                api/coverage — the node evidence channel
                                every temporal-family model fuses)
      - ``x_t``     [S, W, Fn]  windowed node features
      - ``edge_x``  [E, W, 4]   windowed PER-EDGE features (padded)
      - ``src/dst`` [E] int32   edge endpoints, ``mask`` [E] bool
    """
    d_model: int = 48
    n_heads: int = 4
    n_layers: int = 2
    mlp_hidden: int = 96
    hidden: int = 64

    @nn.compact
    def __call__(self, x, x_t, edge_x, src, dst, mask):
        from anomod.models.transformer import (AttentionBlock, ScoreHead,
                                               TokenEmbed)
        S, W, _ = x_t.shape
        E = edge_x.shape[0]
        m = mask.astype(jnp.float32)[:, None]
        # one-hot incidence [E, S]: the edge<->node exchange operator (MXU
        # matmuls; masked rows contribute nothing anywhere)
        inc_src = jnp.eye(S, dtype=jnp.float32)[src] * m
        inc_dst = jnp.eye(S, dtype=jnp.float32)[dst] * m
        deg_out = jnp.maximum(inc_src.sum(axis=0), 1.0)[:, None]
        deg_in = jnp.maximum(inc_dst.sum(axis=0), 1.0)[:, None]

        # ---- node channel: the zoo's sequence backbone ----
        x_full = jnp.concatenate(
            [x_t, jnp.repeat(x[:, None, :], W, axis=1)], axis=-1)
        seq = TokenEmbed(self.d_model)(x_full)
        for _ in range(self.n_layers):
            seq = AttentionBlock(self.d_model, self.n_heads,
                                 self.mlp_hidden)(seq)
        adj = inc_src.T @ inc_dst        # call topology from the edge list
        node_logit = ScoreHead(n_services=S, n_windows=W,
                               hidden=self.hidden)(seq, adj)

        # ---- edge channel: pooled tokens + contrast features ----
        pe = _pool_windows(edge_x) * m                 # [E, 12]
        sum_out = inc_src.T @ pe                       # [S, 12]
        sum_in = inc_dst.T @ pe
        n_out = inc_src.sum(axis=0)[:, None]
        n_in = inc_dst.sum(axis=0)[:, None]
        # exclusive sibling means: the callee's OTHER in-edges and the
        # caller's OTHER out-edges — "hot unlike my siblings" is the
        # pattern that separates a link fault from endpoint-wide heat
        excl_in = (inc_dst @ sum_in - pe) / jnp.maximum(
            inc_dst @ n_in - 1.0, 1.0)
        excl_out = (inc_src @ sum_out - pe) / jnp.maximum(
            inc_src @ n_out - 1.0, 1.0)
        node_pool = _pool_windows(x_t)                 # [S, 3Fn]
        e_in = jnp.concatenate(
            [pe, pe - excl_in, pe - excl_out,
             inc_src @ node_pool, inc_dst @ node_pool], axis=-1)
        h_e = nn.relu(nn.Dense(self.hidden)(e_in)) * m
        h_e = nn.relu(nn.Dense(self.hidden)(h_e)) * m
        edge_logit = nn.Dense(1)(h_e)[:, 0]
        edge_logit = jnp.where(mask, edge_logit, -1e9)

        # per-service edge evidence: hottest incident edge by direction (a
        # link fault is the caller's MAX out-edge; the callee side sees it
        # as its max in-edge) plus the out-mean (an edge-locus fault heats
        # ALL the culprit's out-edges, not one)
        def peak(inc):
            v = jnp.where(inc.T > 0, edge_logit[None, :], -1e9).max(axis=1)
            return jnp.where(v < -1e8, 0.0, v)
        out_peak, in_peak = peak(inc_src), peak(inc_dst)
        out_mean = (inc_src.T @ jnp.where(mask, edge_logit, 0.0)[:, None]
                    / deg_out)[:, 0]
        feats = jnp.stack([node_logit, out_peak, in_peak, out_mean,
                           out_peak - in_peak,
                           jnp.maximum(out_peak - in_peak, 0.0)], axis=-1)
        hid = nn.relu(nn.Dense(16)(feats))
        return nn.Dense(1)(jnp.concatenate([feats, hid], -1))[:, 0]
