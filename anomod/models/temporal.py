"""Temporal GNN: windowed multimodal features → GRU over time → GCN scorer.

BASELINE.json config 5 ("multimodal log+metric+trace temporal-GNN"): inputs
are per-window per-service feature planes straight from the replay engine's
windowed aggregates ([S, W, F], anomod.replay) fused with log/metric planes;
an ``nn.scan`` GRU consumes the window axis (compiler-friendly recurrence),
then a GCN head scores services on the final hidden state.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from anomod.models.gnn import GCNLayer, normalized_adjacency


class TemporalGCN(nn.Module):
    """GRU over windows, then a 2-layer GCN over the service DAG."""
    hidden: int = 64
    gnn_hidden: int = 64

    @nn.compact
    def __call__(self, x_swf, adj_counts):
        # project each window's features, GRU over the window axis
        x = nn.Dense(self.hidden)(x_swf)          # [S, W, hidden]
        h0 = jnp.zeros((x.shape[0], self.hidden), x.dtype)
        xs = jnp.swapaxes(x, 0, 1)                # [W, S, hidden]
        ScanGRU = nn.scan(
            nn.GRUCell, variable_broadcast="params",
            split_rngs={"params": False}, in_axes=0, out_axes=0)
        h_final, _ = ScanGRU(features=self.hidden)(h0, xs)
        a = normalized_adjacency(adj_counts)
        h = nn.relu(GCNLayer(self.gnn_hidden)(h_final, a))
        h = nn.relu(GCNLayer(self.gnn_hidden)(h, a))
        return nn.Dense(1)(h)[:, 0]
