"""Sparse GNN layers/models over the service DAG (flax.linen).

Message passing is ``jax.ops.segment_sum`` over a padded edge list — static
[E_max, 2] shapes so XLA compiles one program for every experiment graph
(SN ~12 services, TT ~45; BASELINE.json configs 3-4).  Edges carry the call
direction from anomod.graph (caller → callee); messages flow both ways via
the symmetrized edge list so upstream effects propagate to culprit scoring.

No reference counterpart: the reference ships labeled data for exactly this
model family but no model code (SURVEY.md §0).
"""

from __future__ import annotations

from typing import Optional, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


def normalized_adjacency(adj_counts, add_self_loops: bool = True):
    """Symmetric GCN normalization D^-1/2 (A + A^T + I) D^-1/2 from the dense
    call-count matrix (counts binarized)."""
    a = (adj_counts > 0).astype(jnp.float32)
    a = jnp.maximum(a, a.T)
    if add_self_loops:
        a = a + jnp.eye(a.shape[0], dtype=jnp.float32)
    d = a.sum(axis=1)
    d_inv_sqrt = jnp.where(d > 0, 1.0 / jnp.sqrt(jnp.maximum(d, 1e-9)), 0.0)
    return a * d_inv_sqrt[:, None] * d_inv_sqrt[None, :]


def segment_mean(messages, dst, num_nodes):
    s = jax.ops.segment_sum(messages, dst, num_segments=num_nodes)
    cnt = jax.ops.segment_sum(jnp.ones((messages.shape[0],), messages.dtype),
                              dst, num_segments=num_nodes)
    return s / jnp.maximum(cnt, 1.0)[:, None]


class GCNLayer(nn.Module):
    features: int

    @nn.compact
    def __call__(self, h, a_norm):
        # dense S×S matmul: S ≤ 64, one MXU tile — cheaper than gather/scatter
        return nn.Dense(self.features, use_bias=True)(a_norm @ h)


class GCN(nn.Module):
    """2-layer GCN anomaly scorer (BASELINE.json config 3)."""
    hidden: int = 64
    n_layers: int = 2

    @nn.compact
    def __call__(self, x, adj_counts):
        a = normalized_adjacency(adj_counts)
        h = x
        for _ in range(self.n_layers - 1):
            h = nn.relu(GCNLayer(self.hidden)(h, a))
        h = GCNLayer(self.hidden)(h, a)
        h = nn.relu(h)
        scores = nn.Dense(1)(h)[:, 0]          # per-service culprit logit
        return scores


class GraphSAGE(nn.Module):
    """GraphSAGE with mean aggregation over the padded edge list."""
    hidden: int = 64
    n_layers: int = 2

    @nn.compact
    def __call__(self, x, edge_src, edge_dst, edge_mask):
        S = x.shape[0]
        # symmetrize: messages flow caller->callee and callee->caller
        src = jnp.concatenate([edge_src, edge_dst])
        dst = jnp.concatenate([edge_dst, edge_src])
        mask = jnp.concatenate([edge_mask, edge_mask]).astype(x.dtype)
        h = x
        for i in range(self.n_layers):
            msgs = h[src] * mask[:, None]
            neigh = segment_mean(msgs, dst, S)
            h = nn.Dense(self.hidden)(h) + nn.Dense(self.hidden)(neigh)
            h = nn.relu(h)
            h = h / jnp.maximum(jnp.linalg.norm(h, axis=-1, keepdims=True), 1e-6)
        return nn.Dense(1)(h)[:, 0]


class GATLayer(nn.Module):
    features: int
    n_heads: int = 4

    @nn.compact
    def __call__(self, h, edge_src, edge_dst, edge_mask):
        S = h.shape[0]
        F, Hd = self.features, self.n_heads
        wh = nn.Dense(F * Hd, use_bias=False)(h).reshape(S, Hd, F)
        a_src = self.param("a_src", nn.initializers.glorot_uniform(), (Hd, F))
        a_dst = self.param("a_dst", nn.initializers.glorot_uniform(), (Hd, F))
        e = (jnp.einsum("shf,hf->sh", wh, a_src)[edge_src]
             + jnp.einsum("shf,hf->sh", wh, a_dst)[edge_dst])  # [E, Hd]
        e = nn.leaky_relu(e, negative_slope=0.2)
        e = jnp.where(edge_mask[:, None], e, -1e9)
        # segment softmax over incoming edges of each dst
        e_max = jax.ops.segment_max(e, edge_dst, num_segments=S)
        e = jnp.exp(e - e_max[edge_dst])
        e = e * edge_mask[:, None]
        denom = jax.ops.segment_sum(e, edge_dst, num_segments=S)
        alpha = e / jnp.maximum(denom[edge_dst], 1e-9)            # [E, Hd]
        msgs = wh[edge_src] * alpha[:, :, None]                   # [E, Hd, F]
        out = jax.ops.segment_sum(msgs, edge_dst, num_segments=S)
        return out.reshape(S, Hd * F)


class GAT(nn.Module):
    """Graph attention RCA scorer (BASELINE.json config 4)."""
    hidden: int = 32
    n_heads: int = 4
    n_layers: int = 2

    @nn.compact
    def __call__(self, x, edge_src, edge_dst, edge_mask):
        # symmetrize + self loops so every node attends to itself
        S = x.shape[0]
        loops = jnp.arange(S, dtype=edge_src.dtype)
        src = jnp.concatenate([edge_src, edge_dst, loops])
        dst = jnp.concatenate([edge_dst, edge_src, loops])
        mask = jnp.concatenate(
            [edge_mask, edge_mask, jnp.ones(S, dtype=edge_mask.dtype)])
        h = x
        for _ in range(self.n_layers):
            h = nn.elu(GATLayer(self.hidden, self.n_heads)(h, src, dst, mask))
        return nn.Dense(1)(h)[:, 0]
