"""LRU-style temporal model: associative linear recurrence over windows.

Unlike the GRU (models/temporal.py), the recurrence here is associative —
``h_t = σ(decay) ⊙ h_{t-1} + W x_t`` — so it parallelizes over time both
within a device (``lax.associative_scan``, log-depth) and across devices
(anomod.parallel.seqscan block scan).  This is the long-context temporal
scorer: window streams can shard over the mesh with exact results.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from anomod.models.gnn import GCNLayer, normalized_adjacency
from anomod.parallel.seqscan import linear_recurrence


class TemporalLRU(nn.Module):
    """Linear-recurrence temporal encoder + 2-layer GCN head."""
    hidden: int = 64
    gnn_hidden: int = 64

    @nn.compact
    def __call__(self, x_swf, adj_counts):
        S = x_swf.shape[0]
        x = nn.Dense(self.hidden)(x_swf)            # [S, W, hidden]
        # learnable per-channel decay in (0, 1)
        decay_logit = self.param("decay_logit", nn.initializers.uniform(2.0),
                                 (self.hidden,))
        decay = nn.sigmoid(decay_logit + 1.0)
        xs = jnp.swapaxes(x, 0, 1)                  # [W, S, hidden]
        h_all = linear_recurrence(xs, decay)        # [W, S, hidden]
        h_final = h_all[-1]
        a = normalized_adjacency(adj_counts)
        h = nn.relu(GCNLayer(self.gnn_hidden)(h_final, a))
        h = nn.relu(GCNLayer(self.gnn_hidden)(h, a))
        return nn.Dense(1)(h)[:, 0]
