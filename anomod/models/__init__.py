"""GNN model zoo for anomaly scoring and root-cause localization."""

from anomod.models.gnn import GCN, GAT, GraphSAGE, normalized_adjacency
from anomod.models.linegraph import LineGraphRCA
from anomod.models.temporal import TemporalGCN
from anomod.models.transformer import TraceTransformer
from anomod.models.lru import TemporalLRU
from anomod.models.moe import MoERCA

__all__ = ["GCN", "GAT", "GraphSAGE", "TemporalGCN", "TemporalLRU",
           "TraceTransformer", "MoERCA", "LineGraphRCA",
           "normalized_adjacency"]
