"""GNN model zoo for anomaly scoring and root-cause localization."""

from anomod.models.gnn import GCN, GAT, GraphSAGE, normalized_adjacency
from anomod.models.temporal import TemporalGCN

__all__ = ["GCN", "GAT", "GraphSAGE", "TemporalGCN", "normalized_adjacency"]
