"""Mixture-of-experts RCA scorer — the expert-parallel (ep) plane.

Tokens are (service, time-window) cells of the windowed replay features
(same tokenization as :class:`anomod.models.transformer.TraceTransformer`).
Each block routes every token to its top-k experts with a learned softmax
router and combines the expert MLP outputs with the renormalized gate
weights.

TPU-first design: dispatch is *dense einsum* over a fixed expert axis — no
ragged gathers, no capacity overflow/dropping logic, one static-shape XLA
program.  Expert kernels carry a leading ``[E, ...]`` axis; under the 2-D
``(data, model)`` mesh the training harness shards that axis over ``model``
(``PartitionSpec('model', None, None)``), so each device computes only its
own experts' FLOPs and XLA inserts the psum that realizes the gate-weighted
combine across devices.  That is expert parallelism in the pjit idiom: the
collective is derived from sharding annotations, not hand-written all-to-alls.

No reference counterpart (the reference ships no models,
``/root/reference`` per SURVEY.md §2.4); seventh member of the RCA zoo
trained on chaos labels by :mod:`anomod.rca`.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from anomod.models.transformer import ScoreHead, TokenEmbed


class MoEBlock(nn.Module):
    """Pre-LN token-wise MoE MLP with residual connection.

    ``[T, d_model] -> [T, d_model]``.  All experts run on all tokens (E is
    small and the MXU is wide); sparsity semantics come from the top-k gate
    mask, which zeroes the combine weight of non-selected experts.
    """

    d_model: int
    n_experts: int = 8
    d_hidden: int = 64
    top_k: int = 2

    @nn.compact
    def __call__(self, tokens):                        # [T, d_model]
        h = nn.LayerNorm()(tokens)
        gates = nn.softmax(
            nn.Dense(self.n_experts, use_bias=False, name="router")(h))
        # top-k mask, renormalized so selected gates sum to 1 per token
        kth = jnp.sort(gates, axis=-1)[:, -self.top_k][:, None]
        mask = (gates >= kth).astype(gates.dtype)
        combine = gates * mask
        combine = combine / jnp.maximum(
            combine.sum(axis=-1, keepdims=True), 1e-9)   # [T, E]

        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (self.n_experts, self.d_model, self.d_hidden))
        b1 = self.param("b1", nn.initializers.zeros,
                        (self.n_experts, self.d_hidden))
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (self.n_experts, self.d_hidden, self.d_model))
        b2 = self.param("b2", nn.initializers.zeros,
                        (self.n_experts, self.d_model))

        # dense dispatch: every einsum keeps the expert axis outermost so a
        # P('model', ...) sharding of w1/w2 partitions the FLOPs per device
        eh = nn.gelu(jnp.einsum("td,edh->eth", h, w1) + b1[:, None, :])
        ey = jnp.einsum("eth,ehd->etd", eh, w2) + b2[:, None, :]
        out = jnp.einsum("etd,te->td", ey, combine)
        return tokens + out


class MoERCA(nn.Module):
    """[S, W, F] windowed features + [S, S] adjacency → [S] culprit scores."""

    d_model: int = 48
    n_layers: int = 2
    n_experts: int = 8
    d_hidden: int = 96
    top_k: int = 2
    hidden: int = 64

    @nn.compact
    def __call__(self, x_swf, adj_counts):
        S, W, _ = x_swf.shape
        seq = TokenEmbed(self.d_model)(x_swf)                  # [S·W, d]
        for _ in range(self.n_layers):
            seq = MoEBlock(self.d_model, self.n_experts, self.d_hidden,
                           self.top_k)(seq)
        return ScoreHead(S, W, self.hidden)(seq, adj_counts)
