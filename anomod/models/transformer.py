"""TraceTransformer — long-context attention RCA scorer.

Tokens are (service, time-window) cells of the windowed replay features: the
experiment is one long sequence of S·W tokens (service embedding + sinusoidal
window position), processed by pre-LN transformer blocks whose attention core
is :func:`anomod.parallel.ring_attention.full_attention` — the exact op the
sequence-parallel ring path computes distributed, so the single-chip model
and the sharded long-context path share semantics.  A final adjacency hop
mixes topology into the pooled per-service states before scoring.

No reference counterpart (the reference has no models); sixth member of the
RCA zoo trained on chaos labels by :mod:`anomod.rca`.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from anomod.models.gnn import normalized_adjacency
from anomod.parallel.ring_attention import full_attention


def sinusoidal_positions(n: int, d: int) -> np.ndarray:
    """Standard fixed sin/cos position table [n, d]."""
    pos = np.arange(n)[:, None].astype(np.float32)
    i = np.arange((d + 1) // 2)[None, :].astype(np.float32)
    angles = pos / np.power(10_000.0, 2.0 * i / d)
    out = np.zeros((n, d), np.float32)
    out[:, 0::2] = np.sin(angles)
    out[:, 1::2] = np.cos(angles[:, : d // 2])
    return out


class TokenEmbed(nn.Module):
    """[S, W, F] windowed features → [S·W, d_model] token sequence.

    Shared tokenizer for every sequence model in the zoo (transformer, MoE,
    pipelined stack): feature projection + learned service embedding +
    sinusoidal window position.
    """
    d_model: int

    @nn.compact
    def __call__(self, x_swf):
        S, W, _ = x_swf.shape
        tok = nn.Dense(self.d_model)(x_swf)
        svc_emb = self.param("svc_emb", nn.initializers.normal(0.02),
                             (S, self.d_model))
        tok = tok + svc_emb[:, None, :] + \
            jnp.asarray(sinusoidal_positions(W, self.d_model))[None]
        return tok.reshape(S * W, self.d_model)


class ScoreHead(nn.Module):
    """[S·W, d_model] tokens + [S, S] adjacency → [S] culprit scores.

    Shared head: LayerNorm, window mean-pool, one adjacency hop to mix call
    topology into the pooled states, then a scoring MLP.
    """
    n_services: int
    n_windows: int
    hidden: int = 64

    @nn.compact
    def __call__(self, seq, adj_counts):
        h = nn.LayerNorm()(seq)
        h = h.reshape(self.n_services, self.n_windows, -1).mean(axis=1)
        a = normalized_adjacency(adj_counts)
        h = jnp.concatenate([h, a @ h], axis=-1)
        h = nn.relu(nn.Dense(self.hidden)(h))
        return nn.Dense(1)(h)[:, 0]


class AttentionBlock(nn.Module):
    """Pre-LN block; ``attention_fn`` is the [L, H, D]-shaped attention
    core — :func:`full_attention` single-chip, or a mesh-built
    sequence-parallel plane (ring / Ulysses) with the SAME semantics and
    param tree, so trained params are interchangeable across planes."""
    d_model: int
    n_heads: int
    mlp_hidden: int
    attention_fn: Callable = full_attention

    @nn.compact
    def __call__(self, seq):                       # [L, d_model]
        L = seq.shape[0]
        h = nn.LayerNorm()(seq)
        d_head = self.d_model // self.n_heads
        qkv = nn.Dense(3 * self.d_model, use_bias=False)(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (L, self.n_heads, d_head)
        attn = self.attention_fn(
            q.reshape(shape), k.reshape(shape),
            v.reshape(shape)).reshape(L, self.d_model)
        seq = seq + nn.Dense(self.d_model)(attn)
        h = nn.LayerNorm()(seq)
        h = nn.Dense(self.mlp_hidden)(h)
        h = nn.gelu(h)
        return seq + nn.Dense(self.d_model)(h)


class TraceTransformer(nn.Module):
    """[S, W, F] windowed features + [S, S] adjacency → [S] culprit scores."""
    d_model: int = 48
    n_heads: int = 4
    n_layers: int = 2
    mlp_hidden: int = 96
    hidden: int = 64
    attention_fn: Callable = full_attention

    @nn.compact
    def __call__(self, x_swf, adj_counts):
        S, W, _ = x_swf.shape
        seq = TokenEmbed(self.d_model)(x_swf)                  # [S·W, d]
        for _ in range(self.n_layers):
            seq = AttentionBlock(self.d_model, self.n_heads,
                                 self.mlp_hidden,
                                 attention_fn=self.attention_fn)(seq)
        return ScoreHead(S, W, self.hidden)(seq, adj_counts)
