"""Sharded span-stream replay: the TPU feature-extraction hot path.

The reference's richest data path is trace ingestion — paginated fetch, then
per-span Python graph building (trace_collector.py:296-547).  The TPU-native
equivalent replays an experiment corpus *as data*: span columns staged into
HBM, then a jitted scan over fixed-size chunks computes windowed per-service
aggregates (count / errors / latency moments / log-latency histogram) on the
MXU.  Throughput (spans/sec/chip) is the headline benchmark
(BASELINE.json: ≥1M spans/sec/chip on TT_data replay).

Design notes (TPU-first):
  - static shapes: spans padded to chunk multiples; windows/services fixed.
  - the scatter-heavy aggregation is expressed as one-hot matmuls (MXU) for
    the [S*W] aggregate plane and a segment histogram over log-latency
    buckets — fused by XLA into a handful of kernels.
  - per-chip state is tiny (S*W*F + S*W*H floats), so the multi-chip replay
    shards the span stream and psum-merges state (anomod.parallel).
"""

from __future__ import annotations

import dataclasses
import os
import time
from functools import lru_cache, partial
from typing import NamedTuple, Optional, Tuple

import numpy as np

from anomod.schemas import SpanBatch

# Feature plane order: the three exact 0/1 columns first (bf16-exact matmul),
# the three latency moments last (HIGHEST-precision matmul).
F_COUNT, F_ERR, F_STATUS5XX, F_LAT, F_LOGLAT, F_LOGLAT2 = range(6)
N_FEATS = 6


class ReplayState(NamedTuple):
    agg: "object"          # [S*W, F] float32
    hist: "object"         # [S*W, H] float32 — log-latency histogram
    hll: "object" = None   # [S, 2^p] int32 — distinct-trace registers (opt.)


@dataclasses.dataclass(frozen=True)
class ReplayConfig:
    n_services: int
    n_windows: int = 32
    n_hist_buckets: int = 16
    chunk_size: int = 1 << 15
    window_us: int = 60_000_000  # 60 s windows
    hll_p: int = 8               # per-service distinct-trace HLL precision

    @property
    def sw(self) -> int:
        return self.n_services * self.n_windows

    @property
    def hll_m(self) -> int:
        return 1 << self.hll_p


def segment_ids(batch: SpanBatch, cfg: ReplayConfig,
                t0_us: Optional[int] = None) -> np.ndarray:
    """[n] int32 (service, window) segment id per span — the ONE definition
    of the replay's segment binning, shared by :func:`stage_columns` and
    lightweight consumers (e.g. bench.py's f32-exactness replicate clamp)
    that need segment occupancy without paying the full staging pass."""
    n = batch.n_spans
    t0 = int(batch.start_us.min()) if t0_us is None and n else (t0_us or 0)
    window = np.minimum((batch.start_us - t0) // cfg.window_us,
                        cfg.n_windows - 1).astype(np.int32)
    window = np.maximum(window, 0)
    return batch.service.astype(np.int32) * cfg.n_windows + window


#: the chunk column schema's row order in the staged matrix — the ONE
#: ordering shared by :func:`stage_columns_fused`, :func:`dead_chunk` and
#: the native packer's matrix fast path (anomod.io.native.StagePlan): a
#: reorder here without a matching ``mat_keys`` change would break the
#: byte-parity pin in tests/test_native.py, never silently stage garbage.
STAGE_KEYS = ("sid", "dur", "dur_raw", "err", "s5", "valid", "tid")


def stage_columns_fused(batch: SpanBatch, cfg: ReplayConfig,
                        t0_us: Optional[int] = None):
    """UNPADDED per-span chunk columns staged as ONE C-contiguous
    ``[7, n]`` float32 matrix (every chunk column is a 4-byte dtype;
    ``sid``/``tid`` live as int32 row views) — ``(mat, columns)`` where
    ``columns`` maps the :data:`STAGE_KEYS` schema to row views of
    ``mat``.  The serving batcher stages through this and pads at
    scratch-fill time into pinned reused buffers (pad value per column =
    the :func:`dead_chunk` fill), so the hot tick loop stops allocating —
    and the single backing matrix is what lets the native GIL-free packer
    (anomod.io.native.stage_lanes) describe a whole lane with ONE base
    pointer + row stride instead of seven per-column pointer
    extractions (each of which costs as much as a small numpy copy)."""
    n = batch.n_spans
    mat = np.empty((len(STAGE_KEYS), n), np.float32)
    sid = mat[0].view(np.int32)
    sid[:] = segment_ids(batch, cfg, t0_us)
    dur_raw = mat[2]
    np.copyto(dur_raw, batch.duration_us, casting="unsafe")
    np.log1p(dur_raw, out=mat[1])
    np.copyto(mat[3], batch.is_error, casting="unsafe")
    np.copyto(mat[4], batch.status >= 500, casting="unsafe")
    mat[5].fill(1.0)
    tid = mat[6].view(np.int32)                 # for distinct-trace HLL
    np.copyto(tid, batch.trace, casting="unsafe")
    return mat, dict(sid=sid, dur=mat[1], dur_raw=dur_raw, err=mat[3],
                     s5=mat[4], valid=mat[5], tid=tid)


def stage_columns_raw(batch: SpanBatch, cfg: ReplayConfig,
                      t0_us: Optional[int] = None) -> dict:
    """UNPADDED per-span chunk columns — the :func:`stage_columns`
    transforms without the pad (:func:`stage_columns_fused`'s column
    dict; the values are row views of one staged matrix, byte-identical
    to independently computed columns)."""
    return stage_columns_fused(batch, cfg, t0_us)[1]


def stage_columns(batch: SpanBatch, cfg: ReplayConfig, t0_us: Optional[int] = None):
    """Host-side packing: SpanBatch -> padded int32/float32 chunk arrays."""
    n = batch.n_spans
    pad = (-n) % cfg.chunk_size
    raw = stage_columns_raw(batch, cfg, t0_us)
    def p(a, fill=0):
        return np.pad(a, (0, pad), constant_values=fill)
    cols = {k: p(v, fill=cfg.sw if k == "sid" else 0)
            for k, v in raw.items()}   # padding rows target a dead segment
    n_chunks = (n + pad) // cfg.chunk_size
    return {k: v.reshape(n_chunks, cfg.chunk_size) for k, v in cols.items()}, n


def dead_chunk(cfg: ReplayConfig, width: Optional[int] = None, xp=None):
    """An all-dead staged chunk (sid = the dead pad lane, valid = 0) —
    numerically a no-op on any replay state.  The ONE definition of the
    chunk column schema's dummy instance, shared by every warm/compile
    path (StreamReplay._warm, the sharded stream's group padding, the
    serve BucketRunner) so a chunk-schema change cannot silently desync
    a warm path from :func:`stage_columns`."""
    if xp is None:
        import jax.numpy as xp
    w = int(width or cfg.chunk_size)
    return {
        "sid": xp.full((w,), cfg.sw, np.int32),
        "dur": xp.zeros((w,), np.float32),
        "dur_raw": xp.zeros((w,), np.float32),
        "err": xp.zeros((w,), np.float32),
        "s5": xp.zeros((w,), np.float32),
        "valid": xp.zeros((w,), np.float32),
        "tid": xp.zeros((w,), np.int32),
    }


def hll_scatter_update(regs, sid, tid, cfg: ReplayConfig):
    """Scatter-max trace-id ranks into per-service HLL registers — the ONE
    definition of the distinct-trace plane, shared by the single-chip chunk
    step and the pod-sharded whole-shard build.  Routes through
    anomod.ops.hll.hll_add (one hash pipeline in the repo); rows with
    sid >= cfg.sw are padding and go to an extra dead lane, dropped."""
    import jax.numpy as jnp

    from anomod.ops.hll import hll_add

    svc = jnp.clip(sid // cfg.n_windows, 0, cfg.n_services - 1)
    lane = jnp.where(sid < cfg.sw, svc, cfg.n_services)
    regs_ext = jnp.concatenate(
        [regs, jnp.zeros((1, cfg.hll_m), regs.dtype)], axis=0)
    return hll_add(regs_ext, tid, p=cfg.hll_p, lane=lane, xp=jnp)[:-1]


def _scatter_rhs(chunk, cfg: ReplayConfig):
    """The [rows, 3+3+3+H] per-row feature payload of the SCATTER-engine
    step: bf16-rounded exact/hi/lo planes + masked bucket one-hot,
    widened back to f32.  Each row's value equals its matmul-path product
    against a one-hot 1.0 EXACTLY (the bf16 rounding happens before
    either reduction), which is what makes the scatter engine's f32
    accumulation bit-compatible with the matmul engine's on XLA:CPU —
    both reduce a segment's rows in row order, and the matmul's extra
    terms from other rows are exact ``+0.0``s.  ONE definition, shared by
    the single-lane scatter step and the fused lane-delta kernel."""
    import jax
    import jax.numpy as jnp
    H = cfg.n_hist_buckets
    exact = jnp.stack([chunk["valid"], chunk["err"], chunk["s5"]],
                      axis=1).astype(jnp.bfloat16)
    bucket = jnp.clip(chunk["dur"].astype(jnp.int32), 0, H - 1)
    bucket_oh = (jax.nn.one_hot(bucket, H, dtype=jnp.bfloat16)
                 * chunk["valid"][:, None].astype(jnp.bfloat16))
    durs = jnp.stack([chunk["dur_raw"], chunk["dur"],
                      chunk["dur"] * chunk["dur"]], axis=1)
    hi = durs.astype(jnp.bfloat16)
    lo = (durs - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return jnp.concatenate([exact, hi, lo, bucket_oh],
                           axis=1).astype(jnp.float32)


def _split_acc(acc, state: ReplayState):
    """Fold a [SW, 3+3+3+H] per-segment accumulation into the state:
    recombine the hi/lo latency moments and apply the SAME elementwise
    f32 adds the matmul step performs."""
    import jax.numpy as jnp
    a_dur = acc[:, 3:6] + acc[:, 6:9]
    agg = state.agg + jnp.concatenate([acc[:, :3], a_dur], axis=1)
    hist = state.hist + acc[:, 9:]
    return agg, hist


def default_step_engine() -> str:
    """The chunk-step engine for the current backend: "scatter" on
    XLA:CPU (a segment-sum over the staged rows — ~10x the one-hot
    matmul there, and pinned BIT-identical to it in tests/test_serve.py,
    so every downstream parity guarantee carries over), "matmul" on
    accelerators (the one-hot bf16 MXU formulation — scatter is the slow
    path on TPU)."""
    import jax
    return "scatter" if jax.default_backend() == "cpu" else "matmul"


def make_chunk_step(cfg: ReplayConfig, with_hll: bool = False,
                    engine: str = "matmul"):
    """The per-chunk aggregation step shared by the single-chip scan and the
    pod-sharded replay (one definition so the split-precision scheme can't
    diverge between them).  Returns ``step(state, chunk) -> (state, None)``
    for ``lax.scan``.

    ``engine="matmul"`` (default) is the one-hot bf16 MXU formulation
    below; ``engine="scatter"`` computes the same per-segment sums with a
    ``jax.ops.segment_sum`` over the identical bf16-rounded row payload —
    on XLA:CPU the two accumulate each segment's rows in the same order,
    so their f32 states are BIT-identical (pinned in tests/test_serve.py;
    the serving plane's BucketRunner picks per backend via
    :func:`default_step_engine`).
    """
    import jax
    import jax.numpy as jnp

    SW = cfg.sw
    H = cfg.n_hist_buckets
    if engine not in ("matmul", "scatter"):
        raise ValueError(f"unknown chunk-step engine {engine!r} "
                         "(matmul|scatter)")

    def hll_update(regs, chunk):
        return hll_scatter_update(regs, chunk["sid"], chunk["tid"], cfg)

    if engine == "scatter":
        def scatter_step(state: ReplayState, chunk):
            # padding rows carry sid = SW (the dead lane): segment-sum
            # them into an extra segment and drop it, exactly as the
            # matmul drops its pad column
            acc = jax.ops.segment_sum(_scatter_rhs(chunk, cfg),
                                      chunk["sid"],
                                      num_segments=SW + 1)[:SW]
            agg, hist = _split_acc(acc, state)
            hll = hll_update(state.hll, chunk) if with_hll else None
            return ReplayState(agg=agg, hist=hist, hll=hll), None

        return scatter_step

    def chunk_step(state: ReplayState, chunk):
        sid = chunk["sid"]                    # [C] int32, SW = padding
        # one-hot [C, SW+1] — pad lane absorbs padding rows, dropped after.
        # ONE bf16 MXU matmul per chunk aggregates every feature plane:
        #   - the 0/1 planes (count, error, 5xx, histogram buckets) are
        #     EXACT in bf16 with the MXU's f32 accumulation;
        #   - the latency moments ride a two-way hi/lo bf16 split
        #     (x = bf16(x) + bf16(x - bf16(x)), ~16 mantissa bits): the
        #     one-hot operand is exact, products accumulate in f32, so the
        #     result carries ~1.5e-5 relative error at 1/3 the passes of a
        #     HIGHEST-precision f32 matmul.  Accepted error bound for
        #     consumers: reconstructing variance as E[x²]−E[x]² amplifies
        #     that to ~1.5e-5·E[x²]/Var(x) relative — fine for the synth
        #     corpus (log-latency σ≈0.4 ⇒ <1e-3) and any σ≳0.1, unreliable
        #     when Var(x)/E[x²] < ~1e-4 (then use the histogram plane
        #     instead; test_replay_variance_reconstruction_low_variance
        #     pins this bound).
        onehot16 = jax.nn.one_hot(sid, SW + 1, dtype=jnp.bfloat16)
        exact = jnp.stack([chunk["valid"], chunk["err"], chunk["s5"]],
                          axis=1).astype(jnp.bfloat16)
        bucket = jnp.clip(chunk["dur"].astype(jnp.int32), 0, H - 1)
        bucket_oh = (jax.nn.one_hot(bucket, H, dtype=jnp.bfloat16)
                     * chunk["valid"][:, None].astype(jnp.bfloat16))
        durs = jnp.stack([chunk["dur_raw"], chunk["dur"],
                          chunk["dur"] * chunk["dur"]], axis=1)
        hi = durs.astype(jnp.bfloat16)
        lo = (durs - hi.astype(jnp.float32)).astype(jnp.bfloat16)
        rhs = jnp.concatenate([exact, hi, lo, bucket_oh], axis=1)
        acc = jnp.matmul(onehot16.T, rhs,
                         preferred_element_type=jnp.float32)[:SW]
        a_dur = acc[:, 3:6] + acc[:, 6:9]
        agg = state.agg + jnp.concatenate([acc[:, :3], a_dur], axis=1)
        # log-latency histogram: hist[s, h] += Σ_c 1[sid=c]·1[bucket=h],
        # the same matmul's trailing lanes instead of a scatter
        hist = state.hist + acc[:, 9:]
        hll = hll_update(state.hll, chunk) if with_hll else None
        return ReplayState(agg=agg, hist=hist, hll=hll), None

    return chunk_step


def default_lane_engine() -> str:
    """The FUSED lane-dispatch engine: the validated
    ``ANOMOD_SERVE_LANE_ENGINE`` knob when set, else
    :func:`default_step_engine`'s choice ("scatter" on XLA:CPU, the
    one-hot matmul on accelerators).

    The hands-off default deliberately FOLLOWS the single-chunk step
    engine on every backend — including TPU — so the fused lane path
    stays BIT-identical to sequential per-chunk dispatch and every
    serving parity guarantee (fused==sequential, N-shard==1-shard,
    pipeline depth-invariant) is backend-stable.  The single Mosaic
    kernel ("pallas", anomod.ops.pallas_replay.make_pallas_lane_delta_fn
    — the whole per-lane score chain as one kernel launch per fused
    shape instead of a vmap of one-hot matmuls) is a deployment OPT-IN
    via ``ANOMOD_SERVE_LANE_ENGINE=pallas``: its alert/histogram planes
    are exact vs the other engines but its latency moments carry the
    bf16 hi/lo envelope of the compiled-replay tolerance contract, so
    defaulting it on would silently soften the serve bit-parity pins."""
    from anomod.config import get_config
    knob = get_config().serve_lane_engine
    return default_step_engine() if knob == "auto" else knob


def make_lane_delta(cfg: ReplayConfig, engine: str = "scatter"):
    """The FUSED (lane-stacked) dispatch surface of the chunk step.

    Returns ``delta(chunks) -> (dagg, dhist)`` where every column in
    ``chunks`` is ``[lanes, width]`` (one staged micro-batch chunk per
    lane, dead-padded lanes carry all-pad rows) and the outputs are
    ``[lanes, SW, F]`` / ``[lanes, SW, H]`` per-lane aggregation DELTAS.
    The caller folds lane ``i`` into its tenant's state with the same
    elementwise f32 add the in-step update performs
    (``state.agg + dagg[i]``) — bit-identical to dispatching that lane's
    chunk through ``make_chunk_step`` alone, because the step's state
    update is exactly ``state + delta`` and a zero-state delta IS the
    per-segment sum.  One jit of this compiles once per
    ``(lane-bucket, width)`` shape.

    ``engine="scatter"`` flattens the lanes into ONE segment-sum over
    ``lanes * (SW+1)`` segments (each lane's rows stay contiguous and in
    row order, so per-lane bits match the single-lane scatter step — the
    "many small irregular work items, one wide regular kernel" shape);
    ``engine="matmul"`` is ``jax.vmap`` of the one-hot step for
    accelerator backends; ``engine="pallas"`` is the single fused Mosaic
    kernel (interpret mode off-TPU, so the kernel logic stays testable in
    tier-1) — 0/1 and histogram planes exact vs the other engines,
    latency moments within the bf16 hi/lo envelope (the compiled-replay
    tolerance contract; see make_pallas_lane_delta_fn).
    """
    import jax
    import jax.numpy as jnp

    SW, H = cfg.sw, cfg.n_hist_buckets
    if engine not in ("matmul", "scatter", "pallas"):
        raise ValueError(f"unknown chunk-step engine {engine!r} "
                         "(matmul|scatter|pallas)")

    if engine == "pallas":
        from anomod.ops.pallas_replay import make_pallas_lane_delta_fn
        pfn = make_pallas_lane_delta_fn(
            SW, H, interpret=jax.default_backend() != "tpu")

        def pallas_lane_delta(chunks):
            dur = chunks["dur"]
            # lane-major [L, 6, W] plane stack in the kernel's PLANES
            # order (stage_pallas_planes' row order, per lane)
            planes = jnp.stack(
                [chunks["valid"], chunks["err"], chunks["s5"],
                 chunks["dur_raw"], dur, dur * dur], axis=1)
            out = pfn(chunks["sid"], planes)       # [L, SW, 6+H]
            return out[..., :N_FEATS], out[..., N_FEATS:]

        return pallas_lane_delta

    if engine == "matmul":
        step = make_chunk_step(cfg, with_hll=False, engine="matmul")

        def one_lane(chunk):
            zero = ReplayState(agg=jnp.zeros((SW, N_FEATS), jnp.float32),
                               hist=jnp.zeros((SW, H), jnp.float32))
            st, _ = step(zero, chunk)
            return st.agg, st.hist

        return jax.vmap(one_lane)

    def lane_delta(chunks):
        L, C = chunks["sid"].shape
        flat = {k: v.reshape(L * C) for k, v in chunks.items()}
        # offset each lane's segment ids into its own [SW+1] block (the
        # +1 block absorbs that lane's padding rows), fold ONE segment
        # sum over the whole stack, then peel the pad segments off
        lane = jnp.repeat(jnp.arange(L, dtype=jnp.int32), C)
        sid = lane * (SW + 1) + flat["sid"]
        acc = jax.ops.segment_sum(_scatter_rhs(flat, cfg), sid,
                                  num_segments=L * (SW + 1))
        acc = acc.reshape(L, SW + 1, acc.shape[-1])[:, :SW]
        a_dur = acc[..., 3:6] + acc[..., 6:9]
        return (jnp.concatenate([acc[..., :3], a_dur], axis=-1),
                acc[..., 9:])

    return lane_delta


def fold_delta(state: ReplayState, dagg, dhist) -> ReplayState:
    """THE host-seam fold: apply one lane's aggregation delta to a tenant
    state with the same elementwise f32 adds the in-step update performs
    (``state + delta``).  ONE definition shared by the synchronous
    (``BucketRunner.run_lanes``) and pipelined (``_retire_one``) fold
    paths — and the contract the device pool's scatter-add is pinned
    bit-identical to (an XLA f32 scatter with unique per-dispatch slots
    performs exactly this add per slot)."""
    return ReplayState(agg=np.asarray(state.agg) + dagg,
                       hist=np.asarray(state.hist) + dhist)


class TenantStatePool:
    """POOL-RESIDENT per-tenant replay states for the serving plane.

    One ``[slots, SW, F]`` agg plane plus a matching ``[slots, SW, H]``
    hist plane per shard runner; tenants map to slots at first service
    (:meth:`acquire`).  Row 0 is the DEAD slot: dead pad lanes (and the
    non-current occurrences of a duplicated slot, see
    :meth:`scatter_fold`) scatter their deltas there, and it is never
    read.  The hot-loop fold becomes one scatter-add per retired
    dispatch — the per-lane interpreter adds (and, on accelerator
    backends, the per-tick device→host materialization barrier) of the
    host seam disappear — while :meth:`gather`/:meth:`put` keep the
    ``get_state``/``set_state`` round-trip bit-exact for parity checks,
    checkpoints and (future) migration.

    Two fold ENGINES behind one seam, picked by backend (``auto``):

    - ``jax`` (accelerator backends): the planes are device arrays, the
      ops are jitted with buffer DONATION (XLA updates them in place —
      no per-op pool copy), the scored-window gather is one fused
      dispatch materializing only the requested columns.
    - ``numpy`` (the CPU backend): "device" memory IS host RAM there,
      and XLA:CPU's fixed per-dispatch overhead (~0.2-0.5 ms/call)
      swamps these row shapes — so the planes are host arrays and every
      op is an in-place vectorized numpy update, with the lane deltas
      read through the CPU backend's zero-copy ``np.asarray`` view (no
      readback copy, no XLA dispatch).  Same pool architecture, same
      adds; the engine choice is measured in
      ``scripts/bench_fold_sweep.py``.

    Bit-parity contract (pinned in tests/test_serve_state.py, both
    engines): every pool operation performs the SAME IEEE f32
    arithmetic as the host seam — scatter-add = ``state + delta`` per
    slot in dispatch order (duplicate slots within one dispatch fold in
    lane order via wave splitting), :meth:`roll` =
    :func:`anomod.stream.roll_ring_state`'s shift+zero, gather/put are
    pure copies — so ``device`` vs ``host`` serving is byte-identical,
    not a tolerance trade.
    """

    def __init__(self, cfg: ReplayConfig, capacity: int = 32,
                 engine: str = "auto", gather_engine: str = "xla"):
        import jax
        import jax.numpy as jnp
        self.cfg = cfg
        self._jnp = jnp
        if engine not in ("auto", "jax", "numpy"):
            raise ValueError(f"unknown pool engine {engine!r} "
                             "(auto|jax|numpy)")
        if engine == "auto":
            engine = "numpy" if jax.default_backend() == "cpu" else "jax"
        self.engine = engine
        if gather_engine not in ("xla", "pallas"):
            raise ValueError(f"unknown pool gather engine "
                             f"{gather_engine!r} (xla|pallas)")
        #: batched-scoring gather formulation: "xla" (take_along_axis /
        #: the numpy engine's fancy-index twin) or "pallas" (the fused
        #: Mosaic gather kernel, anomod.ops.pallas_replay.
        #: make_pallas_window_gather_fn — the serve plane routes
        #: ANOMOD_SERVE_LANE_ENGINE=pallas here).  A pure copy either
        #: way: bit-identical outputs.  The scatter FOLD stays on the
        #: engine's scatter-add (one fused dispatch / one vectorized
        #: in-place add already; see the kernel's docstring for why a
        #: Mosaic scatter is the unverifiable half).
        self.gather_engine = gather_engine
        self._pallas_gather = None
        if gather_engine == "pallas":
            from anomod.ops.pallas_replay import make_pallas_window_gather_fn
            self._pallas_gather = make_pallas_window_gather_fn(
                cfg.n_services, cfg.n_windows, N_FEATS,
                interpret=jax.default_backend() != "tpu")
        cap = max(int(capacity), 1)
        # +1: row 0 is the dead slot
        shape_a = (cap + 1, cfg.sw, N_FEATS)
        shape_h = (cap + 1, cfg.sw, cfg.n_hist_buckets)
        if engine == "numpy":
            self.agg = np.zeros(shape_a, np.float32)
            self.hist = np.zeros(shape_h, np.float32)
        else:
            self.agg = jnp.zeros(shape_a, jnp.float32)
            self.hist = jnp.zeros(shape_h, jnp.float32)
        self._free: list = []
        self._next = 1
        S, W = cfg.n_services, cfg.n_windows
        if engine == "numpy":
            return

        # jitted pool ops (jax engine; jax.jit caches per concrete
        # shape, so pool growth or new lane-bucket widths just add
        # compile-cache entries — warm() precompiles the serve grid).
        # The mutating ops DONATE the planes: the pool is the sole
        # owner of its buffers (every read goes through gather /
        # gather_window), so XLA updates the [slots, SW, *] planes in
        # place instead of copying megabytes per fold — the rebind
        # below always installs the op's output before anything can
        # read again.
        @partial(jax.jit, donate_argnums=(0, 1))
        def _scatter(agg, hist, slots, dagg, dhist):
            return agg.at[slots].add(dagg), hist.at[slots].add(dhist)

        @partial(jax.jit, donate_argnums=(0, 1))
        def _put(agg, hist, slot, ragg, rhist):
            return agg.at[slot].set(ragg), hist.at[slot].set(rhist)

        @partial(jax.jit, donate_argnums=(0, 1))
        def _roll(agg, hist, slot, shift):
            # device twin of anomod.stream.roll_ring_state on one row:
            # shift plane columns left, zero the tail.  Taken values
            # pass through verbatim and the tail is exact 0.0, so the
            # result is bit-identical to the host roll.
            idx = jnp.arange(W) + shift
            take = jnp.clip(idx, 0, W - 1)
            live = (idx < W)[None, :, None]

            def roll2(plane, width):
                x = plane[slot].reshape(S, W, width)
                out = jnp.where(live, jnp.take(x, take, axis=1), 0.0)
                return plane.at[slot].set(out.reshape(S * W, width))

            return (roll2(agg, N_FEATS), roll2(hist, cfg.n_hist_buckets))

        @jax.jit
        def _gather_window(agg, slots, cols):
            # [T, S, F]: ONE dispatch materializing only the scored
            # window column of each requested tenant — the batched
            # scorer's gather (the full [SW, F] rows stay on device)
            rows = agg[slots].reshape(slots.shape[0], S, W, N_FEATS)
            return jnp.take_along_axis(
                rows, cols[:, None, None, None], axis=2)[:, :, 0]

        self._scatter_fn = _scatter
        self._put_fn = _put
        self._roll_fn = _roll
        self._gather_window_fn = _gather_window

    @property
    def capacity(self) -> int:
        return int(self.agg.shape[0]) - 1

    @property
    def live_slots(self) -> int:
        return self._next - 1 - len(self._free)

    def acquire(self) -> int:
        """Map a new tenant to a zeroed slot (>= 1), growing the pool by
        doubling on exhaustion (growth concatenates zero rows — existing
        states keep their bits)."""
        if self._free:
            return self._free.pop()
        if self._next > self.capacity:
            xp = np if self.engine == "numpy" else self._jnp
            grow = max(self.capacity, 1)
            self.agg = xp.concatenate(
                [self.agg, xp.zeros((grow,) + self.agg.shape[1:],
                                    xp.float32)])
            self.hist = xp.concatenate(
                [self.hist, xp.zeros((grow,) + self.hist.shape[1:],
                                     xp.float32)])
        slot = self._next
        self._next += 1
        return slot

    def release(self, slot: int) -> None:
        """Return a churned tenant's slot to the free list, zeroed (the
        next acquire must start from a fresh state)."""
        z = self.zero_state()
        self.put(slot, z)
        self._free.append(int(slot))

    def zero_state(self) -> ReplayState:
        cfg = self.cfg
        return ReplayState(
            agg=np.zeros((cfg.sw, N_FEATS), np.float32),
            hist=np.zeros((cfg.sw, cfg.n_hist_buckets), np.float32))

    def gather(self, slot: int) -> ReplayState:
        """On-demand readback of one tenant's state (the get_state seam:
        parity, checkpoint, calibration, migration).  Always a COPY —
        the returned pytree must not alias rows later folds mutate."""
        slot = int(slot)   # a None slot must raise, not np.newaxis
        if self.engine == "numpy":
            return ReplayState(agg=self.agg[slot].copy(),
                               hist=self.hist[slot].copy())
        return ReplayState(agg=np.asarray(self.agg[slot]),
                           hist=np.asarray(self.hist[slot]))

    def put(self, slot: int, state: ReplayState) -> None:
        """Install an externally-built state into a slot (set_state
        seam); a put(gather()) round-trip is byte-identical."""
        slot = int(slot)   # a None slot must raise, not broadcast
        if self.engine == "numpy":
            self.agg[slot] = np.asarray(state.agg, np.float32)
            self.hist[slot] = np.asarray(state.hist, np.float32)
            return
        self.agg, self.hist = self._put_fn(
            self.agg, self.hist, np.int32(slot),
            np.asarray(state.agg, np.float32),
            np.asarray(state.hist, np.float32))

    def roll(self, slot: int, k: int) -> None:
        """Evict the oldest ``k`` ring windows of one tenant's row —
        bit-identical to the host roll_ring_state (values pass through
        verbatim, the tail is exact 0.0)."""
        slot = int(slot)
        shift = min(int(k), self.cfg.n_windows)
        if self.engine == "numpy":
            cfg = self.cfg
            S, W = cfg.n_services, cfg.n_windows
            for plane, width in ((self.agg, N_FEATS),
                                 (self.hist, cfg.n_hist_buckets)):
                x = plane[slot].reshape(S, W, width)   # in-place view
                if shift < W:
                    x[:, :W - shift] = x[:, shift:].copy()
                    x[:, W - shift:] = 0.0
                else:
                    x[:] = 0.0
            return
        self.agg, self.hist = self._roll_fn(self.agg, self.hist,
                                            np.int32(slot),
                                            np.int32(shift))

    def scatter_fold(self, slots, dagg, dhist) -> None:
        """Fold one retired dispatch's per-lane deltas into the pool:
        ``pool[slot] += delta`` on device, in dispatch order.

        ``slots`` has one entry per LIVE lane (dead pad lanes are
        routed to the dead slot 0 here).  Within one dispatch each live
        slot normally appears once (the engine stacks at most one chunk
        per tenant per round) and the scatter performs exactly one f32
        add per slot — the host seam's :func:`fold_delta` bit-for-bit.
        A duplicated slot folds in lane order on both engines: the
        numpy engine's per-row in-place adds apply sequentially, and
        the jax engine splits the dispatch into WAVES (k-th occurrence
        in wave k, other lanes routed to the dead slot — XLA's
        duplicate-index add order is unspecified) — always
        ((state + d_i) + d_j), never a pre-combined d_i + d_j."""
        L = dagg.shape[0]
        if self.engine == "numpy":
            ls = [int(s) for s in slots]
            n = len(ls)
            if not n:
                return
            # the CPU backend's np.asarray of a jax array is a
            # zero-copy view (it blocks until the dispatch's outputs
            # are ready) — the fold reads the deltas in place, with no
            # readback copy and no fresh state allocations: one slice
            # += when the slots are a contiguous run, else per-row
            # in-place adds (measured in bench_fold_sweep.py — a
            # fancy-index += triggers numpy's gather/add/scatter
            # temporaries and loses to both)
            da = np.asarray(dagg)
            dh = np.asarray(dhist)
            lo = ls[0]
            if ls == list(range(lo, lo + n)):
                self.agg[lo:lo + n] += da[:n]
                self.hist[lo:lo + n] += dh[:n]
            else:
                for i, s in enumerate(ls):
                    a = self.agg[s]
                    np.add(a, da[i], out=a)
                    h = self.hist[s]
                    np.add(h, dh[i], out=h)
            return
        live = np.asarray(slots, np.int32)
        n = len(live)
        waves = 1
        wave_of = None
        if n and len(np.unique(live)) != n:
            order = {}
            wave_of = np.zeros(n, np.int32)
            for i, s in enumerate(live.tolist()):
                wave_of[i] = order.get(s, 0)
                order[s] = wave_of[i] + 1
            waves = int(wave_of.max()) + 1
        lane_slots = np.zeros(L, np.int32)
        lane_slots[:n] = live
        for k in range(waves):
            ws = lane_slots.copy()
            if waves > 1:
                mask = np.zeros(L, bool)
                mask[:n] = wave_of == k
                ws[~mask] = 0
            self.agg, self.hist = self._scatter_fn(
                self.agg, self.hist, ws, dagg, dhist)

    def gather_window(self, slots, cols) -> np.ndarray:
        """[T, S, F] host copy of one plane column per tenant — the
        batched scorer's fused gather (one dispatch, only the scored
        columns materialize).  The request pads to the next power of
        two with dead-slot/column-0 entries (sliced off before return),
        so the jitted gather compiles O(log capacity) shapes instead of
        one per distinct tenant count."""
        slots = np.asarray(slots, np.int32)
        cols = np.asarray(cols, np.int32)
        T = slots.shape[0]
        if self._pallas_gather is None and self.engine == "numpy":
            cfg = self.cfg
            r = self.agg.reshape(self.agg.shape[0], cfg.n_services,
                                 cfg.n_windows, N_FEATS)
            return r[slots[:, None], :, cols[:, None]][:, 0]
        pad = 1
        while pad < T:
            pad *= 2
        if pad != T:
            slots = np.concatenate([slots, np.zeros(pad - T, np.int32)])
            cols = np.concatenate([cols, np.zeros(pad - T, np.int32)])
        fn = (self._pallas_gather if self._pallas_gather is not None
              else self._gather_window_fn)
        return np.asarray(fn(self.agg, slots, cols))[:T]

    def gather_rows(self, slots) -> np.ndarray:
        """[T, SW, F] host copy of whole agg rows (calibration-time
        bulk gather; scoring uses :meth:`gather_window`)."""
        return np.asarray(self.agg[np.asarray(slots, np.int32)])

    def warm(self, lane_buckets: Tuple[int, ...] = ()) -> float:
        """Compile the pool's hot ops OUTSIDE the measured serve wall:
        one scatter shape per lane bucket (all-zero deltas into the dead
        slot — numerically a no-op on any state), the put/roll row ops,
        and the power-of-two gather grid up to capacity.  Idempotent
        per shape (jax.jit caches); a no-op on the numpy engine (nothing
        compiles there).  Returns the warm wall."""
        if self.engine == "numpy" and self._pallas_gather is None:
            return 0.0
        t0 = time.perf_counter()
        cfg = self.cfg
        if self.engine != "numpy":
            for lanes in lane_buckets:
                self.scatter_fold(
                    [0], np.zeros((lanes, cfg.sw, N_FEATS), np.float32),
                    np.zeros((lanes, cfg.sw, cfg.n_hist_buckets),
                             np.float32))
            self.put(0, self.zero_state())
            self.roll(0, 0)
        pad = 1
        while True:
            self.gather_window(np.zeros(pad, np.int32),
                               np.zeros(pad, np.int32))
            if pad >= self.capacity:
                break
            pad *= 2
        if self.engine != "numpy":
            self.agg.block_until_ready()
        return time.perf_counter() - t0


def make_replay_fn(cfg: ReplayConfig, with_hll: bool = False,
                   inner_repeats: int = 1):
    """Build the jitted replay: scan over chunks, one-hot matmul aggregation.

    ``with_hll=True`` additionally maintains per-service distinct-trace-count
    HLL registers ([S, 2^p] int32, merged exactly by max) — the streaming
    replacement for the reference's exact trace-ID sets
    (trace_collector.py:358-360).

    ``inner_repeats > 1`` replays the staged chunks that many times inside one
    dispatch (a fori_loop around the scan): device-side corpus replication for
    throughput measurement without tiling the host arrays — the HBM working
    set stays one copy while the counted span volume scales.
    """
    import jax
    import jax.numpy as jnp

    SW, H, M = cfg.sw, cfg.n_hist_buckets, cfg.hll_m
    chunk_step = make_chunk_step(cfg, with_hll=with_hll)

    def replay(chunks):
        state = ReplayState(
            agg=jnp.zeros((SW, N_FEATS), jnp.float32),
            hist=jnp.zeros((SW, H), jnp.float32),
            hll=(jnp.zeros((cfg.n_services, M), jnp.int32)
                 if with_hll else None))
        if inner_repeats > 1:
            state = jax.lax.fori_loop(
                0, inner_repeats,
                lambda _, st: jax.lax.scan(chunk_step, st, chunks)[0],
                state)
        else:
            state, _ = jax.lax.scan(chunk_step, state, chunks)
        return state

    return jax.jit(replay)


def replay_numpy(chunks, cfg: ReplayConfig) -> ReplayState:
    """CPU oracle for the replay aggregation."""
    SW, H = cfg.sw, cfg.n_hist_buckets
    agg = np.zeros((SW, N_FEATS), np.float32)
    hist = np.zeros((SW, H), np.float32)
    sid = chunks["sid"].reshape(-1)
    valid = chunks["valid"].reshape(-1) > 0
    sid = sid[valid]
    feats = np.stack([
        chunks["valid"].reshape(-1)[valid],
        chunks["err"].reshape(-1)[valid],
        chunks["s5"].reshape(-1)[valid],
        chunks["dur_raw"].reshape(-1)[valid],
        chunks["dur"].reshape(-1)[valid],
        (chunks["dur"] ** 2).reshape(-1)[valid],
    ], axis=1)
    np.add.at(agg, sid, feats.astype(np.float32))
    bucket = np.clip(chunks["dur"].reshape(-1)[valid].astype(np.int32), 0, H - 1)
    np.add.at(hist, (sid, bucket), 1.0)
    return ReplayState(agg=agg, hist=hist)


def percentile_from_hist(hist: np.ndarray, q: float,
                         as_us: bool = False) -> np.ndarray:
    """Per-row percentile from the log-latency histogram, linearly
    interpolated within the winning bucket (continuous log1p-µs value
    instead of a bare bucket index; ``as_us`` converts back to µs).

    Detection deltas only need bucket resolution, but a reported "p99"
    should not quantize to 16 levels.  For reporting-grade accuracy use
    :func:`replay_percentiles`, which runs the t-digest plane over the same
    segments."""
    cum = np.cumsum(hist, axis=-1)
    total = cum[..., -1:]
    target = q * np.maximum(total, 1e-30)
    idx = np.minimum((cum < target).sum(axis=-1), hist.shape[-1] - 1)
    in_bucket = np.take_along_axis(hist, idx[..., None], axis=-1)[..., 0]
    below = np.take_along_axis(np.concatenate(
        [np.zeros_like(cum[..., :1]), cum], axis=-1),
        idx[..., None], axis=-1)[..., 0]
    frac = np.where(in_bucket > 0,
                    (target[..., 0] - below) / np.maximum(in_bucket, 1e-30),
                    0.5)
    p = idx.astype(np.float32) + np.clip(frac, 0.0, 1.0).astype(np.float32)
    p = np.where(total[..., 0] > 0, p, 0.0).astype(np.float32)  # empty row = 0
    return np.expm1(p).astype(np.float32) if as_us else p


def _resolve_tdigest_engine(engine: str) -> str:
    """Normalize the digest-engine selector: "host" (numpy build), "xla"
    (jitted one-hot build over the same staged lanes), "pallas" (Mosaic
    MXU kernel; interpret mode off-TPU), or "auto" — env override
    ``ANOMOD_TDIGEST_ENGINE`` first, else "xla" iff the default JAX
    backend is a TPU, "host" elsewhere.

    The Mosaic kernel is OPT-IN only (``ANOMOD_TDIGEST_ENGINE=pallas``):
    the committed on-chip rematches show it does not beat the XLA build at
    either production regime — 0.956x at the replay-plane shape (1M values
    / 2976 segments) and 0.971x at long skewed lanes (2M / 256 segments,
    L=8064), bench_runs/20260731T011001Z + T011102Z — so auto must not
    route through it.  Auto initializes the backend to look at it; callers
    that must stay host-only in an unknown device environment pass
    engine="host"."""
    engine = (engine or "auto").strip().lower()
    if engine == "auto":
        engine = os.environ.get(
            "ANOMOD_TDIGEST_ENGINE", "").strip().lower() or "auto"
    if engine == "auto":
        import jax
        engine = "xla" if jax.default_backend() == "tpu" else "host"
    if engine not in ("host", "xla", "pallas"):
        raise ValueError(f"unknown t-digest engine {engine!r}")
    return engine


@lru_cache(maxsize=None)
def _xla_tdigest_build(k: int):
    """One jitted XLA digest build per centroid count (compile-cached)."""
    import jax
    import jax.numpy as jnp

    from anomod.ops.tdigest import tdigest_build
    return jax.jit(lambda p, w: tdigest_build(p, k=k, weights=w, xp=jnp))


def _tdigest_by_segment_xla(values, segment_ids, n_segments: int, k: int):
    """Per-segment digests through the jitted XLA one-hot build — the TPU
    auto default.  Host :func:`segment_pad` staging with the kernel path's
    exact lane layout (pad_to=128), so switching engines changes only the
    build, never the staged lanes."""
    from anomod.ops.tdigest import segment_pad
    padded, weights = segment_pad(np.asarray(values, np.float32),
                                  np.asarray(segment_ids), n_segments,
                                  pad_to=128)
    return _xla_tdigest_build(k)(padded, weights)


def _digests_from_staged(chunks, cfg: ReplayConfig, k: int, engine: str):
    """Per-segment t-digest plane from already-staged chunk columns — the
    one engine dispatch shared by every digest entry so a caller that
    already paid ``stage_columns`` (e.g. the combined per-edge reporting
    pass) never re-stages for the digest plane."""
    from anomod.ops.tdigest import TDigest
    sid = chunks["sid"].reshape(-1)
    dur = chunks["dur"].reshape(-1)       # log1p(duration_us), staged
    real = sid < cfg.sw
    engine = _resolve_tdigest_engine(engine)
    if engine == "pallas":
        from anomod.ops.pallas_tdigest import tdigest_by_segment_pallas
        digests = tdigest_by_segment_pallas(dur[real], sid[real], cfg.sw, k=k)
    elif engine == "xla":
        digests = _tdigest_by_segment_xla(dur[real], sid[real], cfg.sw, k=k)
    else:
        from anomod.ops.tdigest import tdigest_by_segment
        digests = tdigest_by_segment(dur[real], sid[real], cfg.sw, k=k)
    return TDigest(mean=np.asarray(digests.mean),
                   weight=np.asarray(digests.weight))


def replay_digests(batch: SpanBatch, cfg: Optional[ReplayConfig] = None,
                   k: int = 64, engine: str = "auto"):
    """The per-(service, window) t-digest plane over the exact segments the
    replay aggregates: [S*W, K] log1p-µs digests (TDigest NamedTuple,
    host-resident numpy arrays — one device transfer regardless of how many
    quantiles are queried afterwards).

    This is the featurization entry the BASELINE names: on a TPU backend
    (engine="auto") the build runs through the jitted XLA one-hot build;
    elsewhere the numpy build.  The Mosaic kernel
    (anomod.ops.pallas_tdigest) remains available as
    ``ANOMOD_TDIGEST_ENGINE=pallas`` but measured no faster than XLA at
    production shapes (see _resolve_tdigest_engine).
    Digests are built in log1p domain — service latencies are heavy-tailed
    and linear-domain centroids smear the p99 tail."""
    cfg = cfg or ReplayConfig(n_services=len(batch.services))
    chunks, _ = stage_columns(batch, cfg)
    return _digests_from_staged(chunks, cfg, k, engine)


def replay_percentiles(batch: SpanBatch, cfg: Optional[ReplayConfig] = None,
                       qs: Tuple[float, ...] = (0.5, 0.95, 0.99),
                       k: int = 64, engine: str = "auto") -> np.ndarray:
    """Reporting-grade per-(service, window) latency percentiles in µs from
    the :func:`replay_digests` plane.

    Returns [S*W, len(qs)] float32.  The streaming digests bound quantile
    error by centroid capacity instead of the histogram's 16-bucket
    quantization — this wires the t-digest plane into the replay path for
    every consumer that reports percentiles rather than detection deltas."""
    from anomod.ops.tdigest import tdigest_quantile
    digests = replay_digests(batch, cfg, k=k, engine=engine)
    out = np.stack([np.expm1(tdigest_quantile(digests, q)) for q in qs],
                   axis=-1)
    return out.astype(np.float32)


def edge_keyed_batch(batch: SpanBatch):
    """Re-key spans to observed call-graph edges: each span maps to the
    (parent-service, own-service) edge (roots and own-parented spans to
    the (svc, svc) self-edge).  Returns ``(batch', edge_table)`` where
    ``batch'.service`` holds dense edge ids and ``edge_table[i]`` is the
    (caller, callee) service-id pair of edge ``i``.

    Parent resolution uses the batch-global ``parent`` row indices, so
    this must run on a FULL corpus (anomod.stream.resolve_parent_services
    has the same contract for the streaming path)."""
    psvc = batch.service.copy()            # default: self-edge
    has = batch.parent >= 0
    psvc[has] = batch.service[batch.parent[has]]
    pairs = psvc.astype(np.int64) * len(batch.services) + batch.service
    uniq, inv = np.unique(pairs, return_inverse=True)
    table = tuple((int(p // len(batch.services)),
                   int(p % len(batch.services))) for p in uniq.tolist())
    return batch._replace(service=inv.astype(np.int32)), table


def _edge_staged(batch: SpanBatch, cfg: Optional[ReplayConfig]):
    """One edge re-key + staging pass shared by every per-edge plane."""
    eb, table = edge_keyed_batch(batch)
    base = cfg or ReplayConfig(n_services=len(batch.services))
    cfg_e = dataclasses.replace(base, n_services=len(table))
    chunks, _ = stage_columns(eb, cfg_e)
    return chunks, cfg_e, table


def _edge_distinct_from_staged(chunks, cfg_e: ReplayConfig):
    from anomod.ops.hll import hll_estimate
    state = make_replay_fn(cfg_e, with_hll=True)(chunks)
    return np.asarray(
        [hll_estimate(r) for r in np.asarray(state.hll)], np.float64)


def _edge_percentiles_from_staged(chunks, cfg_e: ReplayConfig,
                                  qs: Tuple[float, ...], k: int,
                                  engine: str) -> np.ndarray:
    from anomod.ops.tdigest import tdigest_quantile
    digests = _digests_from_staged(chunks, cfg_e, k, engine)
    out = np.stack([np.expm1(tdigest_quantile(digests, q)) for q in qs],
                   axis=-1)
    return out.astype(np.float32)


def replay_edge_distinct(batch: SpanBatch,
                         cfg: Optional[ReplayConfig] = None):
    """PER-EDGE distinct-trace counts via the HLL register plane: how many
    distinct traces cross each observed call-graph edge — the HLL half of
    the BASELINE's per-edge featurization (the t-digest half is
    :func:`replay_edge_percentiles`).  Runs the spans re-keyed to dense
    edge ids through the same jitted chunk step the per-service HLL
    uses; registers merge by max, so shards/streams combine exactly.

    Returns ``(counts, edge_table)``: float64 [E] HLL estimates plus the
    edge id → (caller, callee) service-id table."""
    chunks, cfg_e, table = _edge_staged(batch, cfg)
    return _edge_distinct_from_staged(chunks, cfg_e), table


def replay_edge_percentiles(batch: SpanBatch,
                            cfg: Optional[ReplayConfig] = None,
                            qs: Tuple[float, ...] = (0.5, 0.95, 0.99),
                            k: int = 64, engine: str = "auto"):
    """PER-EDGE latency percentiles: the t-digest plane built over
    (call-graph edge, window) segments instead of (service, window) —
    the per-edge featurization the BASELINE north star names, through
    the same engine dispatch (engine="auto": XLA build on TPU).

    Returns ``(percentiles, edge_table)``: [E*W, len(qs)] float32 µs plus
    the edge id → (caller, callee) service-id table.  Per-edge p99 is
    the reporting view that localizes a slow LINK (the callee side of
    one caller's calls) that per-service percentiles smear across the
    callee's whole traffic mix."""
    chunks, cfg_e, table = _edge_staged(batch, cfg)
    return _edge_percentiles_from_staged(chunks, cfg_e, qs, k, engine), table


def replay_edge_features(batch: SpanBatch,
                         cfg: Optional[ReplayConfig] = None,
                         qs: Tuple[float, ...] = (0.5, 0.95, 0.99),
                         k: int = 64, engine: str = "auto"):
    """Both per-edge planes — t-digest percentiles AND HLL distinct-trace
    counts — from ONE edge re-key + staging pass (the combined reporting
    view ``anomod replay --edge-percentiles`` serves; running the two
    single-plane entries back-to-back would re-key, re-stage and re-scan
    the full corpus twice for the same answer).

    Returns ``(percentiles, counts, edge_table)`` with the same shapes and
    semantics as :func:`replay_edge_percentiles` /
    :func:`replay_edge_distinct`."""
    chunks, cfg_e, table = _edge_staged(batch, cfg)
    pct = _edge_percentiles_from_staged(chunks, cfg_e, qs, k, engine)
    return pct, _edge_distinct_from_staged(chunks, cfg_e), table


def stage_pallas_planes(chunks, xp=np):
    """Flatten staged chunk columns into the fused pallas kernel's layout:
    sid [N] plus the feature-major [6, N] plane stack (anomod.ops.
    pallas_replay.PLANES order; dur² is materialized once so the kernel
    reads every plane in its natural layout).  The single definition of
    the row order — host staging (xp=np) and the sharded replay's
    on-device path (xp=jnp) both use it."""
    sid = chunks["sid"].reshape(-1)
    dur = chunks["dur"].reshape(-1)
    planes = xp.stack([
        chunks["valid"].reshape(-1),
        chunks["err"].reshape(-1),
        chunks["s5"].reshape(-1),
        chunks["dur_raw"].reshape(-1),
        dur,
        dur * dur,
    ])
    return sid, planes


def pallas_block(chunk_size: int) -> int:
    """Pallas kernel block size for a staged corpus: must divide the span
    count (a chunk_size multiple) — chunk_size's largest power-of-2 factor,
    capped at the VMEM-tuned 4096."""
    block = min(4096, chunk_size & -chunk_size)
    if block < 128:
        raise ValueError(
            "pallas replay kernel needs chunk_size with a power-of-2 "
            f"factor >= 128; got chunk_size={chunk_size}")
    return block


@dataclasses.dataclass
class ThroughputResult:
    n_spans: int
    wall_s: float
    spans_per_sec: float
    compile_s: float
    kernel: str = "xla"
    raw_wall_s: Tuple[float, ...] = ()  # per-repeat walls (median -> wall_s)


def measure_throughput(batch: SpanBatch, cfg: Optional[ReplayConfig] = None,
                       repeats: int = 3, replicate: int = 1,
                       kernel: str = "xla") -> ThroughputResult:
    """Compile, warm up, then time the replay over the staged corpus.

    Timing reads the aggregate state back to host each iteration — over a
    tunneled device, ``block_until_ready`` alone returns before execution
    finishes, so a host read-back is the only honest barrier.  ``replicate``
    replays the staged chunks that many times *on device* (inner fori_loop /
    outer grid dimension) to amortize the fixed dispatch/RPC overhead into a
    steady-state number without inflating the host arrays or the HBM
    working set.  ``kernel`` selects the aggregation path: "xla" (scan +
    one-hot matmuls), "pallas" (the fused anomod.ops.pallas_replay
    kernel), "pallas-sorted" (its sorted-window variant — one-time host
    pre-sort into aligned 128-segment windows so the kernel's one-hot is
    128 lanes wide instead of SW+1), or "numpy" — the framework's
    cpu-backend engine
    (BASELINE.json's backend switch): direct scatter-add over the staged
    columns, which is the right shape for a host core (~13x the XLA scan
    on one CPU core, where one-hot matmuls are wasted work) and doubles as
    the parity oracle both device kernels are tested against.
    """
    if kernel not in ("xla", "pallas", "pallas-sorted", "numpy"):
        raise ValueError(f"unknown replay kernel {kernel!r} (expected "
                         "'xla', 'pallas', 'pallas-sorted' or 'numpy')")
    cfg = cfg or ReplayConfig(n_services=len(batch.services))
    chunks_np, n = stage_columns(batch, cfg)
    n *= replicate

    # Per-kernel run_once() -> summed span count (host float); one shared
    # timing/median/count-assert block below so tolerance and median policy
    # can't silently diverge between engines.
    if kernel == "numpy":
        def run_once():
            for _r in range(replicate):        # host analog of inner_repeats
                out = replay_numpy(chunks_np, cfg)
            return float(out.agg[:, F_COUNT].astype(np.float64).sum()
                         ) * replicate
    elif kernel == "pallas":
        import jax
        from anomod.io.prefetch import device_put_columns
        from anomod.ops.pallas_replay import make_pallas_replay_fn
        sid_np, planes_np = stage_pallas_planes(chunks_np)
        staged = device_put_columns({"sid": sid_np, "planes": planes_np})
        sid, planes = staged["sid"], staged["planes"]
        # off-TPU backends can't execute Mosaic — run the kernel's
        # interpret path so this branch stays testable on the CPU mesh
        interpret = jax.devices()[0].platform != "tpu"
        pfn = make_pallas_replay_fn(cfg.sw, cfg.n_hist_buckets,
                                    inner_repeats=replicate,
                                    block=pallas_block(cfg.chunk_size),
                                    interpret=interpret)
        def run_once():
            agg = np.asarray(pfn(sid, planes))
            return float(agg[:, F_COUNT].astype(np.float64).sum())
    elif kernel == "pallas-sorted":
        import jax
        from anomod.ops.pallas_replay import (make_pallas_replay_sorted_fn,
                                              stage_sorted_planes)
        sid_np, planes_np = stage_pallas_planes(chunks_np)
        block = pallas_block(cfg.chunk_size)
        # one-time host re-stage: sort spans into aligned 128-segment
        # windows so the kernel's one-hot is 128 lanes wide, not SW+1
        sid_l, planes_s, wids = stage_sorted_planes(
            sid_np, planes_np, cfg.sw, block=block)
        from anomod.io.prefetch import device_put_columns
        staged = device_put_columns(
            {"sid": sid_l, "planes": planes_s, "wids": wids})
        sid_d, planes_d, wids_d = (staged["sid"], staged["planes"],
                                   staged["wids"])
        interpret = jax.devices()[0].platform != "tpu"
        pfn = make_pallas_replay_sorted_fn(cfg.sw, cfg.n_hist_buckets,
                                           block=block,
                                           inner_repeats=replicate,
                                           interpret=interpret)
        def run_once():
            agg = np.asarray(pfn(sid_d, planes_d, wids_d))
            return float(agg[:, F_COUNT].astype(np.float64).sum())
    else:
        import jax  # noqa: F401 — backend init before the staged puts
        # double-buffered staging (anomod.io.prefetch): the H2D copy of
        # column j overlaps the enqueue of column j+1
        from anomod.io.prefetch import device_put_columns
        chunks = device_put_columns(chunks_np)
        xfn = make_replay_fn(cfg, inner_repeats=replicate)
        def run_once():
            agg = np.asarray(xfn(chunks).agg)
            return float(agg[:, F_COUNT].astype(np.float64).sum())

    from anomod import obs
    t0 = time.perf_counter()
    run_once()                                  # compile / cache warm-up
    compile_s = 0.0 if kernel == "numpy" else time.perf_counter() - t0
    if compile_s:
        obs.counter("anomod_replay_compile_total", kernel=kernel).inc()
        obs.counter("anomod_replay_compile_seconds_total",
                    kernel=kernel).inc(compile_s)
    dispatch_s = obs.histogram("anomod_replay_dispatch_seconds",
                               kernel=kernel)
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        total = run_once()
        times.append(time.perf_counter() - t0)
        dispatch_s.observe(times[-1])
    # Sanity check with f32 headroom: per-segment counts accumulate on device
    # in f32 and lose exactness past 2^24 spans per (service, window) segment,
    # so allow a small relative slack instead of demanding exact equality.
    assert abs(total - n) <= max(8.0, 1e-6 * n), \
        f"span count mismatch: {total} != {n}"
    wall = sorted(times)[len(times) // 2]
    return ThroughputResult(n_spans=n, wall_s=wall,
                            spans_per_sec=n / wall, compile_s=compile_s,
                            kernel=kernel, raw_wall_s=tuple(times))
