"""Deploy topology models + coverage-agent manifest injection.

The reference describes its two systems-under-test declaratively:

- **SN**: a Docker Compose file of 11 gcov-instrumented C++ services (image
  ``socialnetwork-gcov``, ``GCOV_PREFIX``/``GCOV_PREFIX_STRIP`` env, a shared
  ``/coverage-reports`` mount, explicit ``/usr/local/bin/<Service>``
  entrypoints) plus per-service Mongo/Redis/Memcached stores and the
  observability stack — Jaeger :16686, nginx gateway :8080, Prometheus
  :9090, cAdvisor, node-exporter (docker-compose-gcov.yml:2-424).
- **TT**: ~40 k8s Deployments, each with a SkyWalking agent initContainer +
  dual ``-javaagent`` ``JAVA_TOOL_OPTIONS``, nacos configMap env, resource
  requests/limits, and a TCP readiness probe
  (sw_deploy.tcpserver.includes.yaml:1-92).  The JaCoCo half of that
  manifest is produced by a deploy-time rewriter
  (coverage_tools/inject_jacoco_k8s.py:68-213).

This module regenerates both topologies from the framework's service tables
(single source of truth — the same lists the generator, graph builder, and
labels use) and re-implements the JaCoCo rewriter as pure dict→dict
functions, so manifests round-trip through PyYAML and the coverage wiring is
testable without a cluster.
"""

from __future__ import annotations

import copy
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from anomod.synth import SN_SERVICES, TT_SERVICES

# ---------------------------------------------------------------------------
# SN compose model (docker-compose-gcov.yml)
# ---------------------------------------------------------------------------

#: service → backing stores, from the compose dependency wiring
#: (docker-compose-gcov.yml:227-322; redis containers are the chaos targets
#: of the DB_Redis_CacheLimit_* experiments).
SN_STORES: Dict[str, Tuple[str, ...]] = {
    "social-graph-service": ("social-graph-mongodb", "social-graph-redis"),
    "home-timeline-service": ("home-timeline-redis",),
    "user-timeline-service": ("user-timeline-mongodb", "user-timeline-redis"),
    "compose-post-service": ("compose-post-redis",),
    "post-storage-service": ("post-storage-mongodb", "post-storage-memcached"),
    "user-service": ("user-mongodb", "user-memcached"),
    "media-service": ("media-mongodb", "media-memcached"),
    "url-shorten-service": ("url-shorten-mongodb", "url-shorten-memcached"),
    "user-mention-service": (),
    "unique-id-service": (),
    "text-service": (),
}

SN_OBSERVABILITY: Tuple[str, ...] = (
    "jaeger-agent", "prometheus", "cadvisor", "node-exporter")


def _cpp_process_name(service: str) -> str:
    """compose entrypoint binary: CamelCase of the service name
    (docker-compose-gcov.yml:21 e.g. /usr/local/bin/SocialGraphService)."""
    return "".join(w.capitalize() for w in service.split("-"))


def sn_compose() -> Dict:
    """The SN testbed as a compose document (gcov instrumentation included)."""
    services: Dict[str, Dict] = {}
    port = 10000
    for svc in SN_SERVICES:
        if svc == "nginx-web-server":
            services[svc] = {
                "image": "yg397/openresty-thrift:xenial",
                "hostname": svc,
                "ports": ["8080:8080"],        # the HTTP gateway (:340-345)
                "depends_on": [s for s in SN_SERVICES if s != svc],
                "networks": ["socialnetwork"],
                "restart": "always",
            }
            continue
        services[svc] = {
            "image": "socialnetwork-gcov",
            "hostname": svc,
            "ports": [f"{port}:9090"],
            "volumes": ["./config:/social-network-microservices/config:ro",
                        "./coverage-reports:/coverage-reports"],
            "networks": ["socialnetwork"],
            "depends_on": ["jaeger-agent", *SN_STORES.get(svc, ())],
            "restart": "always",
            "environment": [
                "COVERALLS_DIRECTORY=/coverage-reports",
                "GCOV_PREFIX=/social-network-microservices/build",
                "GCOV_PREFIX_STRIP=2",
            ],
            "entrypoint": [f"/usr/local/bin/{_cpp_process_name(svc)}"],
        }
        port += 1
    for stores in SN_STORES.values():
        for store in stores:
            kind = store.rsplit("-", 1)[1]
            services[store] = {
                "image": {"mongodb": "mongo:4.4.6", "redis": "redis",
                          "memcached": "memcached"}[kind],
                "hostname": store,
                "networks": ["socialnetwork"],
                "restart": "always",
            }
    services["jaeger-agent"] = {
        "image": "jaegertracing/all-in-one:latest",
        "hostname": "jaeger-agent",
        "ports": ["16686:16686"],
        "networks": ["socialnetwork"],
        "restart": "always",
    }
    services["prometheus"] = {
        "image": "prom/prometheus:latest",
        "ports": ["9090:9090"],
        "networks": ["socialnetwork"],
        "restart": "always",
    }
    services["cadvisor"] = {
        "image": "gcr.io/cadvisor/cadvisor:latest",
        "ports": ["8081:8080"],
        "networks": ["socialnetwork"],
        "restart": "always",
    }
    services["node-exporter"] = {
        "image": "prom/node-exporter:latest",
        "ports": ["9100:9100"],
        "networks": ["socialnetwork"],
        "restart": "always",
    }
    return {"version": "3.9", "services": services,
            "networks": {"socialnetwork": {"driver": "bridge"}}}


def sn_container_name(service_or_store: str) -> str:
    """Compose container naming (docker stop targets,
    automated_multimodal_collection.sh:466)."""
    return f"socialnetwork_{service_or_store}_1"


# ---------------------------------------------------------------------------
# TT k8s manifest model (sw_deploy.tcpserver.includes.yaml)
# ---------------------------------------------------------------------------

#: JaCoCo excludes defaulted by the injector (inject_jacoco_k8s.py:223).
DEFAULT_EXCLUDES = ("org.springframework.*;ch.qos.logback.*;org.apache.*;"
                    "com.alibaba.*;javax.*;lombok.*;sun.*")

_JACOCO_AGENT_JAR = "/jacoco/jacocoagent.jar"
_SW_AGENT_OPT = "-javaagent:/skywalking/agent/skywalking-agent.jar"

_TT_BASE_PORT = 18000


def tt_service_port(service: str) -> int:
    """Stable per-service container port (manifests pin one port per service,
    e.g. ts-admin-basic-info-service :18767)."""
    return _TT_BASE_PORT + TT_SERVICES.index(service)


def service_package_prefix(service: str) -> str:
    """Dominant Java package prefix for a ts-* service, the quantity the
    reference infers by scanning sources (inject_jacoco_k8s.py:184-213:
    `package adminbasic.…` → `adminbasic.*`).  Without sources we derive it
    from the service name the same way the real packages are named: strip
    the ts- prefix / -service suffix and drop dashes."""
    stem = service
    if stem.startswith("ts-"):
        stem = stem[3:]
    if stem.endswith("-service"):
        stem = stem[: -len("-service")]
    return stem.replace("-", "") + ".*"


def tt_deployment(service: str, with_tracing: bool = True) -> Dict:
    """One TT service Deployment in the reference manifest shape (SkyWalking
    init container + agent env; JaCoCo is added separately by inject_jacoco,
    matching the reference's deploy-time rewrite flow)."""
    port = tt_service_port(service)
    container = {
        "name": service,
        "image": f"codewisdom/{service}:1.0.0",
        "imagePullPolicy": "IfNotPresent",
        "volumeMounts": [],
        "env": [
            {"name": "NODE_IP",
             "valueFrom": {"fieldRef": {"fieldPath": "status.hostIP"}}},
        ],
        "envFrom": [{"configMapRef": {"name": "nacos"}}],
        "ports": [{"containerPort": port}],
        "resources": {
            "requests": {"cpu": "100m", "memory": "300Mi"},
            "limits": {"cpu": "500m", "memory": "2000Mi"},
        },
        "readinessProbe": {
            "tcpSocket": {"port": port},
            "initialDelaySeconds": 60, "periodSeconds": 10,
            "timeoutSeconds": 5,
        },
    }
    pod_spec: Dict = {"volumes": [], "initContainers": [],
                      "containers": [container]}
    if with_tracing:
        pod_spec["volumes"].append({"name": "skywalking-agent", "emptyDir": {}})
        pod_spec["initContainers"].append({
            "name": "agent-container",
            "image": "apache/skywalking-java-agent:8.8.0-alpine",
            "volumeMounts": [{"name": "skywalking-agent",
                              "mountPath": "/agent"}],
            "command": ["/bin/sh"],
            "args": ["-c", "cp -R /skywalking/agent /agent/"],
        })
        container["volumeMounts"].append(
            {"name": "skywalking-agent", "mountPath": "/skywalking"})
        container["env"] += [
            {"name": "SW_AGENT_COLLECTOR_BACKEND_SERVICES",
             "value": "skywalking:11800"},
            {"name": "SW_AGENT_NAME",
             "valueFrom": {"fieldRef":
                           {"fieldPath": "metadata.labels['app']"}}},
            {"name": "JAVA_TOOL_OPTIONS", "value": _SW_AGENT_OPT},
        ]
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": service},
        "spec": {
            "selector": {"matchLabels": {"app": service}},
            "replicas": 1,
            "template": {
                "metadata": {"labels": {"app": service}},
                "spec": pod_spec,
            },
        },
    }


def tt_manifests(with_tracing: bool = True) -> List[Dict]:
    return [tt_deployment(s, with_tracing) for s in TT_SERVICES]


# ---------------------------------------------------------------------------
# JaCoCo injection (inject_jacoco_k8s.py:68-182 semantics, fresh impl)
# ---------------------------------------------------------------------------

def _jacoco_agent_opt(mode: str, tcp_port: int, includes: Optional[str],
                      excludes: Optional[str]) -> str:
    if mode == "file":
        opt = (f"-javaagent:{_JACOCO_AGENT_JAR}="
               "output=file,destfile=/coverage/jacoco-$(HOSTNAME).exec,"
               "append=true")
    else:
        opt = (f"-javaagent:{_JACOCO_AGENT_JAR}="
               f"output=tcpserver,address=*,port={tcp_port},"
               "sessionid=$(HOSTNAME),append=true")
    if includes:
        opt += f",includes={includes}"
    if excludes:
        opt += f",excludes={excludes}"
    return opt


def _ensure_named(items: List[Dict], entry: Dict) -> bool:
    """Append entry unless an item with the same name exists; return changed."""
    if any(it.get("name") == entry["name"] for it in items):
        return False
    items.append(entry)
    return True


def inject_jacoco_pod_spec(pod_spec: Dict, *, mode: str = "tcpserver",
                           tcp_port: int = 6300,
                           includes: Optional[str] = None,
                           excludes: Optional[str] = DEFAULT_EXCLUDES) -> bool:
    """Add the JaCoCo runtime to one pod spec in place; returns whether
    anything changed.  Idempotent; preserves an existing JAVA_TOOL_OPTIONS
    (the SkyWalking agent) by appending after it."""
    changed = False
    volumes = pod_spec.setdefault("volumes", [])
    changed |= _ensure_named(volumes, {"name": "jacoco-vol", "emptyDir": {}})
    changed |= _ensure_named(volumes, {"name": "coverage-vol", "emptyDir": {}})

    inits = pod_spec.setdefault("initContainers", [])
    changed |= _ensure_named(inits, {
        "name": "init-jacoco",
        "image": "curlimages/curl:7.88.1",
        "command": ["sh", "-c"],
        "args": ["set -e; mkdir -p /jacoco && "
                 "curl -sSL -o /jacoco/jacocoagent.jar "
                 "https://repo1.maven.org/maven2/org/jacoco/org.jacoco.agent/"
                 "0.8.10/org.jacoco.agent-0.8.10-runtime.jar && "
                 "curl -sSL -o /jacoco/jacococli.jar "
                 "https://repo1.maven.org/maven2/org/jacoco/org.jacoco.cli/"
                 "0.8.10/org.jacoco.cli-0.8.10-nodeps.jar"],
        "volumeMounts": [{"name": "jacoco-vol", "mountPath": "/jacoco"}],
        "imagePullPolicy": "IfNotPresent",
    })

    agent_opt = _jacoco_agent_opt(mode, tcp_port, includes, excludes)
    for container in pod_spec.get("containers") or []:
        env = container.setdefault("env", [])
        existing = next((e for e in env
                         if e.get("name") == "JAVA_TOOL_OPTIONS"), None)
        if existing is None:
            env.append({"name": "JAVA_TOOL_OPTIONS", "value": agent_opt})
            changed = True
        elif agent_opt not in (existing.get("value") or ""):
            existing["value"] = ((existing.get("value") or "") +
                                 " " + agent_opt).strip()
            changed = True
        mounts = container.setdefault("volumeMounts", [])
        changed |= _ensure_named(mounts, {"name": "jacoco-vol",
                                          "mountPath": "/jacoco"})
        changed |= _ensure_named(mounts, {"name": "coverage-vol",
                                          "mountPath": "/coverage"})
    return changed


def inject_jacoco(docs: Iterable[Dict], *, mode: str = "tcpserver",
                  tcp_port: int = 6300,
                  svc_includes: Optional[Dict[str, str]] = None,
                  excludes: Optional[str] = DEFAULT_EXCLUDES,
                  auto_includes: bool = True) -> Tuple[List[Dict], int]:
    """Rewrite a manifest stream: inject JaCoCo into every workload document
    (Deployment/StatefulSet/DaemonSet — inject_jacoco_k8s.py:160-166).
    Returns (new docs, number changed).  Input docs are not mutated."""
    out: List[Dict] = []
    n_changed = 0
    for doc in docs:
        doc = copy.deepcopy(doc)
        out.append(doc)
        if not isinstance(doc, dict) or doc.get("kind") not in (
                "Deployment", "StatefulSet", "DaemonSet"):
            continue
        pod_spec = doc.get("spec", {}).get("template", {}).get("spec")
        if not isinstance(pod_spec, dict):
            continue
        name = doc.get("metadata", {}).get("name") or ""
        includes = (svc_includes or {}).get(name)
        if includes is None and auto_includes and name.startswith("ts-"):
            includes = service_package_prefix(name)
        if inject_jacoco_pod_spec(pod_spec, mode=mode, tcp_port=tcp_port,
                                  includes=includes, excludes=excludes):
            n_changed += 1
    return out, n_changed


def infer_includes_from_packages(packages: Sequence[str]) -> Optional[str]:
    """Dominant top-level package → `<top>.*` (the source-scanning heuristic
    of inject_jacoco_k8s.py:184-213, over an already-extracted package
    list)."""
    counts: Dict[str, int] = {}
    for pkg in packages:
        top = pkg.split(".")[0].strip()
        if top:
            counts[top] = counts.get(top, 0) + 1
    if not counts:
        return None
    return max(counts.items(), key=lambda kv: kv[1])[0] + ".*"
