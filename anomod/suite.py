"""Generated test suites + the run-id cross-modal join (EvoMaster analog).

The reference's workload of record is EvoMaster-generated black-box unittest
suites replayed against the gateway: SN ships 13 tests covering 72 targets
from a 2-minute budget (BlackBox_tests/Final_version_2m/
EvoMaster_successes_Test.py:17-27), TT ships 256 tests covering 825 targets
from a 10-minute budget, every request tagged ``x-evomaster-run-id`` so
traces can be joined back to the driving suite run
(Evomaster/runs/auth_fixed_10m/EvoMaster_successes_Test.py:33-41,65;
run_experiment.sh:534).  Campaigns can also regenerate suites on the fly
from the OpenAPI spec with a time budget (run_experiment.sh:500-555).

Here a suite is *derived* deterministically from the endpoint catalog (the
synthetic SUT's spec): the budget→test-count calibration matches the two
reference data points, tests are success-path request specs with status
assertions, and executing a suite produces BOTH an ApiBatch and the SpanBatch
of traces those requests caused — trace ids carry the run id, so the
cross-modal join the reference does with headers is a first-class indexed
operation here.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from anomod.scenario import (RequestSpec, ScenarioDriver, SyntheticGateway,
                             _spec)
from anomod.schemas import (ApiBatch, KIND_ENTRY, KIND_EXIT, SpanBatch)
from anomod.synth import SN_SERVICES, TT_EDGES, TT_SERVICES

# Reference calibration points: (budget seconds, shipped tests, covered targets)
_CALIBRATION = {"SN": (120.0, 13, 72), "TT": (600.0, 256, 825)}

# SN suite endpoint pool: the wrk2-api surface
# (enhanced_openapi_monitor.py:36-49).
SN_SUITE_ENDPOINTS: Tuple[Tuple[str, str], ...] = (
    ("POST", "/wrk2-api/user/register"),
    ("POST", "/wrk2-api/user/follow"),
    ("POST", "/wrk2-api/user/unfollow"),
    ("POST", "/wrk2-api/user/login"),
    ("POST", "/wrk2-api/post/compose"),
    ("GET", "/wrk2-api/home-timeline/read"),
    ("GET", "/wrk2-api/user-timeline/read"),
    ("GET", "/wrk2-api/user/profile"),
    ("POST", "/wrk2-api/media/upload"),
    ("POST", "/wrk2-api/text/upload"),
    ("POST", "/wrk2-api/url/shorten"),
    ("POST", "/wrk2-api/user-mention/upload"),
)

# wrk2-api path → SN owning service (the nginx route table)
SN_ROUTE = {
    "/wrk2-api/user/register": "user-service",
    "/wrk2-api/user/follow": "social-graph-service",
    "/wrk2-api/user/unfollow": "social-graph-service",
    "/wrk2-api/user/login": "user-service",
    "/wrk2-api/post/compose": "compose-post-service",
    "/wrk2-api/home-timeline/read": "home-timeline-service",
    "/wrk2-api/user-timeline/read": "user-timeline-service",
    "/wrk2-api/user/profile": "user-service",
    "/wrk2-api/media/upload": "media-service",
    "/wrk2-api/text/upload": "text-service",
    "/wrk2-api/url/shorten": "url-shorten-service",
    "/wrk2-api/user-mention/upload": "user-mention-service",
}


@dataclasses.dataclass(frozen=True)
class SuiteTest:
    name: str                      # test_0 … test_N (generated naming)
    spec: RequestSpec
    expect_status: Tuple[int, ...] = (200, 201)


@dataclasses.dataclass(frozen=True)
class Suite:
    testbed: str
    run_id: str
    budget_s: float
    tests: Tuple[SuiteTest, ...]

    @property
    def n_tests(self) -> int:
        return len(self.tests)

    @property
    def covered_targets(self) -> int:
        """Coverage-target count scaled from the reference calibration
        (72 targets at 13 SN tests; 825 at 256 TT tests), saturating at the
        reference ceiling."""
        _, ref_tests, ref_targets = _CALIBRATION[self.testbed]
        return int(round(ref_targets * min(1.0, self.n_tests / ref_tests)))


def n_tests_for_budget(testbed: str, budget_s: float) -> int:
    """Linear budget→tests using the testbed's reference rate."""
    ref_budget, ref_tests, _ = _CALIBRATION[testbed]
    return max(1, int(round(ref_tests * budget_s / ref_budget)))


def _endpoint_pool(testbed: str) -> List[RequestSpec]:
    if testbed == "SN":
        return [_spec(m, p) for m, p in SN_SUITE_ENDPOINTS]
    # TT: the unique request templates one scenario pass exercises
    seen: Dict[str, RequestSpec] = {}
    for s in ScenarioDriver(seed=0).iteration():
        seen.setdefault(s.endpoint, s)
    return [seen[k] for k in sorted(seen)]


def generate_suite(testbed: str, budget_s: Optional[float] = None,
                   n_tests: Optional[int] = None, seed: int = 0,
                   spec: Optional[dict] = None) -> Suite:
    """Deterministic suite from the endpoint catalog.

    ``budget_s`` mirrors the on-the-fly `--maxTime` generation flow
    (run_experiment.sh:523-535); ``n_tests`` pins the count directly (the
    shipped-suite flow).  Defaults to the testbed's reference budget.

    ``spec`` switches the endpoint pool to a parsed OpenAPI/Swagger
    document (anomod.openapi) — the ``--bbSwaggerUrl`` flow: the suite's
    request surface comes from the spec instead of the internal catalog,
    with the same budget calibration and run-id stamping."""
    if testbed not in _CALIBRATION:
        raise ValueError(f"unknown testbed: {testbed!r}")
    if budget_s is None and n_tests is None:
        budget_s = _CALIBRATION[testbed][0]
    if n_tests is None:
        n_tests = n_tests_for_budget(testbed, budget_s)
    if spec is not None:
        from anomod.openapi import endpoint_pool_from_spec
        pool = endpoint_pool_from_spec(spec, seed=seed)
    else:
        pool = _endpoint_pool(testbed)
    rng = np.random.default_rng(seed)
    run_id = "em-" + hashlib.sha1(
        f"{testbed}:{n_tests}:{seed}".encode()).hexdigest()[:12]
    tests = []
    for i in range(n_tests):
        # round-robin guarantees pool coverage; rng breaks phase alignment
        req = pool[i % len(pool)] if i < len(pool) else \
            pool[int(rng.integers(len(pool)))]
        tests.append(SuiteTest(f"test_{i}", req))
    return Suite(testbed, run_id, float(budget_s or 0.0), tuple(tests))


# ---------------------------------------------------------------------------
# Execution: requests + the traces they cause, joined by run id
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class SuiteRun:
    suite: Suite
    api: ApiBatch
    spans: SpanBatch
    passed: np.ndarray          # bool per (iteration, test), flattened
    trace_of_request: np.ndarray  # int32: api record i → trace index

    @property
    def pass_rate(self) -> float:
        return float(self.passed.mean()) if self.passed.size else 0.0


def _service_of(testbed: str, spec: RequestSpec) -> str:
    if testbed == "SN":
        return SN_ROUTE.get(spec.template, "nginx-web-server")
    return spec.service


def _downstream(testbed: str, service: str, rng) -> List[str]:
    """One seeded downstream hop chain from the entry service."""
    if testbed == "SN":
        from anomod.synth import SN_EDGES
        edges = SN_EDGES
    else:
        edges = TT_EDGES
    out: List[str] = []
    cur = service
    for _ in range(2):
        kids = [b for a, b in edges if a == cur]
        if not kids or rng.random() < 0.3:
            break
        cur = kids[int(rng.integers(len(kids)))]
        out.append(cur)
    return out


def run_suite(suite: Suite, iterations: int = 1, seed: int = 0,
              controller=None) -> SuiteRun:
    """Replay the suite ``iterations`` times (collect_all_modalities.sh:152-171
    replays the TT suite EVOMASTER_TEST_ITERATIONS times) against the
    synthetic SUT; emit the api records AND the traces they cause."""
    testbed = suite.testbed
    services = SN_SERVICES if testbed == "SN" else TT_SERVICES
    svc_idx = {s: i for i, s in enumerate(services)}
    gateway_svc = "nginx-web-server" if testbed == "SN" else "ts-gateway-service"
    gw = SyntheticGateway(seed=seed, controller=controller)
    rng = np.random.default_rng(seed + 1)

    # span columns
    trace_c: List[int] = []; parent_c: List[int] = []
    service_c: List[int] = []; endpoint_c: List[int] = []
    start_c: List[int] = []; dur_c: List[int] = []
    err_c: List[bool] = []; status_c: List[int] = []; kind_c: List[int] = []
    trace_ids: List[str] = []
    endpoints: Dict[str, int] = {}
    passed: List[bool] = []
    trace_of_request: List[int] = []

    for it in range(iterations):
        for ti, test in enumerate(suite.tests):
            statuses = gw.execute([test.spec])
            status = statuses[0]
            _, t_s, _, latency_ms, _ = gw.last_row
            passed.append(status in test.expect_status)

            # the trace this request caused, id stamped with the run id
            # (the x-evomaster-run-id join, EvoMaster_successes_Test.py:65)
            tid = len(trace_ids)
            trace_ids.append(f"{suite.run_id}-{it}-{ti}")
            trace_of_request.append(tid)
            ep = endpoints.setdefault(test.spec.endpoint, len(endpoints))
            entry_svc = _service_of(testbed, test.spec)
            start_us = int(t_s * 1e6)
            total_us = max(int(latency_ms * 1e3), 10)

            def emit(svc: str, parent_row: int, kind: int, frac: float) -> int:
                service_c.append(svc_idx.get(svc, 0))
                trace_c.append(tid)
                parent_c.append(parent_row)
                endpoint_c.append(ep)
                start_c.append(start_us + int(total_us * (1 - frac) * 0.2))
                dur_c.append(max(int(total_us * frac), 5))
                err_c.append(status >= 500)
                status_c.append(status)
                kind_c.append(kind)
                return len(trace_c) - 1

            root = emit(gateway_svc, -1, KIND_ENTRY, 1.0)
            ex = emit(gateway_svc, root, KIND_EXIT, 0.9)
            entry = emit(entry_svc, ex, KIND_ENTRY, 0.85)
            prev, prev_svc = entry, entry_svc
            frac = 0.6
            for svc in _downstream(testbed, entry_svc, rng):
                ex2 = emit(prev_svc, prev, KIND_EXIT, frac)
                prev = emit(svc, ex2, KIND_ENTRY, frac * 0.9)
                prev_svc = svc
                frac *= 0.6

    spans = SpanBatch(
        trace=np.array(trace_c, np.int32),
        parent=np.array(parent_c, np.int32),
        service=np.array(service_c, np.int32),
        endpoint=np.array(endpoint_c, np.int32),
        start_us=np.array(start_c, np.int64),
        duration_us=np.array(dur_c, np.int64),
        is_error=np.array(err_c, np.bool_),
        status=np.array(status_c, np.int16),
        kind=np.array(kind_c, np.int8),
        services=tuple(services),
        endpoints=tuple(endpoints),
        trace_ids=tuple(trace_ids),
    )
    return SuiteRun(suite, gw.to_api_batch(), spans,
                    np.array(passed, np.bool_),
                    np.array(trace_of_request, np.int32))


def traces_for_run(spans: SpanBatch, run_id: str) -> np.ndarray:
    """Trace indices belonging to a suite run — the join the reference does
    by filtering SkyWalking traces on the x-evomaster-run-id tag."""
    wanted = np.array([tid.startswith(run_id + "-")
                       for tid in spans.trace_ids], np.bool_)
    return np.flatnonzero(wanted)


def endpoint_owner(endpoint: str, testbed: str) -> str:
    """Owning service for a monitored endpoint — topology ground truth.

    SN: the nginx route table over the wrk2-api surface (the monitor's
    endpoint list, enhanced_openapi_monitor.py:36-49); full URLs are reduced
    to their path first.  TT: endpoints are ``/api/v1/<short>service`` per
    the gateway's path convention (atomic_queries.py), inverted back to the
    ``ts-*-service`` name.
    """
    if testbed == "SN":
        from urllib.parse import urlparse
        path = urlparse(endpoint).path if "://" in endpoint else endpoint
        return SN_ROUTE.get(path, "nginx-web-server")
    for s in TT_SERVICES:
        short = s.replace("ts-", "").replace("-service", "")
        if endpoint.rstrip("/").endswith(f"/{short}service"):
            return s
    return "ts-gateway-service"
