"""Online (streaming) detection over the replay plane.

The reference is strictly post-hoc: collectors archive an experiment after
it ran, and any detection happens offline on the archive
(`/root/reference/SN_collection-scripts/collect_all_data.sh:379`,
`T-Dataset/collect_all_modalities.sh:196-254`).  An operator of those
testbeds wants the obvious next step — alerts while the fault is live.
This module provides it on top of the existing replay machinery:

- :class:`StreamReplay` feeds span micro-batches (arrival order) through
  the SAME jitted chunk step the batch replay scans with
  (`anomod.replay.make_chunk_step`) — the incremental state is
  bit-identical to a one-shot replay of the same spans (parity-tested),
  so everything downstream of the aggregate plane (percentiles, HLL
  distinct-trace counts, detectors) works unchanged on a live stream.
- :class:`OnlineDetector` scores each *closed* 60 s window per service
  with four plane-derived z statistics (SE-of-mean log-latency, smoothed
  binomial error rate, per-window drop, recovery-resetting CUSUM) and
  raises :class:`Alert` rows with hysteresis; culprit ranking sums alert
  scores under dependency-chain attribution over the observed call
  graph.  Detection latency — windows from fault onset to first alert on
  the culprit — is the streaming-mode quality metric the offline sweep
  cannot measure.
- :class:`MultimodalDetector` fuses the log / metric / API planes — the
  streaming counterpart of the offline detector's five-modality
  features — which closes the span statistics' sparse-service floor.

TPU notes: the hot path is the shared chunk step (one bf16 MXU matmul per
micro-batch chunk); window scoring reads the tiny [S*W, F] plane back to
host, which is the natural cadence point (once per closed window, not per
span).  The plane itself shards over a device mesh
(anomod.parallel.stream.ShardedStreamReplay, injectable via
``OnlineDetector(replay=...)``).

Operating envelope: the SPAN z statistics need traffic density — around
≥10 spans per (service, window) the taxonomy localizes with 0-4 window
latency and the normal baselines stay quiet; below that, span evidence
loses power honestly (a sub-1-span/window service killed mid-run may
never alert from spans alone — CUSUM z ≈ 1.6 at best).  The multimodal
planes close exactly that gap (request-rate collapse and error-rate
series localize the quiet kills: both testbeds reach top-1 = 1.0).
Edge-locus faults (the callee side of the culprit's outgoing calls
degrades while its node-scoped evidence stays healthy) are covered by
the OUT-EDGE plane (``edge_attribution``, default on): every span is
pushed twice through the same jitted chunk scan — once keyed by its
service, once by caller-resolved edge slot — and a hot out-edge slot
with cool callee self-edges alerts the CALLER with evidence="edge"
(11/12 at live density/severity).  This plane is the framework's ONLY
working edge-locus detector: the offline models consume per-service
aggregates, so link faults are architecturally outside their evidence
(every node-feature model ≤ 0.06 once the generator's coverage/API
target-identity leak was gated — see docs/BENCHMARKS.md, "Generator-leak
retraction").  The residual gap is the de-saturated sparse regime, where
pooled out-edge windows against an 8-window baseline cap the z below
threshold at ~1 span/window.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import List, Optional, Sequence

import numpy as np

from anomod.replay import (F_COUNT, F_ERR, F_LOGLAT, N_FEATS, ReplayConfig,
                           ReplayState, make_chunk_step, stage_columns)
from anomod.schemas import LOG_ERROR, SpanBatch, take_spans


@dataclasses.dataclass(frozen=True)
class Alert:
    window: int            # closed window index that scored anomalous
    service: int           # service id (index into the batch's table)
    service_name: str
    score: float           # RANKING score: max of the latency/error z and
    #                        the drop z's weighted by their deficit
    #                        FRACTION — may be far below the raw z fields
    #                        (alerting thresholds the raw max; ranking
    #                        needs specificity, see _score_through)
    z_latency: float       # standard-error z on the window's log-latency mean
    z_error: float         # binomial z on the window's error rate
    z_drop: float          # per-window z on missing throughput
    z_drop_cum: float = 0.0  # CUSUM z: accumulated missing throughput over
    #                          the current deficit run (resets when the
    #                          service returns to its baseline rate) — the
    #                          signal that catches a SPARSE service going
    #                          dark (per-window evidence for a 3-spans/min
    #                          service never clears any sane threshold;
    #                          8 windows of total silence does)
    evidence: str = ""       # which signal won the ranking score for this
    #                          alert: latency/error/drop/cusum, or a
    #                          modality plane (log/metric/api) in the
    #                          multimodal detector


def roll_ring_state(state: ReplayState, cfg: ReplayConfig,
                    k: int) -> ReplayState:
    """Evict the oldest ``k`` windows from a ring-shaped ReplayState:
    shift plane columns left, zero the tail (anchor bookkeeping is the
    caller's).  ONE definition of the ring-eviction math, shared by the
    single-chip and mesh-sharded streaming planes.  HLL registers are
    per-service (not per-window) and pass through untouched."""
    import jax.numpy as jnp
    shift = min(k, cfg.n_windows)

    def roll2(x, width):
        x = np.asarray(x).reshape(cfg.n_services, cfg.n_windows, width)
        out = np.zeros_like(x)
        if shift < cfg.n_windows:
            out[:, :cfg.n_windows - shift] = x[:, shift:]
        return jnp.asarray(out.reshape(cfg.sw, width))

    return state._replace(agg=roll2(state.agg, N_FEATS),
                          hist=roll2(state.hist, cfg.n_hist_buckets))


def plane_view(state: ReplayState, cfg: ReplayConfig) -> np.ndarray:
    """Host copy of the aggregate plane as [S, W, F]."""
    return np.asarray(state.agg).reshape(
        cfg.n_services, cfg.n_windows, N_FEATS)


def edge_combined_cfg(cfg: ReplayConfig, n_services: int) -> ReplayConfig:
    """The COMBINED-id-space config an edge-attributing detector runs its
    replay on: node ids ⊕ self-edge slots ⊕ out-edge slots = 3S rows.
    Use this to construct an injectable plane (e.g.
    ``ShardedStreamReplay(edge_combined_cfg(cfg, S), t0, mesh)``) for
    ``OnlineDetector(..., replay=..., edge_attribution=True)``."""
    return dataclasses.replace(cfg, n_services=3 * n_services)


def _binom_tail_z(x: int, n: int, p: float) -> float:
    """z-equivalent of the upper binomial tail P(X >= x | n, p).

    Exact summation at the small counts the sparse-edge error channel
    lives in (2 errors in 6 spans is not Gaussian; a normal z there is
    either fabricated or blind); normal approximation once n*p is large
    enough for it to be honest.  The tail converts to a z through the
    standard-normal survival function so one threshold governs every
    evidence channel."""
    import math
    if x <= 0 or n <= 0:
        return 0.0
    if n > 60 and n * p > 10.0:
        return float((x - n * p) / math.sqrt(max(n * p * (1.0 - p), 1e-9)))
    tail = 0.0
    for k in range(int(x), int(n) + 1):
        tail += math.comb(int(n), k) * p ** k * (1.0 - p) ** (int(n) - k)
    if tail >= 0.5:
        return 0.0
    lo, hi = 0.0, 40.0
    for _ in range(60):                      # bisection on the survival fn
        mid = 0.5 * (lo + hi)
        if 0.5 * math.erfc(mid / math.sqrt(2.0)) > tail:
            lo = mid
        else:
            hi = mid
    return lo


def resolve_parent_services(batch: SpanBatch) -> np.ndarray:
    """Per-span PARENT-service id (-1 for roots).

    ``SpanBatch.parent`` holds batch-global row indices, so this must run
    on the FULL corpus BEFORE any row slicing (``take_spans`` does not
    remap parents).  A live collector does the same join at ingest from
    the wire format's parentSpanId (Jaeger/SkyWalking both carry it) —
    this helper is that join for the offline stand-in corpora."""
    psvc = np.full(batch.n_spans, -1, np.int32)
    has = batch.parent >= 0
    psvc[has] = batch.service[batch.parent[has]]
    return psvc


def window_span_z(col_plane: np.ndarray, b: dict, cusum, cusum_k,
                  min_count, drop_memory) -> dict:
    """THE per-closed-window span-plane z math, in one place.

    ``col_plane`` is the window's aggregate column ``[..., K, F]``,
    ``b`` the frozen calibration snapshot with ``[..., K]`` fields,
    ``cusum``/``cusum_k`` the CUSUM carry state, ``min_count`` /
    ``drop_memory`` the detector thresholds (scalars, or ``[..., 1]``
    arrays when batching).  Everything is elementwise/broadcast numpy,
    so a leading batch axis prepends freely: the sequential scorer
    (:meth:`OnlineDetector._score_through`, no batch axis) and the
    serving plane's batched scorer (:func:`score_closed_windows_batched`,
    tenants stacked on axis 0) run the IDENTICAL per-element arithmetic
    — which is what makes batched serving scoring byte-identical to
    per-tenant scoring, pinned in tests/test_serve_state.py.

    The three signals read straight off the aggregate plane's moments,
    each normalized by the statistically right denominator for sparse
    windows (see the scoring notes on :class:`OnlineDetector`):
    latency = standard-error z on the window's log-latency mean, error
    rate = binomial z vs the pooled baseline, throughput = Poisson z on
    MISSING spans plus a recovery-resetting CUSUM (the signal that
    catches a SPARSE service going dark — per-window evidence for a
    3-spans/min service never clears any sane threshold; 8 windows of
    total silence does).  The ``frac_*`` weights price detection vs
    localization: a high-fan-in carrier's statistically huge z on a 30%
    dip must not outrank certainty about a service 100% dark, so the
    ranking score weights the drop signals by their deficit FRACTION.

    Returns ``dict(zl, ze, zd, zdc, frac_w, frac_t, cusum, cusum_k)``
    with the CUSUM state advanced (the caller installs it).
    """
    n_w = col_plane[..., F_COUNT]
    safe = np.maximum(n_w, 1.0)
    ok = (n_w >= min_count) & b["calibrated"]
    zl = np.where(ok, (col_plane[..., F_LOGLAT] / safe - b["mu_l"])
                  / np.sqrt(b["var_span"] / safe + b["var_bl"]), 0.0)
    ze = np.where(ok, (col_plane[..., F_ERR] / safe - b["p_err"])
                  / np.sqrt(b["err_var"] / safe + b["var_be"]), 0.0)
    zd = np.where(b["active"], (b["rate0"] - n_w) / b["sd_cnt"], 0.0)
    # CUSUM on missing throughput: the slack term keeps healthy jitter
    # from accumulating; a window back at (or above) the baseline rate
    # RESETS the run — no lingering "still down" alerts after recovery.
    # Run length is capped at drop_memory for the normalization.
    healthy = n_w >= b["rate0"]
    slack = 0.25 * b["sd_cnt"]
    cusum = np.where(healthy, 0.0,
                     np.maximum(0.0, cusum + b["rate0"] - n_w - slack))
    cusum_k = np.where(cusum > 0,
                       np.minimum(cusum_k + 1, drop_memory),
                       0).astype(np.int32)
    k_run = np.maximum(cusum_k, 1)
    zdc = np.where(b["cum_active"],
                   cusum / (b["sd_cnt"] * np.sqrt(k_run)), 0.0)
    frac_t = np.clip(cusum / np.maximum(k_run * b["rate0"], 1e-9),
                     0.0, 1.0)
    frac_w = np.clip(1.0 - n_w / np.maximum(b["rate0"], 1e-9), 0.0, 1.0)
    return dict(zl=zl, ze=ze, zd=zd, zdc=zdc, frac_w=frac_w,
                frac_t=frac_t, cusum=cusum, cusum_k=cusum_k)


#: ranking-evidence channel order of the base span planes — the ONE
#: ordering shared by the sequential scorer's part dicts and the batched
#: scorer's stacks (argmax indices must mean the same channel in both)
SPAN_EV_NAMES = ("latency", "error", "drop", "cusum")


class StreamReplay:
    """Incremental replay state over arrival-ordered span micro-batches.

    ``t0_us`` anchors the window grid at stream start.  The grid ROLLS: a
    push whose spans start past the last column evicts the oldest windows
    (host-side roll of the tiny [S*W, *] state) and advances the anchor,
    so a live stream of any duration keeps scoring — ``window_offset``
    is the absolute index of plane column 0 and only grows.  Late
    stragglers older than the rolled anchor clamp into column 0 (the
    bounded misbinning of any ring buffer).  Chunk size should be sized
    to the expected micro-batch (default 4096 vs the batch path's 32768).
    """

    def __init__(self, cfg: ReplayConfig, t0_us: int,
                 with_hll: bool = False):
        import jax
        import jax.numpy as jnp

        self.cfg = cfg
        self.t0_us = int(t0_us)
        self.window_offset = 0     # absolute window index of plane column 0
        self.n_spans = 0
        step = make_chunk_step(cfg, with_hll=with_hll)
        self._step = jax.jit(lambda st, ch: step(st, ch)[0])
        self.state = ReplayState(
            agg=jnp.zeros((cfg.sw, N_FEATS), jnp.float32),
            hist=jnp.zeros((cfg.sw, cfg.n_hist_buckets), jnp.float32),
            hll=(jnp.zeros((cfg.n_services, cfg.hll_m), jnp.int32)
                 if with_hll else None))
        #: one-time jit compile wall, measured at the first push (lazy —
        #: a detector constructed but never fed must not pay the compile)
        self.compile_s = 0.0
        self._warmed = False

    def _warm(self) -> None:
        """Compile the chunk step on an all-dead dummy chunk (sid = dead
        lane, valid = 0 → numerically a no-op on the state) so push()
        walls measure the steady pipeline, not one-time compilation."""
        from anomod.replay import dead_chunk

        from anomod import obs
        t0 = time.perf_counter()
        self.state = self._step(self.state, dead_chunk(self.cfg))
        np.asarray(self.state.agg)                # compile + execute barrier
        self.compile_s = time.perf_counter() - t0
        obs.counter("anomod_stream_compile_total").inc()
        obs.counter("anomod_stream_compile_seconds_total").inc(
            self.compile_s)
        self._warmed = True

    def _roll(self, k: int) -> None:
        """Evict the oldest ``k`` windows (roll_ring_state) and advance
        the anchor.  The anchor advances by the FULL ``k`` even when that
        clears the whole plane (a feed gap wider than the grid) — only
        the column shift clamps, so later spans always bin into their
        true absolute window."""
        self.state = roll_ring_state(self.state, self.cfg, k)
        self.t0_us += k * self.cfg.window_us
        self.window_offset += k

    def push(self, batch: SpanBatch) -> int:
        """Fold a micro-batch into the plane.

        Returns the newest ABSOLUTE window the batch's spans were binned
        into (-1 for an empty batch) — the one true span→window mapping,
        so consumers never re-derive it from raw timestamps."""
        if batch.n_spans == 0:
            return -1
        if not self._warmed:
            self._warm()
        from anomod import obs
        t_push = time.perf_counter()
        w_need = int((int(batch.start_us.max()) - self.t0_us)
                     // self.cfg.window_us)
        if w_need > self.cfg.n_windows - 1:
            self._roll(w_need - (self.cfg.n_windows - 1))
            w_need = self.cfg.n_windows - 1
        chunks, n = stage_columns(batch, self.cfg, t0_us=self.t0_us)
        # double-buffered host→device staging (anomod.io.prefetch): chunk
        # i+1 transfers while the jitted step on chunk i is in flight
        from anomod.io.prefetch import iter_chunk_dicts, prefetch_to_device
        pipe = prefetch_to_device(iter_chunk_dicts(chunks))
        try:
            for staged in pipe:
                self.state = self._step(self.state, staged)
        finally:
            # a consumer-side error must not leave the worker parked on
            # the bounded queue holding staged device buffers
            pipe.close()
        self.n_spans += n
        obs.histogram("anomod_stream_push_seconds").observe(
            time.perf_counter() - t_push)
        return self.window_offset + max(w_need, 0)

    def agg_plane(self) -> np.ndarray:
        """Host copy of the aggregate plane as [S, W, F] (column w holds
        absolute window ``window_offset + w``)."""
        return plane_view(self.state, self.cfg)

    # -- the lane-stack gather/scatter seam (anomod.serve.batcher) --------
    #
    # Fused serving gathers many tenants' states, folds each tenant's
    # staged chunk through ONE lane-stacked dispatch, and hands each
    # lane's result back.  The seam is deliberately dumb — the state
    # pytree round-trips verbatim — but it is the OFFICIAL boundary:
    # consumers go through it instead of poking ``.state``, so a future
    # replay that keeps extra device-side residency can hook the
    # round-trip in one place.

    def get_state(self) -> ReplayState:
        """The replay plane's current state pytree (gather seam)."""
        return self.state

    def set_state(self, state: ReplayState) -> None:
        """Install an externally-advanced state pytree (scatter seam).
        The caller owns the parity contract: the installed state must be
        what this plane's own dispatch would have produced."""
        self.state = state


class OnlineDetector:
    """Window-closed z-score alerting over a :class:`StreamReplay`.

    The first ``baseline_windows`` closed windows per service calibrate
    mu/sigma for log-latency mean and error rate (the reference's
    pre-fault normal phase — faults start at 600 s = window 10 on the
    default grid, so the default 8 stays inside it).  A window is closed
    once a pushed span starts in a LATER window (in-order arrival is the
    stream contract).  ``consecutive`` windows above ``z_threshold`` are
    required before alerting (hysteresis against single-window noise).
    """

    def __init__(self, batch_services: Sequence[str], cfg: ReplayConfig,
                 t0_us: int, baseline_windows: int = 8,
                 z_threshold: float = 4.0, min_count: float = 5.0,
                 consecutive: int = 1, drop_memory: int = 8,
                 call_edges: Optional[set] = None,
                 replay=None, with_hll: bool = False,
                 edge_attribution: Optional[bool] = None,
                 edge_pool: int = 12, edge_mass: float = 8.0, mesh=None):
        if baseline_windows < 2:
            raise ValueError("need >= 2 baseline windows for a sigma")
        if baseline_windows >= cfg.n_windows:
            raise ValueError("baseline must fit inside the window ring "
                             f"({baseline_windows} >= {cfg.n_windows})")
        if consecutive < 1:
            raise ValueError("consecutive must be >= 1 (0 would alert "
                             "every service in every window)")
        if replay is not None and with_hll:
            raise ValueError("with_hll configures the detector's OWN "
                             "plane; an injected replay manages its own "
                             "HLL state")
        if mesh is not None and replay is not None:
            raise ValueError("give a mesh OR a pre-built replay, not both")
        if mesh is not None and with_hll:
            raise ValueError("the mesh streaming plane carries no HLL "
                             "state (psum-merged agg/hist only)")
        self.services = tuple(batch_services)
        S = len(self.services)
        self._n_svc = S
        #: EDGE-LOCUS coverage (default on when the detector owns its
        #: replay): the replay id space widens from S node ids to a
        #: STATIC 3S — S node ids ⊕ S self-edge slots ⊕ S out-edge
        #: slots — every span pushed twice (node id + edge slot) through
        #: the SAME jitted chunk scan.  A span whose parent belongs to a
        #: DIFFERENT service keys its edge copy to the CALLER's out-edge
        #: slot (2S + caller); own-parented and root spans key to their
        #: service's self-edge slot (S + svc).  A link fault
        #: (anomod.synth fault_locus="edge") degrades only the
        #: callee-side spans of the culprit's outgoing calls — node
        #: statistics then blame the callees, but the edge plane shows
        #: the signature directly: the culprit's OUT-edge slot goes hot
        #: while every callee's SELF-edge slot stays cool, so the
        #: detector alerts on the CALLER with evidence="edge" and
        #: ranking marks the callees edge-explained.  (Per-caller
        #: aggregation, not per-(caller, callee): out-edge traffic is a
        #: fraction of node traffic, and splitting it S-ways again would
        #: starve the z statistics at realistic densities; which callee
        #: is degraded is not needed to name the culprit.)
        # ``mesh`` builds the detector's own mesh-sharded plane (the
        # combined-cfg bookkeeping stays in one place); edge attribution
        # auto-enables for any detector-owned plane, mesh or single-chip
        self.edge_attribution = (replay is None) if edge_attribution is None \
            else bool(edge_attribution)
        if edge_pool < 1:
            raise ValueError("edge_pool must be >= 1 window")
        if edge_mass < 1:
            raise ValueError("edge_mass must be >= 1 span")
        self.edge_pool = edge_pool      # max window REACH of the edge pool
        self.edge_mass = edge_mass      # span-mass target the pool walks to
        if self.edge_attribution:
            K = 3 * S
            cfg = edge_combined_cfg(cfg, S)
            self._edge_hot: dict = {}       # caller id -> summed hot score
            self._self_hot = np.zeros(S, bool)
            # Per-(caller, callee) PAIR accumulators — the ranking's
            # concentration discriminator.  The pooled out-edge ROW can
            # say "caller p's outgoing traffic degraded" but not whether
            # the heat is spread across p's callees (link fault in p) or
            # concentrated on one (blast pointing at a node culprit).
            # O(observed pairs) streaming state: [n, sum_log1p_dur,
            # n_err] keyed caller*S+callee, split baseline/anomalous
            # phase at the calibration boundary.
            self._pair_base: dict = {}
            self._pair_anom: dict = {}
        else:
            K = S
        self._K = K
        # ``replay`` injects an alternative plane with the same contract —
        # e.g. anomod.parallel.stream.ShardedStreamReplay runs this whole
        # alerting stack over a device mesh unchanged.  With edge
        # attribution (pass edge_attribution=True explicitly; the default
        # only auto-enables for the detector's own plane) the injected
        # replay must be built on the COMBINED id space:
        # ``detector cfg with n_services = 3 * len(services)``.
        if replay is not None and (replay.cfg != cfg
                                   or replay.t0_us != int(t0_us)):
            raise ValueError(
                "injected replay's cfg/t0 disagree with the detector's"
                + (" (edge attribution widens the id space: build the "
                   f"replay with n_services = 3*S = {K})"
                   if self.edge_attribution else ""))
        if replay is None and mesh is not None:
            from anomod.parallel.stream import ShardedStreamReplay
            replay = ShardedStreamReplay(cfg, t0_us, mesh)
        self.replay = replay if replay is not None else \
            StreamReplay(cfg, t0_us, with_hll=with_hll)
        #: spans fed by the caller (the combined-id replay counts each
        #: span twice internally; pipeline metrics use THIS number)
        self.n_spans_in = 0
        self.baseline_windows = baseline_windows
        self.z_threshold = z_threshold
        self.min_count = min_count
        self.consecutive = consecutive
        self.drop_memory = drop_memory
        #: observed caller→callee service-id pairs (self-loops ignored);
        #: enables dependency-aware culprit ranking in ranked_services
        self.call_edges = {(a, b) for a, b in (call_edges or set())
                           if a != b}
        self.alerts: List[Alert] = []
        #: accumulated wall time inside push()/push_* (staging + jitted
        #: chunk steps + window scoring) — the live pipeline's cost;
        #: spans/sec = n_spans_in / push_wall_s (NOT replay.n_spans: the
        #: combined-id replay counts each span twice in edge mode)
        self.push_wall_s = 0.0
        self._scored_through = -1          # last closed ABSOLUTE window scored
        self._max_seen = -1                # newest absolute window with data
        # frozen grid anchor for the pair accumulators' phase split (the
        # replay's own t0 ROLLS with the ring)
        self._t0_us = int(t0_us)
        self._window_us = int(cfg.window_us)
        self._callees_cache: dict = {}
        self._streak = np.zeros(self._K, np.int32)
        self._baseline = None              # frozen calibration snapshot
        # CUSUM state for the cumulative drop signal: accumulated span
        # deficit + length of the current deficit run, per row (the drop
        # signals are consumed for node rows only)
        self._cusum = np.zeros(self._K, np.float64)
        self._cusum_k = np.zeros(self._K, np.int32)

    def _callees_of(self, p: int) -> frozenset:
        """Observed callees of service ``p`` (from ``call_edges``)."""
        got = self._callees_cache.get(p)
        if got is None:
            got = frozenset(c for a, c in self.call_edges if a == p)
            self._callees_cache[p] = got
        return got

    def _edge_ids(self, svc: np.ndarray,
                  psvc: Optional[np.ndarray]) -> np.ndarray:
        """Edge slot per span: the CALLER's out-edge slot 2S+p for spans
        whose parent belongs to a different service, else the service's
        self-edge slot S+c (roots, own-parented spans, and every span
        when the pusher has no parent info — node-degraded, honest)."""
        S = self._n_svc
        out = (S + svc).astype(np.int32)
        if psvc is None:
            return out
        cross = (psvc >= 0) & (psvc != svc)
        if cross.any():
            out[cross] = (2 * S + psvc[cross]).astype(np.int32)
        return out

    _DUP_FIELDS = ("trace", "parent", "endpoint", "start_us",
                   "duration_us", "is_error", "status", "kind")

    def _accumulate_pairs(self, batch: SpanBatch, svc: np.ndarray,
                          psvc: np.ndarray) -> None:
        """Fold a micro-batch's cross edges into the per-pair phase
        accumulators (vectorized per unique pair; O(pairs) dict work)."""
        cross = (psvc >= 0) & (psvc != svc)
        if not cross.any():
            return
        wi = (batch.start_us[cross] - self._t0_us) // self._window_us
        keys = psvc[cross].astype(np.int64) * self._n_svc + svc[cross]
        dur = np.log1p(batch.duration_us[cross].astype(np.float64))
        err = batch.is_error[cross].astype(np.float64)
        in_base = wi < self.baseline_windows
        for phase, m in ((self._pair_base, in_base),
                         (self._pair_anom, ~in_base)):
            if not m.any():
                continue
            uk, inv = np.unique(keys[m], return_inverse=True)
            ns = np.bincount(inv).astype(np.float64)
            ds = np.bincount(inv, weights=dur[m])
            es = np.bincount(inv, weights=err[m])
            for k_, n_, d_, e_ in zip(uk.tolist(), ns, ds, es):
                acc = phase.setdefault(k_, [0.0, 0.0, 0.0])
                acc[0] += n_
                acc[1] += d_
                acc[2] += e_

    def _pair_verdict(self, p: int) -> Optional[tuple]:
        """Concentration verdict for caller ``p``'s per-pair heat:
        ``("concentrated", callee)`` when one callee carries >= 60% of
        the degradation mass, ``("spread", -1)`` when it is spread, and
        ``None`` when there is not enough pair data to tell.

        Spread-vs-concentrated is THE link-vs-node discriminator: an
        edge-locus fault degrades ALL of the culprit's outgoing pairs,
        while a node culprit heats exactly the one pair pointing at it
        from each caller."""
        S = self._n_svc
        deltas: List[tuple] = []
        n_obs = 0
        for k, (n_a, d_a, e_a) in self._pair_anom.items():
            if k // S != p or n_a < 3:
                continue
            base = self._pair_base.get(k)
            if not base or base[0] < 3:
                continue
            n_obs += 1
            d = max(d_a / n_a - base[1] / base[0], 0.0) \
                + 5.0 * max(e_a / n_a - base[2] / base[0], 0.0)
            if d > 0:
                deltas.append((d, int(k % S)))
        if n_obs < 2 or not deltas:
            return None          # one observed pair: spread undefined
        tot = sum(d for d, _ in deltas)
        d0, c0 = max(deltas)
        return ("concentrated", c0) if d0 >= 0.6 * tot else ("spread", -1)

    def push(self, batch: SpanBatch,
             parent_service: Optional[np.ndarray] = None) -> List[Alert]:
        """Feed a micro-batch; returns alerts for newly closed windows.

        Window indices in alerts are ABSOLUTE (they keep growing after the
        replay ring rolls past its grid width).  The newest window comes
        from the replay itself — the detector never re-derives binning
        from raw timestamps.

        ``parent_service`` (optional, len n_spans, -1 = root) feeds the
        edge plane; resolve it on the FULL corpus with
        :func:`resolve_parent_services` BEFORE slicing (a live collector
        resolves it at ingest from parentSpanId).  Without it, spans land
        on their self-edge slot and edge attribution degrades to node
        evidence."""
        if batch.n_spans and not self.replay._warmed:
            self.replay._warm()          # compile outside the timed wall
        t0 = time.perf_counter()
        try:
            w_max = self.replay.push(
                self.replay_batch(batch, parent_service))
            return self.note_pushed(batch.n_spans, w_max)
        finally:
            self.push_wall_s += time.perf_counter() - t0

    def replay_batch(self, batch: SpanBatch,
                     parent_service: Optional[np.ndarray] = None
                     ) -> SpanBatch:
        """Host-side pre-replay half of :meth:`push`: the EXACT batch
        push() hands the replay plane (edge-id duplication + per-pair
        phase accumulation applied; the identity when edge attribution is
        off).  The fused serving plane (anomod.serve.engine) calls this,
        folds the result through a lane-stacked dispatch, then finishes
        with :meth:`note_pushed` — one definition of both halves, so the
        fused and sequential scoring paths cannot drift."""
        if not (self.edge_attribution and batch.n_spans):
            return batch
        svc = batch.service.astype(np.int32)
        psvc = None if parent_service is None else \
            np.asarray(parent_service, np.int32)
        if psvc is not None:
            self._accumulate_pairs(batch, svc, psvc)
        eids = self._edge_ids(svc, psvc)
        return batch._replace(
            service=np.concatenate([svc, eids]),
            **{f: np.concatenate([getattr(batch, f)] * 2)
               for f in self._DUP_FIELDS})

    def note_pushed(self, n_spans: int, w_max: int) -> List[Alert]:
        """Post-replay half of :meth:`push`: bookkeeping plus scoring of
        the newly closed windows.  ``n_spans`` is the ORIGINAL batch's
        span count (pre edge duplication); ``w_max`` is the replay
        plane's returned newest absolute window."""
        through = self.note_bookkeep(n_spans, w_max)
        if through is None:
            return []
        return self._score_through(through)

    def note_bookkeep(self, n_spans: int, w_max: int) -> Optional[int]:
        """The bookkeeping half of :meth:`note_pushed` (span count +
        window high-water mark); returns the ``through`` bound scoring
        would scan, or None for an empty push.  The serving plane's
        batched COMMIT phase calls this per tenant and then scores every
        batch-scorable tenant in one vectorized pass
        (:func:`score_closed_windows_batched`) — one definition of the
        bookkeeping for the sequential and batched paths."""
        if w_max < 0:
            return None
        self.n_spans_in += n_spans
        self._max_seen = max(self._max_seen, w_max)
        return self._max_seen - 1

    def scoring_window_range(self, through: int):
        """The closed-window range ``(start, through)`` that
        :meth:`_score_through` would score, or None after recording the
        no-op advance — ONE definition of the early return, shared by
        the sequential scorer and the batched serve scorer (so the two
        advance ``_scored_through`` identically)."""
        start = max(self._scored_through + 1, self.baseline_windows)
        if through < start:
            self._scored_through = max(self._scored_through, through)
            return None
        return start, through

    def ensure_baseline(self, plane: np.ndarray) -> dict:
        """The frozen calibration snapshot, computed from ``plane`` on
        first need.  Calibration reads only columns ``[0, B)``, so the
        batched serve scorer may pass a gathered ``[K, B, F]`` block —
        same values, same frozen statistics."""
        if self._baseline is None:
            self._baseline = self._calibrate(plane)
        return self._baseline

    @property
    def batch_scorable(self) -> bool:
        """True when scoring is exactly the base span-plane math — no
        edge rows, no modality planes — i.e. the serve engine's batched
        scorer (:func:`score_closed_windows_batched`) can score this
        detector in the vectorized pass with byte-identical results.
        Subclasses (the multimodal detector: per-tenant modality dicts)
        and edge-attributing detectors keep the sequential path."""
        return type(self) is OnlineDetector and not self.edge_attribution

    def finish(self) -> List[Alert]:
        """End of stream: the newest window with data counts as closed.

        Windows past the last span are never scored — an ended stream is
        not a fleet-wide outage, and scoring empty windows would fire the
        drop signal for every active service (the busiest loudest)."""
        return self._score_through(self._max_seen)

    # -- scoring ----------------------------------------------------------
    #
    # The three signals read straight off the aggregate plane's moments,
    # each normalized by the statistically right denominator for sparse
    # windows (a handful of spans per (service, window) is the realistic
    # regime — per-window-mean sigmas explode there):
    #   latency:    z = (mean_w - mu0) / sqrt(var_span0 / n_w)
    #               (standard error of the window mean; var_span0 pooled
    #                from the baseline spans via the E[x^2] plane)
    #   error rate: binomial z vs the pooled baseline rate
    #   throughput: Poisson z on MISSING spans — a killed service stops
    #               emitting, which latency/error z-scores cannot see
    #               (the reference's Lv_S kill faults fail exactly this way)

    def _calibrate(self, plane: np.ndarray) -> dict:
        """Freeze baseline statistics from plane columns [0, B).

        Called once, the first time scoring reaches the end of the
        calibration phase — before the ring can roll (B << n_windows), so
        the columns still hold absolute windows 0..B-1.  Frozen stats keep
        every later window scored against the SAME healthy reference even
        after the ring evicts those columns."""
        from anomod.replay import F_LOGLAT2
        B = self.baseline_windows
        if self.replay.window_offset > 0:
            raise RuntimeError(
                "stream jumped past the calibration phase before "
                f"{B} baseline windows closed (ring already rolled)")
        cnt = plane[..., F_COUNT]
        # pooled baseline per service (count-weighted, all B windows)
        C0 = np.maximum(cnt[:, :B].sum(axis=1), 1.0)
        mu_l = plane[:, :B, F_LOGLAT].sum(axis=1) / C0
        var_span = np.maximum(
            plane[:, :B, F_LOGLAT2].sum(axis=1) / C0 - mu_l ** 2, 1e-4)
        # Laplace-smoothed error rate: an all-clean baseline must not make
        # the first stray background error an infinite-z event — the +1/+2
        # prior keeps the binomial variance honest at small counts (one
        # error in a 6-span window on a 24-span clean baseline: z ~ 1.6,
        # vs ~13 with a raw rate and a hard variance floor)
        p_err = (plane[:, :B, F_ERR].sum(axis=1) + 1.0) / (C0 + 2.0)
        err_var = np.maximum(p_err * (1.0 - p_err), 1e-6)
        rate0 = cnt[:, :B].mean(axis=1)          # spans per baseline window
        # between-window baseline variance: endpoint-mix drift and traffic
        # burstiness are real window-to-window variation that the pure
        # within-window denominators (SE-of-mean, binomial, Poisson) do not
        # carry — without these terms a bursty-but-healthy service alerts
        # on every naturally quiet window
        bsafe = np.maximum(cnt[:, :B], 1.0)
        bvalid = cnt[:, :B] >= self.min_count
        nb = np.maximum(bvalid.sum(axis=1), 1)

        def _between_var(per_window):
            m = (per_window * bvalid).sum(axis=1) / nb
            return ((per_window - m[:, None]) ** 2 * bvalid).sum(axis=1) / nb

        # Sparse-row drift variance for the POOLED edge z: var_bl/var_be
        # above average only windows with >= min_count spans, so a row
        # whose every baseline window is thinner (the ~1 span/window edge
        # regime the pooled z exists for) gets 0 — no between-window
        # protection at all.  For those rows estimate drift from ALL
        # non-empty windows and subtract the sampling noise a window mean
        # of n̄ spans carries (E[observed between-var] = drift +
        # var_within/n̄), clamping at 0: a pure-Poisson sparse row prices
        # ~0 drift (keeping sensitivity), a genuinely bursty one keeps
        # its real drift term.
        bvalid1 = cnt[:, :B] >= 1.0
        nb1 = np.maximum(bvalid1.sum(axis=1), 1)
        nbar1 = np.maximum((cnt[:, :B] * bvalid1).sum(axis=1) / nb1, 1.0)

        def _between_var_any(per_window):
            m = (per_window * bvalid1).sum(axis=1) / nb1
            return ((per_window - m[:, None]) ** 2
                    * bvalid1).sum(axis=1) / nb1

        drift_l = np.maximum(
            _between_var_any(plane[:, :B, F_LOGLAT] / bsafe)
            - var_span / nbar1, 0.0)
        drift_e = np.maximum(
            _between_var_any(plane[:, :B, F_ERR] / bsafe)
            - err_var / nbar1, 0.0)
        var_bl = _between_var(plane[:, :B, F_LOGLAT] / bsafe)
        var_be = _between_var(plane[:, :B, F_ERR] / bsafe)

        out = dict(
            mu_l=mu_l, var_span=var_span, p_err=p_err, err_var=err_var,
            rate0=rate0, C0=C0,
            var_bl_pool=np.where(var_bl > 0, var_bl, drift_l),
            var_be_pool=np.where(var_be > 0, var_be, drift_e),
            active=rate0 >= self.min_count,   # per-window drop needs traffic
            # the cumulative drop accumulates evidence across windows, so
            # even ~1 span/window suffices — but a service with a near-zero
            # baseline rate has nothing measurable to lose
            cum_active=rate0 >= 1.0,
            # latency/error z need a calibrated baseline: a service unseen
            # (or barely seen) during calibration has a fabricated mu/var
            # and its first busy window would be a guaranteed false alert
            calibrated=C0 >= 2.0 * self.min_count,
            var_bl=var_bl, var_be=var_be,
            sd_cnt=np.sqrt(np.maximum(cnt[:, :B].var(axis=1),
                                      np.maximum(rate0, 1.0))))
        if self.edge_attribution:
            out.update(self._calibrate_edges(plane))
        return out

    def _calibrate_edges(self, plane: np.ndarray) -> dict:
        """Shrunk baselines for the SPARSE edge rows [S, 3S).

        Edge traffic is a fraction of node traffic, so at realistic
        densities an edge row's own baseline holds a handful of spans —
        a raw mean/variance from 1-5 spans is noise, and the old hard
        ``C0 >= min_count`` gate simply zeroed those rows (the
        sparse-density edge-locus collapse, docs/BENCHMARKS.md).  Instead
        every edge row gets an empirical-Bayes baseline: its own stats
        shrunk toward a borrowed population with prior mass
        ``tau = 1.2*min_count`` —
          - SELF-edge rows borrow the same service's NODE row (their
            spans are a subset of it);
          - OUT-edge rows borrow the count-weighted pooled baseline of
            ALL out-edge rows, with the between-row spread of out-edge
            means priced into the variance (caller populations differ).
        The error channel gets a fleet null instead of the node plane's
        +1/+2 Laplace prior (which at C0=3 fabricates a 20% baseline
        error rate and swallows any real excess): posterior mean under a
        fleet-rate prior, doubled and floored at 0.5% as a drift-safety
        margin — scored by exact binomial tail (:func:`_binom_tail_z`),
        not a normal z, because 2 errors in 6 spans is not Gaussian."""
        from anomod.replay import F_LOGLAT2
        B = self.baseline_windows
        S = self._n_svc
        tau = 1.2 * self.min_count
        cnt = plane[..., F_COUNT]
        c = cnt[S:3 * S, :B].sum(axis=1)             # raw, unclamped
        s1 = plane[S:3 * S, :B, F_LOGLAT].sum(axis=1)
        s2 = plane[S:3 * S, :B, F_LOGLAT2].sum(axis=1)
        csafe = np.maximum(c, 1.0)
        own_mu = s1 / csafe
        own_var = np.maximum(s2 / csafe - own_mu ** 2, 1e-4)
        # borrowed population per row
        node_mu = np.tile(plane[:S, :B, F_LOGLAT].sum(axis=1)
                          / np.maximum(cnt[:S, :B].sum(axis=1), 1.0), 2)
        node_c = np.maximum(cnt[:S, :B].sum(axis=1), 1.0)
        node_var = np.tile(np.maximum(
            plane[:S, :B, F_LOGLAT2].sum(axis=1) / node_c
            - (node_mu[:S]) ** 2, 1e-4), 2)
        oc = c[S:]                                   # out-edge rows
        o_tot = max(float(oc.sum()), 1.0)
        mu_pop_out = float(s1[S:].sum()) / o_tot
        var_pop_out = max(float(s2[S:].sum()) / o_tot - mu_pop_out ** 2,
                          1e-4)
        good = oc >= 4
        if int(good.sum()) >= 3:
            between = float(np.average(
                (own_mu[S:][good] - mu_pop_out) ** 2, weights=oc[good]))
        else:
            between = 0.25 * var_pop_out
        pop_mu = node_mu.copy()
        pop_var = node_var.copy()
        pop_mu[S:] = mu_pop_out
        pop_var[S:] = var_pop_out + between
        w = c / (c + tau)
        mu_sh = w * own_mu + (1 - w) * pop_mu
        var_sh = np.where(c > 1, w * own_var + (1 - w) * pop_var, pop_var)
        # the borrowed prior is worth tau pseudo-spans of baseline mass in
        # the two-sample term — bounded confidence from borrowed data
        c_eff = c + tau
        # fleet error null (node plane pools every span once)
        p_fleet = float(plane[:S, :B, F_ERR].sum()
                        / max(float(cnt[:S, :B].sum()), 1.0))
        own_e = plane[S:3 * S, :B, F_ERR].sum(axis=1)
        p_null = np.clip((own_e + 2 * tau * p_fleet) / (c + 2 * tau)
                         * 2.0 + 0.005, 0.005, 0.5)
        return dict(edge_mu=mu_sh, edge_var=var_sh, edge_c_eff=c_eff,
                    edge_p_null=p_null)

    def _score_through(self, through: int) -> List[Alert]:
        """Score closed ABSOLUTE windows (scored_through, through]."""
        rng = self.scoring_window_range(through)
        if rng is None:
            return []
        start, through = rng
        plane = self.replay.agg_plane()
        b = self.ensure_baseline(plane)
        S, K = self._n_svc, self._K
        cnt = plane[..., F_COUNT]
        off = self.replay.window_offset
        # fleet-activity per column: a window where nobody reported is
        # feed silence, skipped below (never evidence for any service).
        # Node rows [0, S) see every span exactly once, so they alone
        # define fleet activity (edge rows are the same spans re-keyed).
        fleet = cnt[:S].sum(axis=0) > 0
        out: List[Alert] = []
        for w in range(start, through + 1):
            col = w - off
            if col < 0:          # evicted before it could be scored
                self._streak[:] = 0      # a gap breaks any consecutive run
                self._cusum[:] = 0.0
                self._cusum_k[:] = 0
                continue
            if not fleet[col]:
                # nobody at all reported in this window: that is feed
                # silence (collector outage / gap), not per-service
                # evidence — firing z_drop for EVERY active service would
                # be an alert storm carrying no localization signal.  The
                # silence also breaks hysteresis and the CUSUM run:
                # windows on either side of a gap are not consecutive
                self._streak[:] = 0
                self._cusum[:] = 0.0
                self._cusum_k[:] = 0
                continue
            # the per-window z math lives in window_span_z — ONE
            # definition with the batched serve scorer.  CUSUM evidence:
            # per-window Poisson z for a 2-3 spans/window service never
            # clears the threshold, but several windows of silence
            # accumulate to certainty.  Detection vs localization: alerts
            # fire on the raw z (sensitivity); the recorded ranking score
            # weights the drop signals by their deficit FRACTION
            # (specificity) — subclass modality planes (log/metric/api
            # z's) join both sides at full weight, they are per-service
            # direct evidence, not blast-radius carriers.
            z = window_span_z(plane[:, col], b, self._cusum,
                              self._cusum_k, self.min_count,
                              self.drop_memory)
            self._cusum = z["cusum"]
            self._cusum_k = z["cusum_k"]
            zl, ze, zd, zdc = z["zl"], z["ze"], z["zd"], z["zdc"]
            frac_w, frac_t = z["frac_w"], z["frac_t"]
            extras = self._modality_z(w)
            if K > S:
                # modality planes are node-scoped by construction; edge
                # rows carry span evidence only
                extras = {k: np.concatenate([v, np.zeros(K - S)])
                          for k, v in extras.items()}
            det_parts = dict(latency=zl, error=ze, drop=zd, cusum=zdc,
                             **extras)
            rank_parts = dict(latency=zl, error=ze, drop=zd * frac_w,
                              cusum=zdc * frac_t, **extras)
            detect_z = np.stack(list(det_parts.values())).max(axis=0)
            rank_stack = np.stack(list(rank_parts.values()))
            score = rank_stack.max(axis=0)
            ev_names = list(rank_parts)
            ev_idx = rank_stack.argmax(axis=0)
            hot = detect_z >= self.z_threshold
            if K > S:
                # Edge rows alert on span latency/error only: a per-edge
                # drop just mirrors node evidence (caller died / callee
                # died) at lower counts, and the drop z's blast-radius
                # caveats would apply per edge with no extra signal.
                # Edge traffic is a fraction of node traffic (each span
                # keys to ONE edge), so per-window edge counts sit below
                # min_count at realistic densities — the edge z pools a
                # VARIABLE-width window: walk back from the current
                # window until ``edge_mass`` spans accumulate, capped at
                # ``edge_pool`` windows of reach.  Mass-based pooling is
                # what fixes the sparse-density collapse the fixed
                # 8-window pool had: a thin edge reaches further back for
                # the same evidence mass, a dense one pools narrowly and
                # is not diluted by healthy windows.
                P = self.edge_pool
                plo = max(col - P + 1, 0)
                seg = plane[S:, plo:col + 1]
                rev_cnt = seg[..., F_COUNT][:, ::-1]
                cumc = rev_cnt.cumsum(axis=1)
                reach = cumc.shape[1]
                # Two-scale mass pooling, max over scales: the NARROW pool
                # walks back to ``edge_mass`` spans (a concentrated error
                # burst or latency spike scores undiluted); the WIDE pool
                # walks to one baseline-block's worth (C0 ~ B windows of
                # this row's traffic — the smoothing dense rows need, and
                # past n_p ~ C0 the baseline term dominates the variance
                # anyway so wider pooling only dilutes).  A thin row's two
                # scales coincide at the edge_mass floor.
                cuml = seg[..., F_LOGLAT][:, ::-1].cumsum(axis=1)
                cume = seg[..., F_ERR][:, ::-1].cumsum(axis=1)
                zl_p = np.zeros(2 * S)
                ze_p = np.zeros(2 * S)
                scales = (np.full(2 * S, self.edge_mass),
                          np.maximum(b["C0"][S:], self.edge_mass))
                n_p_wide = np.zeros(2 * S)  # wide-scale pooled counts,
                # captured explicitly for the self_ok gate below (must not
                # depend on which scale the loop happens to end on)
                for mass in scales:
                    m = mass[:, None]
                    has = cumc[:, -1:] >= m
                    kidx = np.where(
                        has, np.argmax(cumc >= m, axis=1, keepdims=True),
                        reach - 1)
                    n_p = np.take_along_axis(cumc, kidx, axis=1)[:, 0]
                    suml = np.take_along_axis(cuml, kidx, axis=1)[:, 0]
                    sume = np.take_along_axis(cume, kidx, axis=1)[:, 0]
                    safe_p = np.maximum(n_p, 1.0)
                    # the shrunk empirical-Bayes baselines
                    # (_calibrate_edges) replace the old hard
                    # C0 >= min_count gate: a thin-baseline row scores
                    # against its borrowed baseline, with the borrow
                    # priced as tau pseudo-spans in the two-sample term —
                    # only a minimal evidence mass is still required
                    ok_p = n_p >= min(3.0, self.edge_mass)
                    zl_p = np.maximum(zl_p, np.where(
                        ok_p,
                        (suml / safe_p - b["edge_mu"])
                        / np.sqrt(b["edge_var"] / safe_p
                                  + b["edge_var"] / b["edge_c_eff"]
                                  + b["var_bl_pool"][S:]),
                        0.0))
                    # error channel: exact binomial tail against the
                    # fleet null — only rows with >= 2 pooled errors can
                    # score (one stray background error must never be
                    # 4-sigma evidence)
                    for ei in np.nonzero(ok_p & (sume >= 2.0))[0]:
                        ze_p[ei] = max(ze_p[ei], _binom_tail_z(
                            int(sume[ei]), int(n_p[ei]),
                            float(b["edge_p_null"][ei])))
                    if mass is scales[1]:
                        n_p_wide = n_p
                # The SELF-edge channel is the node-vs-link locus
                # discriminator: a self-edge falsely hot on borrowed-
                # baseline noise reads as "node-borne in the callee" and
                # suppresses the caller's true out-edge attribution.  So
                # self rows keep the conservative gates (own baseline AND
                # evidence mass >= min_count) — the borrowed-baseline
                # liberalization is for OUT-edge attribution only.
                self_ok = (b["C0"][S:2 * S] >= self.min_count) & \
                    (n_p_wide[:S] >= self.min_count)
                zl_p[:S] = np.where(self_ok, zl_p[:S], 0.0)
                ze_p[:S] = np.where(self_ok, ze_p[:S], 0.0)
                span_z = np.concatenate(
                    [np.maximum(zl, ze)[:S], np.maximum(zl_p, ze_p)])
                # Out-edge alerting is two-tier: the pooled scan runs FAR
                # fewer effective tests than the node plane (one
                # correlated statistic per row vs S x W independent
                # windows), which earns a halved-sigma threshold; below
                # that, a row that UNIQUELY dominates the out-edge plane
                # by a wide margin is attribution-grade evidence even
                # sub-threshold (a scan where exactly one of S rows
                # stands out is a stronger event than one row crossing a
                # line).  Self-edge heat (the node-vs-link locus
                # discriminator) stays at the full node threshold —
                # mis-declaring "node-borne" flips rankings.
                hot[S:] = span_z[S:] >= self.z_threshold
                out_z = span_z[2 * S:]
                if os.environ.get("ANOMOD_EDGE_DEBUG"):
                    _t = int(out_z.argmax())
                    print(f"[edge] w{w} top={self.services[_t]} "
                          f"z={out_z[_t]:.2f} "
                          f"2nd={float(np.partition(out_z, -2)[-2]):.2f}")
                hot_hi = out_z >= self.z_threshold - 0.5
                if out_z.size >= 2:
                    top = int(out_z.argmax())
                    second = float(np.partition(out_z, -2)[-2])
                    # the dominance tier exists for rows whose baseline is
                    # STRUCTURALLY too thin to support the hi threshold; a
                    # well-calibrated dense row (C0 >= 4*min_count) that
                    # cannot reach hi is not signal-limited — letting it
                    # through would alert normal baselines on weak flukes
                    if (out_z[top] >= self.z_threshold - 1.5
                            and out_z[top] >= 1.2 * max(second, 1e-9)
                            and b["C0"][2 * S + top]
                            < 4.0 * self.min_count):
                        hot_hi[top] = True
                hot[2 * S:] |= hot_hi
            self._streak = np.where(hot, self._streak + 1, 0)
            for s in np.nonzero(self._streak[:S] >= self.consecutive)[0]:
                out.append(Alert(window=w, service=int(s),
                                 service_name=self.services[s],
                                 score=float(score[s]),
                                 z_latency=float(zl[s]),
                                 z_error=float(ze[s]),
                                 z_drop=float(zd[s]),
                                 z_drop_cum=float(zdc[s]),
                                 evidence=ev_names[int(ev_idx[s])]))
            if K > S:
                # self-edge heat is the node-vs-edge locus discriminator:
                # a NODE fault inflates the culprit's own-parented/root
                # spans (self-edge hot); a LINK fault leaves every self
                # -edge cool and only the culprit's out-edge slot hot
                self._self_hot |= span_z[S:2 * S] >= self.z_threshold
                for pi in np.nonzero(
                        self._streak[2 * S:] >= self.consecutive)[0]:
                    p = int(pi)
                    # if any callee of p shows a hot SELF-edge, the
                    # degradation is node-borne in that callee and the
                    # out-edge heat is its reflection — the node path
                    # owns the blame
                    callees = self._callees_of(p)
                    if callees and bool(
                            (span_z[S + np.fromiter(callees, np.int64)]
                             >= self.z_threshold).any()):
                        continue
                    slot = 2 * S + p
                    sc = float(span_z[slot])
                    self._edge_hot[p] = self._edge_hot.get(p, 0.0) + sc
                    out.append(Alert(window=w, service=p,
                                     service_name=self.services[p],
                                     score=sc,
                                     z_latency=float(zl_p[slot - S]),
                                     z_error=float(ze_p[slot - S]),
                                     z_drop=0.0, z_drop_cum=0.0,
                                     evidence="edge"))
        self._scored_through = through
        self._after_score(through)
        self.alerts.extend(out)
        return out

    def _after_score(self, through: int) -> None:
        """Hook after scoring advances (multimodal subclass prunes its
        per-window host state here)."""

    def _modality_z(self, w: int) -> dict:
        """Hook for extra per-window z planes (multimodal subclass)."""
        return {}

    # -- stream-mode quality metrics --------------------------------------

    def ranked_services(self) -> List[str]:
        """Culprit ranking: deepest anomalous dependency first.

        SUMMED alert scores per service (persistence is signal — a
        culprit sustains, a blast victim flickers), but a service with an
        anomalous service TRANSITIVELY downstream of it (reachable over
        the call graph) ranks after services with none — a gateway/caller whose
        error spike is (at least partly) explained by a misbehaving
        dependency must not outrank that dependency, no matter how
        statistically loud the blast radius is at the aggregation point,
        and a healthy-but-silent middle hop must not shield the caller.
        Reachability runs on the condensation (strongly-connected
        components collapse to one node), so mutual call edges between
        two anomalous services leave BOTH unexplained — peak order
        decides — instead of degenerating the whole ranking.  Needs
        ``call_edges``; without it, pure peak-score order."""
        peak: dict = {}
        total: dict = {}
        windows: dict = {}
        for a in self.alerts:
            peak[a.service] = max(peak.get(a.service, 0.0), a.score)
            total[a.service] = total.get(a.service, 0.0) + a.score
            windows.setdefault(a.service, set()).add(a.window)
        # edge-explained callees: a service whose anomaly is edge-borne —
        # hot incoming cross edge(s), self-edge never hot, and no direct
        # node-scoped modality evidence (a NODE fault degrades the
        # service's own logs/metrics; a link fault cannot) — is a blast
        # victim of the edge's CALLER, which already carries the edge
        # alerts.  It must neither outrank the caller nor "explain" the
        # caller away in the downstream walk.
        edge_explained: set = set()
        edge_dom: set = set()
        direct_node_ev: set = set()
        if self.edge_attribution and self._edge_hot:
            # node-borne modality evidence must SUSTAIN (>= 2 distinct
            # windows): a single 4-sigma log/metric window across S
            # services x W windows is expected multiple-testing noise,
            # and letting it certify a service as node-borne would both
            # shield blast victims from edge-explanation and explain
            # away a genuine edge culprit upstream of the noise
            mod_windows: dict = {}
            plane_groups: dict = {}   # evidence classification, shared
            # with the corroboration tier below (single source for the
            # log/metric/api-vs-span split)
            for a in self.alerts:
                g = a.evidence if a.evidence in ("log", "metric", "api") \
                    else "span"
                plane_groups.setdefault(a.service, set()).add(g)
                if g != "span":
                    mod_windows.setdefault(a.service, set()).add(a.window)
            direct_node_ev = {s for s, ws in mod_windows.items()
                              if len(ws) >= 2}
            # NOTE a known, irreducible single-modality corner: a leaf
            # callee with no own-parented spans (entry-only service)
            # shows IDENTICAL span evidence under "node fault in me" and
            # "link fault from my caller" — its self-edge has no traffic
            # to stay cool or go hot.  The ranking prefers the CALLER
            # (link) reading, which wins every edge-locus benchmark and
            # costs exactly one spans-only cell on SN (the multimodal
            # planes disambiguate it: node faults degrade the callee's
            # logs/metrics, link faults cannot — SN multimodal stays
            # 9/9).  Fan-out-parsimony and self-traffic gating were both
            # tried and measured WORSE on the edge benchmarks (they
            # surrender the caller attribution exactly where the link
            # signal is spread across thin callees).
            hot_children = {c for p in self._edge_hot
                            for c in self._callees_of(p)}
            for c in hot_children:
                if c in peak and not self._self_hot[c] \
                        and c not in direct_node_ev:
                    edge_explained.add(c)
            #: callers whose evidence is mostly edge-borne — their
            #: anomaly is ABOUT their outgoing links, so it must not be
            #: explained away by the blast those same links cause
            #: downstream (stalled traces thin downstream throughput,
            #: firing drop/cusum on the callees' subtrees)
            edge_dom = {p for p, eh in self._edge_hot.items()
                        if p in total and eh >= 0.5 * total[p]}
            if edge_dom:
                # upstream blast: callers of a link-faulted service stall
                # (their traces wait on the slow edge), firing drop/cusum
                # with peaks that can dwarf the culprit's edge z — the
                # walk's magnitude guard then refuses to explain them.
                # A service whose evidence is neither node-borne nor
                # edge-dominant, and from which an edge-dominant caller
                # is reachable, is that caller's blast radius.
                direct = {}
                for a, c in self.call_edges:
                    direct.setdefault(a, set()).add(c)

                def _reaches_edge_dom(q):
                    seen, frontier = {q}, [q]
                    while frontier:
                        nxt = direct.get(frontier.pop(), ())
                        for r in nxt:
                            if r in edge_dom:
                                return True
                            if r not in seen:
                                seen.add(r)
                                frontier.append(r)
                    return False

                for q in set(peak) - edge_dom - edge_explained:
                    if not self._self_hot[q] and q not in direct_node_ev \
                            and _reaches_edge_dom(q):
                        edge_explained.add(q)
        anomalous = set(peak) - edge_explained
        explained = _explained_by_downstream(self.call_edges, anomalous,
                                             peaks=peak, windows=windows)
        if edge_dom:
            # an edge-dominant caller yields only to NODE-borne anomalies
            # downstream (hot self-edge or direct modality evidence — a
            # real culprit living deeper), not to its own blast radius.
            # (A direct-callee-only variant was measured in round 4: it
            # keeps sparse edge culprits from being explained away by
            # unrelated downstream decoys, but costs the same number of
            # in-dist cells where a blast-heated caller must yield to a
            # node culprit whose self-edge is underpowered — net zero on
            # top1, so the general walk stays.)
            # Concentration refutation (round 5): sustained modality
            # evidence alone cannot certify a callee as node-borne when
            # the per-pair data says its edge-dominant caller's heat is
            # SPREAD across callees — under a link fault, planted decoys
            # downstream of the culprit carry exactly that signature and
            # were forcing the culprit to yield to them.  A callee the
            # caller's heat CONCENTRATES on keeps (indeed earns) its
            # node-borne status; with no pair data the old reading
            # stands.
            verdicts = {p: self._pair_verdict(p) for p in edge_dom}

            def _node_borne(s):
                if self._self_hot[s]:
                    return True
                if s not in direct_node_ev:
                    return False
                calling = [verdicts[p] for p in edge_dom
                           if verdicts[p] is not None
                           and s in self._callees_of(p)]
                # concentration wins over a spread refutation from some
                # other caller (one caller's heat pointing squarely at s
                # IS the node-culprit signature, and this must agree
                # with conc_exempt's any-caller semantics — never with
                # set iteration order)
                if any(v == ("concentrated", s) for v in calling):
                    return True
                return not any(v == ("spread", -1) for v in calling)
            node_borne = {s for s in anomalous if _node_borne(s)}
            strict = _explained_by_downstream(
                self.call_edges, node_borne | edge_dom,
                peaks=peak, windows=windows)
            explained = (explained - edge_dom) | (strict & edge_dom)

        # Plane-corroboration tier, active only when (a) an edge-dominant
        # candidate exists and (b) the run is genuinely multimodal (>= 2
        # evidence plane groups fired somewhere).  An out-edge alert is
        # precision-calibrated structural evidence — it survived a
        # dominance scan over the whole out-edge plane — while its z is
        # arithmetically small next to a raw 6-sigma log/metric window on
        # some unrelated service (S x W cells of multiple testing plus
        # planted confounders produce those routinely at sparse density).
        # The reorder is PAIRWISE, not a global tier: each edge-dominant
        # candidate lifts above the single-plane services ranked ahead of
        # it, and every pair NOT involving an edge-dominant candidate
        # keeps its magnitude order — a global tier was measured to cost
        # two in-dist cells by letting arbitrary services pass a
        # single-plane node culprit it had demoted.
        uncorroborated: set = set()
        if edge_dom and os.environ.get("ANOMOD_RANK_TIER", "1") != "0":
            groups = plane_groups
            if len(set().union(*groups.values())) >= 2:
                # span-plane evidence is exempt even alone: latency/error
                # /drop z is anchored to the service's own traffic (a node
                # culprit can legitimately be spans-only at sparse
                # density), while a lone log/metric/api plane with healthy
                # spans is exactly the planted-confounder shape
                # concentration exemption: when an edge-dominant
                # candidate's per-pair heat is CONCENTRATED on one
                # callee, that callee is the node-culprit reading of the
                # same picture (the caller's "edge evidence" is blast
                # pointing at it) — the bubble must not let the blast
                # outrank it.  Spread heat (the edge-locus signature)
                # exempts nobody, which is what lets sustained
                # single-plane decoys be demoted where the earlier
                # sustained-evidence exemption had to protect them.
                conc_exempt = {v[1] for v in verdicts.values()
                               if v is not None and v[0] == "concentrated"}
                # a SUSTAINED-modality service is demotable only under a
                # positive spread refutation (it is a callee of an
                # edge-dominant caller whose pair heat is spread); with
                # no pair data the node-culprit reading stands — absence
                # of evidence must not demote a real culprit
                spread_callees: set = set()
                for p, v in verdicts.items():
                    if v == ("spread", -1):
                        spread_callees |= self._callees_of(p)
                uncorroborated = {
                    s for s in total
                    if s not in edge_dom and not self._self_hot[s]
                    and s not in conc_exempt
                    and (s not in direct_node_ev or s in spread_callees)
                    and len(groups.get(s, ())) < 2
                    and "span" not in groups.get(s, ())}

        # ranking key: SUM of alert scores, not the single peak — a
        # culprit sustains its anomaly across the fault (many windows,
        # several evidence channels) while a blast-radius victim flickers;
        # persistence is signal the peak throws away.  Guards above still
        # compare peaks (comparable instantaneous strength).
        def key(s):
            return (s in explained or s in edge_explained, -total[s])

        order = sorted(total, key=key)
        if uncorroborated:
            # bubble each edge-dominant candidate above adjacent
            # uncorroborated services within the same explained tier:
            # exactly the pairs the corroboration argument covers move
            changed = True
            while changed:
                changed = False
                for i in range(len(order) - 1):
                    a, b = order[i], order[i + 1]
                    if a in uncorroborated and b in edge_dom \
                            and key(a)[0] == key(b)[0]:
                        order[i], order[i + 1] = b, a
                        changed = True
        return [self.services[s] for s in order]

    def first_alert_window(self, service_name: Optional[str] = None):
        ws = [a.window for a in self.alerts
              if service_name is None or a.service_name == service_name]
        return min(ws) if ws else None


def score_closed_windows_batched(work, gather_cols) -> int:
    """Score many detectors' newly closed windows in ONE vectorized pass.

    ``work`` is a list of ``(det, start, through)`` — ``batch_scorable``
    detectors (base span-plane math only) whose
    :meth:`OnlineDetector.scoring_window_range` returned ``(start,
    through)``.  ``gather_cols(items)`` materializes plane columns:
    ``items`` is a list of ``(work_index, col)`` pairs and the return is
    float32 ``[len(items), K, F]`` — the serve engine backs it with one
    fused device-pool gather per window (only the scored columns leave
    the device), host-state replays contribute plane views.

    This is the serving plane's batched COMMIT scorer: the per-window z
    math is :func:`window_span_z` (the sequential scorer's own core)
    applied with a leading tenant axis, and the threshold compare /
    hysteresis streak / CUSUM carry / alert construction run the same
    elementwise ops the per-tenant loop runs — so alerts, streaks, CUSUM
    state and ``_scored_through`` advance BYTE-identically to calling
    ``det._score_through(through)`` per tenant (pinned in
    tests/test_serve_state.py), while the per-tenant Python loop over
    plane readbacks and small-array z pipelines collapses into one
    stacked pass per closed window.  Calibration (a once-per-tenant
    event) gathers each tenant's baseline block through its own
    ``agg_plane()`` exactly as the sequential path would.

    Returns the number of alerts raised.
    """
    if not work:
        return 0
    dets = [d for d, _, _ in work]
    K = dets[0]._K
    assert all(d._K == K for d in dets), \
        "batched scoring needs a uniform service table"
    # calibrate first (the sequential path calibrates at the same
    # moment: the first _score_through that passes the early return)
    for det in dets:
        if det._baseline is None:
            det.ensure_baseline(det.replay.agg_plane())
    # stacked frozen baselines + mutable scoring state (written back at
    # the end; rows are per-tenant, so views never alias across tenants)
    bkeys = ("mu_l", "var_span", "var_bl", "p_err", "err_var", "var_be",
             "active", "cum_active", "calibrated", "rate0", "sd_cnt")
    b_all = {k: np.stack([d._baseline[k] for d in dets]) for k in bkeys}
    streak = np.stack([d._streak for d in dets])
    cusum = np.stack([d._cusum for d in dets])
    cusum_k = np.stack([d._cusum_k for d in dets])
    min_count = np.asarray([d.min_count for d in dets])[:, None]
    drop_memory = np.asarray([d.drop_memory for d in dets])[:, None]
    consecutive = np.asarray([d.consecutive for d in dets])[:, None]
    thr = np.asarray([d.z_threshold for d in dets])[:, None]
    offs = np.asarray([d.replay.window_offset for d in dets])
    new_alerts: dict = {t: [] for t in range(len(dets))}
    lo = min(s for _, s, _ in work)
    hi = max(t for _, _, t in work)
    for w in range(lo, hi + 1):
        act = np.asarray([s <= w <= t for _, s, t in work], bool)
        if not act.any():
            continue
        idx = np.nonzero(act)[0]
        cols = w - offs[idx]
        gathered = gather_cols(
            [(int(i), int(max(c, 0))) for i, c in zip(idx, cols)])
        # fleet activity per tenant (node rows see every span once);
        # a window nobody reported in is feed silence, and — exactly as
        # a column evicted before it could score — it breaks hysteresis
        # and the CUSUM run instead of becoming per-service evidence
        fleet = gathered[..., F_COUNT].sum(axis=1) > 0
        skip = (cols < 0) | ~fleet
        if skip.any():
            reset = idx[skip]
            streak[reset] = 0
            cusum[reset] = 0.0
            cusum_k[reset] = 0
        live = idx[~skip]
        if live.size == 0:
            continue
        z = window_span_z(gathered[~skip],
                          {k: v[live] for k, v in b_all.items()},
                          cusum[live], cusum_k[live],
                          min_count[live], drop_memory[live])
        cusum[live] = z["cusum"]
        cusum_k[live] = z["cusum_k"]
        # channel order = SPAN_EV_NAMES, the sequential part-dict order
        det_stack = np.stack([z["zl"], z["ze"], z["zd"], z["zdc"]])
        rank_stack = np.stack([z["zl"], z["ze"], z["zd"] * z["frac_w"],
                               z["zdc"] * z["frac_t"]])
        detect_z = det_stack.max(axis=0)
        score = rank_stack.max(axis=0)
        ev_idx = rank_stack.argmax(axis=0)
        hot = detect_z >= thr[live]
        streak[live] = np.where(hot, streak[live] + 1, 0)
        firing = streak[live] >= consecutive[live]
        for j, s in np.argwhere(firing):
            t = int(live[j])
            det = dets[t]
            new_alerts[t].append(Alert(
                window=w, service=int(s),
                service_name=det.services[s],
                score=float(score[j, s]),
                z_latency=float(z["zl"][j, s]),
                z_error=float(z["ze"][j, s]),
                z_drop=float(z["zd"][j, s]),
                z_drop_cum=float(z["zdc"][j, s]),
                evidence=SPAN_EV_NAMES[int(ev_idx[j, s])]))
    n_alerts = 0
    for t, (det, _, through) in enumerate(work):
        det._streak = streak[t].copy()
        det._cusum = cusum[t].copy()
        det._cusum_k = cusum_k[t].copy()
        det._scored_through = through
        det._after_score(through)
        det.alerts.extend(new_alerts[t])
        n_alerts += len(new_alerts[t])
    return n_alerts


class MultimodalDetector(OnlineDetector):
    """Online detector fusing all the time-resolved modalities.

    The offline detector scores five modalities at experiment granularity
    (anomod.detect.extract_features); this is its streaming counterpart:
    logs, metrics, and API responses accumulate into per-(service,
    absolute-window) host planes (kB/s volumes — the MXU plane is for
    spans) and contribute three per-service z signals to every closed
    window, fused with the span statistics in the base class:

    - ``log``: Laplace-smoothed binomial z on the window's log-error rate
      (collect_log.sh's error counting, made into a statistic);
    - ``metric``: per-SERIES |z| of the window mean vs its own frozen
      baseline (counters detected by monotone baseline means and
      rate-ified by window diffs, Prometheus-style), max over the
      service's series — this is the plane that localizes a killed
      sparse service (request-rate collapse, error-rate series,
      kube_pod restarts) when its span stream is too thin to matter;
    - ``api``: binomial z on per-owner-service probe error rates
      (endpoint→owner via the gateway route tables, as offline).

    Coverage is not time-resolved (end-of-run artifact) and stays
    offline-only.  Modalities must be pushed before the span push that
    closes their windows (stream_experiment_multimodal slices all four
    on one clock).
    """

    #: minimum lines/records in a window for its rate to be scored
    MIN_EVENTS = 3.0

    def __init__(self, batch_services: Sequence[str], cfg: ReplayConfig,
                 t0_us: int, testbed: Optional[str] = None, **kw):
        super().__init__(batch_services, cfg, t0_us, **kw)
        self.testbed = testbed
        self._t0_s = t0_us / 1e6
        self._win_s = cfg.window_us / 1e6
        self._svc_index = {s: i for i, s in enumerate(batch_services)}
        S = len(batch_services)
        self._S = S
        self._log_tot: dict = {}     # abs window -> [S] float
        self._log_err: dict = {}
        self._api_tot: dict = {}
        self._api_err: dict = {}
        # metric series: canonical key -> {"svc": id, "win": {w: [sum, n]}}
        self._met: dict = {}
        self._mm_base: Optional[dict] = None
        self._owner_cache: dict = {}

    def _windows_of(self, t_s: np.ndarray) -> np.ndarray:
        return ((t_s - self._t0_s) // self._win_s).astype(np.int64)

    def push_logs(self, lb) -> None:
        if lb is None or lb.n_lines == 0:
            return
        t0 = time.perf_counter()
        smap = np.array([self._svc_index.get(n, -1) for n in lb.services],
                        np.int32)
        svc = smap[lb.service]
        w = self._windows_of(lb.t_s)
        keep = (svc >= 0) & (w >= 0)
        err = keep & (lb.level == LOG_ERROR)
        for wv in np.unique(w[keep]):
            m = keep & (w == wv)
            tot = self._log_tot.setdefault(int(wv), np.zeros(self._S))
            np.add.at(tot, svc[m], 1.0)
            ev = self._log_err.setdefault(int(wv), np.zeros(self._S))
            me = err & (w == wv)
            np.add.at(ev, svc[me], 1.0)
        self.push_wall_s += time.perf_counter() - t0

    def push_metrics(self, mb) -> None:
        if mb is None or mb.n_samples == 0:
            return
        t0 = time.perf_counter()
        smap = np.array([self._svc_index.get(n, -1) for n in mb.services],
                        np.int32)
        w = self._windows_of(mb.t_s)
        finite = np.isfinite(mb.value)
        # one accumulator per (metric, label-set) PAIR: the schema allows
        # a producer to reuse one series id (label-set id) across metrics,
        # and pooling different metrics' values would poison the baseline
        nm = len(mb.metric_names)
        combo = mb.series.astype(np.int64) * nm + mb.metric
        ok = finite & (w >= 0)
        for cv in np.unique(combo[ok]):
            si, mi = int(cv) // nm, int(cv) % nm
            sv = mb.series_service[si]
            svc = int(smap[sv]) if sv >= 0 else -1
            if svc < 0:
                continue
            sel = ok & (combo == cv)
            key = f"{mb.metric_names[mi]}|{mb.series_keys[si]}"
            rec = self._met.setdefault(key, {"svc": svc, "win": {}})
            for wv, val in zip(w[sel], mb.value[sel]):
                acc = rec["win"].setdefault(int(wv), [0.0, 0])
                acc[0] += float(val)
                acc[1] += 1
        self.push_wall_s += time.perf_counter() - t0

    def push_api(self, ab) -> None:
        if ab is None or ab.n_records == 0:
            return
        t0 = time.perf_counter()
        from anomod.suite import endpoint_owner
        owner = np.empty(len(ab.endpoints), np.int32)
        for i, e in enumerate(ab.endpoints):
            if e not in self._owner_cache:
                self._owner_cache[e] = self._svc_index.get(
                    endpoint_owner(e, self.testbed or "TT"), -1)
            owner[i] = self._owner_cache[e]
        svc = owner[ab.endpoint]
        w = self._windows_of(ab.t_s)
        keep = (svc >= 0) & (w >= 0)
        err = keep & (ab.status >= 500)
        for wv in np.unique(w[keep]):
            m = keep & (w == wv)
            tot = self._api_tot.setdefault(int(wv), np.zeros(self._S))
            np.add.at(tot, svc[m], 1.0)
            ev = self._api_err.setdefault(int(wv), np.zeros(self._S))
            me = err & (w == wv)
            np.add.at(ev, svc[me], 1.0)
        self.push_wall_s += time.perf_counter() - t0

    # -- modality baselines + per-window z --------------------------------

    def _rate_baseline(self, tot: dict, err: dict) -> dict:
        B = self.baseline_windows
        T0 = np.zeros(self._S)
        E0 = np.zeros(self._S)
        rates = []
        for wv in range(B):
            t = tot.get(wv)
            if t is None:
                continue
            e = err.get(wv, np.zeros(self._S))
            T0 += t
            E0 += e
            with np.errstate(invalid="ignore", divide="ignore"):
                rates.append(np.where(t >= self.MIN_EVENTS, e / np.maximum(
                    t, 1.0), np.nan))
        p = (E0 + 1.0) / (T0 + 2.0)
        var = np.maximum(p * (1.0 - p), 1e-6)
        if rates:
            stack = np.stack(rates)           # [B_present, S], NaN = too few
            mask = np.isfinite(stack)
            n = np.maximum(mask.sum(axis=0), 1)
            mean = np.where(mask, stack, 0.0).sum(axis=0) / n
            var_b = np.where(mask, (stack - mean) ** 2, 0.0).sum(axis=0) / n
        else:
            var_b = np.zeros(self._S)
        return dict(p=p, var=var, var_b=var_b)

    def _metric_baseline(self) -> dict:
        B = self.baseline_windows
        out = {}
        for key, rec in self._met.items():
            means = {wv: s / n for wv, (s, n) in rec["win"].items() if n}
            base = [means[wv] for wv in range(B) if wv in means]
            if len(base) < 3:
                continue
            arr = np.asarray(base)
            counter = bool(np.all(np.diff(arr) >= -1e-12) and arr[-1] > arr[0])
            if counter:
                arr = np.diff(arr)
            mu = float(arr.mean())
            # relative sd floor: B windows underestimate a series' true
            # spread often enough that a tighter floor turns ordinary
            # gauge jitter into fake certainty (multiple testing over
            # every series x window)
            sd = float(max(arr.std(), 0.1 * (abs(mu) + 1.0)))
            out[key] = dict(svc=rec["svc"], mu=mu, sd=sd, counter=counter)
        return out

    def _series_z(self, key: str, b: dict, w: int) -> float:
        rec = self._met.get(key)
        if rec is None:
            return 0.0
        acc = rec["win"].get(w)
        if not acc or not acc[1]:
            return 0.0
        v = acc[0] / acc[1]
        if b["counter"]:
            prev = rec["win"].get(w - 1)
            if not prev or not prev[1]:
                return 0.0
            v = v - prev[0] / prev[1]
        return abs(v - b["mu"]) / b["sd"]

    def _mm_calibrate(self) -> None:
        self._mm_base = dict(
            log=self._rate_baseline(self._log_tot, self._log_err),
            api=self._rate_baseline(self._api_tot, self._api_err),
            met=self._metric_baseline())

    def _rate_z(self, w: int, tot: dict, err: dict, base: dict) -> np.ndarray:
        t = tot.get(w)
        if t is None:
            return np.zeros(self._S)
        e = err.get(w, np.zeros(self._S))
        ok = t >= self.MIN_EVENTS
        safe = np.maximum(t, 1.0)
        return np.where(ok, (e / safe - base["p"])
                        / np.sqrt(base["var"] / safe + base["var_b"]), 0.0)

    def _metric_z(self, w: int) -> np.ndarray:
        """Per-service metric z: max over the service's series of the
        SUSTAINED two-window z (min of this window's and the previous
        window's) — metric sampling noise is window-uncorrelated, fault
        effects persist, so the min clips single-window spikes that the
        per-series multiple testing would otherwise surface."""
        z = np.zeros(self._S)
        for key, b in self._mm_base["met"].items():
            zi = min(self._series_z(key, b, w),
                     self._series_z(key, b, w - 1))
            s = b["svc"]
            if zi > z[s]:
                z[s] = zi
        return z

    def _modality_z(self, w: int) -> dict:
        if self._mm_base is None:
            self._mm_calibrate()
        out = {}
        if self._log_tot:
            out["log"] = self._rate_z(w, self._log_tot, self._log_err,
                                      self._mm_base["log"])
        if self._api_tot:
            out["api"] = self._rate_z(w, self._api_tot, self._api_err,
                                      self._mm_base["api"])
        if self._mm_base["met"]:
            out["metric"] = self._metric_z(w)
        return out

    def _after_score(self, through: int) -> None:
        """Bound the per-window host planes: once calibrated, windows
        older than ``through - 1`` are never read again (counter diffs
        need one lookback), so evict them — the modality state stays
        O(ring), matching the span plane's bounded footprint on an
        unbounded live stream."""
        if self._mm_base is None:
            return
        cut = through - 1
        for d in (self._log_tot, self._log_err, self._api_tot,
                  self._api_err):
            for wv in [k for k in d if k < cut]:
                del d[wv]
        for rec in self._met.values():
            win = rec["win"]
            for wv in [k for k in win if k < cut]:
                del win[wv]


#: per-batch-type row fields (explicit — a shape heuristic would corrupt
#: a side table whose length coincidentally equals the sample count,
#: e.g. MetricBatch.series_service when n_series == n_samples)
_ROW_FIELDS = {
    "LogBatch": ("service", "t_s", "level"),
    "MetricBatch": ("metric", "series", "t_s", "value"),
    "ApiBatch": ("endpoint", "t_s", "status", "latency_ms",
                 "content_length"),
}


def _take_nt(nt, mask):
    """Row-subset of a NamedTuple batch: sample-axis fields masked, side
    tables kept whole."""
    fields = _ROW_FIELDS[type(nt).__name__]
    return nt._replace(**{f: getattr(nt, f)[mask] for f in fields})


def stream_experiment_multimodal(exp, cfg: Optional[ReplayConfig] = None,
                                 slice_s: float = 60.0, **detector_kw):
    """Replay a full experiment bundle — spans, logs, metrics, API — in
    arrival order through the multimodal online detector.  One clock
    slices all four modalities; within each slice the low-volume
    modalities are pushed first so their windows are populated before the
    span push closes them.  Returns the finished detector."""
    batch = exp.spans
    cfg = cfg or ReplayConfig(n_services=batch.n_services, chunk_size=4096)
    edges = set()
    if batch.n_spans:
        has_parent = batch.parent >= 0
        edges = set(zip(batch.service[batch.parent[has_parent]].tolist(),
                        batch.service[has_parent].tolist()))
    psvc = resolve_parent_services(batch)
    order = np.argsort(batch.start_us, kind="stable")
    batch = take_spans(batch, order)
    psvc = psvc[order]
    t0 = int(batch.start_us.min()) if batch.n_spans else 0
    det = MultimodalDetector(batch.services, cfg, t0, testbed=exp.testbed,
                             call_edges=edges, **detector_kw)
    if not batch.n_spans:
        det.finish()
        return det
    t0_s = t0 / 1e6
    end_s = float(batch.start_us.max()) / 1e6
    lo_s = t0_s
    while lo_s <= end_s:
        hi_s = lo_s + slice_s
        if exp.logs is not None and exp.logs.n_lines:
            det.push_logs(_take_nt(exp.logs, (exp.logs.t_s >= lo_s)
                                   & (exp.logs.t_s < hi_s)))
        if exp.metrics is not None and exp.metrics.n_samples:
            det.push_metrics(_take_nt(exp.metrics, (exp.metrics.t_s >= lo_s)
                                      & (exp.metrics.t_s < hi_s)))
        if exp.api is not None and exp.api.n_records:
            det.push_api(_take_nt(exp.api, (exp.api.t_s >= lo_s)
                                  & (exp.api.t_s < hi_s)))
        m = (batch.start_us >= lo_s * 1e6) & (batch.start_us < hi_s * 1e6)
        if m.any():
            det.push(take_spans(batch, m), parent_service=psvc[m])
        lo_s = hi_s
    det.finish()
    return det


def _explained_by_downstream(call_edges: set, anomalous: set,
                             peaks: Optional[dict] = None,
                             windows: Optional[dict] = None,
                             rho: float = 0.5) -> set:
    """Anomalous nodes explained by an anomalous node strictly downstream.

    Condense the call graph into strongly-connected components (iterative
    Tarjan), then mark an anomalous node "explained" iff some OTHER SCC
    reachable from its own contains an anomalous node that passes two
    guards (when the data is provided):

    - **magnitude** (``peaks``): the downstream anomaly's peak ranking
      score must be ≥ ``rho`` × the caller's — blame flows downstream
      only onto an anomaly of comparable strength; a marginal noise
      alert deep in the graph must not demote a loud true culprit above
      it;
    - **onset** (``windows``): the explanation must start WITH the
      symptom — the explainer's first alert may lag the caller's by at
      most 2 windows (sparse-culprit detection lag) but never more: a
      downstream victim that only turns anomalous 8 windows into the
      caller's sustained anomaly is a consequence, not a cause (the
      code-fault-in-the-caller case);
    - **concentration** (``windows``): the explainer's activity must
      either mostly fall inside the caller's anomalous interval (±1) or
      cover at least half of that interval — an "explainer" that mostly
      fires outside the symptom's period (scattered noise blips) explains
      nothing, while a sustained culprit that OUTLASTS a briefly-detected
      symptom still does.

    Nodes locked in a cycle with their only anomalous dependency stay
    unexplained — the edge direction carries no blame signal inside an
    SCC."""
    nodes = {n for e in call_edges for n in e} | set(anomalous)
    adj = {n: [] for n in nodes}
    for a, b in call_edges:
        adj[a].append(b)
    # iterative Tarjan SCC
    index = {}
    low = {}
    comp = {}
    stack, on_stack = [], set()
    counter = [0]
    n_comp = [0]
    for root in nodes:
        if root in index:
            continue
        work = [(root, iter(adj[root]))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for u in it:
                if u not in index:
                    index[u] = low[u] = counter[0]
                    counter[0] += 1
                    stack.append(u)
                    on_stack.add(u)
                    work.append((u, iter(adj[u])))
                    advanced = True
                    break
                if u in on_stack:
                    low[v] = min(low[v], index[u])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                while True:
                    u = stack.pop()
                    on_stack.discard(u)
                    comp[u] = n_comp[0]
                    if u == v:
                        break
                n_comp[0] += 1
    # condensation adjacency + anomalous members per SCC
    canom = {}
    for n in anomalous:
        canom.setdefault(comp[n], set()).add(n)
    cadj = {}
    for a, b in call_edges:
        if comp[a] != comp[b]:
            cadj.setdefault(comp[a], set()).add(comp[b])
    # anomalous nodes in strictly-downstream SCCs.  Tarjan emits SCCs in
    # REVERSE topological order (every successor SCC is completed — gets a
    # smaller id — before its predecessors), so one pass over component
    # ids in emission order visits children before parents: no recursion,
    # no stack-depth limit (the reason Tarjan above is iterative too).
    memo = {}
    for c in range(n_comp[0]):
        acc = set()
        for d in cadj.get(c, ()):
            acc |= canom.get(d, set())
            acc |= memo.get(d, set())
        memo[c] = acc

    def downstream_anom(c):
        return memo[c]

    def guards_pass(n, b):
        if peaks is not None and peaks.get(b, 0.0) < rho * peaks.get(n, 0.0):
            return False
        if windows is not None:
            wn, wb = windows.get(n, set()), windows.get(b, set())
            if not wn or not wb:
                return False
            first_n, last_n = min(wn), max(wn)
            if min(wb) > first_n + 2:          # consequence, not cause
                return False
            inside = sum(1 for y in wb
                         if first_n - 1 <= y <= last_n + 1)
            span_n = last_n - first_n + 1
            if inside < 0.5 * len(wb) and inside < 0.5 * span_n:
                return False                   # scattered blips
        return True

    return {n for n in anomalous
            if any(guards_pass(n, b) for b in downstream_anom(comp[n]))}


def stream_quality(testbed: str = "TT", n_traces: int = 400, seed: int = 0,
                   experiments: Optional[Sequence[str]] = None,
                   multimodal: bool = False, severity: float = 1.0,
                   noise: float = 0.0, n_confounders: int = 0,
                   shift: str = "in-dist", **detector_kw) -> List[dict]:
    """Streaming-mode quality over the full fault taxonomy: one row per
    experiment with localization (top1/top3 among alerted services) and
    signed detection latency in windows (fault onset = window 10).  The
    streaming analog of detect.evaluate_corpus — measures what the
    offline sweep cannot: how FAST the fault surfaces.  ``experiments``
    filters to a subset by name (tests); ``multimodal`` fuses the
    log/metric/api planes (stream_experiment_multimodal); ``severity`` /
    ``noise`` / ``n_confounders`` de-saturate the generator via the SAME
    corpus builder as the offline quality sweep (rca.experiment_stream) —
    a streaming-vs-offline comparison at matching knobs scores identical
    difficulty; ``shift`` evaluates under the offline sweep's shifted
    generators (quality.SHIFTS: effect shape / fault timing / locus) —
    the detector is training-free, so this measures raw statistic
    robustness, e.g. whether bursty on/off faults defeat the CUSUM's
    recovery reset."""
    from anomod import synth
    from anomod.quality import SHIFTS
    from anomod.rca import experiment_stream
    # fault onset in WINDOWS follows the window width actually in use
    # (synth faults start at 600 s; a custom cfg rescales the grid)
    cfg = detector_kw.get("cfg")
    win_us = cfg.window_us if cfg is not None else 60_000_000
    onset_w = int(600_000_000 // win_us)
    hard = synth.HardMode(severity=severity, noise=noise, **SHIFTS[shift])
    rows = []
    for label, exp in experiment_stream(testbed, seed, n_traces=n_traces,
                                        hard=hard,
                                        n_confounders=n_confounders,
                                        experiments=experiments):
        det = (stream_experiment_multimodal(exp, **detector_kw) if multimodal
               else stream_experiment(exp.spans, **detector_kw))
        ranked = det.ranked_services()
        row = dict(experiment=label.experiment, testbed=testbed,
                   target_service=label.target_service,
                   n_alerts=len(det.alerts), ranked_top3=ranked[:3])
        if label.is_anomaly and label.target_service:
            fw = det.first_alert_window(label.target_service)
            row.update(
                top1_hit=bool(ranked) and ranked[0] == label.target_service,
                top3_hit=label.target_service in ranked[:3],
                first_culprit_alert_window=fw,
                detection_latency_windows=(None if fw is None
                                           else fw - onset_w))
        rows.append(row)
    return rows


def stream_experiment(batch: SpanBatch, cfg: Optional[ReplayConfig] = None,
                      slice_s: float = 60.0, **detector_kw):
    """Replay a corpus in arrival order through the online detector.

    Sorts spans by start time, slices the timeline into ``slice_s``-second
    micro-batches, and pushes each — the offline corpus standing in for a
    live feed.  Returns the finished :class:`OnlineDetector`.
    """
    cfg = cfg or ReplayConfig(n_services=batch.n_services, chunk_size=4096)
    # observed call graph from span parents — computed on the FULL batch
    # (time slices cut parent/child pairs across micro-batches, so the
    # caller of each span must be resolved before slicing)
    edges = set()
    if batch.n_spans and "call_edges" not in detector_kw:
        has_parent = batch.parent >= 0
        callers = batch.service[batch.parent[has_parent]]
        callees = batch.service[has_parent]
        edges = set(zip(callers.tolist(), callees.tolist()))
        detector_kw = dict(detector_kw, call_edges=edges)
    # parent services resolve on the FULL batch (same reason as edges:
    # slicing breaks the parent row indices), then ride the sort order
    psvc = resolve_parent_services(batch)
    order = np.argsort(batch.start_us, kind="stable")
    batch = take_spans(batch, order)
    psvc = psvc[order]
    t0 = int(batch.start_us.min()) if batch.n_spans else 0
    det = OnlineDetector(batch.services, cfg, t0, **detector_kw)
    if batch.n_spans:
        rel_s = (batch.start_us - t0) / 1e6
        bounds = np.searchsorted(
            rel_s, np.arange(slice_s, float(rel_s[-1]) + slice_s, slice_s))
        for lo, hi in zip(np.concatenate([[0], bounds]),
                          np.concatenate([bounds, [batch.n_spans]])):
            if hi > lo:
                sl = slice(int(lo), int(hi))
                det.push(take_spans(batch, sl), parent_service=psvc[sl])
    det.finish()
    return det
