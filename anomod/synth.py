"""Deterministic synthetic data generator — schema-identical to the reference.

Most SN_data/TT_data payloads in the reference checkout are git-LFS pointer
stubs (SURVEY.md §2.3), so this generator is the stand-in corpus: seeded like
the reference's graph seeder (``random.seed(1)``, init_social_graph.py:149),
it emits data matching each modality's schema contract exactly:

  - TT traces: SkyWalking collector JSON (trace_collector.py:552-584 metadata
    + traces[{summary, spans[to_dict contract :86-123]}]).
  - SN traces: Jaeger API JSON (data[{traceID, processes, spans}]) as consumed
    by jaeger_to_csv.py:20-74, plus the flattened 13-column CSV.
  - Metrics: SN per-query CSVs (timestamp,value,metric,<labels> —
    fetch_prometheus_metrics.py:57-66) and the TT long CSV
    (metric_name,timestamp,datetime,value,<labels> — metric_collector.py:431-443).
  - Logs: per-service line streams + summary counts (collect_log.sh:101-137).
  - API responses: JSONL records (enhanced_openapi_monitor.py:155-169).
  - Coverage: per-(service,file) line counters (gcov / JaCoCo LINE,
    coverage_summary.py:97-125).

Fault labels condition the generated distributions so detectors and RCA have
ground-truth signal: latency inflation for performance/database faults, error
injection for service/code faults, matching the reference's sanity thresholds
(SN_collection-scripts/README.md:106: CPU fault ⇒ >90% system CPU).
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from anomod import labels as labels_mod
from anomod.labels import FaultLabel
from anomod.schemas import (
    KIND_ENTRY, KIND_EXIT, KIND_LOCAL, KIND_NAMES,
    LOG_ERROR, LOG_INFO, LOG_OTHER, LOG_WARN,
    ApiBatch, CoverageBatch, Experiment, FileCoverage, LogBatch, LogSummary,
    MetricBatch, SpanBatch, coverage_batch_from_files,
)

#: Ingest-cache key component (anomod.io.cache) for synth-fallback entries:
#: bump whenever generator output changes for the same (label, seed,
#: n_traces), invalidating every cached synthetic modality.
SYNTH_VERSION = 1

# ---------------------------------------------------------------------------
# Service topologies.
# SN: the 12 core services of DeathStarBench SocialNetwork
# (collect_log.sh:31-44); edges reflect the compose/read call paths
# (mixed-workload.lua:111-125 drives home-timeline/user-timeline/compose).
# ---------------------------------------------------------------------------

SN_SERVICES: Tuple[str, ...] = (
    "nginx-web-server", "compose-post-service", "post-storage-service",
    "user-service", "user-mention-service", "unique-id-service",
    "media-service", "social-graph-service", "user-timeline-service",
    "url-shorten-service", "home-timeline-service", "text-service",
)

SN_EDGES: Tuple[Tuple[str, str], ...] = (
    ("nginx-web-server", "compose-post-service"),
    ("nginx-web-server", "home-timeline-service"),
    ("nginx-web-server", "user-timeline-service"),
    ("nginx-web-server", "user-service"),
    ("nginx-web-server", "social-graph-service"),
    ("compose-post-service", "unique-id-service"),
    ("compose-post-service", "user-service"),
    ("compose-post-service", "media-service"),
    ("compose-post-service", "text-service"),
    ("compose-post-service", "post-storage-service"),
    ("compose-post-service", "user-timeline-service"),
    ("compose-post-service", "home-timeline-service"),
    ("text-service", "url-shorten-service"),
    ("text-service", "user-mention-service"),
    ("home-timeline-service", "post-storage-service"),
    ("home-timeline-service", "social-graph-service"),
    ("user-timeline-service", "post-storage-service"),
)

# TT: the Train-Ticket ts-* services observed in TT_data pod logs
# (TT_data/log_data/<exp>/ listing) and gen-mysql-secret.sh:2; edges follow
# the booking flow exercised by test_all_services.py:127-196.
TT_SERVICES: Tuple[str, ...] = (
    "ts-gateway-service", "ts-auth-service", "ts-user-service", "ts-verification-code-service",
    "ts-travel-service", "ts-travel2-service", "ts-travel-plan-service", "ts-route-plan-service",
    "ts-route-service", "ts-train-service", "ts-station-service", "ts-basic-service",
    "ts-seat-service", "ts-config-service", "ts-price-service", "ts-ticketinfo-service",
    "ts-preserve-service", "ts-preserve-other-service", "ts-security-service",
    "ts-contacts-service", "ts-assurance-service", "ts-food-service",
    "ts-station-food-service", "ts-train-food-service", "ts-food-delivery-service",
    "ts-consign-service", "ts-consign-price-service", "ts-order-service",
    "ts-order-other-service", "ts-inside-payment-service", "ts-payment-service",
    "ts-cancel-service", "ts-execute-service", "ts-rebook-service", "ts-delivery-service",
    "ts-notification-service", "ts-news-service", "ts-voucher-service",
    "ts-wait-order-service", "ts-admin-order-service", "ts-admin-route-service",
    "ts-admin-travel-service", "ts-admin-user-service", "ts-admin-basic-info-service",
    "ts-avatar-service",
)

TT_EDGES: Tuple[Tuple[str, str], ...] = (
    ("ts-gateway-service", "ts-auth-service"),
    ("ts-gateway-service", "ts-user-service"),
    ("ts-gateway-service", "ts-travel-service"),
    ("ts-gateway-service", "ts-travel2-service"),
    ("ts-gateway-service", "ts-travel-plan-service"),
    ("ts-gateway-service", "ts-preserve-service"),
    ("ts-gateway-service", "ts-preserve-other-service"),
    ("ts-gateway-service", "ts-order-service"),
    ("ts-gateway-service", "ts-order-other-service"),
    ("ts-gateway-service", "ts-cancel-service"),
    ("ts-gateway-service", "ts-execute-service"),
    ("ts-gateway-service", "ts-rebook-service"),
    ("ts-gateway-service", "ts-consign-service"),
    ("ts-gateway-service", "ts-food-service"),
    ("ts-gateway-service", "ts-contacts-service"),
    ("ts-gateway-service", "ts-admin-order-service"),
    ("ts-gateway-service", "ts-admin-route-service"),
    ("ts-gateway-service", "ts-admin-travel-service"),
    ("ts-gateway-service", "ts-admin-user-service"),
    ("ts-gateway-service", "ts-admin-basic-info-service"),
    ("ts-auth-service", "ts-verification-code-service"),
    ("ts-user-service", "ts-auth-service"),
    ("ts-user-service", "ts-avatar-service"),
    ("ts-travel-service", "ts-basic-service"),
    ("ts-travel-service", "ts-train-service"),
    ("ts-travel-service", "ts-route-service"),
    ("ts-travel-service", "ts-seat-service"),
    ("ts-travel-service", "ts-ticketinfo-service"),
    ("ts-travel2-service", "ts-basic-service"),
    ("ts-travel2-service", "ts-route-service"),
    ("ts-travel-plan-service", "ts-route-plan-service"),
    ("ts-travel-plan-service", "ts-travel-service"),
    ("ts-route-plan-service", "ts-route-service"),
    ("ts-route-plan-service", "ts-travel-service"),
    ("ts-basic-service", "ts-station-service"),
    ("ts-basic-service", "ts-train-service"),
    ("ts-basic-service", "ts-route-service"),
    ("ts-basic-service", "ts-price-service"),
    ("ts-ticketinfo-service", "ts-basic-service"),
    ("ts-seat-service", "ts-config-service"),
    ("ts-seat-service", "ts-order-service"),
    ("ts-preserve-service", "ts-seat-service"),
    ("ts-preserve-service", "ts-security-service"),
    ("ts-preserve-service", "ts-contacts-service"),
    ("ts-preserve-service", "ts-assurance-service"),
    ("ts-preserve-service", "ts-food-service"),
    ("ts-preserve-service", "ts-consign-service"),
    ("ts-preserve-service", "ts-order-service"),
    ("ts-preserve-service", "ts-user-service"),
    ("ts-preserve-service", "ts-travel-service"),
    ("ts-preserve-service", "ts-station-service"),
    ("ts-preserve-other-service", "ts-seat-service"),
    ("ts-preserve-other-service", "ts-security-service"),
    ("ts-preserve-other-service", "ts-order-other-service"),
    ("ts-security-service", "ts-order-service"),
    ("ts-security-service", "ts-order-other-service"),
    ("ts-food-service", "ts-station-food-service"),
    ("ts-food-service", "ts-train-food-service"),
    ("ts-food-service", "ts-food-delivery-service"),
    ("ts-consign-service", "ts-consign-price-service"),
    ("ts-consign-service", "ts-order-service"),
    ("ts-order-service", "ts-station-service"),
    ("ts-inside-payment-service", "ts-order-service"),
    ("ts-inside-payment-service", "ts-payment-service"),
    ("ts-cancel-service", "ts-order-service"),
    ("ts-cancel-service", "ts-order-other-service"),
    ("ts-cancel-service", "ts-inside-payment-service"),
    ("ts-cancel-service", "ts-notification-service"),
    ("ts-execute-service", "ts-order-service"),
    ("ts-rebook-service", "ts-travel-service"),
    ("ts-rebook-service", "ts-order-service"),
    ("ts-rebook-service", "ts-seat-service"),
    ("ts-rebook-service", "ts-inside-payment-service"),
    ("ts-delivery-service", "ts-food-service"),
    ("ts-wait-order-service", "ts-order-service"),
    ("ts-admin-order-service", "ts-order-service"),
    ("ts-admin-order-service", "ts-order-other-service"),
    ("ts-admin-route-service", "ts-route-service"),
    ("ts-admin-travel-service", "ts-travel-service"),
    ("ts-admin-user-service", "ts-user-service"),
    ("ts-admin-basic-info-service", "ts-basic-service"),
)

SN_API_ENDPOINTS: Tuple[str, ...] = tuple(
    f"http://localhost:8080/wrk2-api/{p}" for p in (
        "user/register", "user/follow", "user/unfollow", "user/login",
        "post/compose", "home-timeline/read", "user-timeline/read",
        "user/profile", "media/upload", "text/upload", "url/shorten",
        "user-mention/upload",
    )
)  # enhanced_openapi_monitor.py:36-49


def _seed_for(name: str, salt: int = 0) -> int:
    h = hashlib.sha256(f"{name}:{salt}".encode()).digest()
    return int.from_bytes(h[:8], "little") % (2**63)


def _topology(testbed: str):
    if testbed == "SN":
        return SN_SERVICES, SN_EDGES, "nginx-web-server"
    return TT_SERVICES, TT_EDGES, "ts-gateway-service"


# ---------------------------------------------------------------------------
# Trace templates: deterministic random walks over the topology.  Each
# template is a list of (service_idx, parent_pos, kind) triples; traces are
# instantiated per-template in vectorized batches.
# ---------------------------------------------------------------------------

def build_templates(testbed: str, n_templates: int = 24, max_depth: int = 5,
                    seed: int = 1) -> List[List[Tuple[int, int, int]]]:
    services, edges, root = _topology(testbed)
    svc_idx = {s: i for i, s in enumerate(services)}
    children: Dict[int, List[int]] = {}
    for a, b in edges:
        children.setdefault(svc_idx[a], []).append(svc_idx[b])
    rng = np.random.default_rng(seed)
    templates = []
    for _ in range(n_templates):
        tpl: List[Tuple[int, int, int]] = [(svc_idx[root], -1, KIND_ENTRY)]
        frontier = [(svc_idx[root], 0, 0)]  # (svc, pos in tpl, depth)
        while frontier:
            svc, pos, depth = frontier.pop()
            kids = children.get(svc, [])
            if not kids or depth >= max_depth:
                continue
            n_kids = int(rng.integers(1, min(len(kids), 3) + 1))
            picked = rng.choice(len(kids), size=n_kids, replace=False)
            for k in picked:
                child_svc = kids[int(k)]
                # Exit span on caller, Entry span on callee (SkyWalking style).
                tpl.append((svc, pos, KIND_EXIT))
                exit_pos = len(tpl) - 1
                tpl.append((child_svc, exit_pos, KIND_ENTRY))
                entry_pos = len(tpl) - 1
                frontier.append((child_svc, entry_pos, depth + 1))
        templates.append(tpl)
    return templates


@dataclasses.dataclass(frozen=True)
class HardMode:
    """Difficulty knobs for de-saturated evaluation corpora.

    The full-strength fault effects (6-20x latency, 0.5-0.7 error rates) make
    every detector score 1.0; these knobs produce the regimes where models
    actually separate:

    - ``severity`` interpolates every fault effect toward baseline
      (0.05 => ~1.25x latency / ~2.5% error on a service fault — the
      1.2-2x / 2-5% operating band).
    - ``noise`` widens the baseline distributions (log-latency sigma scales
      by 1+noise, baseline error jitter grows), shrinking the fault SNR.
    - ``confounders`` names decoy services that also degrade (fixed mild
      1.5x latency / 2% errors in the same anomaly window, independent of
      severity) — the ranking must still put the labeled culprit first.

    The three ``*_shape/profile/locus`` knobs are the DISTRIBUTION-SHIFT
    axes (round-2 weak #4: generator and evaluator shared one effect
    model, so quality rankings could be statements about the generator).
    Train on the default effect model, evaluate under shift:

    - ``effect_shape``: how fault latency manifests on affected spans —
      "mult" (lognormal location shift, the training shape), "add" (a
      constant offset — spread does not scale with the effect), "tail"
      (only ~12% of affected spans inflate, 3x harder — p99 moves, the
      median barely does).
    - ``fault_profile``: when the fault is active inside the anomaly
      window — "sustained" (the whole [600, 1200) s window), "bursty"
      (alternating 60 s on/off bursts), "partial" (first half only).
      Applied consistently across ALL modality generators via
      :func:`anomaly_window_mask` so the corpus stays time-synchronized.
    - ``fault_locus``: where the fault manifests — "node" (the culprit
      service's own spans) or "edge" (the callee side of the culprit's
      outgoing calls, like a link fault: node-scoped metrics/logs stay
      healthy, coverage does not shift, API routes degrade only when the
      target actually has outgoing calls, and attribution must come from
      trace structure; a target with NO outgoing calls faults no edge, so
      its corpus carries no localizing signal at all — the honest floor
      for every detector).
    """
    severity: float = 1.0
    noise: float = 0.0
    confounders: Tuple[str, ...] = ()
    effect_shape: str = "mult"        # "mult" | "add" | "tail"
    fault_profile: str = "sustained"  # "sustained" | "bursty" | "partial"
    fault_locus: str = "node"         # "node" | "edge"


_EASY = HardMode()

# Fixed confounder effect (NOT scaled by severity: decoys stay at this level
# while the true fault shrinks, so low severity is genuinely confusable).
_CONFOUND_LAT, _CONFOUND_ERR = 1.5, 0.02


def scale_mult(mult: float, severity: float) -> float:
    """Interpolate a fault multiplier toward 1.0 (works for <1 drops too)."""
    return 1.0 + (mult - 1.0) * severity


def anomaly_window_mask(rel_s, profile: str = "sustained"):
    """Fault-active mask from experiment-relative times in SECONDS — the one
    definition of the anomaly window every modality generator uses, so a
    fault_profile shift stays time-synchronized across spans, metrics,
    logs, and API records.

    "sustained" = the whole middle third [600, 1200); "bursty" = alternating
    60 s on/off bursts inside it (5 bursts); "partial" = its first half
    [600, 900) only.
    """
    rel_s = np.asarray(rel_s)
    base = (rel_s >= 600) & (rel_s < 1200)
    if profile == "sustained":
        return base
    if profile == "bursty":
        return base & (((rel_s - 600) // 60).astype(np.int64) % 2 == 0)
    if profile == "partial":
        return base & (rel_s < 900)
    raise ValueError(f"unknown fault_profile {profile!r}")


# Per-(level,type) effect multipliers applied to the target service.
def _fault_effects(label: FaultLabel,
                   severity: float = 1.0) -> Tuple[float, float]:
    """Return (latency_multiplier, error_probability) for the culprit
    service, interpolated toward baseline by ``severity``."""
    if not label.is_anomaly:
        return 1.0, 0.002
    lvl, typ = label.anomaly_level, label.anomaly_type
    if lvl == "performance":
        lat, err = {"cpu_contention": 6.0, "disk_io_stress": 4.0,
                    "network_loss": 8.0}.get(typ, 5.0), 0.02
    elif lvl == "service":
        lat, err = ({"kill_service_instance": 2.0, "http_abort": 1.5,
                     "dns_failure": 3.0}.get(typ, 2.0),
                    {"http_abort": 0.7, "kill_service_instance": 0.5,
                     "dns_failure": 0.6}.get(typ, 0.5))
    elif lvl == "database":
        lat, err = {"transaction_timeout": 20.0,
                    "connection_pool_exhaustion": 12.0,
                    "cache_limit": 5.0}.get(typ, 8.0), 0.10
    else:  # code-level: immediate failure responses / exceptions
        lat, err = 1.2, 0.6
    return scale_mult(lat, severity), 0.002 + (err - 0.002) * severity


def generate_spans(label: FaultLabel, n_traces: int = 200,
                   seed: Optional[int] = None,
                   base_time_us: int = 1_762_180_000_000_000,
                   hard: HardMode = _EASY) -> SpanBatch:
    """Generate a fault-conditioned SpanBatch for one experiment."""
    services, _, _ = _topology(label.testbed)
    if n_traces <= 0:
        from anomod.schemas import empty_span_batch
        return empty_span_batch()._replace(services=tuple(services))
    if seed is None:
        seed = _seed_for(label.experiment)
    # Templates are seeded per-TESTBED, not per-experiment: the reference
    # replays the same EvoMaster suite in every experiment, so every
    # experiment sees the same call-path mix (collect_all_modalities.sh:152-171)
    templates = build_templates(label.testbed, seed=_seed_for(label.testbed, 11))
    rng = np.random.default_rng(seed)

    lat_mult, err_p = _fault_effects(label, hard.severity)
    sigma = 0.4 * (1.0 + hard.noise)
    decoy_set = frozenset(hard.confounders)
    target = label.target_service
    target_idx = services.index(target) if target in services else -1
    # SN host-level performance faults hit every service.
    host_level = label.is_anomaly and target_idx < 0

    # Deterministic proportional template assignment: every call path shows
    # up in every experiment (the reference replays its complete suite each
    # iteration — random sampling would leave rare paths out of the normal
    # baseline and fabricate latency-inflation artifacts), with SN templates
    # weighted by the wrk2 request mix (mixed-workload.lua:113-115).
    weights = np.ones(len(templates))
    if label.testbed == "SN":
        from anomod.workload import SN_REQUEST_MIX
        svc_of_root_child = [services[tpl[2][0]] if len(tpl) > 2 else ""
                             for tpl in templates]
        for i, svc in enumerate(svc_of_root_child):
            weights[i] = SN_REQUEST_MIX.get(svc, 0.05) * 10
    alloc = np.maximum((weights / weights.sum() * n_traces).astype(int), 1)
    # trim/pad to exactly n_traces while keeping every template present
    tpl_ids = np.repeat(np.arange(len(templates)), alloc)[:n_traces]
    if tpl_ids.shape[0] < n_traces:
        tpl_ids = np.concatenate([
            tpl_ids, np.arange(n_traces - tpl_ids.shape[0]) % len(templates)])
    rng.shuffle(tpl_ids)
    # Per-service baseline latency (ms, lognormal median), deterministic per testbed.
    svc_rng = np.random.default_rng(_seed_for(label.testbed, 7))
    base_ms = svc_rng.uniform(2.0, 30.0, size=len(services))

    cols = {k: [] for k in ("trace", "parent", "service", "endpoint",
                            "start_us", "duration_us", "is_error", "status", "kind")}
    endpoints: Dict[str, int] = {}
    offset = 0
    # Traces span the full 1800 s experiment; the fault is active in the middle
    # third [600, 1200) s — the same anomaly window generate_metrics and
    # generate_api use, so the five modalities stay time-synchronized.
    trace_start = base_time_us + np.sort(rng.integers(0, 1_800_000_000, size=n_traces))
    trace_in_window = anomaly_window_mask(
        (trace_start - base_time_us) / 1e6, hard.fault_profile)

    for t_id in range(len(templates)):
        mask = tpl_ids == t_id
        m = int(mask.sum())
        if m == 0:
            continue
        tpl = templates[t_id]
        L = len(tpl)
        svc = np.array([s for s, _, _ in tpl], np.int32)
        par_local = np.array([p for _, p, _ in tpl], np.int32)
        kind = np.array([k for _, _, k in tpl], np.int8)
        ep_names = [f"{services[s]}/{'entry' if k == KIND_ENTRY else 'exit'}/{i % 4}"
                    for i, (s, _, k) in enumerate(tpl)]
        ep_ids = np.array([endpoints.setdefault(e, len(endpoints)) for e in ep_names],
                          np.int32)

        # durations: lognormal around per-service base, inflated on the
        # culprit service only while the trace falls in the anomaly window
        tw = trace_in_window[mask]  # (m,)
        if hard.fault_locus == "edge" and not host_level:
            # link fault: the callee side of the culprit's outgoing calls
            # degrades; the culprit's own spans (including its entry->exit
            # self-edges) stay healthy, so node-level attribution has no
            # direct signal and the ranking must come from trace structure
            par_svc = np.where(par_local >= 0,
                               svc[np.clip(par_local, 0, None)], -1)
            culprit = (par_svc == target_idx) & (svc != target_idx)  # (L,)
        else:
            culprit = (np.full(L, True) if host_level
                       else (svc == target_idx))  # (L,)
        active = label.is_anomaly & (tw[:, None] & culprit[None, :])  # (m, L)
        mult = np.where(active, lat_mult, 1.0)
        err_prob = np.where(active, err_p, 0.005 if label.is_anomaly else 0.002)
        if decoy_set:
            # confounders degrade mildly in the same window (HardMode)
            decoy = np.array([services[s] in decoy_set for s in svc])  # (L,)
            decoy_active = (tw[:, None] & decoy[None, :]) & ~active
            mult = np.where(decoy_active, _CONFOUND_LAT, mult)
            err_prob = np.where(decoy_active, _CONFOUND_ERR, err_prob)
        if hard.effect_shape == "mult":
            dur_ms = rng.lognormal(mean=np.log(base_ms[svc][None, :] * mult),
                                   sigma=sigma, size=(m, L))
        elif hard.effect_shape == "add":
            # constant offset: location moves, spread does not scale
            dur_ms = rng.lognormal(mean=np.log(base_ms[svc][None, :]),
                                   sigma=sigma, size=(m, L)) \
                + (mult - 1.0) * base_ms[svc][None, :]
        elif hard.effect_shape == "tail":
            # only ~12% of affected spans inflate, 3x harder: the p99 moves,
            # the median barely does (mean-based detectors see ~1/3 of the
            # "mult" signal)
            tail_sel = rng.random((m, L)) < 0.12
            eff = np.where(tail_sel, 1.0 + (mult - 1.0) * 3.0, 1.0)
            dur_ms = rng.lognormal(mean=np.log(base_ms[svc][None, :] * eff),
                                   sigma=sigma, size=(m, L))
        else:
            raise ValueError(f"unknown effect_shape {hard.effect_shape!r}")
        errors = rng.random((m, L)) < err_prob
        # Entry spans of parents of failed spans also error (propagation).
        prop = errors.copy()
        for i in range(L - 1, 0, -1):
            p = par_local[i]
            if p >= 0:
                prop[:, p] |= prop[:, i] & (rng.random(m) < 0.6)

        start = (trace_start[mask][:, None]
                 + np.cumsum(rng.integers(50, 2000, size=(m, L)), axis=1))
        dur_us = (dur_ms * 1000.0).astype(np.int64)
        status = np.where(prop, 500, 200).astype(np.int16)

        glob_idx = offset + np.arange(m * L, dtype=np.int64).reshape(m, L)
        parent = np.where(par_local[None, :] >= 0,
                          glob_idx[:, np.clip(par_local, 0, None)],
                          -1).astype(np.int32)
        trace_idx = np.repeat(np.flatnonzero(mask).astype(np.int32), L)

        cols["trace"].append(trace_idx)
        cols["parent"].append(parent.reshape(-1))
        cols["service"].append(np.tile(svc, m))
        cols["endpoint"].append(np.tile(ep_ids, m))
        cols["start_us"].append(start.astype(np.int64).reshape(-1))
        cols["duration_us"].append(dur_us.reshape(-1))
        cols["is_error"].append(prop.reshape(-1))
        cols["status"].append(status.reshape(-1))
        cols["kind"].append(np.tile(kind, m))
        offset += m * L

    trace_ids = tuple(f"{label.experiment}.{i:08x}" for i in range(n_traces))
    batch = SpanBatch(
        trace=np.concatenate(cols["trace"]),
        parent=np.concatenate(cols["parent"]),
        service=np.concatenate(cols["service"]),
        endpoint=np.concatenate(cols["endpoint"]),
        start_us=np.concatenate(cols["start_us"]),
        duration_us=np.concatenate(cols["duration_us"]),
        is_error=np.concatenate(cols["is_error"]),
        status=np.concatenate(cols["status"]),
        kind=np.concatenate(cols["kind"]),
        services=tuple(services),
        endpoints=tuple(endpoints),
        trace_ids=trace_ids,
    )
    # Sort spans by start time (stable), preserving parent links via permutation.
    order = np.argsort(batch.start_us, kind="stable").astype(np.int32)
    inv = np.empty_like(order)
    inv[order] = np.arange(order.shape[0], dtype=np.int32)
    parent_sorted = batch.parent[order]
    parent_sorted = np.where(parent_sorted >= 0, inv[np.clip(parent_sorted, 0, None)], -1)
    batch = batch._replace(
        trace=batch.trace[order], parent=parent_sorted.astype(np.int32),
        service=batch.service[order], endpoint=batch.endpoint[order],
        start_us=batch.start_us[order], duration_us=batch.duration_us[order],
        is_error=batch.is_error[order], status=batch.status[order],
        kind=batch.kind[order],
    )
    return batch.validate()


# ---------------------------------------------------------------------------
# JSON emitters matching the raw reference artifacts (used for loader tests
# and for materializing a synthetic dataset tree).
# ---------------------------------------------------------------------------

def spans_to_skywalking_json(batch: SpanBatch, experiment: str) -> dict:
    """Emit the TT SkyWalking collector JSON (trace_collector.py:552-584)."""
    traces: List[dict] = []
    by_trace: Dict[int, List[int]] = {}
    for i in range(batch.n_spans):
        by_trace.setdefault(int(batch.trace[i]), []).append(i)
    for t, rows in by_trace.items():
        # segment per service within the trace (simplified: one segment/service)
        pos = {row: j for j, row in enumerate(rows)}
        seg_of_svc: Dict[int, str] = {}
        node_ids = {}
        for i in rows:
            svc = int(batch.service[i])
            seg = seg_of_svc.setdefault(svc, f"seg-{batch.trace_ids[t]}-{svc}")
            node_ids[i] = f"{seg}:{pos[i]}"
        spans = []
        roots = []
        for i in rows:
            svc = int(batch.service[i])
            seg = seg_of_svc[svc]
            par = int(batch.parent[i])
            parent_node = node_ids.get(par) if par >= 0 else None
            same_segment = par >= 0 and int(batch.service[par]) == svc
            start_ms = int(batch.start_us[i] // 1000)
            end_ms = int((batch.start_us[i] + batch.duration_us[i]) // 1000)
            refs = []
            if par >= 0 and not same_segment:
                par_svc = int(batch.service[par])
                refs.append({
                    "traceId": batch.trace_ids[t],
                    "parentSegmentId": seg_of_svc[par_svc],
                    "parentSpanId": pos[par],
                    "type": "CROSS_PROCESS",
                })
            if par < 0:
                roots.append(node_ids[i])
            spans.append({
                "node_id": node_ids[i],
                "trace_id": batch.trace_ids[t],
                "segment_id": seg,
                "span_id": pos[i],
                "parent_span_id": pos[par] if same_segment else -1,
                "parent_node_id": parent_node,
                "depth": 0,
                "children_node_ids": [],
                "service_code": batch.services[svc],
                "service_instance": f"{batch.services[svc]}-instance",
                "start_timestamp_ms": start_ms,
                "end_timestamp_ms": end_ms,
                "duration_ms": max(0, end_ms - start_ms),
                "endpoint_name": batch.endpoints[int(batch.endpoint[i])],
                "type": KIND_NAMES[int(batch.kind[i])] if int(batch.kind[i]) < 3 else "Local",
                "peer": None,
                "component": "SpringMVC",
                "layer": "Http",
                "is_error": bool(batch.is_error[i]),
                "tags": [{"key": "http.status_code", "value": str(int(batch.status[i]))}],
                "tags_map": {"http.status_code": str(int(batch.status[i]))},
                "logs": [],
                "refs": refs,
            })
        svcs = sorted({s["service_code"] for s in spans})
        traces.append({
            "summary": {"trace_ids": [batch.trace_ids[t]],
                        "duration": max(s["duration_ms"] for s in spans),
                        "is_error": any(s["is_error"] for s in spans)},
            "trace_id": batch.trace_ids[t],
            "span_count": len(spans),
            "services_involved": svcs,
            "root_span_node_ids": roots,
            "spans": spans,
        })
    return {
        "metadata": {
            "experiment": experiment,
            "collection_hours": 24,
            "trace_count": len(traces),
            "span_count": batch.n_spans,
            "services": sorted(set(batch.services)),
            "generator": "anomod.synth",
        },
        "traces": traces,
    }


_KIND_TO_JAEGER = {KIND_ENTRY: "server", KIND_EXIT: "client", KIND_LOCAL: "internal"}


def spans_to_jaeger_json(batch: SpanBatch) -> dict:
    """Emit Jaeger API JSON (consumed by jaeger_to_csv.py:20-74)."""
    data = []
    by_trace: Dict[int, List[int]] = {}
    for i in range(batch.n_spans):
        by_trace.setdefault(int(batch.trace[i]), []).append(i)
    for t, rows in by_trace.items():
        processes = {f"p{int(batch.service[i])}":
                     {"serviceName": batch.services[int(batch.service[i])]}
                     for i in rows}
        spans = []
        for i in rows:
            refs = []
            par = int(batch.parent[i])
            if par >= 0:
                refs.append({"refType": "CHILD_OF",
                             "traceID": batch.trace_ids[t],
                             "spanID": f"s{par:08x}"})
            spans.append({
                "traceID": batch.trace_ids[t],
                "spanID": f"s{i:08x}",
                "processID": f"p{int(batch.service[i])}",
                "operationName": batch.endpoints[int(batch.endpoint[i])],
                "startTime": int(batch.start_us[i]),
                "duration": int(batch.duration_us[i]),
                "references": refs,
                "tags": [
                    {"key": "http.status_code", "value": int(batch.status[i])},
                    {"key": "span.kind",
                     "value": _KIND_TO_JAEGER[int(batch.kind[i])]},
                    {"key": "component", "value": "thrift"},
                ] + ([{"key": "error", "value": True}]
                     if bool(batch.is_error[i]) else []),
                "logs": [],
            })
        data.append({"traceID": batch.trace_ids[t],
                     "processes": processes, "spans": spans})
    return {"data": data}


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------

# Complete reference catalogs live in anomod.metrics_catalog (level-keyed);
# re-exported here because the generator is where they become data.
from anomod.metrics_catalog import (  # noqa: E402
    SN_METRIC_FILES, SN_PER_SERVICE_FILES, TT_ALL_METRIC_NAMES,
    TT_METRIC_NAMES, TT_PER_SERVICE_METRICS)


def _host_family_values(name: str, label: FaultLabel, rng, t, in_window,
                        lat_mult: float, sev: float = 1.0) -> np.ndarray:
    """One host-scoped series for an SN/TT metric family, fault-conditioned.

    Shapes follow the reference's sanity thresholds where it states them
    (SN README.md:106: CPU fault ⇒ system_cpu_usage > 90%, Redis cache fault
    ⇒ reduced redis_memory_used plateau); otherwise: performance faults
    inflate their matching resource family inside the anomaly window,
    database faults move storage/fd families, everything else is stationary
    noise around a per-family operating point.
    """
    nt = t.shape[0]
    anomaly = label.is_anomaly
    typ = label.anomaly_type
    lvl = label.anomaly_level

    def gauge(base: float, noise: float) -> np.ndarray:
        return base + rng.normal(0, noise, nt)

    if name in ("system_cpu_usage",):
        base = gauge(rng.uniform(15, 35), 3)
        if anomaly and typ == "cpu_contention":
            spike = rng.uniform(91, 99, nt)
            base = np.where(in_window, base + (spike - base) * sev, base)
        return np.clip(base, 0, 100)
    if name == "node_cpu_seconds_total":
        # counter: cumulative busy seconds; slope rises under CPU faults
        rate = np.clip(gauge(rng.uniform(2, 6), 0.5), 0.1, None)
        if anomaly and typ == "cpu_contention":
            rate = np.where(in_window, rate * lat_mult, rate)
        return np.cumsum(rate)
    if name in ("system_load1", "node_load5"):
        base = np.abs(gauge(rng.uniform(0.5, 2.0), 0.3))
        if anomaly and typ == "cpu_contention":
            base = np.where(in_window, base * scale_mult(5.0, sev), base)
        return base
    if name == "system_memory_usage_percent":
        return np.clip(gauge(rng.uniform(35, 60), 2), 0, 100)
    if name == "node_memory_MemTotal_bytes":
        return np.full(nt, 16.0e9)
    if name in ("node_memory_MemAvailable_bytes", "node_memory_MemFree_bytes"):
        base = gauge(rng.uniform(6e9, 9e9), 2e8)
        if anomaly and typ == "cache_limit":  # memory stress on the DB host
            base = np.where(in_window, base * scale_mult(0.4, sev), base)
        return np.clip(base, 1e8, None)
    if name in ("system_disk_io_time", "node_disk_io_time_seconds_total",
                "system_disk_read_bytes", "system_disk_write_bytes",
                "node_disk_read_bytes_total", "node_disk_written_bytes_total"):
        base = np.abs(gauge(rng.uniform(5, 50), 5))
        if anomaly and typ == "disk_io_stress":
            base = np.where(in_window, base * lat_mult, base)
        return base
    if name == "system_disk_usage_percent":
        return np.clip(gauge(rng.uniform(40, 70), 0.5), 0, 100)
    if name in ("node_filesystem_size_bytes",):
        return np.full(nt, 200.0e9)
    if name == "node_filesystem_avail_bytes":
        drain = 1e5 if not (anomaly and lvl == "database") else 1e5 + 4.9e6 * sev
        return 80.0e9 - np.cumsum(np.full(nt, drain)) + rng.normal(0, 1e6, nt)
    if name == "volume_manager_total_volumes":
        return np.full(nt, float(rng.integers(20, 40)))
    if name in ("system_network_receive_bytes", "system_network_transmit_bytes",
                "node_network_receive_bytes_total",
                "node_network_transmit_bytes_total"):
        base = np.abs(gauge(rng.uniform(1e6, 5e6), 2e5))
        if anomaly and typ == "network_loss":
            # lost throughput
            base = np.where(in_window, base * scale_mult(0.3, sev), base)
        return base
    if name in ("system_network_errors", "node_network_receive_drop_total",
                "node_network_transmit_drop_total",
                "node_network_receive_errs_total",
                "node_network_transmit_errs_total"):
        base = np.abs(gauge(1.0, 0.5))
        if anomaly and typ in ("network_loss", "dns_failure"):
            base = np.where(in_window, base + rng.uniform(50, 200, nt) * sev,
                            base)
        return base
    if name == "jaeger_spans_rate":
        base = np.abs(gauge(rng.uniform(100, 300), 20))
        if anomaly and lvl == "performance":
            base = np.where(in_window, base / max(lat_mult / 2, 1.0), base)
        return base
    if name == "jaeger_sampling_rate":
        return np.clip(gauge(1.0, 0.01), 0, 1)
    if name in ("post_creation_rate", "timeline_read_rate"):
        from anomod.workload import SN_REQUEST_MIX
        mix = (SN_REQUEST_MIX["compose-post-service"]
               if name == "post_creation_rate"
               else SN_REQUEST_MIX["home-timeline-service"]
               + SN_REQUEST_MIX["user-timeline-service"])
        base = np.abs(gauge(150.0 * mix, 15.0 * mix))
        if anomaly and lvl == "performance":  # host fault slows the workload
            base = np.where(in_window, base / max(lat_mult / 2, 1.0), base)
        return base
    # stationary default for families without a fault hook
    return np.abs(gauge(rng.uniform(1, 100), 5))


# SN store topology: the gcov compose stack runs one Redis/Mongo instance
# per owning service (docker-compose-gcov.yml:227-322), and the ChaosBlade
# cache-limit fault targets ONE service's Redis — so the store-family
# PromQL (redis_memory_used_bytes etc., no grouping) returns one series per
# exporter instance, attributed here to the owning service.
SN_REDIS_OWNERS: Tuple[str, ...] = (
    "home-timeline-service", "user-timeline-service", "social-graph-service")
SN_MONGO_OWNERS: Tuple[str, ...] = (
    "post-storage-service", "user-timeline-service", "social-graph-service",
    "user-service", "media-service", "url-shorten-service")
SN_STORE_FILES: Dict[str, Tuple[str, ...]] = {
    "mongodb_latency_p95": SN_MONGO_OWNERS,
    "redis_memory_used": SN_REDIS_OWNERS,
    "redis_command_rate": SN_REDIS_OWNERS,
}


def _store_family_values(name: str, label: FaultLabel, rng, t, in_window,
                         lat_mult: float, is_target: bool,
                         sev: float = 1.0) -> np.ndarray:
    """One per-store-instance series (owner-service attributed)."""
    nt = t.shape[0]
    anomaly = label.is_anomaly and is_target
    lvl = label.anomaly_level
    typ = label.anomaly_type
    if name == "mongodb_latency_p95":
        base = np.abs(rng.uniform(0.005, 0.02) + rng.normal(0, 0.002, nt))
        if anomaly and lvl == "database":
            # cache limit pushes misses onto the backing store
            base = np.where(in_window, base * lat_mult, base)
        return base
    if name == "redis_memory_used":
        base = rng.uniform(4e7, 6e7) + rng.normal(0, 1e6, nt)
        if anomaly and typ == "cache_limit":
            # README.md:106 plateau drop
            base = np.where(in_window, base * scale_mult(0.3, sev), base)
        return base
    # redis_command_rate
    base = np.abs(rng.uniform(200, 500) + rng.normal(0, 30, nt))
    if anomaly and typ == "cache_limit":
        base = np.where(in_window, base * scale_mult(0.5, sev), base)
    return base


def _service_family_values(name: str, label: FaultLabel, rng, t, in_window,
                           lat_mult: float, err_p: float,
                           is_target: bool, sev: float = 1.0) -> np.ndarray:
    """One per-service series, fault-conditioned on the culprit service."""
    nt = t.shape[0]
    anomaly = label.is_anomaly and is_target
    typ = label.anomaly_type

    def gauge(base: float, noise: float) -> np.ndarray:
        return base + rng.normal(0, noise, nt)

    if name == "up":
        v = np.ones(nt)
        if anomaly and typ == "kill_service_instance":
            v = np.where(in_window & (rng.random(nt) < 0.5 * sev), 0.0, v)
        return v
    if name == "kube_pod_status_phase":
        v = np.ones(nt)  # 1 == Running
        if anomaly and typ == "kill_service_instance":
            v = np.where(in_window & (rng.random(nt) < 0.5 * sev), 0.0, v)
        return v
    if name == "kube_pod_container_status_restarts_total":
        if anomaly and typ == "kill_service_instance":
            # Schedule+PodChaos kills every 3 s (Lv_S_KILLPOD_*.yaml:15-22)
            return np.cumsum(in_window * rng.poisson(2.0 * sev, nt)).astype(float)
        return np.zeros(nt)
    if name in ("microservice_request_rate", "http_requests_total"):
        rate = np.abs(gauge(rng.uniform(20, 80), 5))
        if anomaly and typ in ("kill_service_instance", "dns_failure"):
            # requests not arriving
            rate = np.where(in_window, rate * scale_mult(0.2, sev), rate)
        if name == "http_requests_total":
            return np.cumsum(rate)  # counter
        return rate
    if name == "microservice_error_rate":
        base = np.clip(gauge(0.002, 0.001), 0, 1)
        if anomaly:
            base = np.where(in_window, np.clip(err_p + rng.normal(0, 0.02, nt),
                                               0, 1), base)
        return base
    if name == "microservice_latency_p95":
        base = np.abs(gauge(rng.uniform(0.01, 0.06), 0.005))
        if anomaly:
            base = np.where(in_window, base * lat_mult, base)
        return base
    if name in ("socialnet_container_cpu", "container_cpu_usage_seconds_total",
                "process_cpu_seconds_total"):
        base = np.abs(gauge(rng.uniform(5, 20), 2))
        if anomaly and label.anomaly_level in ("performance", "database"):
            base = np.where(in_window, base * lat_mult, base)
        return base
    if name == "container_cpu_cfs_throttled_periods_total":
        rate = np.zeros(nt)
        if anomaly and typ == "cpu_contention":
            rate = in_window * rng.poisson(5.0 * sev, nt).astype(float)
        return np.cumsum(rate)
    if name in ("socialnet_container_memory", "container_memory_usage_bytes",
                "container_memory_working_set_bytes",
                "process_resident_memory_bytes"):
        base = np.abs(gauge(rng.uniform(2e8, 8e8), 2e7))
        if anomaly and typ == "cache_limit":
            base = np.where(in_window, base * scale_mult(1.8, sev), base)
        return base
    if name == "container_spec_memory_limit_bytes":
        return np.full(nt, 2.0e9)
    if name == "container_memory_failcnt":
        if anomaly and typ == "cache_limit":
            return np.cumsum(in_window * rng.poisson(1.0 * sev, nt)).astype(float)
        return np.zeros(nt)
    if name in ("socialnet_container_network_receive",
                "socialnet_container_network_transmit",
                "container_network_receive_bytes_total",
                "container_network_transmit_bytes_total"):
        base = np.abs(gauge(rng.uniform(1e5, 1e6), 5e4))
        if anomaly and typ in ("network_loss", "http_abort"):
            base = np.where(in_window, base * scale_mult(0.3, sev), base)
        return base
    if name in ("container_network_receive_errors_total",
                "container_network_transmit_errors_total"):
        base = np.abs(gauge(0.5, 0.3))
        if anomaly and typ in ("network_loss", "dns_failure"):
            base = np.where(in_window, base + rng.uniform(20, 80, nt) * sev,
                            base)
        return base
    if name == "process_open_fds":
        base = np.abs(gauge(rng.uniform(50, 150), 10))
        if anomaly and typ == "connection_pool_exhaustion":
            base = np.where(in_window, base * scale_mult(8.0, sev), base)
        return base
    if name == "process_max_fds":
        return np.full(nt, 1024.0)
    if name == "container_processes":
        return np.abs(gauge(rng.uniform(10, 40), 1))
    if name == "kubelet_volume_stats_used_bytes":
        drain = 5e4 if not (anomaly and label.anomaly_level == "database") \
            else 5e4 + (5e6 - 5e4) * sev
        return 1.0e9 + np.cumsum(np.full(nt, drain)) + rng.normal(0, 1e5, nt)
    # generic per-service level with target inflation
    base = np.abs(gauge(10 * rng.uniform(0.5, 2.0), 2))
    if anomaly:
        base = np.where(in_window, base * lat_mult, base)
    return base


def generate_metrics(label: FaultLabel, duration_s: int = 1800, step_s: int = 15,
                     seed: Optional[int] = None,
                     base_time_s: float = 1.7621800e9,
                     hard: HardMode = _EASY) -> MetricBatch:
    """Fault-conditioned metric samples at the reference's 15 s step
    (collect_metric.sh:4-5), over the COMPLETE reference catalogs: all 24 SN
    per-query families (collect_metric.sh:20-125) and all TT level-group +
    kube-state families (metric_collector.py:37-104,283-303) — see
    anomod.metrics_catalog."""
    if seed is None:
        seed = _seed_for(label.experiment, 2)
    rng = np.random.default_rng(seed)
    services, _, _ = _topology(label.testbed)
    if label.testbed == "SN":
        names: Tuple[str, ...] = SN_METRIC_FILES
        per_service = frozenset(SN_PER_SERVICE_FILES)
    else:
        names = TT_ALL_METRIC_NAMES
        per_service = frozenset(TT_PER_SERVICE_METRICS)
    t = np.arange(0, duration_s, step_s, dtype=np.float64) + base_time_s
    nt = t.shape[0]
    sev = hard.severity
    lat_mult, err_p = _fault_effects(label, sev)

    metric_col, series_col, t_col, v_col = [], [], [], []
    series_keys: List[str] = []
    series_service: List[int] = []

    def add_series(m_idx: int, key: str, svc: int, values: np.ndarray):
        s_idx = len(series_keys)
        series_keys.append(key)
        series_service.append(svc)
        metric_col.append(np.full(nt, m_idx, np.int32))
        series_col.append(np.full(nt, s_idx, np.int32))
        t_col.append(t)
        v_col.append(values)

    # anomaly window: middle third of the experiment (same [600, 1200) s
    # window generate_spans / generate_logs / generate_api use; rescaled to
    # the canonical 1800 s so non-default durations keep proportional
    # boundaries under every fault_profile)
    in_window = anomaly_window_mask((t - t[0]) * (1800.0 / duration_s),
                                    hard.fault_profile)
    # SN host-level performance faults (ChaosBlade on the Docker host) hit
    # every service's containers; named-target faults hit one service.
    host_level = label.is_anomaly and label.target_service not in services
    # an edge-locus fault is a link fault: node-scoped series stay healthy
    # (the trace plane carries the only attribution evidence); is_anomaly
    # derives from anomaly_level, so neutralize the level
    if hard.fault_locus == "edge" and not host_level:
        label = dataclasses.replace(label, anomaly_level="normal")
    for m_idx, name in enumerate(names):
        if label.testbed == "SN" and name in SN_STORE_FILES:
            store = name.split("_")[0]  # "mongodb" | "redis"
            for svc_name in SN_STORE_FILES[name]:
                s = services.index(svc_name)
                is_target = label.is_anomaly and (
                    host_level or svc_name == label.target_service)
                add_series(m_idx, f'instance="{svc_name}-{store}"', s,
                           _store_family_values(name, label, rng, t,
                                                in_window, lat_mult,
                                                is_target, sev))
        elif name in per_service:
            for s, svc_name in enumerate(services):
                is_target = label.is_anomaly and (
                    host_level or svc_name == label.target_service)
                key = (f'name="{svc_name}"' if label.testbed == "SN"
                       else f'pod="{svc_name}-0",service="{svc_name}"')
                add_series(m_idx, key, s,
                           _service_family_values(name, label, rng, t,
                                                  in_window, lat_mult, err_p,
                                                  is_target, sev))
        else:
            add_series(m_idx, 'instance="host"', -1,
                       _host_family_values(name, label, rng, t, in_window,
                                           lat_mult, sev))

    return MetricBatch(
        metric=np.concatenate(metric_col),
        series=np.concatenate(series_col),
        t_s=np.concatenate(t_col),
        value=np.concatenate(v_col),
        metric_names=tuple(names),
        series_keys=tuple(series_keys),
        series_service=np.array(series_service, np.int32),
        services=tuple(services),
    )


# ---------------------------------------------------------------------------
# Logs, API responses, coverage
# ---------------------------------------------------------------------------

def generate_logs(label: FaultLabel, lines_per_service: int = 400,
                  seed: Optional[int] = None,
                  base_time_s: float = 1.7621800e9,
                  hard: HardMode = _EASY) -> Tuple[LogBatch, List[LogSummary]]:
    if seed is None:
        seed = _seed_for(label.experiment, 3)
    rng = np.random.default_rng(seed)
    services, _, _ = _topology(label.testbed)
    svc_col, t_col, lvl_col = [], [], []
    summaries = []
    host_level = label.is_anomaly and label.target_service not in services
    sev = hard.severity
    p_culprit = 0.01 + ((0.35 if not host_level else 0.12) - 0.01) * sev
    for s, svc in enumerate(services):
        n = int(lines_per_service * rng.uniform(0.5, 2.0))
        tt = base_time_s + np.sort(rng.uniform(0, 1800, n))
        # edge-locus faults leave node-scoped logs healthy (link fault)
        culprit = label.is_anomaly and (host_level or label.target_service == svc) \
            and not (hard.fault_locus == "edge" and not host_level)
        # elevated error rate only inside the shared anomaly window [600,1200)s
        in_window = anomaly_window_mask(tt - base_time_s, hard.fault_profile)
        p_err = np.where(culprit & in_window, p_culprit, 0.01)
        if svc in hard.confounders and not culprit:
            p_err = np.where(in_window, 0.03, p_err)
        r = rng.random(n)
        lvl = np.where(r < p_err, LOG_ERROR,
                       np.where(r < p_err + 0.05, LOG_WARN, LOG_INFO)).astype(np.int8)
        svc_col.append(np.full(n, s, np.int32))
        t_col.append(tt)
        lvl_col.append(lvl)
        summaries.append(LogSummary(
            service=svc, n_lines=n,
            n_error=int((lvl == LOG_ERROR).sum()),
            n_warn=int((lvl == LOG_WARN).sum()),
            n_info=int((lvl == LOG_INFO).sum()),
            size_bytes=n * 120))
    return LogBatch(
        service=np.concatenate(svc_col), t_s=np.concatenate(t_col),
        level=np.concatenate(lvl_col), services=tuple(services),
    ), summaries


def generate_api(label: FaultLabel, n_records: int = 600,
                 seed: Optional[int] = None,
                 base_time_s: float = 1.7621800e9,
                 hard: HardMode = _EASY) -> ApiBatch:
    if seed is None:
        seed = _seed_for(label.experiment, 4)
    rng = np.random.default_rng(seed)
    if label.testbed == "SN":
        eps = SN_API_ENDPOINTS
    else:
        eps = tuple(f"/api/v1/{s.replace('ts-', '').replace('-service', '')}service"
                    for s in TT_SERVICES[:20])
    lat_mult, err_p = _fault_effects(label, hard.severity)
    ep = rng.integers(0, len(eps), n_records).astype(np.int32)
    t = base_time_s + np.sort(rng.uniform(0, 1800, n_records))
    lat = rng.lognormal(np.log(40.0), 0.5 * (1.0 + hard.noise),
                        n_records).astype(np.float32)
    status = np.full(n_records, 200, np.int16)
    # An edge-locus fault lives on the target's OUTGOING links.  End-to-end
    # API routes through the target still slow down (the route waits on the
    # slow downstream call) — but ONLY if the target has outgoing calls: a
    # leaf target faults no edge, so the whole API surface stays healthy.
    # Without this gate the api artifact named the culprit for corpora
    # that carry zero fault signal anywhere else (a target-identity leak
    # the learned models exploited to fake 1.00 on edge-locus leaf kills).
    edge_inert = (hard.fault_locus == "edge" and label.target_service
                  and not any(a == label.target_service
                              for a, _c in _topology(label.testbed)[1]))
    if label.is_anomaly and not edge_inert:
        # endpoints routed through the culprit service bear the brunt; a
        # host-level fault (no target) hits the whole surface (matches how
        # the reference's monitor sees chaos: per-endpoint p95/p99 spikes on
        # affected routes, enhanced_openapi_monitor.py:318-397)
        from anomod.suite import endpoint_owner  # deferred: suite imports synth
        owners = np.array([endpoint_owner(e, label.testbed) for e in eps])
        on_target = (owners == label.target_service)[ep] \
            if label.target_service else np.ones(n_records, bool)
        hit_p = np.where(on_target, min(err_p + 0.05, 0.6),
                         min(err_p * 0.1 + 0.01, 0.1))
        affected = rng.random(n_records) < hit_p
        # API records see end-to-end latency, so they stay fault-conditioned
        # under an edge locus (a slow outgoing call still slows the route);
        # only the active-window profile shifts
        in_window = anomaly_window_mask(t - t[0], hard.fault_profile)
        affected &= in_window
        lat = np.where(affected, lat * lat_mult, lat).astype(np.float32)
        status = np.where(affected & (rng.random(n_records) < err_p), 500, status)
    clen = rng.integers(64, 4096, n_records).astype(np.int32)
    if label.testbed == "SN":
        # compose-post records carry the wrk2 content model's body-length
        # distribution (mixed-workload.lua:33-83) instead of the generic
        # response-size draw.
        from anomod.workload import sample_compose_lengths
        compose = np.array(["post/compose" in e for e in eps])[ep]
        if compose.any():
            clen[compose] = sample_compose_lengths(rng, int(compose.sum()))
    return ApiBatch(endpoint=ep, t_s=t, status=status.astype(np.int16),
                    latency_ms=lat, content_length=clen, endpoints=eps)


@functools.lru_cache(maxsize=4096)
def _file_coverage_base(svc: str, i: int) -> Tuple[int, float]:
    """Line count + base coverage ratio of one source file.  These belong to
    the *codebase*, not the experiment: seeded per (service, file) so coverage
    is stable across experiments and only fault-conditioned shifts move it
    (the reference's per-run reports differ mainly on the culprit, e.g.
    ts-order-service under Lv_C_exception_injection)."""
    frng = np.random.default_rng(_seed_for(f"{svc}/file_{i}", 5))
    return int(frng.integers(50, 800)), float(frng.uniform(0.3, 0.7))


def generate_coverage(label: FaultLabel, files_per_service: int = 6,
                      seed: Optional[int] = None,
                      hard: HardMode = _EASY) -> CoverageBatch:
    if seed is None:
        seed = _seed_for(label.experiment, 5)
    rng = np.random.default_rng(seed)
    services, _, _ = _topology(label.testbed)
    files: List[FileCoverage] = []
    for svc in services:
        for i in range(files_per_service):
            total, base_ratio = _file_coverage_base(svc, i)
            ratio = base_ratio + float(rng.uniform(-0.02, 0.02))  # run jitter
            if label.is_anomaly and label.target_service == svc \
                    and hard.fault_locus != "edge":
                # injected faults shift executed paths on the culprit — but
                # only NODE faults: a link fault is in the network between
                # services, the culprit's own code runs the same paths
                # (leaving this ungated leaked the target's identity into
                # edge-locus corpora through an artifact no real link
                # fault would move)
                ratio = max(0.05, ratio - 0.15 * hard.severity)
            ext = "cpp" if label.testbed == "SN" else "java"
            files.append(FileCoverage(
                service=svc, path=f"src/{svc}/file_{i}.{ext}",
                lines_total=total, lines_covered=int(total * min(ratio, 1.0))))
    return coverage_batch_from_files(files)


def generate_experiment(label_or_name, n_traces: int = 200,
                        seed: Optional[int] = None,
                        hard: HardMode = _EASY) -> Experiment:
    """Generate a full five-modality experiment bundle.

    ``hard`` tunes corpus difficulty (severity / noise / confounders) for
    de-saturated evaluation — see :class:`HardMode`.  Confounders degrade
    spans and logs only: a decoy slowdown plausibly moves latency and log
    errors but not kube-state counters, so the metric modality is the
    disambiguating evidence, as it would be for a real operator.
    """
    if isinstance(label_or_name, str):
        label = labels_mod.label_for(label_or_name)
        if label is None:
            raise KeyError(f"unknown experiment: {label_or_name}")
    else:
        label = label_or_name
    logs, summaries = generate_logs(label, seed=seed, hard=hard)
    return Experiment(
        name=label.experiment, testbed=label.testbed,
        spans=generate_spans(label, n_traces=n_traces, seed=seed, hard=hard),
        metrics=generate_metrics(label, seed=seed, hard=hard),
        logs=logs, log_summaries=summaries,
        api=generate_api(label, seed=seed, hard=hard),
        coverage=generate_coverage(label, seed=seed, hard=hard),
        synthetic=True,
    )


def generate_corpus(testbed: str, n_traces: int = 200) -> List[Experiment]:
    """All 13 experiments (12 faults + normal) for one testbed — the synthetic
    mirror of the shipped SN_data/TT_data trees."""
    return [generate_experiment(l, n_traces=n_traces)
            for l in labels_mod.labels_for_testbed(testbed)]
