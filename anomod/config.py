"""Configuration: env-var contract + dataclass config.

Mirrors the reference's ``${VAR:-default}`` env contract style
(SN_collection-scripts/README.md:38-53, collect_all_data.sh:37-54) but as a
typed, non-interactive config object.  Placeholder values of the form
``{SOMETHING}`` are treated as unset, matching the reference's anonymization
placeholder policy (``ensure_path_var``, collect_all_data.sh:37-44).
"""

from __future__ import annotations

import dataclasses
import os
from pathlib import Path
from typing import Optional


def _env(name: str, default: str) -> str:
    """Read an env var; reference-style ``{PLACEHOLDER}`` values count as unset."""
    val = os.environ.get(name, "").strip()
    if not val or (val.startswith("{") and val.endswith("}")):
        return default
    return val


# Default data roots: the reference checkout mounted read-only, and this repo.
_DEFAULT_REFERENCE_ROOT = "/root/reference"

# Sentinel values that disable the ingest cache entirely.
_CACHE_OFF = ("0", "off", "none", "disabled", "false")


def _cache_dir_env() -> Optional[Path]:
    """ANOMOD_CACHE_DIR: ingest-cache root; "0"/"off"/"none" disables it.

    Unset means the default user cache location — the cache is on by
    default so repeat bench captures measure the kernel, not host parsing.
    """
    raw = _env("ANOMOD_CACHE_DIR", "")
    if raw.lower() in _CACHE_OFF:
        return None
    if raw:
        return Path(raw).expanduser()
    return Path(os.path.expanduser("~/.cache/anomod"))


def _ingest_workers_env() -> int:
    """ANOMOD_INGEST_WORKERS: corpus-loader process-pool size (0/1 = serial).

    Validated here so a typo fails loudly at config construction instead of
    silently falling back to the serial path.
    """
    raw = _env("ANOMOD_INGEST_WORKERS", "0")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_INGEST_WORKERS must be a non-negative integer "
            f"(0/1 = serial), got {raw!r}")
    if n < 0:
        raise ValueError(
            f"ANOMOD_INGEST_WORKERS must be >= 0, got {n}")
    return n


#: default serving-plane micro-batch bucket widths (spans) — one XLA
#: compile per width (anomod.serve.batcher re-exports this and the
#: validator below as its contract; they live HERE so Config()
#: construction never pays the serve/stream import chain).  The 64
#: bucket joined with the tenant-fused dispatch path: a power-law
#: fleet's tail tenants flush a handful of spans per tick, and staging
#: them 256-wide was ~80% of all staged rows as padding — narrow
#: buckets only became affordable once lane stacking amortized the
#: per-dispatch cost across tenants.
DEFAULT_SERVE_BUCKETS = (64, 256, 1024, 4096, 16384)


def validate_serve_buckets(buckets) -> tuple:
    """The one bucket-set contract: positive, strictly ascending ints."""
    try:
        out = tuple(int(b) for b in buckets)
    except (TypeError, ValueError):
        raise ValueError(f"bucket set must be integers, got {buckets!r}")
    if not out:
        raise ValueError("bucket set must not be empty")
    if any(b < 1 for b in out):
        raise ValueError(f"bucket widths must be >= 1, got {out}")
    if any(b >= c for b, c in zip(out, out[1:])):
        raise ValueError(f"bucket widths must be strictly ascending: {out}")
    return out


def _serve_buckets_env() -> tuple:
    """ANOMOD_SERVE_BUCKETS: comma-separated micro-batch bucket widths
    (spans) for the serving plane's dynamic batcher.

    Validated at config construction (positive, strictly ascending ints)
    so a typo'd bucket set fails loudly instead of compiling garbage
    shapes mid-serve.
    """
    raw = _env("ANOMOD_SERVE_BUCKETS", "")
    if not raw:
        return DEFAULT_SERVE_BUCKETS
    parts = [p.strip() for p in raw.split(",") if p.strip()]
    try:
        return validate_serve_buckets(parts)
    except ValueError as e:
        raise ValueError(f"ANOMOD_SERVE_BUCKETS: {e}") from e


#: default serving-plane lane-bucket set for the FUSED dispatch path
#: (anomod.serve.batcher): per tick, same-width staged chunks from many
#: tenants stack into [lanes, width] dispatches, lanes padded up to the
#: smallest bucket here (one XLA compile per (width, lane-bucket) shape).
DEFAULT_SERVE_LANE_BUCKETS = (1, 2, 4, 8, 16, 32)


def validate_lane_buckets(lanes) -> tuple:
    """The lane-bucket contract: positive, strictly ascending ints —
    the same shape discipline as the width buckets (every (width,
    lane-bucket) pair is one compiled executable, so the set must be
    small and fixed)."""
    try:
        out = tuple(int(b) for b in lanes)
    except (TypeError, ValueError):
        raise ValueError(f"lane-bucket set must be integers, got {lanes!r}")
    if not out:
        raise ValueError("lane-bucket set must not be empty")
    if any(b < 1 for b in out):
        raise ValueError(f"lane buckets must be >= 1, got {out}")
    if any(b >= c for b, c in zip(out, out[1:])):
        raise ValueError(f"lane buckets must be strictly ascending: {out}")
    return out


def _serve_lane_buckets_env() -> tuple:
    """ANOMOD_SERVE_LANE_BUCKETS: comma-separated lane counts for the
    serving plane's fused (lane-stacked) dispatch.

    Validated at config construction, same contract as
    ``ANOMOD_SERVE_BUCKETS`` — a typo'd set fails loudly instead of
    compiling garbage lane shapes mid-serve.
    """
    raw = _env("ANOMOD_SERVE_LANE_BUCKETS", "")
    if not raw:
        return DEFAULT_SERVE_LANE_BUCKETS
    parts = [p.strip() for p in raw.split(",") if p.strip()]
    try:
        return validate_lane_buckets(parts)
    except ValueError as e:
        raise ValueError(f"ANOMOD_SERVE_LANE_BUCKETS: {e}") from e


def _serve_fuse_env() -> bool:
    """ANOMOD_SERVE_FUSE: serving-plane fused-dispatch switch.

    Default ON; "0"/"false"/"off" is the escape hatch back to one
    dispatch per tenant micro-batch.  The fused path is pinned
    bit-identical on CPU to SEQUENTIAL scoring of the same per-tick
    COALESCED batches — coalescing itself regroups a tenant's same-tick
    micro-batches into one staging, so flipping this switch can move
    borderline f32 bits (and admission/SLO numbers are byte-identical
    either way); see docs/SERVING.md "Fused dispatch" for the exact
    contract."""
    return _env("ANOMOD_SERVE_FUSE", "1").strip().lower() \
        not in ("0", "false", "off", "no")


def _serve_shards_env() -> int:
    """ANOMOD_SERVE_SHARDS: serving-plane engine-worker shard count.

    ``1`` (the default) is the single-threaded engine, output
    bit-identical to the pre-sharding serving plane (its DISPATCH may
    still pipeline per ``ANOMOD_SERVE_PIPELINE``; set that to 1 for the
    exact synchronous code path).  ``N > 1`` partitions tenants across
    N worker threads (anomod.serve.shard), each owning its tenants'
    scoring plane end to end; admission/shedding stay on the
    coordinator, so every decision is identical to the 1-shard engine on
    the same seed.  Validated here so a typo fails loudly at config
    construction instead of silently serving unsharded.
    """
    raw = _env("ANOMOD_SERVE_SHARDS", "1")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_SERVE_SHARDS must be a positive integer, got {raw!r}")
    if not 1 <= n <= 256:
        raise ValueError(
            f"ANOMOD_SERVE_SHARDS must be in [1, 256], got {n}")
    return n


def _serve_pipeline_env() -> int:
    """ANOMOD_SERVE_PIPELINE: in-flight fused dispatches per runner
    (the inline 1-shard engine and every shard worker alike).

    Depth ``1`` is synchronous (each lane-stacked dispatch materializes
    before the next stages); depth ``d > 1`` double-buffers — a shard
    stages and dispatches batch t+1 while batch t's XLA dispatch is
    still in flight, deferring readback/fold by up to ``d-1`` dispatches
    (drained at tick end).  Per-slot pinned scratch keeps reuse safe:
    a slot refills only after its dispatch's outputs materialized.
    Bit-identical at any depth (folds apply in dispatch order).
    """
    raw = _env("ANOMOD_SERVE_PIPELINE", "2")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_SERVE_PIPELINE must be a positive integer, got {raw!r}")
    if not 1 <= n <= 64:
        raise ValueError(
            f"ANOMOD_SERVE_PIPELINE must be in [1, 64], got {n}")
    return n


def _serve_lane_engine_env() -> str:
    """ANOMOD_SERVE_LANE_ENGINE: the serving plane's fused lane-dispatch
    formulation (anomod.replay.make_lane_delta).

    ``auto`` (the default) follows :func:`anomod.replay.
    default_step_engine` — scatter on XLA:CPU, the one-hot matmul on
    accelerators — so the fused path stays BIT-identical to the
    single-chunk step on every backend and the serving plane's
    fused==sequential parity pins hold unconditionally.  ``pallas`` is
    the deliberate TPU opt-in: the whole per-lane score chain as ONE
    Mosaic kernel launch per fused shape (ops.pallas_replay.
    make_pallas_lane_delta_fn) — alert/histogram planes exact vs the
    other engines, latency moments within the bf16 hi/lo envelope (the
    compiled-replay tolerance contract), which is exactly why it is NOT
    the hands-off default.  ``matmul``/``scatter`` pin one exact
    formulation explicitly.  Validated here so a typo fails loudly at
    config construction instead of silently serving the wrong kernel.
    """
    raw = _env("ANOMOD_SERVE_LANE_ENGINE", "auto").strip().lower()
    if raw in ("auto", ""):
        return "auto"
    if raw in ("matmul", "scatter", "pallas"):
        return raw
    raise ValueError(
        "ANOMOD_SERVE_LANE_ENGINE must be auto, matmul, scatter or "
        f"pallas, got {raw!r}")


def _serve_state_env() -> str:
    """ANOMOD_SERVE_STATE: where the serving plane keeps tenant replay
    states between ticks (anomod.serve.batcher).

    ``host`` is the pre-device-pool seam: per-tenant numpy state pytrees,
    the lane fold materializes every dispatch's deltas to host and adds
    them per lane.  ``device`` keeps every shard's tenant states in ONE
    device-resident pool ([slots, SW, F] agg + hist planes, tenants
    mapped to slots at first service) and folds lane deltas with an
    on-device scatter-add in dispatch order — pinned BIT-identical to
    the host seam (an XLA f32 scatter-add with unique per-dispatch slots
    performs exactly the same elementwise adds), with
    ``get_state``/``set_state`` surviving as the on-demand gather seam
    for parity checks, checkpoints and migration.  ``auto`` (the
    default) resolves to ``device`` for the bucket-runner serve plane on
    every backend (the pool is exact, not a tolerance trade) and to
    ``host`` where a pool cannot apply (the mesh plane manages its own
    sharded state).  Validated here so a typo fails loudly at config
    construction instead of silently serving the slow seam.
    """
    raw = _env("ANOMOD_SERVE_STATE", "auto").strip().lower()
    if raw in ("auto", ""):
        return "auto"
    if raw in ("host", "device"):
        return raw
    raise ValueError(
        f"ANOMOD_SERVE_STATE must be auto, host or device, got {raw!r}")


def _serve_async_commit_env() -> bool:
    """ANOMOD_SERVE_ASYNC_COMMIT: deferred-commit serve tick
    (anomod.serve.engine).

    Default OFF — the synchronous engine stays the parity oracle.  When
    on, tick N's fold+score dispatch is issued but NOT waited on; the
    XLA execute wait runs concurrent with tick N+1's coordinator phases
    (admission, drain, shed, SLO accounting) and tick N's results drain
    at a commit barrier placed just before they are first read.  Every
    decision is a function of seed+config alone, so states, alerts,
    SLO, shed and the canonical flight journal are pinned byte-identical
    to the synchronous engine (``anomod audit replay`` crosses the two
    freely); only the wall-time attribution moves — the hidden wait is
    reported on the ``commit_defer`` perf leg (anomod.obs.perf).

    Validated against the explicit token sets (not the legacy
    anything-truthy bool idiom): the knob silently flips the engine's
    whole tick structure, so ``ANOMOD_SERVE_ASYNC_COMMIT=treu`` must
    fail at config construction, not serve synchronously all night.
    """
    raw = _env("ANOMOD_SERVE_ASYNC_COMMIT", "0").strip().lower()
    if raw in ("1", "on", "true", "yes"):
        return True
    if raw in ("0", "off", "false", "no", ""):
        return False
    raise ValueError(
        f"ANOMOD_SERVE_ASYNC_COMMIT must be 0/off/false/no or "
        f"1/on/true/yes, got {raw!r}")


def _serve_native_drain_env() -> str:
    """ANOMOD_SERVE_NATIVE_DRAIN: the admission plane's SFQ drain/shed
    engine (anomod.serve.queues).

    ``off`` (``0``) is the per-span Python heap — the original drain
    loop, kept as the parity oracle.  ``auto`` (the default) runs the
    COLUMNAR engine: candidate selection over parallel NumPy arrays,
    with the sort/select kernels in the native runtime
    (``anomod_sfq_drain`` / ``anomod_sfq_victim``) when the .so loads
    and a pure-NumPy fallback otherwise.  ``on`` (``1``) requires the
    native kernels — the first drain raises with the recorded
    build-failure reason instead of silently serving the slow path (the
    ``ANOMOD_NATIVE=on`` contract).  All three engines are pinned
    byte-identical: same served order, same shed/evict victims, same
    SFQ virtual-time floats.  Validated here so a typo fails loudly at
    config construction.
    """
    raw = _env("ANOMOD_SERVE_NATIVE_DRAIN", "auto").strip().lower()
    if raw in ("auto", ""):
        return "auto"
    if raw in ("1", "on", "true", "yes"):
        return "on"
    if raw in ("0", "off", "false", "no"):
        return "off"
    raise ValueError(
        f"ANOMOD_SERVE_NATIVE_DRAIN must be auto, on/1 or off/0, "
        f"got {raw!r}")


def _serve_worker_env() -> str:
    """ANOMOD_SERVE_WORKER: the serving plane's shard-worker kind
    (anomod.serve.shard / anomod.serve.procshard).

    ``thread`` (the default) is the PR-5 in-process worker — shared
    memory, GIL-bound, the byte-parity oracle.  ``process`` hosts each
    shard's scoring plane (detectors, replays, BucketRunner, RCA plane,
    obs registry) in a spawn-context worker PROCESS driven by a
    picklable per-tick command protocol — the GIL leaves the dispatch
    path entirely.  States, alerts, SLO, shed and the canonical flight
    journal are pinned byte-identical across the two (and across
    process counts); only wall attribution moves.  Validated here so a
    typo fails at config construction, not after a fleet spawn.
    """
    raw = _env("ANOMOD_SERVE_WORKER", "thread").strip().lower()
    if raw in ("thread", ""):
        return "thread"
    if raw == "process":
        return "process"
    raise ValueError(
        f"ANOMOD_SERVE_WORKER must be thread or process, got {raw!r}")


def _serve_worker_start_timeout_s_env() -> float:
    """ANOMOD_SERVE_WORKER_START_TIMEOUT_S: how long the coordinator
    waits for a spawned process worker's ready handshake (spawn +
    imports + sub-plane construction) before failing the run loudly.
    Generous default — a cold jax import on a busy box is slow — but
    bounded, so a wedged child can never hang a serve run forever."""
    raw = _env("ANOMOD_SERVE_WORKER_START_TIMEOUT_S", "120")
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_SERVE_WORKER_START_TIMEOUT_S must be a number, "
            f"got {raw!r}")
    if not 1 <= v <= 3600:
        raise ValueError(
            f"ANOMOD_SERVE_WORKER_START_TIMEOUT_S must be in [1, 3600], "
            f"got {v}")
    return v


def _serve_fold_env() -> str:
    """ANOMOD_SERVE_FOLD: the tick barrier's cross-shard registry merge
    mode (anomod.obs.registry.Registry.delta_snapshot).

    ``sparse`` (the default) serializes only families TOUCHED since the
    previous barrier — Zipf traffic leaves most families idle most
    ticks, so barrier payload follows active tenants, not registered
    fleet size (the Sparse Allreduce observation, PAPERS.md).  ``dense``
    walks and serializes every registered family every barrier — the
    payload-accounting oracle the sparse win is measured against.  The
    two are pinned byte-identical on every scrape surface; only
    ``fold_payload_bytes`` moves.  Validated here so a typo fails at
    config construction.
    """
    raw = _env("ANOMOD_SERVE_FOLD", "sparse").strip().lower()
    if raw in ("sparse", ""):
        return "sparse"
    if raw == "dense":
        return "dense"
    raise ValueError(
        f"ANOMOD_SERVE_FOLD must be dense or sparse, got {raw!r}")


def _serve_rca_env() -> bool:
    """ANOMOD_SERVE_RCA: online root-cause inference in the serve tick.

    Default OFF — RCA rides inside the serve SLO, so enabling it is an
    operator decision.  When on (and scoring is on), a tenant's detector
    firing queues incremental GNN culprit inference over that tenant's
    live service graph (anomod.serve.rca); detector states, alerts,
    admission and shedding are byte-identical either way (RCA is a pure
    read-side consumer of the alert stream).
    """
    return _env("ANOMOD_SERVE_RCA", "0").strip().lower() \
        not in ("0", "false", "off", "no", "")


#: default online-RCA bucket grid: (nodes, sampled neighbors) shapes the
#: culprit scorer compiles once each (anomod.serve.rca — the same fixed-
#: shape discipline as the serve width/lane buckets).  A tenant's live
#: graph pads into the smallest bucket whose node count holds its
#: service table; neighbor lists sample down (seeded) / dead-pad up to
#: the bucket's neighbor width.
DEFAULT_SERVE_RCA_BUCKETS = ((16, 8), (64, 16))


def validate_rca_buckets(buckets) -> tuple:
    """The RCA bucket-grid contract: (nodes, neighbors) int pairs with
    strictly ascending node counts, every dimension >= 1 — each pair is
    one compiled executable, so the grid must be small and fixed."""
    try:
        out = tuple((int(n), int(k)) for n, k in buckets)
    except (TypeError, ValueError):
        raise ValueError(
            f"RCA bucket grid must be (nodes, neighbors) integer pairs, "
            f"got {buckets!r}")
    if not out:
        raise ValueError("RCA bucket grid must not be empty")
    if any(n < 1 or k < 1 for n, k in out):
        raise ValueError(f"RCA bucket dims must be >= 1, got {out}")
    if any(a[0] >= b[0] for a, b in zip(out, out[1:])):
        raise ValueError(
            f"RCA bucket node counts must be strictly ascending: {out}")
    return out


def _serve_rca_buckets_env() -> tuple:
    """ANOMOD_SERVE_RCA_BUCKETS: comma-separated ``NODESxNEIGHBORS``
    pairs (e.g. ``16x8,64x16``) for the online-RCA scorer's fixed
    compile grid.  Validated at config construction, same fail-loud
    contract as ``ANOMOD_SERVE_BUCKETS``.
    """
    raw = _env("ANOMOD_SERVE_RCA_BUCKETS", "")
    if not raw:
        return DEFAULT_SERVE_RCA_BUCKETS
    pairs = []
    for part in (p.strip() for p in raw.split(",") if p.strip()):
        dims = part.lower().split("x")
        if len(dims) != 2:
            raise ValueError(
                f"ANOMOD_SERVE_RCA_BUCKETS entries must be NODESxNEIGHBORS "
                f"pairs, got {part!r}")
        pairs.append(dims)
    try:
        return validate_rca_buckets(pairs)
    except ValueError as e:
        raise ValueError(f"ANOMOD_SERVE_RCA_BUCKETS: {e}") from e


def _serve_rca_int_env(name: str, default: str, lo: int, hi: int) -> int:
    """Shared validator for the bounded integer RCA knobs."""
    raw = _env(name, default)
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {raw!r}")
    if not lo <= n <= hi:
        raise ValueError(f"{name} must be in [{lo}, {hi}], got {n}")
    return n


def _serve_rca_topk_env() -> int:
    """ANOMOD_SERVE_RCA_TOPK: ranked culprit list length per verdict."""
    return _serve_rca_int_env("ANOMOD_SERVE_RCA_TOPK", "5", 1, 64)


def _serve_rca_budget_env() -> int:
    """ANOMOD_SERVE_RCA_BUDGET: max RCA runs per serve tick — the
    per-tick SLO budget; alerts past it queue to later ticks (the RCA
    queue drains FIFO, so verdict order stays deterministic)."""
    return _serve_rca_int_env("ANOMOD_SERVE_RCA_BUDGET", "4", 1, 4096)


def _serve_rca_windows_env() -> int:
    """ANOMOD_SERVE_RCA_WINDOWS: windowed-feature reach (windows) of the
    online extractor — also bounds each tenant's RCA span buffer."""
    return _serve_rca_int_env("ANOMOD_SERVE_RCA_WINDOWS", "8", 2, 128)


def _flight_env() -> bool:
    """ANOMOD_FLIGHT: the serve plane's black-box flight recorder
    (anomod.obs.flight).

    Default ON — the recorder is the always-on tick journal every
    determinism contract replays against (bounded ring, bounded
    per-tick cost; the serve bench gates its overhead at <= 5% like
    telemetry) — "0"/"false"/"off" disables it end to end.
    """
    return _env("ANOMOD_FLIGHT", "1").strip().lower() \
        not in ("0", "false", "off", "no")


def _flight_digest_every_env() -> int:
    """ANOMOD_FLIGHT_DIGEST_EVERY: tenant-state digest cadence (ticks).

    Every Nth tick the flight recorder folds a crc32 over every live
    tenant's replay state (through the ``get_state``/pool-gather seam)
    into the tick record's fold plane — the cheap end-state parity
    anchor ``anomod audit diff`` bisects state divergence with.  Small
    values localize tighter; 1 digests every tick.  Validated here so a
    typo fails loudly at config construction.
    """
    raw = _env("ANOMOD_FLIGHT_DIGEST_EVERY", "16")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_FLIGHT_DIGEST_EVERY must be a positive integer, "
            f"got {raw!r}")
    if not 1 <= n <= 1_000_000:
        raise ValueError(
            f"ANOMOD_FLIGHT_DIGEST_EVERY must be in [1, 1000000], got {n}")
    return n


def _flight_max_ticks_env() -> int:
    """ANOMOD_FLIGHT_MAX_TICKS: flight-recorder ring capacity (ticks).

    The journal is a bounded ring — oldest tick records drop past this
    (counted, never silent: ``anomod_flight_dropped_ticks_total``), so
    an unbounded serve run cannot grow host memory without bound.
    """
    raw = _env("ANOMOD_FLIGHT_MAX_TICKS", "65536")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_FLIGHT_MAX_TICKS must be a positive integer, "
            f"got {raw!r}")
    if not 1 <= n <= 10_000_000:
        raise ValueError(
            f"ANOMOD_FLIGHT_MAX_TICKS must be in [1, 10000000], got {n}")
    return n


def _flight_dump_dir_env() -> Optional[Path]:
    """ANOMOD_FLIGHT_DUMP_DIR: alert-triggered forensic-dump directory.

    When set, the first serve tick that raises a new detector alert
    publishes ONE forensic bundle there (flight ring + registry scrape +
    tracer spans, atomically — anomod.obs.flight.forensic_bundle).
    Unset (the default) disables the dump; the in-memory ring and the
    ``anomod audit`` dump path are unaffected.
    """
    raw = _env("ANOMOD_FLIGHT_DUMP_DIR", "")
    if not raw or raw.lower() in _CACHE_OFF:
        return None
    return Path(raw).expanduser()


#: serve-chaos fault taxonomy (anomod.serve.chaos — the framework analog
#: of the paper's injected-fault campaigns, aimed at the serve plane
#: itself): ``crash`` kills the shard WORKER THREAD mid-tick, ``except``
#: raises a plain exception at a score-path phase, ``stall`` sleeps
#: (slow-shard), ``poolput`` fails the state-pool fold, ``surge``
#: multiplies the fleet's offered arrivals for a window of ticks (the
#: load-shift taxonomy — what forces elastic-policy scaling episodes).
#: Phases are the score path's five injection points; a surge has no
#: phase (it acts on admission input, before the score path exists).
CHAOS_KINDS = ("crash", "except", "stall", "poolput", "surge")
CHAOS_PHASES = ("stage", "dispatch", "fold", "score", "commit")
_CHAOS_DEFAULT_PHASE = {"crash": "dispatch", "except": "dispatch",
                        "stall": "stage", "poolput": "fold",
                        "surge": "stage"}


def validate_chaos_script(script: str) -> list:
    """Parse/validate an ``ANOMOD_SERVE_CHAOS`` fault script.

    Grammar: semicolon-separated ``KIND@TICK[:key=value]*`` items, e.g.
    ``crash@5:shard=1;stall@8:ms=20;except@12:phase=score:repeat=2``.
    Keys: ``shard`` (default 0), ``phase`` (one of
    :data:`CHAOS_PHASES`; per-kind default), ``ms`` (stall wall
    milliseconds, default 10), ``repeat`` (how many ATTEMPTS of that
    tick's slice the fault fires on — 1 by default so a recovery retry
    succeeds; ``-1`` = every attempt forever, the quarantine probe).
    A ``surge`` item instead takes ``factor`` (arrival multiplier,
    default 4) and ``ticks`` (duration, default 10): from its origin
    tick, every tenant's offered arrivals are replicated ``factor``×
    for ``ticks`` ticks — a deterministic fleet-wide load shift (the
    elastic-policy episode probe).  Score-path keys on a surge (and
    surge keys on a score-path fault) are refused: a silently-inert
    knob is worse than an error.
    Returns the parsed fault dicts; raises ``ValueError`` with the
    offending item on any malformed script — the same fail-loud contract
    as every other serve knob.  Lives HERE (pure string parsing) so
    Config() never pays the serve import chain.
    """
    faults = []
    for item in (p.strip() for p in str(script).split(";") if p.strip()):
        head, _, tail = item.partition(":")
        kind, at, tick = head.partition("@")
        kind = kind.strip().lower()
        if kind not in CHAOS_KINDS or not at:
            raise ValueError(
                f"chaos item {item!r}: expected KIND@TICK with KIND in "
                f"{'/'.join(CHAOS_KINDS)}")
        try:
            tick_i = int(tick)
        except ValueError:
            raise ValueError(f"chaos item {item!r}: tick must be an "
                             f"integer, got {tick!r}")
        if tick_i < 0:
            raise ValueError(f"chaos item {item!r}: tick must be >= 0")
        fault = {"kind": kind, "tick": tick_i, "shard": 0,
                 "phase": _CHAOS_DEFAULT_PHASE[kind], "ms": 10.0,
                 "repeat": 1, "factor": 4, "ticks": 10}
        allowed = (("factor", "ticks") if kind == "surge"
                   else ("shard", "phase", "ms", "repeat"))
        for kv in (p.strip() for p in tail.split(":") if p.strip()):
            key, eq, val = kv.partition("=")
            key = key.strip().lower()
            if not eq or key not in allowed:
                raise ValueError(
                    f"chaos item {item!r}: unknown key {kv!r} (want "
                    + "/".join(f"{k}=" for k in allowed) + ")")
            try:
                if key == "phase":
                    val = val.strip().lower()
                    if val not in CHAOS_PHASES:
                        raise ValueError
                    fault["phase"] = val
                elif key == "ms":
                    fault["ms"] = float(val)
                    # capped like the backoff knob: a stall is a fault
                    # INJECTION, not a way to park the scoring thread
                    # for minutes inside the measured wall
                    if not 0 <= fault["ms"] <= 10_000:
                        raise ValueError
                else:
                    fault[key] = int(val)
            except ValueError:
                raise ValueError(
                    f"chaos item {item!r}: bad value for {key!r}: {val!r}")
        if fault["shard"] < 0:
            raise ValueError(f"chaos item {item!r}: shard must be >= 0")
        if fault["repeat"] < -1 or fault["repeat"] == 0:
            raise ValueError(f"chaos item {item!r}: repeat must be a "
                             "positive count or -1 (forever)")
        if not 2 <= fault["factor"] <= 64:
            raise ValueError(f"chaos item {item!r}: surge factor must "
                             f"be in [2, 64], got {fault['factor']}")
        if not 1 <= fault["ticks"] <= 1_000_000:
            raise ValueError(f"chaos item {item!r}: surge ticks must "
                             f"be in [1, 1000000], got {fault['ticks']}")
        faults.append(fault)
    return faults


def _serve_chaos_env() -> str:
    """ANOMOD_SERVE_CHAOS: scripted fault injection aimed at the serve
    plane ITSELF (anomod.serve.chaos) — the framework analog of the
    paper's chaos campaigns, behind the supervised engine's
    checkpoint/restore recovery (anomod.serve.supervise).

    Empty (the default) = off.  Otherwise a semicolon-separated fault
    script (``crash@5:shard=1;stall@8:ms=20`` — see
    :func:`validate_chaos_script` for the grammar), validated here so a
    typo fails loudly at config construction instead of silently
    injecting nothing.
    """
    raw = _env("ANOMOD_SERVE_CHAOS", "").strip()
    if raw:
        validate_chaos_script(raw)
    return raw


#: elastic-policy decision taxonomy (anomod.serve.policy): ``up`` grows
#: the shard set by one worker, ``down`` drains and retires the highest
#: shard, ``rebalance`` moves the top-K hottest tenants off the most-
#: loaded shard, ``brownout`` forces a degradation-ladder level.
POLICY_ACTIONS = ("up", "down", "rebalance", "brownout")


def validate_policy_script(script: str) -> list:
    """Parse/validate an ``ANOMOD_SERVE_POLICY_SCRIPT`` scaling script.

    Grammar: semicolon-separated ``ACTION@TICK[:key=value]`` items with
    ACTION in :data:`POLICY_ACTIONS`, e.g.
    ``up@10;rebalance@25:k=2;down@40;brownout@50:level=1``.  Keys:
    ``k`` (rebalance move count, default 1), ``level`` (brownout ladder
    level 0..2, default 1); any key on the wrong action is refused (a
    silently-inert knob is worse than an error).  The engine executes
    each action at its tick (clamped by the min/max-shards envelope,
    journaled either way).  Same fail-loud contract as the chaos
    grammar; lives HERE (pure string parsing) so Config() never pays
    the serve import chain.
    """
    actions = []
    for item in (p.strip() for p in str(script).split(";") if p.strip()):
        head, _, tail = item.partition(":")
        act, at, tick = head.partition("@")
        act = act.strip().lower()
        if act not in POLICY_ACTIONS or not at:
            raise ValueError(
                f"policy item {item!r}: expected ACTION@TICK with "
                f"ACTION in {'/'.join(POLICY_ACTIONS)}")
        try:
            tick_i = int(tick)
        except ValueError:
            raise ValueError(f"policy item {item!r}: tick must be an "
                             f"integer, got {tick!r}")
        if tick_i < 0:
            raise ValueError(f"policy item {item!r}: tick must be >= 0")
        entry = {"action": act, "tick": tick_i, "k": 1, "level": 1}
        allowed = {"rebalance": ("k",), "brownout": ("level",)} \
            .get(act, ())
        for kv in (p.strip() for p in tail.split(":") if p.strip()):
            key, eq, val = kv.partition("=")
            key = key.strip().lower()
            if not eq or key not in allowed:
                raise ValueError(
                    f"policy item {item!r}: unknown key {kv!r}"
                    + (f" (want {'/'.join(f'{k}=' for k in allowed)})"
                       if allowed else f" ({act} takes no keys)"))
            try:
                entry[key] = int(val)
            except ValueError:
                raise ValueError(
                    f"policy item {item!r}: bad value for {key!r}: "
                    f"{val!r}")
        if not 1 <= entry["k"] <= 1024:
            raise ValueError(f"policy item {item!r}: k must be in "
                             f"[1, 1024], got {entry['k']}")
        if not 0 <= entry["level"] <= 2:
            raise ValueError(f"policy item {item!r}: level must be in "
                             f"[0, 2], got {entry['level']}")
        actions.append(entry)
    return actions


def _serve_policy_env() -> str:
    """ANOMOD_SERVE_POLICY: the serving plane's elastic scaling policy
    (anomod.serve.policy).

    ``off`` (the default) is the static engine — the shard count never
    changes and the policy plane costs nothing.  ``auto`` evaluates the
    signal-fed autoscaler at every tick boundary on the coordinator:
    scale-up / scale-down / rebalance / brownout decisions with
    hysteresis and cooldown, fed ONLY canonical (seed-deterministic)
    signals, executed through the live-migration seams — tenant states,
    alerts, SLO and shed stay byte-identical to a static run of the
    same seed.  ``script`` executes a fixed scaling schedule from
    ``ANOMOD_SERVE_POLICY_SCRIPT`` instead of the signals (the
    episode-replay probe).  Validated here so a typo fails loudly at
    config construction instead of silently serving static.
    """
    raw = _env("ANOMOD_SERVE_POLICY", "off").strip().lower()
    if raw in ("off", ""):
        return "off"
    if raw in ("auto", "script"):
        return raw
    raise ValueError(
        f"ANOMOD_SERVE_POLICY must be off, auto or script, got {raw!r}")


def _serve_policy_script_env() -> str:
    """ANOMOD_SERVE_POLICY_SCRIPT: the fixed scaling schedule
    ``ANOMOD_SERVE_POLICY=script`` executes (anomod.serve.policy).

    Empty (the default) = no schedule — the script MODE then refuses at
    the engine (an empty scripted policy is a misconfiguration, not a
    quiet static run).  Otherwise a semicolon-separated action script
    (``up@10;down@40;rebalance@25:k=2`` — see
    :func:`validate_policy_script`), validated here so a typo fails
    loudly at config construction.
    """
    raw = _env("ANOMOD_SERVE_POLICY_SCRIPT", "").strip()
    if raw:
        validate_policy_script(raw)
    return raw


def _serve_policy_int_env(name: str, default: str, lo: int,
                          hi: int) -> int:
    """Shared validator for the bounded integer policy knobs."""
    raw = _env(name, default)
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(f"{name} must be an integer, got {raw!r}")
    if not lo <= n <= hi:
        raise ValueError(f"{name} must be in [{lo}, {hi}], got {n}")
    return n


def _serve_policy_min_shards_env() -> int:
    """ANOMOD_SERVE_POLICY_MIN_SHARDS: the elastic policy's scale-down
    floor — ``down`` decisions never shrink the shard set below it."""
    return _serve_policy_int_env("ANOMOD_SERVE_POLICY_MIN_SHARDS", "1",
                                 1, 256)


def _serve_policy_max_shards_env() -> int:
    """ANOMOD_SERVE_POLICY_MAX_SHARDS: the elastic policy's scale-up
    ceiling — ``up`` decisions never grow the shard set past it (the
    brownout ladder takes over once load persists at the ceiling)."""
    return _serve_policy_int_env("ANOMOD_SERVE_POLICY_MAX_SHARDS", "8",
                                 1, 256)


def _serve_policy_target_imbalance_env() -> float:
    """ANOMOD_SERVE_POLICY_TARGET_IMBALANCE: the max-shard-load /
    mean-shard-load ratio (over the live served-rate EWMAs) past which
    the auto policy triggers a rebalance pass.  1.0 would rebalance on
    any skew; the default tolerates the skew a power-law head tenant
    makes unavoidable."""
    raw = _env("ANOMOD_SERVE_POLICY_TARGET_IMBALANCE", "1.5")
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_SERVE_POLICY_TARGET_IMBALANCE must be a number, "
            f"got {raw!r}")
    if not 1.0 <= v <= 100.0:
        raise ValueError(
            f"ANOMOD_SERVE_POLICY_TARGET_IMBALANCE must be in "
            f"[1.0, 100.0], got {v}")
    return v


def _serve_policy_cooldown_env() -> int:
    """ANOMOD_SERVE_POLICY_COOLDOWN_TICKS: minimum ticks between
    executed scaling decisions (scale-up/down/rebalance) — the
    anti-thrash half of the hysteresis contract.  Brownout ladder
    steps pace on the same cooldown."""
    return _serve_policy_int_env("ANOMOD_SERVE_POLICY_COOLDOWN_TICKS",
                                 "8", 1, 100_000)


def _serve_ckpt_every_env() -> int:
    """ANOMOD_SERVE_CKPT_EVERY: shard-checkpoint cadence in ticks
    (anomod.serve.supervise) — the flight-digest cadence idiom, at
    twice the digest period (the snapshot is ~10x a digest's cost:
    state copies + detector bookkeeping, not one crc sweep).

    Every Nth tick each shard snapshots its tenants' detector/replay
    state through the ``get_state``/pool-gather seam (plus the runner's
    dispatch book), and the coordinator retains the ticks' served-batch
    slices since the last snapshot — together that makes any mid-tick
    shard failure recoverable with NO score gap: restore the checkpoint,
    re-execute the retained slices deterministically, and the recovered
    run's states/alerts/SLO/shed are byte-identical to a fault-free run
    of the same seed.  ``0`` disables supervision entirely (a shard
    fault fails the tick, the pre-supervision behavior).  Snapshots are
    pure reads, so the cadence only trades recovery-log memory against
    snapshot wall — decisions are byte-identical at every value.
    """
    raw = _env("ANOMOD_SERVE_CKPT_EVERY", "32")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_SERVE_CKPT_EVERY must be a non-negative integer "
            f"(0 = supervision off), got {raw!r}")
    if not 0 <= n <= 1_000_000:
        raise ValueError(
            f"ANOMOD_SERVE_CKPT_EVERY must be in [0, 1000000], got {n}")
    return n


def _serve_retries_env() -> int:
    """ANOMOD_SERVE_RETRIES: consecutive recovery failures of ONE tick
    slice before that slice is QUARANTINED (anomod.serve.supervise).

    A batch that kills its shard K consecutive times is dropped from the
    recovery log (counted + journaled, never retried forever) and the
    shard recovers without it — bounded unavailability over livelock.
    """
    raw = _env("ANOMOD_SERVE_RETRIES", "3")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_SERVE_RETRIES must be a positive integer, got {raw!r}")
    if not 1 <= n <= 64:
        raise ValueError(
            f"ANOMOD_SERVE_RETRIES must be in [1, 64], got {n}")
    return n


def _serve_retry_backoff_s_env() -> float:
    """ANOMOD_SERVE_RETRY_BACKOFF_S: wall-clock backoff before each
    recovery attempt, doubling per consecutive attempt (capped 5 s).

    ``0`` (the default) retries immediately — recovery stays
    deterministic either way (backoff is wall time, never virtual
    time); a positive value spaces respawn storms on a genuinely sick
    host the way the paper's recovery controllers do.
    """
    raw = _env("ANOMOD_SERVE_RETRY_BACKOFF_S", "0")
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_SERVE_RETRY_BACKOFF_S must be a number, got {raw!r}")
    if not 0 <= v <= 60:
        raise ValueError(
            f"ANOMOD_SERVE_RETRY_BACKOFF_S must be in [0, 60], got {v}")
    return v


def _serve_max_respawns_env() -> int:
    """ANOMOD_SERVE_MAX_RESPAWNS: per-shard worker respawns per run
    before the shard is declared DEAD and its tenants migrate to the
    surviving shards through the ``set_state`` seam
    (anomod.serve.supervise — the elastic-tenancy migration step).
    """
    raw = _env("ANOMOD_SERVE_MAX_RESPAWNS", "8")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_SERVE_MAX_RESPAWNS must be a non-negative integer, "
            f"got {raw!r}")
    if not 0 <= n <= 4096:
        raise ValueError(
            f"ANOMOD_SERVE_MAX_RESPAWNS must be in [0, 4096], got {n}")
    return n


def _perf_env() -> bool:
    """ANOMOD_PERF: the performance observatory's dispatch-lifecycle
    timeline (anomod.obs.perf).

    Default OFF — it is a deep-dive instrument (the flight recorder is
    the always-on journal); when on, every fused lane dispatch records
    staged/submitted/materialized/folded/slot-refilled event
    timestamps, the per-tick overlap-headroom bound is computed, and
    the events ride the flight journal's ``perf`` VARIANT key.  A pure
    read-side consumer: decisions are byte-identical on or off
    (pinned), overhead priced in the bench ``perf`` block (≤5% bar).
    """
    return _env("ANOMOD_PERF", "0").strip().lower() \
        not in ("0", "false", "off", "no", "")


def _perf_max_events_env() -> int:
    """ANOMOD_PERF_MAX_EVENTS: retained dispatch-timeline event bound.

    The engine keeps the drained lifecycle events for report/export;
    past this bound the OLDEST drop and every eviction is counted
    (``anomod_perf_dropped_events_total`` — loss visible, never
    silent, the flight-ring discipline).
    """
    raw = _env("ANOMOD_PERF_MAX_EVENTS", "262144")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_PERF_MAX_EVENTS must be a positive integer, "
            f"got {raw!r}")
    if not 1 <= n <= 100_000_000:
        raise ValueError(
            f"ANOMOD_PERF_MAX_EVENTS must be in [1, 100000000], got {n}")
    return n


def _perf_noise_floor_env() -> float:
    """ANOMOD_PERF_NOISE_FLOOR: the box noise model `anomod perf diff`
    tests wall ratios against (fraction; 0.35 = this box's measured
    ±35% run-to-run floor, docs/BENCHMARKS.md).

    A wall regression is flagged only when the whole 95% bootstrap CI
    of the B/A mean-wall ratio clears ``1 + floor`` — the floor is the
    EXPLICIT noise hedge every capture comparison used to carry as
    prose.
    """
    raw = _env("ANOMOD_PERF_NOISE_FLOOR", "0.35")
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_PERF_NOISE_FLOOR must be a number, got {raw!r}")
    if not 0 <= v <= 10:
        raise ValueError(
            f"ANOMOD_PERF_NOISE_FLOOR must be in [0, 10], got {v}")
    return v


def _census_env() -> bool:
    """ANOMOD_CENSUS: the fleet census observatory (anomod.obs.census).

    Default OFF — like the perf timeline it is a deep-dive instrument
    (the flight recorder stays the always-on journal); when on, every
    ``ANOMOD_CENSUS_EVERY``-th tick takes a deterministic resident-
    bytes census (per-(shard, plane) byte counts from array shapes and
    container lengths — never an RSS wall) plus the hot-set/Zipf
    census, exported as registry gauges and the flight journal's
    ``census`` VARIANT key.  A pure read-side consumer: decisions are
    byte-identical on or off (pinned), overhead priced in the bench
    ``census`` block (≤5% bar).
    """
    return _env("ANOMOD_CENSUS", "0").strip().lower() \
        not in ("0", "false", "off", "no", "")


def _census_every_env() -> int:
    """ANOMOD_CENSUS_EVERY: census cadence in ticks (the flight
    digest-cadence idiom).  Every Nth tick the census drains at the
    tick barrier; a census is also always forced into the run-end
    settlement record.  1 censuses every tick."""
    raw = _env("ANOMOD_CENSUS_EVERY", "8")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_CENSUS_EVERY must be a positive integer, got {raw!r}")
    if not 1 <= n <= 1_000_000:
        raise ValueError(
            f"ANOMOD_CENSUS_EVERY must be in [1, 1000000], got {n}")
    return n


#: default hot-set decay thresholds (ticks): the census reports the
#: hot-set size at each — how many tenants were served within the last
#: N ticks (anomod.obs.census.CensusTracker.hot_doc)
DEFAULT_CENSUS_DECAY_TICKS = (4, 16, 64, 256)


def _census_int_tuple_env(name: str, default: tuple, lo: int,
                          hi: int) -> tuple:
    """Shared validator for the census's ascending-int-list knobs
    (decay thresholds, sweep sizes): comma-separated positive ints,
    strictly ascending — the bucket-set contract."""
    raw = _env(name, "")
    if not raw:
        return default
    parts = [p.strip() for p in raw.split(",") if p.strip()]
    try:
        out = tuple(int(p) for p in parts)
    except ValueError:
        raise ValueError(f"{name} must be comma-separated integers, "
                         f"got {raw!r}")
    if not out:
        raise ValueError(f"{name} must not be empty")
    if any(not lo <= v <= hi for v in out):
        raise ValueError(f"{name} entries must be in [{lo}, {hi}], "
                         f"got {out}")
    if any(a >= b for a, b in zip(out, out[1:])):
        raise ValueError(f"{name} must be strictly ascending: {out}")
    return out


def _census_decay_ticks_env() -> tuple:
    """ANOMOD_CENSUS_DECAY_TICKS: comma-separated hot-set decay
    thresholds in ticks, strictly ascending (e.g. ``4,16,64``) — the
    hot-set-size-at-decay-threshold curve's x axis."""
    return _census_int_tuple_env("ANOMOD_CENSUS_DECAY_TICKS",
                                 DEFAULT_CENSUS_DECAY_TICKS,
                                 1, 10_000_000)


#: default registered-fleet sweep sizes for the census cost-attribution
#: probe (anomod.obs.census.fleet_probe): tick wall + resident bytes
#: measured at each registered count (fixed ~1e3-hot traffic), slopes
#: fitted vs registered — the O(registered) baseline the ROADMAP's
#: tiering refactor must flatten
DEFAULT_CENSUS_SWEEP = (1_000, 10_000, 100_000)


def _census_sweep_env() -> tuple:
    """ANOMOD_CENSUS_SWEEP: comma-separated registered-fleet sizes for
    the census probe sweep, strictly ascending; at least two sizes (a
    slope needs two points)."""
    out = _census_int_tuple_env("ANOMOD_CENSUS_SWEEP",
                                DEFAULT_CENSUS_SWEEP, 1, 10_000_000)
    if len(out) < 2:
        raise ValueError(
            f"ANOMOD_CENSUS_SWEEP needs >= 2 sizes (a slope fit needs "
            f"two points), got {out}")
    return out


def _census_coldest_k_env() -> int:
    """ANOMOD_CENSUS_COLDEST_K: coldest-K eviction-candidate preview
    length per census tick — since the tiering plane landed this is
    ALSO the demotion policy's candidate-batch size (one ordering,
    :meth:`anomod.obs.census.CensusTracker.coldest_candidates`, shared
    by the preview and the policy so they can never disagree)."""
    raw = _env("ANOMOD_CENSUS_COLDEST_K", "8")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_CENSUS_COLDEST_K must be a positive integer, "
            f"got {raw!r}")
    if not 1 <= n <= 4096:
        raise ValueError(
            f"ANOMOD_CENSUS_COLDEST_K must be in [1, 4096], got {n}")
    return n


def _serve_tier_hot_env() -> int:
    """ANOMOD_SERVE_TIER_HOT: tenant-state tiering hot capacity — the
    max tenants resident in the device ``TenantStatePool`` before the
    decay-driven demotion plane starts spilling the coldest to the host
    warm tier (anomod.serve.tiering).  ``0`` (the default) disables
    tiering entirely: every ever-served tenant stays pool-resident, the
    pre-tiering engine byte-for-byte."""
    raw = _env("ANOMOD_SERVE_TIER_HOT", "0")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_SERVE_TIER_HOT must be a non-negative integer "
            f"(0 = tiering off), got {raw!r}")
    if n < 0:
        raise ValueError(
            f"ANOMOD_SERVE_TIER_HOT must be >= 0, got {n}")
    return n


def _serve_tier_demote_after_env() -> int:
    """ANOMOD_SERVE_TIER_DEMOTE_AFTER: idle ticks (since a tenant's
    last served batch, the census ``last_served`` signal) before a
    pool-resident tenant is eligible for demotion.  The decay knob of
    the demotion plane — small values demote aggressively, large ones
    keep bursty tenants hot across their gaps."""
    raw = _env("ANOMOD_SERVE_TIER_DEMOTE_AFTER", "8")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_SERVE_TIER_DEMOTE_AFTER must be a positive "
            f"integer (idle ticks), got {raw!r}")
    if n < 1:
        raise ValueError(
            f"ANOMOD_SERVE_TIER_DEMOTE_AFTER must be >= 1, got {n}")
    return n


def _serve_tier_warm_bytes_env() -> int:
    """ANOMOD_SERVE_TIER_WARM_BYTES: host warm-tier state-bytes budget.
    Past it the warm tier spills its coldest entries' state arrays to
    the content-addressed disk cold tier — which only acts when
    ``ANOMOD_SERVE_TIER_COLD_DIR`` is set; without a cold dir the warm
    tier is terminal and the budget is advisory (documented in
    SERVING.md, never a silent data drop)."""
    raw = _env("ANOMOD_SERVE_TIER_WARM_BYTES", str(64 * 1024 * 1024))
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_SERVE_TIER_WARM_BYTES must be a non-negative "
            f"integer (bytes), got {raw!r}")
    if n < 0:
        raise ValueError(
            f"ANOMOD_SERVE_TIER_WARM_BYTES must be >= 0, got {n}")
    return n


def _serve_tier_cold_dir_env() -> Optional[Path]:
    """ANOMOD_SERVE_TIER_COLD_DIR: content-addressed disk cold-tier
    root for demoted tenant state (anomod.serve.tiering; the
    io/cache.py atomic tmp-rename publish idiom).  Unset or
    "0"/"off"/"none" disables the cold tier — the warm tier is then
    terminal regardless of its bytes budget."""
    raw = _env("ANOMOD_SERVE_TIER_COLD_DIR", "")
    if not raw or raw.lower() in _CACHE_OFF:
        return None
    return Path(raw).expanduser()


def _serve_tier_prefetch_env() -> int:
    """ANOMOD_SERVE_TIER_PREFETCH: cold-tier prefetch lane depth — max
    concurrent disk fetches issued at offer time so the read overlaps
    the tick's admission/drain/SLO phases (the PR-16 deferred-commit
    overlap idiom).  Promotion from cold always defers exactly one tick
    (a counted, journaled ``tier_miss``) so the hot loop never blocks
    on disk and the deferral count stays seed-deterministic."""
    raw = _env("ANOMOD_SERVE_TIER_PREFETCH", "4")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_SERVE_TIER_PREFETCH must be a positive integer, "
            f"got {raw!r}")
    if not 1 <= n <= 256:
        raise ValueError(
            f"ANOMOD_SERVE_TIER_PREFETCH must be in [1, 256], got {n}")
    return n


def _native_env() -> str:
    """ANOMOD_NATIVE: the C++ native runtime switch (anomod.io.native) —
    ingest scanning AND the serving plane's GIL-free lane staging.

    ``auto`` (the default) uses the native .so when it loads (building it
    on first use if a toolchain is present) and degrades to the pure-
    Python paths otherwise; ``on`` (``1``) REQUIRES it — the first native
    consumer raises with the recorded build-failure reason instead of
    silently serving the slow path, and ``anomod validate`` /
    ``scripts/pre_bench_check.py --mode serve`` surface the same reason
    (exit 5 on a requested-but-unusable runtime); ``off`` (``0``) forces
    the pure-Python paths even when the .so is fine.  Validated here so a
    typo fails loudly at config construction.
    """
    raw = _env("ANOMOD_NATIVE", "auto").strip().lower()
    if raw in ("auto", ""):
        return "auto"
    if raw in ("1", "on", "true", "yes"):
        return "on"
    if raw in ("0", "off", "false", "no"):
        return "off"
    raise ValueError(
        f"ANOMOD_NATIVE must be auto, on/1 or off/0, got {raw!r}")


def _jit_cache_env() -> bool:
    """ANOMOD_JIT_CACHE: persistent XLA compilation cache switch.

    When on AND ``ANOMOD_CACHE_DIR`` caching is enabled, the serve/bench
    entry points point jax's persistent compilation cache at
    ``<cache_dir>/jit`` (anomod.utils.platform.enable_jit_cache), so a
    warm restart skips the (width x lane-bucket) compile wall — and the
    2nd..Nth shard's identical-HLO grids compile once, not N times.
    Default OFF: mutating global jax config is an operator opt-in.
    """
    return _env("ANOMOD_JIT_CACHE", "0").strip().lower() \
        not in ("0", "false", "off", "no", "")


def _obs_http_env() -> bool:
    """ANOMOD_OBS_HTTP: embedded /metrics endpoint plane
    (anomod.obs.http).

    Default OFF — serving HTTP from a benchmark process is opt-in.
    When on, ``anomod serve`` starts a localhost-bound stdlib
    ``http.server`` thread exposing ``/metrics`` (Prometheus text
    exposition), ``/healthz`` and ``/flight``.  Scrapes are pure
    registry reads, so every decision plane stays byte-identical
    endpoint-on vs off.  Validated against the explicit token sets:
    a typo must fail at config construction, not silently skip the
    endpoint all night.
    """
    raw = _env("ANOMOD_OBS_HTTP", "0").strip().lower()
    if raw in ("1", "on", "true", "yes"):
        return True
    if raw in ("0", "off", "false", "no", ""):
        return False
    raise ValueError(
        f"ANOMOD_OBS_HTTP must be 0/off/false/no or "
        f"1/on/true/yes, got {raw!r}")


def _obs_http_port_env() -> int:
    """ANOMOD_OBS_HTTP_PORT: TCP port for the embedded endpoint plane.

    ``9464`` (the OpenMetrics convention neighborhood) by default; ``0``
    asks the OS for an ephemeral port — the test/dogfood mode, where the
    bound port is read back off the server object rather than assumed.
    """
    raw = _env("ANOMOD_OBS_HTTP_PORT", "9464")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_OBS_HTTP_PORT must be an integer port, got {raw!r}")
    if not 0 <= n <= 65535:
        raise ValueError(
            f"ANOMOD_OBS_HTTP_PORT must be in [0, 65535], got {n}")
    return n


def _serve_feed_lag_s_env() -> float:
    """ANOMOD_SERVE_FEED_LAG_S: live-feed wall->virtual lag budget in
    seconds (anomod.serve.feed).

    A sample collected at wall time ``w`` maps to virtual time
    ``w - t0_wall + lag``; the budget keeps the feed's virtual arrival
    times ahead of the poll that discovers them, so a tick never asks
    for spans the pollers have not fetched yet.  Walls are measured,
    never consulted for decisions — the bridge itself is recorded in
    the wire journal so replay reuses the live run's anchor.
    """
    raw = _env("ANOMOD_SERVE_FEED_LAG_S", "2.0")
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_SERVE_FEED_LAG_S must be a number, got {raw!r}")
    if not 0 <= v <= 3600:
        raise ValueError(
            f"ANOMOD_SERVE_FEED_LAG_S must be in [0, 3600], got {v}")
    return v


def _feed_journal_env() -> Optional[Path]:
    """ANOMOD_FEED_JOURNAL: live-feed wire-journal path.

    When set, every HTTP response the live feed consumes is recorded in
    sequence and published atomically to this path at the end of the
    run (anomod.serve.feed.FeedJournal); ``anomod serve --live-replay``
    re-serves it through a replay transport, reproducing the live run's
    states/alerts/SLO/shed byte-for-byte with no network.  Unset (the
    default) disables recording.
    """
    raw = _env("ANOMOD_FEED_JOURNAL", "")
    if not raw or raw.lower() in _CACHE_OFF:
        return None
    return Path(raw).expanduser()


def _serve_max_backlog_env() -> int:
    """ANOMOD_SERVE_MAX_BACKLOG: global admission backlog bound (spans) —
    the serving plane's backpressure/shed budget."""
    raw = _env("ANOMOD_SERVE_MAX_BACKLOG", "200000")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_SERVE_MAX_BACKLOG must be a positive integer, "
            f"got {raw!r}")
    if n < 1:
        raise ValueError(
            f"ANOMOD_SERVE_MAX_BACKLOG must be >= 1, got {n}")
    return n


def _obs_enabled_env() -> bool:
    """ANOMOD_OBS_ENABLED: process-wide metrics registry switch.

    Default ON — the hot-path cost of a disabled-check-free counter bump
    is nanoseconds, and the serve bench pins the enabled-vs-off overhead
    at <= 5% — "0"/"false"/"off" turns every metric handle into a shared
    no-op object (anomod.obs.registry)."""
    return _env("ANOMOD_OBS_ENABLED", "1").strip().lower() \
        not in ("0", "false", "off", "no")


def _obs_max_samples_env() -> int:
    """ANOMOD_OBS_MAX_SAMPLES: scrape-journal bound (samples).

    The registry's time-series journal (what the TT-CSV self-scrape
    export reads) is a bounded deque — oldest samples drop past this, so
    an unbounded run cannot grow host memory without bound.  Validated
    here so a typo fails loudly at config construction."""
    raw = _env("ANOMOD_OBS_MAX_SAMPLES", "500000")
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"ANOMOD_OBS_MAX_SAMPLES must be a positive integer, "
            f"got {raw!r}")
    if n < 1:
        raise ValueError(
            f"ANOMOD_OBS_MAX_SAMPLES must be >= 1, got {n}")
    return n


@dataclasses.dataclass(frozen=True)
class Config:
    """Global framework configuration.

    Attributes mirror the reference env contract where one exists:
      - ``data_root``     ~ DATA_ARCHIVE_ROOT (collect_all_data.sh:207-211)
      - ``sn_data``/``tt_data`` ~ the shipped SN_data/ and TT_data/ trees
      - ``backend``       ~ the BASELINE.json {cpu, jax-tpu} switch
    """

    data_root: Path = dataclasses.field(
        default_factory=lambda: Path(_env("ANOMOD_DATA_ROOT", _DEFAULT_REFERENCE_ROOT)))
    backend: str = dataclasses.field(
        default_factory=lambda: _env("ANOMOD_BACKEND", "cpu"))  # "cpu" | "jax" | "jax-tpu"
    synth_on_lfs: bool = dataclasses.field(
        default_factory=lambda: _env("ANOMOD_SYNTH_ON_LFS", "1") not in ("0", "false"))
    # init_social_graph.py:149 seeds with 1
    seed: int = dataclasses.field(default_factory=lambda: int(_env("ANOMOD_SEED", "1")))
    # ANOMOD_CACHE_DIR — content-addressed ingest cache root (anomod.io.cache);
    # None disables caching entirely ("0"/"off"/"none" in the env).
    cache_dir: Optional[Path] = dataclasses.field(
        default_factory=_cache_dir_env)
    # ANOMOD_INGEST_WORKERS — load_corpus process-pool size (0/1 = serial).
    ingest_workers: int = dataclasses.field(
        default_factory=_ingest_workers_env)
    # ANOMOD_SERVE_BUCKETS — serving-plane micro-batch bucket widths
    # (anomod.serve.batcher; one XLA compile per width).
    serve_buckets: tuple = dataclasses.field(
        default_factory=_serve_buckets_env)
    # ANOMOD_SERVE_LANE_BUCKETS — fused-dispatch lane counts
    # (anomod.serve.batcher; one XLA compile per (width, lane-bucket)).
    serve_lane_buckets: tuple = dataclasses.field(
        default_factory=_serve_lane_buckets_env)
    # ANOMOD_SERVE_FUSE — serving-plane fused-dispatch switch
    # (anomod.serve.engine; off = one dispatch per tenant micro-batch).
    serve_fuse: bool = dataclasses.field(default_factory=_serve_fuse_env)
    # ANOMOD_SERVE_SHARDS — serving-plane engine-worker shard count
    # (anomod.serve.shard; 1 = the single-threaded engine, bit-identical
    # to the pre-sharding plane).
    serve_shards: int = dataclasses.field(default_factory=_serve_shards_env)
    # ANOMOD_SERVE_PIPELINE — in-flight fused dispatches per shard worker
    # (anomod.serve.batcher; 1 = synchronous, d > 1 = double-buffered
    # staging under in-flight XLA dispatches, per-slot pinned scratch).
    serve_pipeline: int = dataclasses.field(
        default_factory=_serve_pipeline_env)
    # ANOMOD_SERVE_LANE_ENGINE — fused lane-dispatch formulation: auto
    # (= the step engine, bit-parity backend-stable), pallas (single
    # Mosaic kernel, TPU opt-in), matmul/scatter (explicit pin).
    serve_lane_engine: str = dataclasses.field(
        default_factory=_serve_lane_engine_env)
    # ANOMOD_SERVE_STATE — tenant replay state residency: auto (default,
    # = device for the bucket-runner plane), device (shard-owned
    # device-resident pool, scatter-add fold, bit-identical), host (the
    # per-tenant numpy seam; anomod.serve.batcher).
    serve_state: str = dataclasses.field(default_factory=_serve_state_env)
    # ANOMOD_SERVE_ASYNC_COMMIT — deferred-commit serve tick
    # (anomod.serve.engine; off = the synchronous parity oracle, on =
    # tick N's fold/score commit drains under tick N+1's coordinator
    # work, decisions pinned byte-identical either way).
    serve_async_commit: bool = dataclasses.field(
        default_factory=_serve_async_commit_env)
    # ANOMOD_SERVE_WORKER — shard-worker kind: thread (in-process, the
    # byte-parity oracle) or process (spawn-context worker processes
    # behind the same submit/join seam; anomod.serve.procshard).
    serve_worker: str = dataclasses.field(default_factory=_serve_worker_env)
    # ANOMOD_SERVE_WORKER_START_TIMEOUT_S — process-worker ready
    # handshake deadline in seconds (spawn + imports + plane build).
    serve_worker_start_timeout_s: float = dataclasses.field(
        default_factory=_serve_worker_start_timeout_s_env)
    # ANOMOD_SERVE_FOLD — tick-barrier registry merge mode: sparse
    # (touched-family deltas, payload follows active tenants) or dense
    # (full-registry walk, the payload oracle; anomod.obs.registry).
    serve_fold: str = dataclasses.field(default_factory=_serve_fold_env)
    # ANOMOD_SERVE_NATIVE_DRAIN — SFQ drain/shed engine: auto (columnar,
    # native kernels when the .so loads, NumPy fallback), on (native
    # required, fail loud), off (the Python heap parity oracle;
    # anomod.serve.queues).
    serve_native_drain: str = dataclasses.field(
        default_factory=_serve_native_drain_env)
    # ANOMOD_SERVE_RCA — online root-cause inference in the serve tick
    # (anomod.serve.rca; off = the serving plane stops at alerts).
    serve_rca: bool = dataclasses.field(default_factory=_serve_rca_env)
    # ANOMOD_SERVE_RCA_BUCKETS — (nodes, neighbors) compile grid for the
    # online-RCA culprit scorer (anomod.serve.rca; one XLA compile per
    # pair, AOT like the serve lane grid).
    serve_rca_buckets: tuple = dataclasses.field(
        default_factory=_serve_rca_buckets_env)
    # ANOMOD_SERVE_RCA_TOPK — ranked culprit list length per verdict.
    serve_rca_topk: int = dataclasses.field(
        default_factory=_serve_rca_topk_env)
    # ANOMOD_SERVE_RCA_BUDGET — max RCA runs per serve tick (queued past
    # it; the per-tick SLO budget).
    serve_rca_budget: int = dataclasses.field(
        default_factory=_serve_rca_budget_env)
    # ANOMOD_SERVE_RCA_WINDOWS — windowed-feature reach of the online
    # extractor (also bounds the per-tenant RCA span buffer).
    serve_rca_windows: int = dataclasses.field(
        default_factory=_serve_rca_windows_env)
    # ANOMOD_SERVE_CHAOS — scripted serve-plane fault injection
    # (anomod.serve.chaos; "" = off, else a validated fault script).
    serve_chaos: str = dataclasses.field(default_factory=_serve_chaos_env)
    # ANOMOD_SERVE_POLICY — elastic scaling policy: off (static), auto
    # (signal-fed autoscaler), script (fixed schedule from
    # ANOMOD_SERVE_POLICY_SCRIPT; anomod.serve.policy).
    serve_policy: str = dataclasses.field(default_factory=_serve_policy_env)
    # ANOMOD_SERVE_POLICY_SCRIPT — the scripted scaling schedule
    # ("" = none; validated action grammar, see validate_policy_script).
    serve_policy_script: str = dataclasses.field(
        default_factory=_serve_policy_script_env)
    # ANOMOD_SERVE_POLICY_MIN_SHARDS — elastic scale-down floor.
    serve_policy_min_shards: int = dataclasses.field(
        default_factory=_serve_policy_min_shards_env)
    # ANOMOD_SERVE_POLICY_MAX_SHARDS — elastic scale-up ceiling (past
    # it sustained overload climbs the brownout ladder instead).
    serve_policy_max_shards: int = dataclasses.field(
        default_factory=_serve_policy_max_shards_env)
    # ANOMOD_SERVE_POLICY_TARGET_IMBALANCE — max/mean shard-load ratio
    # past which the auto policy rebalances (live served-rate EWMAs).
    serve_policy_target_imbalance: float = dataclasses.field(
        default_factory=_serve_policy_target_imbalance_env)
    # ANOMOD_SERVE_POLICY_COOLDOWN_TICKS — minimum ticks between
    # executed scaling decisions (the anti-thrash hysteresis half).
    serve_policy_cooldown_ticks: int = dataclasses.field(
        default_factory=_serve_policy_cooldown_env)
    # ANOMOD_SERVE_CKPT_EVERY — shard-checkpoint cadence in ticks
    # (anomod.serve.supervise; 0 = supervision off, faults fail the
    # tick as before).
    serve_ckpt_every: int = dataclasses.field(
        default_factory=_serve_ckpt_every_env)
    # ANOMOD_SERVE_RETRIES — consecutive failures of one tick slice
    # before it is quarantined (anomod.serve.supervise).
    serve_retries: int = dataclasses.field(
        default_factory=_serve_retries_env)
    # ANOMOD_SERVE_RETRY_BACKOFF_S — wall backoff between recovery
    # attempts (0 = immediate; doubling, capped 5 s).
    serve_retry_backoff_s: float = dataclasses.field(
        default_factory=_serve_retry_backoff_s_env)
    # ANOMOD_SERVE_MAX_RESPAWNS — per-shard worker respawn budget per
    # run; past it the shard's tenants migrate to survivors.
    serve_max_respawns: int = dataclasses.field(
        default_factory=_serve_max_respawns_env)
    # ANOMOD_FLIGHT — serve-plane black-box flight recorder switch
    # (anomod.obs.flight; off = no tick journal, no audit surface).
    flight: bool = dataclasses.field(default_factory=_flight_env)
    # ANOMOD_FLIGHT_DIGEST_EVERY — tenant-state digest cadence in ticks
    # (anomod.obs.flight; crc32 over the get_state/pool-gather bytes).
    flight_digest_every: int = dataclasses.field(
        default_factory=_flight_digest_every_env)
    # ANOMOD_FLIGHT_MAX_TICKS — flight-journal ring capacity in ticks
    # (oldest records drop past it, counted in the registry).
    flight_max_ticks: int = dataclasses.field(
        default_factory=_flight_max_ticks_env)
    # ANOMOD_FLIGHT_DUMP_DIR — alert-triggered forensic-bundle directory
    # (anomod.obs.flight.forensic_bundle; None = dumps off).
    flight_dump_dir: Optional[Path] = dataclasses.field(
        default_factory=_flight_dump_dir_env)
    # ANOMOD_PERF — dispatch-lifecycle timeline + overlap-bubble
    # accounting (anomod.obs.perf; off by default, pure read-side).
    perf: bool = dataclasses.field(default_factory=_perf_env)
    # ANOMOD_PERF_MAX_EVENTS — retained timeline-event bound (oldest
    # drop past it, counted in the registry).
    perf_max_events: int = dataclasses.field(
        default_factory=_perf_max_events_env)
    # ANOMOD_PERF_NOISE_FLOOR — the explicit box noise model `anomod
    # perf diff` tests bootstrap wall-ratio CIs against.
    perf_noise_floor: float = dataclasses.field(
        default_factory=_perf_noise_floor_env)
    # ANOMOD_CENSUS — fleet census observatory: deterministic
    # resident-bytes + hot-set/Zipf census per cadence tick
    # (anomod.obs.census; off by default, pure read-side).
    census: bool = dataclasses.field(default_factory=_census_env)
    # ANOMOD_CENSUS_EVERY — census cadence in ticks (the flight
    # digest-cadence idiom; a census is always forced at run end).
    census_every: int = dataclasses.field(
        default_factory=_census_every_env)
    # ANOMOD_CENSUS_DECAY_TICKS — hot-set decay thresholds in ticks
    # (the hot-set-size-at-decay-threshold curve's x axis).
    census_decay_ticks: tuple = dataclasses.field(
        default_factory=_census_decay_ticks_env)
    # ANOMOD_CENSUS_SWEEP — registered-fleet sizes for the census
    # cost-attribution probe (anomod.obs.census.fleet_probe).
    census_sweep: tuple = dataclasses.field(
        default_factory=_census_sweep_env)
    # ANOMOD_CENSUS_COLDEST_K — coldest-K eviction-candidate preview
    # length per census tick.
    census_coldest_k: int = dataclasses.field(
        default_factory=_census_coldest_k_env)
    # ANOMOD_SERVE_TIER_HOT — tenant-state tiering hot capacity in
    # tenants; 0 = tiering off (anomod.serve.tiering).
    serve_tier_hot: int = dataclasses.field(
        default_factory=_serve_tier_hot_env)
    # ANOMOD_SERVE_TIER_DEMOTE_AFTER — idle ticks before a resident
    # tenant is demotion-eligible (the census last-served decay signal).
    serve_tier_demote_after: int = dataclasses.field(
        default_factory=_serve_tier_demote_after_env)
    # ANOMOD_SERVE_TIER_WARM_BYTES — host warm-tier state-bytes budget;
    # past it the coldest warm entries spill to the disk cold tier.
    serve_tier_warm_bytes: int = dataclasses.field(
        default_factory=_serve_tier_warm_bytes_env)
    # ANOMOD_SERVE_TIER_COLD_DIR — content-addressed disk cold-tier
    # root (io/cache atomic publish idiom); unset/off = no cold tier.
    serve_tier_cold_dir: Optional[Path] = dataclasses.field(
        default_factory=_serve_tier_cold_dir_env)
    # ANOMOD_SERVE_TIER_PREFETCH — cold-tier prefetch lane depth (max
    # concurrent disk fetches overlapping the admission phases).
    serve_tier_prefetch: int = dataclasses.field(
        default_factory=_serve_tier_prefetch_env)
    # ANOMOD_NATIVE — C++ native runtime switch: auto (use when the .so
    # loads), on (required, fail loud with the build reason), off
    # (pure-Python paths; anomod.io.native).
    native: str = dataclasses.field(default_factory=_native_env)
    # ANOMOD_JIT_CACHE — persistent XLA compilation cache under
    # ANOMOD_CACHE_DIR/jit (anomod.utils.platform.enable_jit_cache).
    jit_cache: bool = dataclasses.field(default_factory=_jit_cache_env)
    # ANOMOD_SERVE_MAX_BACKLOG — global admission backlog bound in spans
    # (anomod.serve.queues; the backpressure/shed budget).
    serve_max_backlog: int = dataclasses.field(
        default_factory=_serve_max_backlog_env)
    # ANOMOD_OBS_ENABLED — process-wide metrics registry switch
    # (anomod.obs.registry; off = shared no-op metric handles).
    obs_enabled: bool = dataclasses.field(default_factory=_obs_enabled_env)
    # ANOMOD_OBS_MAX_SAMPLES — scrape-journal bound in samples
    # (anomod.obs.registry; oldest samples drop past it).
    obs_max_samples: int = dataclasses.field(
        default_factory=_obs_max_samples_env)
    # ANOMOD_OBS_HTTP — embedded /metrics endpoint plane switch
    # (anomod.obs.http; localhost-bound, off by default).
    obs_http: bool = dataclasses.field(default_factory=_obs_http_env)
    # ANOMOD_OBS_HTTP_PORT — endpoint-plane TCP port; 0 = OS-assigned
    # ephemeral (anomod.obs.http).
    obs_http_port: int = dataclasses.field(
        default_factory=_obs_http_port_env)
    # ANOMOD_SERVE_FEED_LAG_S — live-feed wall->virtual lag budget in
    # seconds (anomod.serve.feed; walls measured, never decisive).
    serve_feed_lag_s: float = dataclasses.field(
        default_factory=_serve_feed_lag_s_env)
    # ANOMOD_FEED_JOURNAL — live-feed wire-journal path, or unset/off to
    # disable recording (anomod.serve.feed.FeedJournal).
    feed_journal: Optional[Path] = dataclasses.field(
        default_factory=_feed_journal_env)

    @property
    def sn_data(self) -> Path:
        return self.data_root / "SN_data"

    @property
    def tt_data(self) -> Path:
        return self.data_root / "TT_data"

    def with_backend(self, backend: str) -> "Config":
        return dataclasses.replace(self, backend=backend)


_DEFAULT: Optional[Config] = None


def get_config() -> Config:
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = Config()
    return _DEFAULT


def set_config(cfg: Config) -> None:
    global _DEFAULT
    _DEFAULT = cfg
