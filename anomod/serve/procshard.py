"""Process shard workers: the GIL-escape half of the serving plane.

``ANOMOD_SERVE_WORKER=process`` replaces each shard's worker THREAD
(:class:`anomod.serve.shard.ShardWorker`) with a spawn-context worker
PROCESS that owns the shard's whole scoring plane end to end —
detectors, replay states, its :class:`~anomod.serve.batcher.BucketRunner`
(own jitted executables, own pinned scratch) and its own obs
:class:`~anomod.obs.registry.Registry` — so N shards score on N
interpreters instead of time-slicing one GIL.

The seam is DATA, not code: a process cannot share the engine's memory,
so the coordinator drives each child through a picklable per-tick
command protocol over a duplex pipe — the drained-batch fan-out goes
out (``{"op": "score", "served": [...], "origin_tick": t}``), the
canonical results come back (new alerts, the runner's cumulative
wall/dispatch book, sparse registry deltas, chaos fired-counts).  The
child executes the slice through the SAME ``ServeEngine._score_shard``
code path as the thread worker — it builds a real 1-shard sub-engine
over its owned tenants (flight/perf/census/policy/supervision/tiering
off; those planes live on the coordinator) — so the score plane is
byte-identical to the thread engine BY CONSTRUCTION, not by a parallel
reimplementation.

Determinism inventory (what crosses the pipe and why it's safe):

- **Alerts** ship as ``(tenant_id, base, alerts[base:])`` suffixes
  against a per-tenant high-water; the coordinator's mirror truncates
  to ``base`` and extends, so a supervised recovery's checkpoint rewind
  self-heals to the child's exact list.
- **Registry deltas** are :meth:`anomod.obs.registry.Registry.
  delta_snapshot` payloads (the sparse/dense tick-barrier wire shape);
  the child owns its fold high-water state, so a respawned child's
  fresh registry folds from zero without double counting.
- **State digests** ship as per-tenant ``(crc, len)`` fragments
  (:func:`anomod.obs.flight.state_digest_parts`) and fold with
  ``crc32_combine`` — bit-equal to the coordinator walking the states
  itself, without shipping a single state pytree.
- **Chaos fired-counts** ride every reply: a scripted fault's
  ``repeat`` budget lives in the child, and a respawned child must
  resume the budget where the dead one left it or a one-shot crash
  fault would re-trip on recovery re-execution, forever.

Errors cross the pipe as a pickled summary (type name, message,
``kills_worker``, formatted traceback) and are reconstructed on the
coordinator — chaos exception types by name from
:mod:`anomod.serve.chaos`, anything else as ``RuntimeError`` — so the
supervisor's retry/quarantine/migrate ladder sees the same exception
surface the thread worker raises at join().  A ``kills_worker`` fault
sends its reply first, then the child exits: force-delete-and-respawn,
exactly the thread seam's contract.
"""

from __future__ import annotations

import multiprocessing as mp
import traceback
from typing import Dict, List, Optional, Set

#: exception modules the coordinator will re-import by name when
#: rebuilding a shipped child error; everything else degrades to
#: RuntimeError (the pipe is trusted — same user, same box — but the
#: reconstruction surface stays a closed set anyway)
_TRUSTED_EXC_MODULES = ("builtins", "anomod.serve.chaos")


def ship_exc(e: BaseException) -> dict:
    """One child-side exception as a picklable summary."""
    return {"type": type(e).__name__,
            "module": type(e).__module__,
            "msg": str(e),
            "kills_worker": bool(getattr(e, "kills_worker", False)),
            "traceback": traceback.format_exc()}


def rebuild_exc(doc: dict) -> BaseException:
    """Coordinator-side reconstruction of :func:`ship_exc`.

    Chaos types (``ChaosFault`` / ``ChaosWorkerCrash``) and builtins
    rebuild as themselves so the supervisor's ``kills_worker``
    duck-typing and the tests' ``pytest.raises`` surfaces match the
    thread engine; unknown types become RuntimeError with the child's
    traceback attached for forensics."""
    exc: Optional[BaseException] = None
    mod = doc.get("module", "")
    name = doc.get("type", "RuntimeError")
    if mod in _TRUSTED_EXC_MODULES:
        try:
            import importlib
            cls = getattr(importlib.import_module(mod), name, None)
            if isinstance(cls, type) and issubclass(cls, BaseException):
                exc = cls(doc.get("msg", ""))
        except Exception:       # noqa: BLE001 — fall through to generic
            exc = None
    if exc is None:
        exc = RuntimeError(
            f"shard worker {name}: {doc.get('msg', '')}")
    if doc.get("kills_worker") and not getattr(exc, "kills_worker",
                                               False):
        exc.kills_worker = True        # type: ignore[attr-defined]
    exc.remote_traceback = doc.get("traceback")  # type: ignore[attr-defined]
    return exc


class RunnerMirror:
    """The coordinator's stand-in for a child-owned BucketRunner.

    Every runner fact the coordinator-side planes read — flight-header
    buckets, per-tick ``leg_walls()`` deltas, the supervisor's
    ``book_snapshot``/``book_restore`` double-count guard, the policy's
    ``n_dispatches`` chunk signal, the report's ``_runner_stats`` shape
    — is served from the child's last barrier reply, so the planes
    themselves never branch on the worker kind.  Resolution of the
    static facts (buckets, lane buckets, native staging, state mode)
    reuses the EXACT BucketRunner validators: the flight header is
    written in the engine ctor, before any child exists."""

    def __init__(self, cfg, buckets=None, lane_buckets=None,
                 native_stage=None, state=None):
        from anomod.config import get_config, validate_lane_buckets
        from anomod.config import validate_serve_buckets
        from anomod.io import native as native_io
        if buckets is None:
            buckets = get_config().serve_buckets
        if lane_buckets is None:
            lane_buckets = get_config().serve_lane_buckets
        self.cfg = cfg
        self.buckets = validate_serve_buckets(buckets)
        self.lane_buckets = validate_lane_buckets(lane_buckets)
        self.native_stage = native_io.staging_enabled(native_stage)
        _state = state if state is not None else get_config().serve_state
        self.state_mode = "device" if _state == "auto" else _state
        self.pool = None               # the pool lives in the child
        # cumulative book (the book_snapshot/book_restore shape)
        self.n_dispatches = 0
        self.dispatches_by_width: Dict[int, int] = {}
        self.fused_dispatches = 0
        self.native_staged = 0
        self.staged_lanes = 0
        self.live_lanes = 0
        self.lanes_by_bucket: Dict[int, int] = {}
        # wall/compile legs (the _runner_stats shape)
        self.compile_s = 0.0
        self.lane_compile_s = 0.0
        self.stage_wall_s = 0.0
        self.dispatch_wall_s = 0.0
        self.fold_wall_s = 0.0
        self.score_wall_s = 0.0
        self.inflight_dispatches = 0

    def apply(self, doc: dict) -> None:
        """Install one barrier reply's cumulative runner book."""
        self.book_restore(doc["book"])
        self.compile_s = doc["compile_s"]
        self.lane_compile_s = doc["lane_compile_s"]
        walls = doc["walls"]
        self.stage_wall_s = walls["stage_s"]
        self.dispatch_wall_s = walls["dispatch_s"]
        self.fold_wall_s = walls["fold_s"]
        self.score_wall_s = walls["score_s"]

    def leg_walls(self) -> dict:
        return {"stage_s": self.stage_wall_s,
                "dispatch_s": self.dispatch_wall_s,
                "fold_s": self.fold_wall_s,
                "score_s": self.score_wall_s,
                "chunks": self.n_dispatches,
                "fused": self.fused_dispatches,
                "native_staged": self.native_staged,
                "by_width": dict(self.dispatches_by_width)}

    def book_snapshot(self) -> dict:
        return {"n_dispatches": self.n_dispatches,
                "dispatches_by_width": dict(self.dispatches_by_width),
                "fused_dispatches": self.fused_dispatches,
                "native_staged": self.native_staged,
                "staged_lanes": self.staged_lanes,
                "live_lanes": self.live_lanes,
                "lanes_by_bucket": dict(self.lanes_by_bucket)}

    def book_restore(self, book: dict) -> None:
        self.n_dispatches = book["n_dispatches"]
        self.dispatches_by_width = dict(book["dispatches_by_width"])
        self.fused_dispatches = book["fused_dispatches"]
        self.native_staged = book["native_staged"]
        self.staged_lanes = book["staged_lanes"]
        self.live_lanes = book["live_lanes"]
        self.lanes_by_bucket = dict(book["lanes_by_bucket"])

    @property
    def lane_pad_waste(self) -> float:
        return (1.0 - self.live_lanes / self.staged_lanes
                if self.staged_lanes else 0.0)

    def abort_lanes(self) -> None:
        """In-flight dispatches live in the child; nothing to drop
        here (the child aborts its own lanes on a failed slice and on
        the ``drop`` command)."""


class DetMirror:
    """The coordinator's stand-in for a child-owned OnlineDetector:
    just the alert list (the only detector surface the coordinator
    planes read — flight alert digests, RCA enqueue, report counts),
    kept in sync by the barrier replies' suffix protocol."""

    __slots__ = ("alerts",)

    def __init__(self):
        self.alerts: list = []


class ProcShardWorker:
    """One shard's worker PROCESS behind the ShardWorker seam.

    Presents the thread seam's four members (``submit`` / ``join`` /
    ``close`` / ``alive``) plus the data-protocol halves the engine's
    process branches use directly: ``send`` (fan-out, non-blocking),
    ``recv`` (barrier, returns the raw reply dict), ``call``
    (send+recv, raising the reconstructed child error).  ``submit``
    takes a picklable command dict instead of a closure — a process
    cannot share the engine's memory, so the engine hands it data, not
    code; ``join`` re-raises the shipped error exactly like the thread
    worker's barrier."""

    kind = "process"

    def __init__(self, shard_id: int, init: dict,
                 start_timeout_s: float = 120.0,
                 name: str = "anomod-procshard"):
        ctx = mp.get_context("spawn")
        self.shard_id = shard_id
        parent_conn, child_conn = ctx.Pipe()
        self._conn = parent_conn
        self._proc = ctx.Process(target=_shard_main, args=(child_conn,),
                                 name=f"{name}-{shard_id}", daemon=True)
        self._closed = False
        self._dying = False
        self.last_reply: Optional[dict] = None
        self._proc.start()
        child_conn.close()
        try:
            self._conn.send(dict(init))
            # the spawn handshake: the child imports jax and compiles
            # nothing yet, but a wedged interpreter (or an init error)
            # must surface HERE, bounded by the validated knob, not
            # hang the first tick barrier forever
            if not self._conn.poll(start_timeout_s):
                raise TimeoutError(
                    f"shard {shard_id} worker process did not finish "
                    f"startup within {start_timeout_s:.0f}s "
                    "(ANOMOD_SERVE_WORKER_START_TIMEOUT_S)")
            hello = self._conn.recv()
        except BaseException:
            self.close(force=True)
            raise
        if hello.get("error") is not None:
            err = rebuild_exc(hello["error"])
            self.close(force=True)
            raise err
        #: the child's resolved runner facts (buckets / native staging /
        #: state mode) — forensic cross-check against the RunnerMirror
        self.hello = hello

    # -- data protocol ----------------------------------------------------

    def send(self, msg: dict) -> None:
        """Fan-out half: enqueue one command without waiting."""
        try:
            self._conn.send(msg)
        except (BrokenPipeError, OSError) as e:
            self._dying = True
            raise RuntimeError(
                f"shard {self.shard_id} worker process is gone "
                f"(command {msg.get('op')!r} not delivered)") from e

    def recv(self) -> dict:
        """Barrier half: one raw reply dict.  A shipped error stays IN
        the reply (the engine folds the partial results first and
        reconstructs the exception itself); only a dead pipe raises
        here."""
        try:
            rep = self._conn.recv()
        except (EOFError, OSError) as e:
            self._dying = True
            raise RuntimeError(
                f"shard {self.shard_id} worker process died "
                "mid-command") from e
        err = rep.get("error")
        if err is not None and err.get("kills_worker"):
            # the child exits right after this reply; flip alive NOW so
            # a respawn check can never race the process teardown
            self._dying = True
        self.last_reply = rep
        return rep

    def call(self, msg: dict) -> dict:
        """send + recv, raising the reconstructed child error."""
        self.send(msg)
        rep = self.recv()
        if rep.get("error") is not None:
            raise rebuild_exc(rep["error"])
        return rep

    # -- the ShardWorker seam ---------------------------------------------

    def submit(self, msg: dict) -> None:
        self.send(msg)

    def join(self) -> dict:
        rep = self.recv()
        if rep.get("error") is not None:
            raise rebuild_exc(rep["error"])
        return rep

    def close(self, force: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            if not force and self._proc.is_alive():
                self._conn.send({"op": "close"})
        except (BrokenPipeError, OSError):
            pass
        self._proc.join(timeout=10.0)
        if self._proc.is_alive():
            self._proc.terminate()
            self._proc.join(timeout=5.0)
        try:
            self._conn.close()
        except OSError:
            pass

    @property
    def alive(self) -> bool:
        return (not self._closed and not self._dying
                and self._proc.is_alive())


# -- the child ------------------------------------------------------------

def _shard_main(conn) -> None:
    """Worker-process entry point: receive the init payload, build the
    shard plane, then serve commands until ``close``/EOF (or until a
    ``kills_worker`` fault ends the process after its error reply)."""
    try:
        init = conn.recv()
    except (EOFError, OSError):
        return
    try:
        plane = _ShardPlane(init)
        conn.send({"ok": True, **plane.static_facts()})
    except BaseException as e:          # noqa: BLE001 — shipped
        try:
            conn.send({"error": ship_exc(e)})
        except (BrokenPipeError, OSError):
            pass
        return
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            return
        if msg.get("op") == "close":
            return
        reply, die = plane.handle(msg)
        try:
            conn.send(reply)
        except (BrokenPipeError, OSError):
            return
        if die:
            return


class _ShardPlane:
    """The child's side of the protocol: a real 1-shard sub-ServeEngine
    over the shard's owned tenants, plus the bookkeeping that turns its
    state changes into barrier replies.

    The sub-engine runs with every coordinator plane OFF — flight,
    perf, census, policy, supervision, tiering, RCA (evidence buffering
    is documented coordinator-side: rca.py keeps buffer content
    shard-count-invariant there) — and every knob passed EXPLICITLY
    from the parent's resolved values, so the child can never drift
    onto a different env-sourced configuration than the engine that
    spawned it."""

    def __init__(self, init: dict):
        from anomod import obs
        from anomod.serve.engine import ServeEngine
        reg = obs.get_registry()
        # the child's process-default registry IS the shard registry:
        # match the parent's enabled bit (the env normally agrees, but
        # a test that force-enabled the parent's registry must see the
        # child's metrics too)
        reg.enabled = bool(init["registry_enabled"])
        self.shard_id = int(init["shard_id"])
        self.chaos = None
        chaos_script = init.get("chaos_script")
        if chaos_script:
            from anomod.serve.chaos import ServeChaos
            self.chaos = ServeChaos(chaos_script)
            # keep only this shard's faults, remapped to the
            # sub-engine's shard 0 (surge is coordinator-side arrival
            # amplification and never fires here)
            self.chaos.faults = [f for f in self.chaos.faults
                                 if f.kind != "surge"
                                 and f.shard == self.shard_id]
            for f in self.chaos.faults:
                f.shard = 0
            self._restore_chaos_fired(init.get("chaos_fired"))
        det_kw = init["det_kw"]
        self.eng = ServeEngine(
            init["specs"], init["services"], cfg=init["cfg"],
            t0_us=init["t0_us"],
            capacity_spans_per_s=init["capacity_spans_per_s"],
            tick_s=init["tick_s"], buckets=init["buckets"],
            max_backlog=init["max_backlog"], score=init["score"],
            fuse=init["fuse"], lane_buckets=init["lane_buckets"],
            shards=1, pipeline=init["pipeline"], rca=False,
            native=init["native"], state=init["state"], flight=False,
            perf=False, census=False,
            chaos=self.chaos if self.chaos is not None else "",
            ckpt_every=0, policy="off", async_commit=False, tier_hot=0,
            worker="thread", fold="sparse", **det_kw)
        self._fold_state: Dict[tuple, float] = {}
        self._reg = reg
        #: per-tenant alert high-water: how much of each detector's
        #: alert list the coordinator's mirror already holds
        self._sent: Dict[int, int] = {}
        self._shipped_replay: Set[int] = set()
        self._shipped_det: Set[int] = set()

    def static_facts(self) -> dict:
        r = self.eng.runner
        return {"buckets": tuple(r.buckets),
                "lane_buckets": tuple(r.lane_buckets),
                "native_stage": bool(r.native_stage),
                "state_mode": r.state_mode}

    def _restore_chaos_fired(self, fired: Optional[List[int]]) -> None:
        """Reinstall a dead predecessor's fault fired-counts: a
        ``repeat``-budgeted fault must not reset its budget just
        because the crash it injected respawned the process."""
        if not fired or self.chaos is None:
            return
        for f, n in zip(self.chaos.faults, fired):
            f.fired = int(n)

    # -- reply assembly ---------------------------------------------------

    def _mirror_doc(self) -> dict:
        r = self.eng.runner
        return {"book": r.book_snapshot(),
                "compile_s": float(r.compile_s),
                "lane_compile_s": float(r.lane_compile_s),
                "walls": {"stage_s": r.stage_wall_s,
                          "dispatch_s": r.dispatch_wall_s,
                          "fold_s": r.fold_wall_s,
                          "score_s": r.score_wall_s}}

    def _alert_updates(self) -> list:
        ups = []
        for tid in sorted(self.eng._tenant_det):
            alerts = self.eng._tenant_det[tid].alerts
            prev = self._sent.get(tid, 0)
            if len(alerts) != prev:
                base = min(prev, len(alerts))
                ups.append((tid, base, list(alerts[base:])))
                self._sent[tid] = len(alerts)
        return ups

    def _residency_updates(self) -> dict:
        new_rep = [t for t in self.eng._tenant_replay
                   if t not in self._shipped_replay]
        new_det = [t for t in self.eng._tenant_det
                   if t not in self._shipped_det]
        self._shipped_replay.update(new_rep)
        self._shipped_det.update(new_det)
        return {"resident_new": sorted(new_rep),
                "det_new": sorted(new_det)}

    def handle(self, msg: dict):
        op = msg["op"]
        reply: dict = {}
        die = False
        try:
            out = getattr(self, "_op_" + op, self._op_unknown)(msg)
            if out:
                reply.update(out)
        except BaseException as e:      # noqa: BLE001 — shipped
            reply["error"] = ship_exc(e)
            die = bool(getattr(e, "kills_worker", False))
        if op in ("score", "warm", "finish", "install_tenant",
                  "put_tenant"):
            try:
                reply.update(self._mirror_doc())
                reply["alerts"] = self._alert_updates()
                reply.update(self._residency_updates())
                if op in ("score", "finish"):
                    reply["reg_delta"] = self._reg.delta_snapshot(
                        self._fold_state, mode=msg.get("fold", "sparse"),
                        final=False)
            except BaseException as e:  # noqa: BLE001 — shipped
                reply.setdefault("error", ship_exc(e))
        if self.chaos is not None:
            reply["chaos_fired"] = [f.fired for f in self.chaos.faults]
        return reply, die

    def _op_unknown(self, msg: dict):
        raise ValueError(f"unknown procshard command {msg.get('op')!r}")

    # -- command handlers -------------------------------------------------

    def _op_score(self, msg: dict):
        self.eng._score_shard(0, msg["served"], msg["origin_tick"])

    def _op_warm(self, msg: dict):
        r = self.eng.runner
        r.warm()
        if self.eng._fused:
            r.warm_lanes()

    def _op_finish(self, msg: dict):
        for det in self.eng._tenant_det.values():
            det.finish()

    def _op_digest(self, msg: dict):
        from anomod.obs.flight import state_digest_parts
        return {"parts": state_digest_parts(self.eng._tenant_replay)}

    def _op_reg_delta(self, msg: dict):
        return {"delta": self._reg.delta_snapshot(
            self._fold_state, mode=msg.get("fold", "sparse"),
            final=bool(msg.get("final", False)))}

    def _op_snapshot(self, msg: dict):
        from anomod.serve.supervise import (snapshot_detector,
                                            snapshot_replay)
        tenants = {}
        for tid, rep in self.eng._tenant_replay.items():
            det = self.eng._tenant_det.get(tid)
            tenants[tid] = (snapshot_replay(rep),
                            snapshot_detector(det)
                            if det is not None else None)
        return {"tenants": tenants,
                "book": self.eng.runner.book_snapshot()}

    def _op_book_restore(self, msg: dict):
        self.eng.runner.book_restore(msg["book"])

    def _op_drop(self, msg: dict):
        eng = self.eng
        for tid in list(eng._tenant_replay):
            rep = eng._tenant_replay.pop(tid)
            release = getattr(rep, "release", None)
            if release is not None:
                release()
        eng._tenant_det.clear()
        eng.runner.abort_lanes()
        self._sent.clear()
        self._shipped_replay.clear()
        self._shipped_det.clear()

    def _op_install_tenant(self, msg: dict):
        from anomod.serve.supervise import restore_detector, restore_replay
        tid = msg["tid"]
        rep = self.eng._replay_for(tid)
        restore_replay(rep, msg["replay"])
        det_snap = msg.get("det")
        if det_snap is not None:
            det = self.eng._detector_for(tid)
            restore_detector(det, det_snap)
            # the coordinator installs the mirror's alert list from the
            # same snapshot — nothing to ship
            self._sent[tid] = len(det.alerts)

    def _op_put_tenant(self, msg: dict):
        self._op_install_tenant(msg)

    def _op_take_tenant(self, msg: dict):
        from anomod.serve.supervise import (snapshot_detector,
                                            snapshot_replay)
        tid = msg["tid"]
        eng = self.eng
        rep = eng._tenant_replay.pop(tid, None)
        if rep is None:
            return {"snap": None}
        rep_snap = snapshot_replay(rep)
        release = getattr(rep, "release", None)
        if release is not None:
            release()
        det = eng._tenant_det.pop(tid, None)
        det_snap = snapshot_detector(det) if det is not None else None
        self._sent.pop(tid, None)
        self._shipped_replay.discard(tid)
        self._shipped_det.discard(tid)
        return {"snap": (rep_snap, det_snap)}
