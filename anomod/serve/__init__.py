"""Multi-tenant serving plane over the streaming detectors.

The reference pipeline is strictly post-hoc and the streaming layer
(anomod.stream) assumes one well-behaved feed; this package is what stands
between "millions of users" and the jitted chunk step: admission control
with per-tenant weighted-fair queues (queues), a dynamic micro-batcher
that coalesces tenant micro-batches into fixed padded bucket shapes so the
shared chunk step compiles once per bucket — and, fused
(ANOMOD_SERVE_FUSE), lane-stacks same-width chunks across tenants into
one dispatch per (width, lane-bucket) shape, pinned bit-identical to
sequential scoring (batcher) — a deterministic virtual-clock serving
engine with per-tenant SLO accounting (engine), a seeded power-law
traffic generator standing in for the tenant fleet (traffic), and —
scale-out (ANOMOD_SERVE_SHARDS) — deterministic tenant sharding across
engine worker threads with pipelined async dispatch (shard), pinned
identical to the 1-shard engine on the same seed.  Online RCA
(ANOMOD_SERVE_RCA): a tenant's detector firing queues incremental GNN
culprit inference over that tenant's live service graph in a fixed
AOT-compiled (nodes, neighbors) bucket grid (rca), verdicts deterministic
per seed and identical at every shard count.  Fault tolerance
(ANOMOD_SERVE_CKPT_EVERY, on by default): supervised shard workers with
cadenced checkpoint/restore through the get_state seam and deterministic
re-execution (supervise) — a mid-tick shard crash recovers with NO score
gap, byte-identical to fault-free — proven against scripted chaos aimed
at the serve plane itself (chaos, ANOMOD_SERVE_CHAOS).  Elastic
serving (ANOMOD_SERVE_POLICY): a signal-fed autoscaler evaluated at
every tick boundary drives scale-up/down/rebalance/brownout through
the same migration seams at POLICY time (policy) — scaling episodes
are seed-deterministic (same schedule under rerun and audit replay)
and leave states/alerts/SLO/shed byte-identical to a static run.
"""

from anomod.serve.batcher import (BucketedStreamReplay, BucketRunner,
                                  split_plan)
from anomod.serve.engine import ServeEngine, ServeReport, VirtualClock
from anomod.serve.queues import AdmissionController, QueuedBatch, TenantSpec
from anomod.serve.chaos import ChaosFault, ChaosWorkerCrash, ServeChaos
from anomod.serve.policy import ElasticPolicy, TickSignals, plan_rebalance
from anomod.serve.rca import OnlineRCA, RCAVerdict, RcaRunner
from anomod.serve.shard import ShardWorker, plan_shards, rendezvous_shard
from anomod.serve.supervise import ShardSupervisor
from anomod.serve.traffic import PowerLawTraffic, ScriptedTraffic

__all__ = [
    "AdmissionController", "BucketRunner", "BucketedStreamReplay",
    "ChaosFault", "ChaosWorkerCrash", "ElasticPolicy", "OnlineRCA",
    "PowerLawTraffic", "QueuedBatch", "RCAVerdict", "RcaRunner",
    "ScriptedTraffic", "ServeChaos", "ServeEngine", "ServeReport",
    "ShardSupervisor", "ShardWorker", "TenantSpec", "TickSignals",
    "VirtualClock", "plan_rebalance", "plan_shards",
    "rendezvous_shard", "split_plan",
]
