"""Dynamic micro-batching into fixed padded bucket shapes, with a FUSED
lane-stacked dispatch path for the multi-tenant tick loop.

The serving plane's hot path is the SAME jitted chunk step the batch
replay scans with (anomod.replay.make_chunk_step) — but tenant
micro-batches are small and ragged, and staging every 150-span batch
into a 32768-wide chunk wastes 99% of each dispatch.  The batcher pads
each admitted micro-batch to the smallest shape from a FIXED bucket set
(``ANOMOD_SERVE_BUCKETS``), so XLA compiles the step once per bucket
width and every later dispatch of that width reuses the executable.

On top of the width buckets, the FUSED path (``ANOMOD_SERVE_FUSE``,
default on) batches across TENANTS: per engine tick, same-width staged
chunks from many tenants stack into ``[lanes, width]`` arrays and run as
ONE dispatch of the lane-stacked chunk step
(anomod.replay.make_lane_delta), with lane counts padded up to a small
fixed bucket set (``ANOMOD_SERVE_LANE_BUCKETS``) so XLA compiles once
per (width, lane-bucket) shape.  Dead pad lanes carry all-pad rows and
their outputs are dropped — the corresponding tenants' states pass
through untouched.  This is the power-law-fleet shape: many small
irregular work items, one wide regular kernel (cf. the Sparse-Allreduce
and VersaGNN batched-aggregation framings in PAPERS.md).

Replay parity is exact by construction, at every level:

- WIDTH buckets: a batch is split at ``cfg.chunk_size`` boundaries (full
  chunks stage exactly as the sequential StreamReplay would) and only
  the TAIL remainder is padded to a bucket.  Padding rows target the
  dead lane (sid = cfg.sw, valid = 0), whose contribution to every live
  segment is exactly 0.0 — and the real rows occupy the same leading
  positions they would in the sequential staging — so the f32 state
  after a bucketed push is BIT-IDENTICAL to the sequential fixed-chunk
  push on CPU (tests/test_serve.py pins this, alert stream included).
- STEP engine: on XLA:CPU the runner dispatches the scatter
  (segment-sum) formulation of the chunk step, pinned bit-identical to
  the one-hot matmul formulation there (anomod.replay.make_chunk_step's
  engine contract) — ~10x faster on a host core, same bits.
- LANE stacking: each lane of the fused dispatch reduces its own rows in
  the same order the single-lane dispatch would, and the per-lane DELTA
  is folded into the tenant's state with the same elementwise f32 add
  the in-step update performs — so a fused tick's states (and therefore
  the alert stream) are BIT-IDENTICAL to dispatching every tenant's
  chunks one by one (tests/test_serve.py pins this too).  The fused
  surface follows the step engine on every backend unless
  ``ANOMOD_SERVE_LANE_ENGINE=pallas`` opts into the single Mosaic
  kernel, whose latency moments carry the bf16 hi/lo envelope instead
  of matching bit-for-bit (anomod.replay.default_lane_engine).

STAGING is interpreter-free end to end (``ANOMOD_NATIVE``): the pinned
``[lanes, width]`` scratch slots are 64-byte-aligned host buffers the
AOT executables may alias zero-copy on XLA:CPU, and the packing of
drained micro-batches into them (live rows + dead-chunk fills) runs
through the C++ ``stage_lanes`` entry (anomod.io.native) with the GIL
RELEASED — byte-identical to the interpreter fill (pinned), but staging
for scratch slot k+1 overlaps the in-flight dispatch on slot k, and
shard workers stage concurrently instead of convoying on the GIL.  The
per-dispatch stage/dispatch/fold walls are accounted separately (the
bench ``staging`` block / ``anomod_serve_{stage,dispatch,fold}_seconds_
total``), so the serving-overhead decomposition is measured, not prose.

:class:`BucketedStreamReplay` duck-types :class:`anomod.stream.StreamReplay`
(it subclasses it and overrides only the dispatch), so
``OnlineDetector(..., replay=...)`` runs the full alerting stack over the
shared bucket runner unchanged — thousands of tenants share ONE compiled
step per (width, lane-bucket) shape instead of compiling per tenant.
"""

from __future__ import annotations

import collections
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from anomod import obs
from anomod.config import DEFAULT_SERVE_BUCKETS as DEFAULT_BUCKETS
from anomod.config import validate_lane_buckets
from anomod.config import validate_serve_buckets as validate_buckets
from anomod.io import native as native_io
from anomod.replay import (N_FEATS, STAGE_KEYS, ReplayConfig, ReplayState,
                           TenantStatePool, dead_chunk,
                           default_lane_engine, default_step_engine,
                           fold_delta, make_chunk_step, make_lane_delta,
                           stage_columns_fused)
from anomod.schemas import SpanBatch
from anomod.stream import StreamReplay


def split_plan(n_spans: int, chunk_size: int,
               buckets: Tuple[int, ...]) -> List[Tuple[int, int, int]]:
    """(lo, hi, staged_width) slices for one micro-batch.

    Full ``chunk_size`` slices first (identical to sequential staging),
    then the tail remainder padded to the smallest bucket that holds it
    (``chunk_size`` itself when every bucket is narrower).  This is the
    ONE definition of the parity-preserving split, shared by the runner
    and its tests.
    """
    plan: List[Tuple[int, int, int]] = []
    lo = 0
    while n_spans - lo >= chunk_size:
        plan.append((lo, lo + chunk_size, chunk_size))
        lo += chunk_size
    rem = n_spans - lo
    if rem > 0:
        width = next((b for b in buckets if b >= rem and b <= chunk_size),
                     chunk_size)
        plan.append((lo, n_spans, width))
    return plan


class BucketRunner:
    """The shared compile-once-per-shape chunk-step dispatcher.

    One ``jax.jit`` of the shared chunk step serves every tenant; XLA
    compiles one executable per distinct chunk width (= per bucket, plus
    the full ``cfg.chunk_size``), tracked in ``compile_s_by_width`` /
    ``dispatches_by_width`` for the ServeReport.  The FUSED path adds
    one jit of the lane-stacked delta kernel, compiled once per
    (width, lane-bucket) shape (``lane_shapes`` / ``lane_compile_s``).
    """

    def __init__(self, cfg: ReplayConfig,
                 buckets: Optional[Tuple[int, ...]] = None,
                 lane_buckets: Optional[Tuple[int, ...]] = None,
                 engine: Optional[str] = None, registry=None,
                 pipeline: int = 1,
                 native_stage: Optional[bool] = None,
                 lane_engine: Optional[str] = None,
                 state: Optional[str] = None,
                 pool_slots: int = 32,
                 perf=None):
        import jax
        from anomod.config import get_config
        if buckets is None:
            buckets = get_config().serve_buckets
        if lane_buckets is None:
            lane_buckets = get_config().serve_lane_buckets
        if pipeline < 1:
            raise ValueError("pipeline depth must be >= 1")
        self.cfg = cfg
        #: tenant-state residency (the validated ANOMOD_SERVE_STATE knob
        #: unless the caller overrides): "device" owns a per-runner
        #: TenantStatePool — tenants map to slots at first service, the
        #: retire fold is an on-device scatter-add in dispatch order,
        #: pinned BIT-identical to the host seam — "host" is the
        #: per-tenant numpy pytree seam.  "auto" resolves to device on
        #: every backend: the pool performs the exact same f32 adds, so
        #: there is no tolerance trade to gate on.
        _state = state if state is not None else get_config().serve_state
        if _state not in ("auto", "host", "device"):
            raise ValueError(f"unknown serve state mode {_state!r} "
                             "(auto|host|device)")
        self.state_mode = "device" if _state == "auto" else _state
        _lane_eng = lane_engine if lane_engine is not None else \
            (engine if engine is not None else default_lane_engine())
        #: the shard's device-resident state pool (None on the host
        #: seam).  ANOMOD_SERVE_LANE_ENGINE=pallas routes the pool's
        #: batched-scoring gather to the fused Mosaic kernel too (the
        #: same TPU opt-in; bit-identical — a pure copy either way).
        self.pool = (TenantStatePool(
            cfg, capacity=max(int(pool_slots), 1),
            gather_engine="pallas" if _lane_eng == "pallas" else "xla")
            if self.state_mode == "device" else None)
        #: GIL-free native scratch packing (anomod.io.native.stage_lanes):
        #: resolved from the validated ANOMOD_NATIVE knob (auto/on/off)
        #: unless the caller overrides — the bench's python-staging
        #: reference leg passes False; byte-identical either way
        self.native_stage = native_io.staging_enabled(native_stage)
        #: metric sink: the sharded engine hands each shard's runner its
        #: OWN registry (thread-isolated hot path; merged into the
        #: process registry at the tick barrier) — default is the
        #: process registry, exactly as before
        self._reg = registry if registry is not None else obs.get_registry()
        #: dispatch-lifecycle event sink (anomod.obs.perf.PerfRecorder,
        #: the performance observatory's read-side seam) — None (the
        #: default) records nothing; when set, the fused submit/retire
        #: path stamps staged/submitted/materialized/folded/refill
        #: events REUSING the wall-leg clock reads below, so the
        #: timeline reconciles with the five-leg walls to float
        #: rounding and recording costs no extra perf_counter call on
        #: the already-timed points
        self.perf = perf
        #: max in-flight fused dispatches is ``pipeline - 1`` (depth 1 =
        #: fully synchronous, the pre-pipelining behavior); the submit/
        #: drain path keeps ``pipeline`` pinned scratch slots per
        #: (width, lane-bucket) shape so staging slot s+1 never touches
        #: buffers an in-flight dispatch still reads
        self.pipeline = int(pipeline)
        self.buckets = validate_buckets(buckets)
        self.lane_buckets = validate_lane_buckets(lane_buckets)
        #: chunk-step engine: scatter on XLA:CPU (bit-identical, ~10x),
        #: the one-hot bf16 matmul on accelerators (the MXU shape)
        self.engine = engine if engine is not None else \
            default_step_engine()
        #: fused lane-dispatch engine: an explicit ``engine=`` pins both
        #: surfaces to one formulation (the parity tests rely on that);
        #: otherwise default_lane_engine — the ANOMOD_SERVE_LANE_ENGINE
        #: knob when set (``pallas`` = the single fused Mosaic kernel,
        #: a deliberate TPU opt-in whose latency moments carry the bf16
        #: hi/lo envelope), else the step engine itself so fused and
        #: single-chunk dispatch stay BIT-identical on every backend
        self.lane_engine = _lane_eng
        step = make_chunk_step(cfg, with_hll=False, engine=self.engine)
        self._step = jax.jit(lambda st, ch: step(st, ch)[0])
        self._lane_fn = jax.jit(make_lane_delta(cfg,
                                                engine=self.lane_engine))
        #: AOT-compiled lane executables, one per (width, lane-bucket)
        #: shape: calling the compiled object skips the pjit python
        #: dispatch path (~5-10 ms per call on this class of host for
        #: the 7-column chunk dict — a third of the whole dispatch wall)
        #: and is bit-identical to calling the jit (same HLO, same
        #: executable)
        self._lane_exec: Dict[Tuple[int, int], object] = {}
        self.compile_s_by_width: Dict[int, float] = {}
        #: one compile wall per fused (width, lane-bucket) shape — the
        #: compile-count pin asserts this never grows past the warm grid
        self._lane_compile_s: Dict[Tuple[int, int], float] = {}
        self.dispatches_by_width: Dict[int, int] = {}
        self.n_dispatches = 0
        self.fused_dispatches = 0
        #: the serve tick's wall decomposition (the numbers behind the
        #: bench ``staging`` block): host packing (stage_plan + scratch
        #: fill), dispatch issue (the executable call — an ENQUEUE wall
        #: on async backends), and fold (output materialization — the
        #: execute barrier — plus the per-lane state adds).  What the
        #: serve wall spends OUTSIDE these three is admission/detector/
        #: bookkeeping time.
        self.stage_wall_s = 0.0
        self.dispatch_wall_s = 0.0
        self.fold_wall_s = 0.0
        #: window-scoring wall (the engine's COMMIT phase adds here, so
        #: the decomposition splits the old ``other`` leg into score vs
        #: true bookkeeping)
        self.score_wall_s = 0.0
        #: fused dispatches whose scratch was packed natively (GIL-free)
        self.native_staged = 0
        #: fused dispatches per lane-bucket (the lanes histogram's
        #: deterministic report twin)
        self.lanes_by_bucket: Dict[int, int] = {}
        self.staged_lanes = 0
        self.live_lanes = 0
        # pinned host scratch, reused across ticks: ``pipeline``
        # [lanes, width] buffer sets (SLOTS) per fused shape, so
        # steady-state staging stops reallocating (and re-faulting)
        # megabytes per tick — staged columns arrive UNPADDED
        # (stage_columns_raw) and pad here.  Reuse is safe ONLY because
        # a slot refills strictly after the dispatch that last read it
        # materialized its outputs (run_lanes materializes immediately;
        # the pipelined submit/drain path retires a slot's dispatch
        # before cycling back to it); the single-lane dispatch pads into
        # fresh buffers instead (see dispatch()).
        self._lane_scratch: Dict[Tuple[int, int, int],
                                 Dict[str, np.ndarray]] = {}
        #: per-slot native marshalling plans (anomod.io.native.StagePlan):
        #: the pinned slots outlive every dispatch, so dst pointers /
        #: fill patterns / ctypes arrays marshal once per slot, not per
        #: call — None caches a slot the runtime refused
        self._stage_plans: Dict[Tuple[int, int, int], object] = {}
        self._slot_next: Dict[Tuple[int, int], int] = {}
        #: FIFO of in-flight fused dispatches: (replays, dagg, dhist,
        #: slot key).  Retiring materializes the deltas (the execute
        #: barrier) and folds them through the get_state/set_state seam
        #: in dispatch order — so any pipeline depth is bit-identical.
        self._inflight: "collections.deque" = collections.deque()
        self._dead_cols: Dict[int, dict] = {}
        # registry mirrors (anomod.obs): staged-vs-live row counters make
        # the bucket-pad waste fraction derivable from any scrape
        # (waste = 1 - live/staged); handles cached — staging and the
        # fused dispatch are the serving hot path.  The lane twins
        # (staged/live LANES + lanes-per-dispatch histogram) price the
        # fused path's dead-lane padding the same way.
        reg = self._reg
        self._obs_dispatches = reg.counter("anomod_serve_dispatches_total")
        self._obs_staged = reg.counter("anomod_serve_staged_rows_total")
        self._obs_live = reg.counter("anomod_serve_live_rows_total")
        self._obs_waste = reg.gauge("anomod_serve_pad_waste_fraction")
        self._obs_fused = reg.counter(
            "anomod_serve_fused_dispatches_total")
        self._obs_lanes = reg.histogram("anomod_serve_fused_lanes")
        self._obs_staged_lanes = reg.counter(
            "anomod_serve_staged_lanes_total")
        self._obs_live_lanes = reg.counter(
            "anomod_serve_live_lanes_total")
        self._obs_lane_waste = reg.gauge(
            "anomod_serve_lane_pad_waste_fraction")
        # tick-wall decomposition mirrors: seconds counters per phase so
        # any scrape can attribute the serve wall (stage vs dispatch vs
        # fold) instead of guessing, + the native-staging counters
        self._obs_stage_s = reg.counter("anomod_serve_stage_seconds_total")
        self._obs_dispatch_s = reg.counter(
            "anomod_serve_dispatch_seconds_total")
        self._obs_fold_s = reg.counter("anomod_serve_fold_seconds_total")
        self._obs_score_s = reg.counter("anomod_serve_score_seconds_total")
        self._obs_native = reg.counter("anomod_serve_native_staged_total")
        reg.gauge("anomod_serve_native_staging").set(
            1.0 if self.native_stage else 0.0)

    @property
    def widths(self) -> Tuple[int, ...]:
        """Every chunk width this runner may dispatch."""
        per_bucket = tuple(b for b in self.buckets
                           if b <= self.cfg.chunk_size)
        return tuple(sorted(set(per_bucket) | {self.cfg.chunk_size}))

    @property
    def lane_shapes(self) -> set:
        """Every (width, lane-bucket) fused shape compiled so far."""
        return set(self._lane_compile_s)

    def zero_state(self) -> ReplayState:
        # host-side zeros: the fused scatter-back keeps tenant states as
        # host arrays (jit transfers them per dispatch either way on the
        # shapes involved, and host residency makes the per-lane
        # delta-add allocation-cheap)
        cfg = self.cfg
        return ReplayState(
            agg=np.zeros((cfg.sw, N_FEATS), np.float32),
            hist=np.zeros((cfg.sw, cfg.n_hist_buckets), np.float32))

    def warm(self) -> float:
        """Compile every bucket width on an all-dead chunk (numerically a
        no-op on any state) so serving never pays a compile wall mid-
        stream.  Returns the total compile wall; idempotent."""
        total = 0.0
        state = self.zero_state()
        for width in self.widths:
            if width in self.compile_s_by_width:
                continue
            t0 = time.perf_counter()
            state = self._step(state, dead_chunk(self.cfg, width))
            np.asarray(state.agg)               # compile + execute barrier
            self.compile_s_by_width[width] = time.perf_counter() - t0
            total += self.compile_s_by_width[width]
            self._reg.counter("anomod_serve_compile_total").inc()
            self._reg.counter("anomod_serve_compile_seconds_total").inc(
                self.compile_s_by_width[width])
        return total

    def warm_lanes(self) -> float:
        """Compile the full (width x lane-bucket) fused-dispatch grid on
        all-dead lane stacks, so a fused serve never pays a compile wall
        mid-stream.  Returns the total compile wall; idempotent.  The
        serve pre-bench gate drives this and fails on any shape miss."""
        total = 0.0
        for width in self.widths:
            dead = self._dead_cols_for(width)
            for lanes in self.lane_buckets:
                key = (width, lanes)
                if key in self._lane_compile_s:
                    continue
                stacked = {k: np.broadcast_to(
                    v, (lanes, width)) for k, v in dead.items()}
                exe = self._lane_exec_for(key, stacked)
                dagg, _ = exe(stacked)
                np.asarray(dagg)                # execute barrier
                total += self._lane_compile_s[key]
        if self.pool is not None:
            # device-state mode: the pool's scatter/gather/roll shapes
            # compile here too, so the first serving tick never pays a
            # pool-op compile inside the measured wall
            total += self.pool.warm(self.lane_buckets)
        return total

    def _lane_exec_for(self, key: Tuple[int, int], args: dict):
        """The AOT lane executable for one (width, lane-bucket) shape,
        lowered+compiled on first need (``args`` supplies the concrete
        shapes) — exactly one compile per shape per runner, recorded in
        ``_lane_compile_s`` / the registry compile counters like every
        other compile in this file."""
        exe = self._lane_exec.get(key)
        if exe is None:
            t0 = time.perf_counter()
            exe = self._lane_fn.lower(args).compile()
            self._lane_exec[key] = exe
            self._record_lane_compile(key, time.perf_counter() - t0)
        return exe

    def _record_lane_compile(self, key: Tuple[int, int],
                             wall_s: float) -> None:
        self._lane_compile_s[key] = wall_s
        self._reg.counter("anomod_serve_fused_compile_total").inc()
        self._reg.counter(
            "anomod_serve_fused_compile_seconds_total").inc(wall_s)

    @property
    def compile_s(self) -> float:
        return float(sum(self.compile_s_by_width.values()))

    @property
    def lane_compile_s(self) -> float:
        return float(sum(self._lane_compile_s.values()))

    def _dead_cols_for(self, width: int) -> dict:
        got = self._dead_cols.get(width)
        if got is None:
            got = dead_chunk(self.cfg, width, xp=np)
            self._dead_cols[width] = got
        return got

    # -- staging (shared by the sequential and fused paths) ---------------

    def stage_plan(self, batch: SpanBatch,
                   t0_us: int) -> List[Tuple[int, dict]]:
        """Host-side staging of one micro-batch into its bucket plan:
        the ordered ``(width, columns)`` chunks a push dispatches, with
        UNPADDED columns (each entry holds its slice's live rows; the
        pad to ``width`` happens at scratch-fill time with the
        dead-chunk fill values — same bits, no per-batch allocation).

        ``t0_us`` is the caller's (rolled) window anchor — binning is the
        caller's contract, exactly as in StreamReplay.push.  This is the
        ONE staging definition: the sequential path dispatches the
        returned chunks one by one, the fused path stacks the identical
        chunks across tenants — so the two paths cannot stage apart.
        Logical-dispatch and pad-waste accounting live here for the same
        reason (``dispatches_by_width`` counts staged chunks, identical
        under either execution strategy).
        """
        cfg = self.cfg
        t0 = time.perf_counter()
        mat, raw = stage_columns_fused(batch, cfg, t0_us)
        # the staged matrix's pointer, extracted ONCE per batch: every
        # chunk below carries its slice as ptr/stride/m ints, so the
        # native packer marshals a lane without touching ndarray
        # internals on the per-dispatch path (anomod.io.native.StagedChunk)
        mat_ptr = mat.ctypes.data
        stride = mat.shape[1]
        out: List[Tuple[int, dict]] = []
        staged_rows = 0
        for lo, hi, width in split_plan(batch.n_spans, cfg.chunk_size,
                                        self.buckets):
            cols = native_io.StagedChunk(
                (k, v[lo:hi]) for k, v in raw.items())
            cols.mat = mat
            cols.ptr = mat_ptr + 4 * lo
            cols.stride = stride
            cols.m = hi - lo
            out.append((width, cols))
            self.n_dispatches += 1
            self.dispatches_by_width[width] = \
                self.dispatches_by_width.get(width, 0) + 1
            staged_rows += width
        dt = time.perf_counter() - t0
        self.stage_wall_s += dt
        self._obs_stage_s.inc(dt)
        if out:
            self._obs_dispatches.inc(len(out))
            self._obs_staged.inc(staged_rows)
            self._obs_live.inc(batch.n_spans)
            staged = self._obs_staged.value
            if staged:
                self._obs_waste.set(1.0 - self._obs_live.value / staged)
        return out

    def _pad_fill(self, key: str):
        """The per-column dead-row fill value (= the dead_chunk fill)."""
        return self.cfg.sw if key == "sid" else 0

    def dispatch(self, state: ReplayState, cols: dict,
                 width: int) -> ReplayState:
        """Fold ONE staged chunk into ``state`` (single-lane path),
        padding the live rows to ``width`` exactly as ``stage_columns``
        would.

        The pad buffers are FRESH per call, never reused: jax's CPU
        backend may zero-copy an aligned host array into the dispatch
        under an immutability promise, and this path hands the state
        back WITHOUT materializing it — mutating a shared scratch here
        while the async step still reads it corrupts the fold (the fused
        ``run_lanes`` path is the one that may reuse pinned scratch,
        because it materializes its outputs — completing the dispatch's
        reads — before every refill).
        """
        n = cols["sid"].shape[0]
        if n != width:
            t0 = time.perf_counter()
            padded = {}
            for k, c in cols.items():
                buf = np.empty(width, c.dtype)
                buf[:n] = c
                buf[n:] = self._pad_fill(k)
                padded[k] = buf
            dt = time.perf_counter() - t0
            self.stage_wall_s += dt
            self._obs_stage_s.inc(dt)
            cols = padded
        elif type(cols) is not dict:
            # StagedChunk is a dict subclass jax's pytree registry won't
            # flatten — hand the jitted step a plain dict view
            cols = dict(cols)
        t0 = time.perf_counter()
        out = self._step(state, cols)
        dt = time.perf_counter() - t0
        self.dispatch_wall_s += dt
        self._obs_dispatch_s.inc(dt)
        return out

    # -- the fused (lane-stacked) path ------------------------------------

    def lane_plan(self, n: int) -> List[Tuple[int, int]]:
        """``(n_live, lane_bucket)`` dispatch groups covering ``n``
        lanes: the largest bucket repeatedly, then the smallest bucket
        covering the remainder (dead-padded)."""
        out: List[Tuple[int, int]] = []
        big = self.lane_buckets[-1]
        while n > big:
            out.append((big, big))
            n -= big
        if n > 0:
            out.append((n, next(b for b in self.lane_buckets if b >= n)))
        return out

    def _fill_slot(self, width: int, lanes: int,
                   group_cols: List[dict]) -> Tuple[dict, Tuple[int, int,
                                                                int]]:
        """Stage ``group_cols`` (one unpadded chunk per live lane) into
        the next free pinned scratch slot for the (width, lanes) shape,
        dead-padding the row tails and any dead lanes.  Cycles through
        ``self.pipeline`` slots per shape; before reusing a slot, any
        in-flight dispatch still reading it is retired (materialized) —
        the PR-4 aliasing hazard (mutating host arrays under an async
        dispatch) is structurally impossible here.

        With ``native_stage`` the packing runs through the C++
        ``stage_lanes`` entry (anomod.io.native): byte-identical to the
        interpreter fill below (pinned in tests/test_native.py /
        test_serve.py), but GIL-FREE — staging slot k+1 makes progress
        under the in-flight dispatch on slot k, and shard workers stage
        concurrently.  Slots are 64-byte-aligned (aligned_empty) so
        XLA:CPU's zero-copy host aliasing applies to the very buffers
        the packer writes — the scratch ring is end-to-end zero-copy."""
        shape = (width, lanes)
        slot = self._slot_next.get(shape, 0)
        self._slot_next[shape] = (slot + 1) % self.pipeline
        key = (width, lanes, slot)
        while any(e[3] == key for e in self._inflight):
            self._retire_one()
        t0 = time.perf_counter()
        scratch = self._lane_scratch.get(key)
        if scratch is None:
            scratch = {k: native_io.aligned_empty((lanes, width), v.dtype)
                       for k, v in self._dead_cols_for(width).items()}
            self._lane_scratch[key] = scratch
            if self.native_stage:
                self._stage_plans[key] = native_io.make_stage_plan(
                    scratch, self._pad_fill, mat_keys=STAGE_KEYS)
        elif self.perf is not None:
            # an existing scratch slot is being REUSED: stamp the
            # slot-refilled event on the dispatch that last held it
            self.perf.note_refill(key, t0)
        plan = self._stage_plans.get(key)
        if plan is not None and plan.stage(group_cols):
            self.native_staged += 1
            self._obs_native.inc()
        else:
            self._fill_slot_py(scratch, group_cols, width, lanes)
        dt = time.perf_counter() - t0
        self.stage_wall_s += dt
        self._obs_stage_s.inc(dt)
        if self.perf is not None:
            self.perf.note_staged(key, t0, t0 + dt)
        return scratch, key

    def _fill_slot_py(self, scratch: dict, group_cols: List[dict],
                      width: int, lanes: int) -> None:
        """The interpreter fill — the behavioral oracle the native packer
        is pinned byte-identical to, and the fallback when the .so is
        unavailable (or a column breaks its 4-byte contract)."""
        n_live = len(group_cols)
        for k, buf in scratch.items():
            fill = self._pad_fill(k)
            for i, cols in enumerate(group_cols):
                c = cols[k]
                m = c.shape[0]
                buf[i, :m] = c
                if m < width:
                    buf[i, m:] = fill
            if n_live < lanes:
                buf[n_live:] = fill

    def _account_group(self, n_live: int, lanes: int) -> None:
        self.fused_dispatches += 1
        self.lanes_by_bucket[lanes] = \
            self.lanes_by_bucket.get(lanes, 0) + 1
        self.staged_lanes += lanes
        self.live_lanes += n_live
        self._obs_fused.inc()
        self._obs_lanes.observe(n_live)
        self._obs_staged_lanes.inc(lanes)
        self._obs_live_lanes.inc(n_live)
        self._obs_lane_waste.set(1.0 - self.live_lanes / self.staged_lanes)

    def run_lanes(self, width: int,
                  work: List[Tuple[ReplayState, dict]]) -> List[ReplayState]:
        """Fold ``work[i]``'s staged chunk into ``work[i]``'s state via
        lane-bucketed fused dispatches; returns the updated states in
        order (synchronous: each dispatch materializes before the next
        stages — the pipelined twin is :meth:`submit_lanes`).

        Per-lane results are BIT-identical to :meth:`dispatch` per lane:
        each lane reduces its own rows in the same order, dead pad lanes
        contribute nothing and are dropped (their tenants' states pass
        through untouched), and the per-lane delta folds into the state
        with the same elementwise f32 add the in-step update performs.
        Staging rides pinned scratch buffers reused across ticks.
        """
        self.drain_lanes()      # never interleave with pipelined folds
        out: List[ReplayState] = []
        pos = 0
        for n_live, lanes in self.lane_plan(len(work)):
            group = work[pos:pos + n_live]
            pos += n_live
            scratch, key = self._fill_slot(width, lanes,
                                           [cols for _, cols in group])
            exe = self._lane_exec_for((width, lanes), scratch)
            prf = self.perf
            t0 = time.perf_counter()
            dagg, dhist = exe(scratch)
            t1 = time.perf_counter()
            if prf is not None:
                prf.note_submitted(key, t0, t1)
                prf.note_retire(key, t1)
            # materialize before the scratch is reused: the host copy is
            # the execute barrier, and the scatter-back below reads it
            dagg = np.asarray(dagg)
            dhist = np.asarray(dhist)
            if prf is not None:
                t_mat = time.perf_counter()
                prf.note_materialized(key, t_mat)
            for i, (st, _) in enumerate(group):
                out.append(fold_delta(st, dagg[i], dhist[i]))
            t2 = time.perf_counter()
            if prf is not None:
                prf.note_folded(key, t2)
            self.dispatch_wall_s += t1 - t0
            self._obs_dispatch_s.inc(t1 - t0)
            self.fold_wall_s += t2 - t1
            self._obs_fold_s.inc(t2 - t1)
            self._account_group(n_live, lanes)
        return out

    # -- the pipelined (async double-buffered) path -----------------------

    def submit_lanes(self, width: int, work: List[Tuple[object, dict]],
                     ) -> None:
        """Pipelined twin of :meth:`run_lanes`: ``work`` pairs each
        REPLAY PLANE (anything with the ``get_state``/``set_state`` seam)
        with its staged unpadded chunk.  Dispatches are issued
        immediately; readback + state fold are DEFERRED until the
        dispatch retires — at most ``pipeline - 1`` dispatches stay in
        flight, so with depth d the shard stages dispatch t+1 while
        dispatch t's XLA work is still running.  Folds always apply in
        dispatch order through ``set_state`` (bit-identical to the
        synchronous path at any depth); callers MUST :meth:`drain_lanes`
        before reading the planes (the sharded engine drains at tick
        end, before window scoring).
        """
        pos = 0
        for n_live, lanes in self.lane_plan(len(work)):
            group = work[pos:pos + n_live]
            pos += n_live
            scratch, key = self._fill_slot(width, lanes,
                                           [cols for _, cols in group])
            exe = self._lane_exec_for((width, lanes), scratch)
            t0 = time.perf_counter()
            dagg, dhist = exe(scratch)
            dt = time.perf_counter() - t0
            self.dispatch_wall_s += dt
            self._obs_dispatch_s.inc(dt)
            if self.perf is not None:
                self.perf.note_submitted(key, t0, t0 + dt)
            self._inflight.append(
                ([replay for replay, _ in group], dagg, dhist, key))
            self._account_group(n_live, lanes)
            while len(self._inflight) > self.pipeline - 1:
                self._retire_one()

    def _retire_one(self) -> None:
        """Retire the OLDEST in-flight dispatch and fold its per-lane
        deltas into the paired replay planes.

        DEVICE path (every paired replay lives in this runner's state
        pool): the fold is ONE on-device scatter-add
        (``TenantStatePool.scatter_fold``) — no host materialization of
        the [lanes, SW, F+H] deltas, no per-lane numpy adds — pinned
        bit-identical to the host seam because the scatter performs the
        same f32 ``state + delta`` per slot in the same dispatch order.
        The scratch-reuse barrier is ``block_until_ready`` on the delta:
        the lane dispatch's outputs being ready means it can no longer
        read its host scratch slot (no host copy needed).

        HOST path (any replay without a slot on this pool — the
        host-seam mode, or generic callers pairing plain replays): the
        host copy is the execute barrier, then :func:`fold_delta` per
        lane through the get_state/set_state seam — the same
        elementwise f32 add the in-step update performs."""
        replays, dagg, dhist, key = self._inflight.popleft()
        prf = self.perf
        t0 = time.perf_counter()
        if prf is not None:
            prf.note_retire(key, t0)
        pool = self.pool
        if pool is not None and replays and all(
                getattr(r, "_slot", None) is not None
                and getattr(r, "_runner", None) is self
                for r in replays):
            pool.scatter_fold([r._slot for r in replays], dagg, dhist)
            dagg.block_until_ready()           # scratch-reuse barrier
            if prf is not None:
                t_wait = time.perf_counter() - t0
                prf.note_materialized(key, t0 + t_wait)
        else:
            dagg = np.asarray(dagg)
            dhist = np.asarray(dhist)
            if prf is not None:
                t_wait = time.perf_counter() - t0
                prf.note_materialized(key, t0 + t_wait)
            for i, replay in enumerate(replays):
                replay.set_state(fold_delta(replay.get_state(),
                                            dagg[i], dhist[i]))
        dt = time.perf_counter() - t0
        self.fold_wall_s += dt
        self._obs_fold_s.inc(dt)
        if prf is not None:
            prf.note_folded(key, t0 + dt)

    def drain_lanes(self) -> None:
        """Retire every in-flight dispatch (tick-end barrier)."""
        while self._inflight:
            self._retire_one()

    def mark_deferred(self, t0: float, t1: float) -> None:
        """Stamp every in-flight dispatch's ``deferred`` lifecycle leg
        (anomod.obs.perf): issued at ``t0``, left executing under the
        coordinator's next-tick work until the commit barrier read it
        at ``t1`` — the deferred-commit engine calls this at the
        barrier, before :meth:`drain_lanes`, so `anomod perf diff`
        can attribute the hidden wait to the ``commit_defer`` leg."""
        if self.perf is None:
            return
        for _, _, _, key in self._inflight:
            self.perf.note_deferred(key, t0, t1)

    def abort_lanes(self) -> None:
        """Failed-tick cleanup: discard every in-flight dispatch WITHOUT
        folding.  Outputs are still materialized — the execute barrier;
        a scratch slot must never be refilled under a dispatch that can
        still read it — but the deltas are dropped, so the paired replay
        planes keep their last-folded states instead of silently
        absorbing an aborted tick's work on some later drain."""
        while self._inflight:
            _, dagg, dhist, key = self._inflight.popleft()
            np.asarray(dagg)
            np.asarray(dhist)
            if self.perf is not None:
                # dropped, counted — an aborted dispatch must not
                # complete its timeline as if it folded
                self.perf.note_aborted(key)

    @property
    def inflight_dispatches(self) -> int:
        return len(self._inflight)

    def leg_walls(self) -> dict:
        """Cumulative flight-leg snapshot of this runner's wall/dispatch
        book — what the flight recorder (anomod.obs.flight) deltas per
        tick.  ``by_width`` (staged chunks per width) is the canonical
        dispatch-plane content: ``stage_plan`` is the ONE staging
        definition, so the counts are identical under every execution
        strategy (fused/unfused, any shard count, any pipeline depth).
        The walls and lane-grouping counts are journal-variant (wall
        clock / topology).  Read at the tick barrier only — the dicts
        mutate on this runner's worker thread mid-tick."""
        return {"stage_s": self.stage_wall_s,
                "dispatch_s": self.dispatch_wall_s,
                "fold_s": self.fold_wall_s,
                "score_s": self.score_wall_s,
                "chunks": self.n_dispatches,
                "fused": self.fused_dispatches,
                "native_staged": self.native_staged,
                "by_width": dict(self.dispatches_by_width)}

    def book_snapshot(self) -> dict:
        """The runner's cumulative dispatch-COUNT book — what the shard
        supervisor (anomod.serve.supervise) checkpoints and restores
        around a recovery re-execution, so re-executed slices cannot
        double-count the flight journal's canonical dispatch plane
        (``chunks``/``by_width`` deltas) or the ServeReport counters.
        Walls and compile bookkeeping deliberately stay OUT: recovery
        wall is real work, reported in its own report leg, and compiles
        happened regardless of what the counters say."""
        return {"n_dispatches": self.n_dispatches,
                "dispatches_by_width": dict(self.dispatches_by_width),
                "fused_dispatches": self.fused_dispatches,
                "native_staged": self.native_staged,
                "staged_lanes": self.staged_lanes,
                "live_lanes": self.live_lanes,
                "lanes_by_bucket": dict(self.lanes_by_bucket)}

    def book_restore(self, book: dict) -> None:
        """Install a :meth:`book_snapshot` (checkpoint restore)."""
        self.n_dispatches = book["n_dispatches"]
        self.dispatches_by_width = dict(book["dispatches_by_width"])
        self.fused_dispatches = book["fused_dispatches"]
        self.native_staged = book["native_staged"]
        self.staged_lanes = book["staged_lanes"]
        self.live_lanes = book["live_lanes"]
        self.lanes_by_bucket = dict(book["lanes_by_bucket"])

    @property
    def lane_pad_waste(self) -> float:
        """Dead-lane fraction of every fused dispatch so far (the lane
        twin of the row pad-waste gauge)."""
        return (1.0 - self.live_lanes / self.staged_lanes
                if self.staged_lanes else 0.0)


class BucketedStreamReplay(StreamReplay):
    """StreamReplay whose dispatch rides a shared :class:`BucketRunner`.

    Same ring/anchor bookkeeping as the parent (``_roll`` is inherited —
    ONE definition of the eviction math); only ``push`` and ``_warm``
    differ: chunks stage through the runner's bucket plan and the
    compiled executables are shared across every tenant on the runner.
    ``plan_push`` additionally exposes the staging half alone, for the
    fused engine's lane-stacked dispatch.
    """

    def __init__(self, cfg: ReplayConfig, t0_us: int, runner: BucketRunner):
        if runner.cfg != cfg:
            raise ValueError("runner cfg disagrees with the replay cfg")
        # deliberately NOT super().__init__: the parent builds a
        # per-instance jitted step and zero planes this subclass never
        # uses (the runner owns the ONE jit for the whole fleet), and a
        # live-looking unused self._step would dispatch outside the
        # runner's accounting if anything ever called it
        self.cfg = cfg
        self.t0_us = int(t0_us)
        self.window_offset = 0
        self.n_spans = 0
        self._step = None                 # dispatch goes through the runner
        self.compile_s = 0.0
        self._warmed = False
        self._runner = runner
        self.state = runner.zero_state()

    def _warm(self) -> None:
        self._runner.warm()
        self.compile_s = self._runner.compile_s
        self._warmed = True

    def plan_push(self, batch: SpanBatch):
        """The staging half of :meth:`push`: roll the ring, account the
        spans, stage the bucket plan — WITHOUT dispatching.  Returns
        ``(newest absolute window, ordered (width, columns) chunks)``;
        applying the chunks to ``state`` in order (``runner.dispatch``,
        or lanes of them stacked across tenants via ``runner.run_lanes``)
        reproduces ``push()`` bit-exactly.  This is the fused engine's
        gather seam."""
        if batch.n_spans == 0:
            return -1, []
        if not self._warmed:
            self._warm()
        w_need = int((int(batch.start_us.max()) - self.t0_us)
                     // self.cfg.window_us)
        if w_need > self.cfg.n_windows - 1:
            self._roll(w_need - (self.cfg.n_windows - 1))
            w_need = self.cfg.n_windows - 1
        plan = self._runner.stage_plan(batch, self.t0_us)
        self.n_spans += batch.n_spans
        return self.window_offset + max(w_need, 0), plan

    def push(self, batch: SpanBatch) -> int:
        w_ret, plan = self.plan_push(batch)
        for width, cols in plan:
            self.state = self._runner.dispatch(self.state, cols, width)
        return w_ret


class PooledStreamReplay(BucketedStreamReplay):
    """BucketedStreamReplay whose state lives in the runner's
    DEVICE-RESIDENT tenant pool (``ANOMOD_SERVE_STATE=device``/``auto``).

    The tenant maps to a pool slot at construction (= first service).
    ``state`` stays the official surface — reads GATHER the slot to host,
    writes SCATTER it back, so every ``get_state``/``set_state`` consumer
    (parity tests, checkpoints, the host-seam fold fallback, future
    migration) behaves exactly as before and round-trips byte-identically
    — but the hot paths never touch it: the lane fold is the runner's
    on-device scatter-add (:meth:`BucketRunner._retire_one`), the ring
    roll runs on the pool row (bit-identical to the host roll), and the
    batched serve scorer gathers only the scored window columns."""

    def __init__(self, cfg: ReplayConfig, t0_us: int, runner: BucketRunner):
        if runner.pool is None:
            raise ValueError(
                "runner keeps host-seam states (ANOMOD_SERVE_STATE=host); "
                "use BucketedStreamReplay or a device-state runner")
        self._slot = runner.pool.acquire()
        try:
            super().__init__(cfg, t0_us, runner)
        except BaseException:
            # a failed construction must hand its slot back, or every
            # retried admission leaks a pool row
            runner.pool.release(self._slot)
            self._slot = None
            raise

    def _live_slot(self) -> int:
        # a released replay must fail loud: pool.put(None, ...) would
        # broadcast over EVERY slot (None is np.newaxis on the numpy
        # engine) — silent fleet-wide state corruption
        if self._slot is None:
            raise ValueError("pool slot was released (tenant churn); "
                             "this PooledStreamReplay is dead")
        return self._slot

    @property
    def state(self) -> ReplayState:
        return self._runner.pool.gather(self._live_slot())

    @state.setter
    def state(self, st: ReplayState) -> None:
        self._runner.pool.put(self._live_slot(), st)

    def _roll(self, k: int) -> None:
        self._runner.pool.roll(self._live_slot(), k)
        self.t0_us += k * self.cfg.window_us
        self.window_offset += k

    def release(self) -> None:
        """Return the slot to the pool, zeroed (tenant churn; the
        migration seam's teardown half).  Idempotent is NOT the
        contract — a double release would re-free a slot another
        tenant may already own."""
        self._runner.pool.release(self._live_slot())
        self._slot = None
