"""Dynamic micro-batching into fixed padded bucket shapes.

The serving plane's hot path is the SAME jitted chunk step the batch
replay scans with (anomod.replay.make_chunk_step) — but tenant
micro-batches are small and ragged, and staging every 150-span batch
into a 32768-wide chunk wastes 99% of each dispatch.  The batcher pads
each admitted micro-batch to the smallest shape from a FIXED bucket set
(``ANOMOD_SERVE_BUCKETS``), so XLA compiles the step once per bucket
width and every later dispatch of that width reuses the executable.

Replay parity is exact by construction: a batch is split at
``cfg.chunk_size`` boundaries (full chunks stage exactly as the
sequential StreamReplay would) and only the TAIL remainder is padded to
a bucket.  Padding rows target the dead lane (sid = cfg.sw, valid = 0),
whose one-hot contribution to every live segment is exactly 0.0 — and
the real rows occupy the same leading positions they would in the
sequential staging — so the f32 state after a bucketed push is
BIT-IDENTICAL to the sequential fixed-chunk push on CPU
(tests/test_serve.py pins this, alert stream included).

:class:`BucketedStreamReplay` duck-types :class:`anomod.stream.StreamReplay`
(it subclasses it and overrides only the dispatch), so
``OnlineDetector(..., replay=...)`` runs the full alerting stack over the
shared bucket runner unchanged — thousands of tenants share ONE compiled
step per bucket instead of compiling per tenant.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from anomod import obs
from anomod.config import DEFAULT_SERVE_BUCKETS as DEFAULT_BUCKETS
from anomod.config import validate_serve_buckets as validate_buckets
from anomod.replay import (N_FEATS, ReplayConfig, ReplayState,
                           make_chunk_step, stage_columns)
from anomod.schemas import SpanBatch, take_spans
from anomod.stream import StreamReplay


def split_plan(n_spans: int, chunk_size: int,
               buckets: Tuple[int, ...]) -> List[Tuple[int, int, int]]:
    """(lo, hi, staged_width) slices for one micro-batch.

    Full ``chunk_size`` slices first (identical to sequential staging),
    then the tail remainder padded to the smallest bucket that holds it
    (``chunk_size`` itself when every bucket is narrower).  This is the
    ONE definition of the parity-preserving split, shared by the runner
    and its tests.
    """
    plan: List[Tuple[int, int, int]] = []
    lo = 0
    while n_spans - lo >= chunk_size:
        plan.append((lo, lo + chunk_size, chunk_size))
        lo += chunk_size
    rem = n_spans - lo
    if rem > 0:
        width = next((b for b in buckets if b >= rem and b <= chunk_size),
                     chunk_size)
        plan.append((lo, n_spans, width))
    return plan


class BucketRunner:
    """The shared compile-once-per-bucket chunk-step dispatcher.

    One ``jax.jit`` of the shared chunk step serves every tenant; XLA
    compiles one executable per distinct chunk width (= per bucket, plus
    the full ``cfg.chunk_size``), tracked in ``compile_s_by_width`` /
    ``dispatches_by_width`` for the ServeReport.
    """

    def __init__(self, cfg: ReplayConfig,
                 buckets: Optional[Tuple[int, ...]] = None):
        import jax
        if buckets is None:
            from anomod.config import get_config
            buckets = get_config().serve_buckets
        self.cfg = cfg
        self.buckets = validate_buckets(buckets)
        step = make_chunk_step(cfg, with_hll=False)
        self._step = jax.jit(lambda st, ch: step(st, ch)[0])
        self.compile_s_by_width: Dict[int, float] = {}
        self.dispatches_by_width: Dict[int, int] = {}
        self.n_dispatches = 0
        # registry mirrors (anomod.obs): staged-vs-live row counters make
        # the bucket-pad waste fraction derivable from any scrape
        # (waste = 1 - live/staged); handles cached — push_into is the
        # serving hot path
        self._obs_dispatches = obs.counter("anomod_serve_dispatches_total")
        self._obs_staged = obs.counter("anomod_serve_staged_rows_total")
        self._obs_live = obs.counter("anomod_serve_live_rows_total")
        self._obs_waste = obs.gauge("anomod_serve_pad_waste_fraction")

    @property
    def widths(self) -> Tuple[int, ...]:
        """Every chunk width this runner may dispatch."""
        per_bucket = tuple(b for b in self.buckets
                           if b <= self.cfg.chunk_size)
        return tuple(sorted(set(per_bucket) | {self.cfg.chunk_size}))

    def zero_state(self) -> ReplayState:
        import jax.numpy as jnp
        cfg = self.cfg
        return ReplayState(
            agg=jnp.zeros((cfg.sw, N_FEATS), jnp.float32),
            hist=jnp.zeros((cfg.sw, cfg.n_hist_buckets), jnp.float32))

    def warm(self) -> float:
        """Compile every bucket width on an all-dead chunk (numerically a
        no-op on any state) so serving never pays a compile wall mid-
        stream.  Returns the total compile wall; idempotent."""
        from anomod.replay import dead_chunk
        total = 0.0
        state = self.zero_state()
        for width in self.widths:
            if width in self.compile_s_by_width:
                continue
            t0 = time.perf_counter()
            state = self._step(state, dead_chunk(self.cfg, width))
            np.asarray(state.agg)               # compile + execute barrier
            self.compile_s_by_width[width] = time.perf_counter() - t0
            total += self.compile_s_by_width[width]
            obs.counter("anomod_serve_compile_total").inc()
            obs.counter("anomod_serve_compile_seconds_total").inc(
                self.compile_s_by_width[width])
        return total

    @property
    def compile_s(self) -> float:
        return float(sum(self.compile_s_by_width.values()))

    def push_into(self, state: ReplayState, batch: SpanBatch,
                  t0_us: int) -> ReplayState:
        """Fold one micro-batch into ``state`` via the bucketed split.

        ``t0_us`` is the caller's (rolled) window anchor — binning is the
        caller's contract, exactly as in StreamReplay.push.
        """
        cfg = self.cfg
        for lo, hi, width in split_plan(batch.n_spans, cfg.chunk_size,
                                        self.buckets):
            sub = take_spans(batch, slice(lo, hi)) \
                if (lo, hi) != (0, batch.n_spans) else batch
            staged_cfg = dataclasses.replace(cfg, chunk_size=width)
            chunks, _ = stage_columns(sub, staged_cfg, t0_us=t0_us)
            n_chunks = chunks["sid"].shape[0]
            for i in range(n_chunks):
                state = self._step(state,
                                   {k: v[i] for k, v in chunks.items()})
                self.n_dispatches += 1
                self.dispatches_by_width[width] = \
                    self.dispatches_by_width.get(width, 0) + 1
            self._obs_dispatches.inc(n_chunks)
            self._obs_staged.inc(n_chunks * width)
            self._obs_live.inc(hi - lo)
        staged = self._obs_staged.value
        if staged:
            self._obs_waste.set(1.0 - self._obs_live.value / staged)
        return state


class BucketedStreamReplay(StreamReplay):
    """StreamReplay whose dispatch rides a shared :class:`BucketRunner`.

    Same ring/anchor bookkeeping as the parent (``_roll`` is inherited —
    ONE definition of the eviction math); only ``push`` and ``_warm``
    differ: chunks stage through the runner's bucket plan and the
    compiled executables are shared across every tenant on the runner.
    """

    def __init__(self, cfg: ReplayConfig, t0_us: int, runner: BucketRunner):
        if runner.cfg != cfg:
            raise ValueError("runner cfg disagrees with the replay cfg")
        # deliberately NOT super().__init__: the parent builds a
        # per-instance jitted step and zero planes this subclass never
        # uses (the runner owns the ONE jit for the whole fleet), and a
        # live-looking unused self._step would dispatch outside the
        # runner's accounting if anything ever called it
        self.cfg = cfg
        self.t0_us = int(t0_us)
        self.window_offset = 0
        self.n_spans = 0
        self._step = None                 # dispatch goes through the runner
        self.compile_s = 0.0
        self._warmed = False
        self._runner = runner
        self.state = runner.zero_state()

    def _warm(self) -> None:
        self._runner.warm()
        self.compile_s = self._runner.compile_s
        self._warmed = True

    def push(self, batch: SpanBatch) -> int:
        if batch.n_spans == 0:
            return -1
        if not self._warmed:
            self._warm()
        w_need = int((int(batch.start_us.max()) - self.t0_us)
                     // self.cfg.window_us)
        if w_need > self.cfg.n_windows - 1:
            self._roll(w_need - (self.cfg.n_windows - 1))
            w_need = self.cfg.n_windows - 1
        self.state = self._runner.push_into(self.state, batch, self.t0_us)
        self.n_spans += batch.n_spans
        return self.window_offset + max(w_need, 0)
