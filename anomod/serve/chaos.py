"""ServeChaos: seeded, scripted fault injection aimed at the serving
plane ITSELF.

The paper's whole methodology is injecting faults into a running system
and checking the collection/detection stack survives them (SURVEY §5 —
ChaosBlade/Chaos Mesh campaigns, modeled for the SUT in
``anomod.chaos``).  This module turns the same discipline on the
FRAMEWORK: a validated fault script (``ANOMOD_SERVE_CHAOS``, off by
default) injects the serve plane's own fault taxonomy — shard-worker
crashes mid-tick, staging/dispatch exceptions, slow-shard stalls,
state-pool fold failures — at deterministic (tick, shard, phase)
points in the score path, so the supervised engine's
checkpoint/restore recovery (``anomod.serve.supervise``) is testable,
benchable and CI-gated instead of trusted.

Determinism contract: faults key on the ORIGIN tick of the slice being
scored (the tick its batches were drained on), not the wall clock — so
a recovery RE-execution of an older slice never re-trips a fault
scripted for a newer tick, and a fault's ``repeat`` budget counts
attempts at its own tick's slice.  With ``repeat=1`` (the default) the
first recovery retry runs clean; ``repeat=-1`` fails every attempt —
the quarantine/migration probe.

The script grammar and validation live in :func:`anomod.config.
validate_chaos_script` (the knob must validate without importing the
serve chain); this module owns the runtime behavior.
"""

from __future__ import annotations

import threading
import time
from typing import List

from anomod import obs
from anomod.config import (CHAOS_KINDS, CHAOS_PHASES,
                           validate_chaos_script)

__all__ = ["CHAOS_KINDS", "CHAOS_PHASES", "ChaosFault",
           "ChaosWorkerCrash", "ServeChaos"]


class ChaosFault(RuntimeError):
    """An injected serve-plane fault (a plain score-path exception: the
    shard worker survives, the tick fails at the barrier)."""
    #: duck-typed by ShardWorker._loop: a True value makes the worker
    #: THREAD exit after reporting the error — the crash taxonomy —
    #: without shard.py importing this module
    kills_worker = False


class ChaosWorkerCrash(ChaosFault):
    """An injected shard-worker crash: the error propagates at the
    barrier AND the worker thread dies (respawn is the supervisor's
    job)."""
    kills_worker = True


class _Fault:
    __slots__ = ("kind", "tick", "shard", "phase", "ms", "repeat",
                 "factor", "ticks", "fired")

    def __init__(self, spec: dict):
        self.kind = spec["kind"]
        self.tick = spec["tick"]
        self.shard = spec["shard"]
        self.phase = spec["phase"]
        self.ms = spec["ms"]
        self.repeat = spec["repeat"]
        self.factor = spec["factor"]
        self.ticks = spec["ticks"]
        self.fired = 0


class ServeChaos:
    """The scripted injector the engine consults at every score-path
    phase boundary (``hit``).  Thread-safe: shard workers hit
    concurrently; the fired-count bookkeeping is locked so a fault's
    ``repeat`` budget is exact under any interleaving."""

    def __init__(self, script: str):
        self.script = str(script).strip()
        self.faults: List[_Fault] = [
            _Fault(spec) for spec in validate_chaos_script(self.script)]
        self._lock = threading.Lock()
        self.n_injected = 0
        self.n_stalls = 0
        self._obs_injected = obs.counter(
            "anomod_serve_chaos_injected_total")
        self._obs_stalls = obs.counter("anomod_serve_chaos_stalls_total")

    def surge_factor(self, tick: int) -> int:
        """The fleet-wide arrival multiplier at virtual ``tick`` — the
        product of every active ``surge`` fault's factor (surges are
        deterministic functions of the tick index alone, so a replay of
        the same script amplifies the same arrivals).  The first tick
        of each surge counts as one injection (the never-a-silent-
        fault contract: a surge that shows up nowhere reads as 'the
        policy scaled for no reason')."""
        factor = 1
        for f in self.faults:
            if f.kind != "surge" or not f.tick <= tick < f.tick + f.ticks:
                continue
            factor *= f.factor
            if tick == f.tick:
                with self._lock:
                    if f.fired == 0:
                        f.fired = 1
                        self.n_injected += 1
                        self._obs_injected.inc()
        return factor

    def hit(self, phase: str, tick: int, shard: int) -> None:
        """One score-path phase boundary on one shard's slice of one
        ORIGIN tick.  Raises (or stalls) per the script; a no-op when
        nothing matches — the engine calls this unconditionally on the
        hot path only when a script is configured."""
        for f in self.faults:
            if f.kind == "surge" or f.tick != tick or f.shard != shard \
                    or f.phase != phase:
                continue
            with self._lock:
                if 0 <= f.repeat <= f.fired:
                    continue
                f.fired += 1
                self.n_injected += 1
                self._obs_injected.inc()
                if f.kind == "stall":
                    self.n_stalls += 1
                    self._obs_stalls.inc()
            where = (f"@tick {tick} shard {shard} phase {phase} "
                     f"(attempt {f.fired})")
            if f.kind == "stall":
                # anomod-lint: disable=D101 — the stall FAULT is a scripted wall delay by definition; it perturbs walls (variant tier), never decisions
                time.sleep(f.ms / 1000.0)
            elif f.kind == "crash":
                raise ChaosWorkerCrash(f"chaos: shard-worker crash "
                                       f"{where}")
            elif f.kind == "poolput":
                raise ChaosFault(f"chaos: state-pool put failure {where}")
            else:
                raise ChaosFault(f"chaos: injected exception {where}")
