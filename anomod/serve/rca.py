"""Online root-cause inference inside the serve tick: alert → culprit.

The offline harness (anomod.rca) trains and evaluates RCA models
post-hoc; the serving plane (anomod.serve.engine) stopped at per-tenant
alerts.  This module is the bridge the paper's product implies: when a
tenant's ``OnlineDetector`` fires during a tick, run incremental GNN
culprit inference over that tenant's LIVE service graph — within the
serve SLO — and emit a ranked culprit list (:class:`RCAVerdict`).

Shape discipline is the serving plane's (the batcher's): inference runs
in a FIXED grid of padded ``(nodes, neighbors)`` bucket shapes
(``ANOMOD_SERVE_RCA_BUCKETS``), AOT-compiled once per bucket through the
same ``lower().compile()`` seam as the fused lane grid — so a sustained
run pays exactly one XLA compile per bucket (pinned via the registry
compile counters), never a mid-tick compile wall.  Neighbor lists use
SAMPLED aggregation (the VersaGNN / GNN-sampling-accelerator playbook,
PAPERS.md arXiv 2105.01280, 2209.02916): each node keeps at most K
seeded-uniformly-sampled callees, padded to the bucket's K — sample +
aggregate stays cheap and shape-stable at any live-graph degree.

Determinism contract (tests/test_serve_rca.py):

- the neighbor sampler is seeded by ``(RCA_SEED, tenant_id,
  alert_window)`` alone, and a verdict's evidence window is anchored to
  its TRIGGERING alert window (not the tick it ran in), so reruns of the
  same seed, N-shard vs 1-shard runs, and budget-delayed runs all
  produce byte-identical culprit rankings;
- RCA is a pure READ-side consumer of the alert stream and its own span
  buffers: detector states, alerts, admission, SLO and shed decisions
  are byte-identical with RCA on or off.

Node features come from the shared offline/online feature module
(anomod.rca_features — ONE definition with the training harness, parity
pinned in tests/test_rca_features.py) plus two alert-evidence channels;
the scorer itself is training-free blame propagation: per-node evidence
``e = x @ W`` (fixed documented weights), then ``ROUNDS`` rounds of
``h = e − β · mean(sampled callee h)`` — a caller whose degradation is
explained by a hot callee hands its blame downstream, so ranking
concentrates on the deepest anomalous node (the classic dependency-walk
RCA heuristic, here as a fixed-shape GNN message pass).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from anomod import obs
from anomod.config import validate_rca_buckets
from anomod.graph import build_service_graph
from anomod.rca_features import windowed_features
from anomod.replay import ReplayConfig
from anomod.schemas import SpanBatch, concat_span_batches, take_spans

#: feature width of the culprit scorer's node inputs: 4 per-window means
#: + 4 recent-vs-early trend deltas (anomod.rca_features) + 2 alert
#: evidence channels (max alert ranking score, max raw z)
N_RCA_FEATS = 10

#: the sampler seed root — a constant, so verdicts depend only on
#: (tenant stream, alert window), never on shard count or run order
RCA_SEED = 0x52CA

#: fixed evidence weights over the N_RCA_FEATS columns
#: [cnt_mean, err_mean, lat_mean, 5xx_mean,
#:  cnt_trend, err_trend, lat_trend, 5xx_trend, alert_score, alert_zmax]
#: — means carry no blame (a busy healthy service must not outrank a
#: quiet broken one); trends carry it (error/5xx jumps loudest, latency
#: next, a count DROP — negative trend — via the negative weight); the
#: detector's own alert evidence dominates (it already encodes the
#: calibrated per-service baselines the raw trends lack)
EVIDENCE_WEIGHTS = np.array(
    [0.0, 0.0, 0.0, 0.0, -0.5, 2.0, 1.0, 2.0, 1.0, 0.25], np.float32)

#: blame handed from a caller to its sampled callees per round
BLAME_SHIFT = 0.5
#: message-pass rounds (2 ≈ the call-depth of the testbed graphs)
RCA_ROUNDS = 2


@dataclasses.dataclass(frozen=True)
class RCAVerdict:
    """One alert→culprit inference result (JSON-able, byte-comparable:
    no wall-clock fields — run wall rides the engine's RCA SLO digest)."""
    tenant_id: int
    alert_window: int          # absolute window of the triggering alert
    alert_close_s: float       # virtual close time of that window
    enqueued_s: float          # virtual tick the alert entered the queue
    scored_s: float            # virtual tick the verdict was produced
    services: Tuple[str, ...]  # ranked culprits, best first (top-k)
    scores: Tuple[float, ...]  # their scores, same order
    n_spans: int               # evidence spans in the feature window
    n_edges: int               # live service-graph edges
    bucket: Tuple[int, int]    # (nodes, neighbors) shape it ran in

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["services"] = list(self.services)
        d["scores"] = list(self.scores)
        d["bucket"] = list(self.bucket)
        return d


def make_culprit_scorer():
    """The jittable fixed-shape scorer: evidence + sampled-neighbor
    blame propagation.  Inputs are one bucket's padded arrays
    (``x [N, F]``, ``neigh [N, K]`` int32, ``nmask [N, K]`` f32,
    ``node_mask [N]`` f32); dead pad rows score ``-inf`` so they can
    never enter a ranking."""
    import jax.numpy as jnp
    w = jnp.asarray(EVIDENCE_WEIGHTS)

    def score(x, neigh, nmask, node_mask):
        e = (x @ w) * node_mask
        h = e
        for _ in range(RCA_ROUNDS):
            msgs = h[neigh] * nmask                       # [N, K]
            agg = msgs.sum(-1) / jnp.maximum(nmask.sum(-1), 1.0)
            # only POSITIVE callee evidence de-blames the caller: a
            # healthy callee is no excuse, and a negative aggregate
            # must never amplify the caller's score
            h = e - BLAME_SHIFT * jnp.maximum(agg, 0.0)
        return jnp.where(node_mask > 0, h, -jnp.inf)

    return score


def sample_neighbors(g, k: int,
                     rng: np.random.Generator) -> Tuple[np.ndarray,
                                                        np.ndarray]:
    """``([S, k] callee ids, [S, k] f32 mask)`` — each node's observed
    callees sampled WITHOUT replacement down to ``k`` (seeded; kept in
    CSR order so a node at/below the cap is exact, not resampled).  The
    fixed-width sample is what keeps the aggregate shape-stable at any
    live-graph degree (the VersaGNN bucket discipline)."""
    S = g.n_services
    neigh = np.zeros((S, k), np.int32)
    mask = np.zeros((S, k), np.float32)
    for i in range(S):
        cal = g.neighbors[i][g.neighbor_mask[i]]
        if cal.shape[0] > k:
            sel = np.sort(rng.choice(cal.shape[0], size=k, replace=False))
            cal = cal[sel]
        m = cal.shape[0]
        neigh[i, :m] = cal
        mask[i, :m] = 1.0
    return neigh, mask


def online_node_features(batch: Optional[SpanBatch], services,
                         cfg: ReplayConfig) -> np.ndarray:
    """[S, 8] online node features: per-window means + recent-vs-early
    trend deltas of the SHARED windowed extractor
    (anomod.rca_features.windowed_features — the offline harness's exact
    feature code, so online and offline RCA can never drift)."""
    S = len(services)
    if batch is None or batch.n_spans == 0:
        return np.zeros((S, 8), np.float32)
    wf = windowed_features(batch, tuple(services), cfg)       # [S, W, 4]
    q = max(cfg.n_windows // 4, 1)
    mean = wf.mean(axis=1)
    trend = wf[:, -q:].mean(axis=1) - wf[:, :q].mean(axis=1)
    return np.concatenate([mean, trend], axis=-1).astype(np.float32)


class RcaRunner:
    """The compile-once-per-bucket culprit-scorer dispatcher (the RCA
    twin of :class:`anomod.serve.batcher.BucketRunner`): one jit of the
    scorer, AOT ``lower().compile()``d per (nodes, neighbors) bucket,
    compile wall + counts recorded in the runner AND the registry
    (``anomod_serve_rca_compile_total`` — the exactly-one-compile-per-
    bucket pin reads these)."""

    def __init__(self, buckets: Optional[tuple] = None, registry=None):
        import jax
        from anomod.config import get_config
        if buckets is None:
            buckets = get_config().serve_rca_buckets
        self.buckets = validate_rca_buckets(buckets)
        self._reg = registry if registry is not None else obs.get_registry()
        self._fn = jax.jit(make_culprit_scorer())
        self._exec: Dict[Tuple[int, int], object] = {}
        self.compile_s_by_bucket: Dict[Tuple[int, int], float] = {}
        self.runs_by_bucket: Dict[Tuple[int, int], int] = {}
        self._obs_runs = self._reg.counter("anomod_serve_rca_runs_total")

    def bucket_for(self, n_services: int) -> Tuple[int, int]:
        """The smallest bucket whose node count holds ``n_services``."""
        for n, k in self.buckets:
            if n >= n_services:
                return (n, k)
        raise ValueError(
            f"no RCA bucket holds {n_services} services (grid "
            f"{self.buckets}; raise ANOMOD_SERVE_RCA_BUCKETS)")

    def _dead_args(self, n: int, k: int) -> tuple:
        return (np.zeros((n, N_RCA_FEATS), np.float32),
                np.zeros((n, k), np.int32),
                np.zeros((n, k), np.float32),
                np.zeros(n, np.float32))

    def _exec_for(self, key: Tuple[int, int], args: tuple):
        exe = self._exec.get(key)
        if exe is None:
            t0 = time.perf_counter()
            exe = self._fn.lower(*args).compile()
            self._exec[key] = exe
            wall = time.perf_counter() - t0
            self.compile_s_by_bucket[key] = wall
            self._reg.counter("anomod_serve_rca_compile_total").inc()
            self._reg.counter(
                "anomod_serve_rca_compile_seconds_total").inc(wall)
        return exe

    def warm(self) -> float:
        """Compile the whole bucket grid on dead inputs (outside any
        measured wall); returns the total compile wall; idempotent.  The
        serve pre-bench gate drives this and fails on any shape miss."""
        total = 0.0
        for n, k in self.buckets:
            if (n, k) in self.compile_s_by_bucket:
                continue
            args = self._dead_args(n, k)
            exe = self._exec_for((n, k), args)
            np.asarray(exe(*args))              # compile+execute barrier
            total += self.compile_s_by_bucket[(n, k)]
        return total

    @property
    def compile_s(self) -> float:
        return float(sum(self.compile_s_by_bucket.values()))

    @property
    def bucket_shapes(self) -> set:
        """Every (nodes, neighbors) bucket compiled so far."""
        return set(self.compile_s_by_bucket)

    def score(self, x: np.ndarray, neigh: np.ndarray, nmask: np.ndarray,
              node_mask: np.ndarray) -> np.ndarray:
        """Run one padded bucket through its compiled executable."""
        key = (int(x.shape[0]), int(neigh.shape[1]))
        exe = self._exec_for(key, (x, neigh, nmask, node_mask))
        out = np.asarray(exe(x, neigh, nmask, node_mask))
        self.runs_by_bucket[key] = self.runs_by_bucket.get(key, 0) + 1
        self._obs_runs.inc()
        return out


class OnlineRCA:
    """Per-shard online-RCA plane: bounded span buffers (the live
    service-graph source) + the bucketed culprit scorer.

    The engine buffers each tenant's SERVED spans here (coordinator
    side, so buffer content is shard-count-invariant), and — when that
    tenant's detector fires — calls :meth:`run` on the shard that owns
    the tenant.  A verdict's evidence is anchored to its triggering
    alert window: the feature extractor reads exactly the ``windows``
    windows ENDING at the alert window, so a budget-delayed run scores
    the same evidence a same-tick run would.
    """

    def __init__(self, services: Sequence[str], window_us: int, t0_us: int,
                 runner: RcaRunner, topk: int = 5, windows: int = 8,
                 seed: int = RCA_SEED):
        self.services = tuple(services)
        S = len(self.services)
        self._svc_index = {s: i for i, s in enumerate(self.services)}
        self.cfg = ReplayConfig(n_services=S, n_windows=int(windows),
                                window_us=int(window_us), chunk_size=4096)
        self.runner = runner
        runner.bucket_for(S)        # fail loud at construction, not mid-tick
        self.topk = min(int(topk), S)
        self.windows = int(windows)
        self.window_us = int(window_us)
        self.t0_us = int(t0_us)
        self.seed = int(seed)
        self._buf: Dict[int, List[SpanBatch]] = {}
        self._buf_hi: Dict[int, int] = {}

    def buffer(self, tenant_id: int, batch: SpanBatch,
               keep_window: Optional[int] = None) -> None:
        """Append a served micro-batch to the tenant's evidence buffer,
        pruning batches that fell entirely out of feature reach (one
        extra window of slack: a verdict's window range ends at its
        alert window, which trails the newest buffered span).

        ``keep_window`` floors the pruning at the oldest QUEUED alert
        window for this tenant: a budget-delayed run must still find
        its full ``[keep_window+1-windows, keep_window+1)`` evidence
        range in the buffer, no matter how far the live stream has run
        ahead of the queue (the delayed-run determinism clause)."""
        if batch.n_spans == 0:
            return
        buf = self._buf.setdefault(tenant_id, [])
        buf.append(batch)
        hi = max(self._buf_hi.get(tenant_id, 0), int(batch.start_us.max()))
        self._buf_hi[tenant_id] = hi
        cutoff = hi - (self.windows + 1) * self.window_us
        if keep_window is not None:
            cutoff = min(
                cutoff,
                self.t0_us + (keep_window + 1 - self.windows)
                * self.window_us)
        while buf and int(buf[0].start_us.max()) < cutoff:
            buf.pop(0)

    def move_tenant_evidence(self, other: "OnlineRCA",
                             tenant_id: int) -> None:
        """Hand one tenant's evidence buffer (and its high-water mark)
        to ``other`` — the migration seam for dead-shard recovery
        (anomod.serve.supervise) and elastic scaling
        (anomod.serve.engine), so neither reaches into the private
        buffer dicts.  A tenant with no buffered evidence is a no-op;
        batches move by reference (they are immutable)."""
        buf = self._buf.pop(tenant_id, None)
        hi = self._buf_hi.pop(tenant_id, None)
        if buf is not None:
            other._buf[tenant_id] = buf
        if hi is not None:
            other._buf_hi[tenant_id] = hi

    def _evidence_batch(self, tenant_id: int,
                        alert_window: int) -> Optional[SpanBatch]:
        lo = self.t0_us + (alert_window + 1 - self.windows) * self.window_us
        hi = self.t0_us + (alert_window + 1) * self.window_us
        parts = []
        for b in self._buf.get(tenant_id, ()):
            m = (b.start_us >= lo) & (b.start_us < hi)
            if m.any():
                parts.append(take_spans(b, m))
        if not parts:
            return None
        return parts[0] if len(parts) == 1 else concat_span_batches(parts)

    def run(self, tenant_id: int, alert_window: int, alerts,
            enqueued_s: float,
            scored_s: float) -> Tuple[RCAVerdict, float]:
        """One alert→culprit inference; returns ``(verdict, wall_s)``
        (wall kept out of the verdict so verdicts stay byte-comparable
        across reruns and shard counts)."""
        t0 = time.perf_counter()
        S = len(self.services)
        batch = self._evidence_batch(tenant_id, alert_window)
        feats = online_node_features(batch, self.services, self.cfg)
        ev = np.zeros((S, 2), np.float32)
        lo_w = alert_window - self.windows
        for a in alerts:
            if not (lo_w < a.window <= alert_window):
                continue
            i = self._svc_index.get(a.service_name)
            if i is None:
                continue
            ev[i, 0] = max(ev[i, 0], np.float32(a.score))
            ev[i, 1] = max(ev[i, 1], np.float32(
                max(a.z_latency, a.z_error, a.z_drop, a.z_drop_cum)))
        x = np.concatenate([feats, ev], axis=-1)
        n, k = self.runner.bucket_for(S)
        xp = np.zeros((n, N_RCA_FEATS), np.float32)
        xp[:S] = x
        node_mask = np.zeros(n, np.float32)
        node_mask[:S] = 1.0
        neigh = np.zeros((n, k), np.int32)
        nmask = np.zeros((n, k), np.float32)
        n_edges = 0
        if batch is not None:
            g = build_service_graph(batch, services=self.services)
            n_edges = g.n_edges
            rng = np.random.default_rng(
                (self.seed, tenant_id, alert_window))
            sn, sm = sample_neighbors(g, k, rng)
            neigh[:S] = sn
            nmask[:S] = sm
        scores = self.runner.score(xp, neigh, nmask, node_mask)[:S]
        # stable descending rank, ties to the lower service index
        order = np.lexsort((np.arange(S), -scores))[:self.topk]
        verdict = RCAVerdict(
            tenant_id=int(tenant_id),
            alert_window=int(alert_window),
            alert_close_s=round(
                (self.t0_us + (alert_window + 1) * self.window_us) / 1e6, 6),
            enqueued_s=round(float(enqueued_s), 6),
            scored_s=round(float(scored_s), 6),
            services=tuple(self.services[i] for i in order),
            scores=tuple(round(float(scores[i]), 6) for i in order),
            n_spans=int(batch.n_spans) if batch is not None else 0,
            n_edges=int(n_edges),
            bucket=(n, k))
        return verdict, time.perf_counter() - t0
