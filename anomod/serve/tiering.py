"""Tenant-state tiering: device hot pool → host warm tier →
content-addressed disk cold tier, with stall-free re-admission.

The refactor the census observatory (PR 15) was built to score: the
committed baselines said 384 B of resident bytes and 5.6e-6 s of tick
wall per REGISTERED tenant, with the ``TenantStatePool``, the
admission/SLO registries and the flight totals walk named as the
O(registered) offenders.  This module is the state half of the fix
(the registry half is the lazy/columnar restructure in
``anomod.serve.queues`` + the engine's lazy SLO map): tenants that go
cold leave the device pool entirely, so pool bytes track the HOT set
while the registered fleet scales to millions.

Three tiers, two moves:

- **Demote** (engine tick end, decay-driven): when more than
  ``ANOMOD_SERVE_TIER_HOT`` tenants are pool-resident, the coldest
  residents past ``ANOMOD_SERVE_TIER_DEMOTE_AFTER`` idle ticks — the
  census ``coldest_candidates`` ordering, the eviction preview promoted
  from observed-only to policy — are snapshotted out through the PR-10
  copier seams (:func:`anomod.serve.supervise.snapshot_replay`; the
  pool gather is ALWAYS a copy) and their pool slot released.  The warm
  tier holds the snapshot on host.  Past the
  ``ANOMOD_SERVE_TIER_WARM_BYTES`` budget, the coldest warm entries'
  state ARRAYS spill to a content-addressed ``.npc`` entry under
  ``ANOMOD_SERVE_TIER_COLD_DIR`` (the io/cache payload format and
  atomic tmp-rename publish — publish first, drop the host copy only
  after, so a kill mid-spill leaves the warm entry intact and a reader
  never sees a torn file).  The detector's host bookkeeping (alerts,
  streaks, CUSUM — small, O(alerts)) stays resident in the entry
  either way; the arrays are what the budget meters.

- **Promote** (engine scoring gate, transparent): a demoted tenant's
  next drained batch re-admits it.  Warm promotion is a synchronous
  host memcpy through :func:`restore_replay` — never a miss.  Cold
  promotion is DETERMINISTICALLY deferred exactly one tick: the disk
  fetch is issued on the prefetch lane at offer time (overlapping the
  tick's admission/drain/SLO phases, the PR-16 overlap idiom), the
  tenant's batches park for one tick as a counted, journaled
  ``tier_miss``, and the next tick's gate joins the (by then almost
  always complete) fetch.  The hot loop never blocks on a same-tick
  disk read, and — because the deferral never depends on wall clock —
  every tier decision is a function of seed+config alone:
  ``anomod audit replay`` reproduces demotions, promotions and misses
  byte-for-byte.  The fraction of cold fetches already complete at
  their join is wall telemetry (``prefetch_hidden``), reported but
  never decisive.

Parity is the contract (tests/test_serve_tiering.py): a tiered run's
final states, alerts, SLO and shed are byte-identical to a
never-evicted run — parking preserves per-tenant push order and
scoring is a pure function of (state, slices).  With no cold deferrals
the canonical flight journal is byte-equal too; a ``tier_miss`` moves
WHICH tick the deferred tenant's fold/score entries land in (content
conserved), and the journal stays byte-equal across same-config
reruns.
"""

from __future__ import annotations

import zlib
from concurrent.futures import Future, ThreadPoolExecutor
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

from anomod.io.cache import (_atomic_publish, _read_payload,
                             _write_payload, cache_key, entry_paths)
from anomod.obs.census import TIER_COLD_INDEX_BYTES, TIER_WARM_ENTRY_BYTES

__all__ = ["TierPlane", "TIER_FORMAT"]

#: cold-entry payload format (bump to invalidate published entries)
TIER_FORMAT = 1


class _TierStateShim:
    """A demoted tenant's stand-in for the flight recorder's
    ``state_digest`` walk: exposes exactly the ``get_state`` /
    ``window_offset`` / ``n_spans`` surface the digest reads, backed by
    the warm snapshot (cheap references) or a cold-tier load (digest
    ticks only, bounded by the demoted set)."""

    __slots__ = ("_get", "window_offset", "n_spans")

    def __init__(self, get_state, window_offset: int, n_spans: int):
        self._get = get_state
        self.window_offset = window_offset
        self.n_spans = n_spans

    def get_state(self):
        return self._get()


class TierPlane:
    """The warm/cold store and its counters.  Pure mechanism — WHO
    demotes (the coldest-candidates policy, backlog/parked exclusions)
    and WHEN promotions install (the scoring gate) live in the engine;
    this class owns the entries, the bytes accounting, the cold-tier
    publish/load and the prefetch lane."""

    def __init__(self, hot_capacity: int, demote_after: int,
                 warm_budget_bytes: int, cold_dir: Optional[Path],
                 prefetch_depth: int, slot_nbytes: int):
        self.hot_capacity = int(hot_capacity)
        self.demote_after = int(demote_after)
        self.warm_budget_bytes = int(warm_budget_bytes)
        self.cold_dir = Path(cold_dir) if cold_dir else None
        self.prefetch_depth = int(prefetch_depth)
        self.slot_nbytes = int(slot_nbytes)
        #: tid -> entry.  A WARM entry holds {"replay": snapshot_replay
        #: dict, "det": detector, "cold_key": None}; a COLD entry's
        #: replay slot is the retained scalar meta instead of arrays
        #: ({"meta": ..., "leaves": n, "none": [...]}) and "cold_key"
        #: addresses the published payload.  Insertion order is
        #: last-demoted order; demotion re-inserts, so the FRONT is the
        #: coldest warm entry — the spill ordering.
        self._entries: Dict[int, dict] = {}
        self._state_cls = None          # the get_state pytree type
        self._pool: Optional[ThreadPoolExecutor] = None
        self._fetching: Dict[int, Future] = {}
        # canonical counters (functions of seed+config — parity surface)
        self.demotions_warm = 0
        self.demotions_cold = 0
        self.promotions = 0
        self.misses = 0
        # wall-side telemetry (variant surface): how many cold joins
        # found the fetch already complete vs had to wait
        self.prefetch_hits = 0
        self.prefetch_joins = 0
        #: demote/promote/miss events for the flight journal's
        #: ``tiering`` VARIANT key (drained per tick by the engine);
        #: wall-free, so the stream is byte-equal across reruns
        self.events: List[dict] = []
        self.warm_state_bytes = 0       # exact array bytes, warm only

    # -- introspection ----------------------------------------------------

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, tid: int) -> bool:
        return tid in self._entries

    def tids(self):
        return self._entries.keys()

    @property
    def n_warm(self) -> int:
        return sum(1 for e in self._entries.values()
                   if e["cold_key"] is None)

    @property
    def n_cold(self) -> int:
        return sum(1 for e in self._entries.values()
                   if e["cold_key"] is not None)

    def status(self, tid: int) -> Optional[str]:
        e = self._entries.get(tid)
        if e is None:
            return None
        return "cold" if e["cold_key"] is not None else "warm"

    def resident_nbytes(self) -> int:
        """Deterministic host-resident bytes for the census tier plane:
        warm state arrays exact + nominal per-entry bookkeeping; cold
        entries price as index entries only (their arrays are on
        disk — that residency drop is the tier's point)."""
        return (self.warm_state_bytes
                + self.n_warm * TIER_WARM_ENTRY_BYTES
                + self.n_cold * TIER_COLD_INDEX_BYTES)

    # -- demotion ---------------------------------------------------------

    def demote(self, tick: int, tid: int, replay_snap: dict,
               detector, idle_ticks: int) -> None:
        """Accept one demoted tenant (the engine already snapshotted it
        through the PR-10 seams and released its pool slot), then spill
        past the warm budget."""
        if tid in self._entries:
            raise RuntimeError(f"tenant {tid} is already tiered")
        if self._state_cls is None:
            self._state_cls = type(replay_snap["state"])
        self._entries[tid] = {"replay": replay_snap, "det": detector,
                              "cold_key": None}
        self.warm_state_bytes += self.slot_nbytes
        self.demotions_warm += 1
        self.events.append({"kind": "demote", "tier": "warm",
                            "tick": int(tick), "tenant": int(tid),
                            "idle_ticks": int(idle_ticks)})
        self._spill(tick)

    def _spill(self, tick: int) -> None:
        """Spill the coldest warm entries' arrays to the cold tier
        until the warm budget holds.  No cold dir → the warm tier is
        terminal and the budget is advisory (documented in SERVING.md);
        a refused publish (OSError) keeps the entry warm — the budget
        is a target, data loss is not an option."""
        if self.cold_dir is None:
            return
        while self.warm_state_bytes > self.warm_budget_bytes:
            victim = next((t for t, e in self._entries.items()
                           if e["cold_key"] is None), None)
            if victim is None:
                return
            if not self._publish_cold(tick, victim):
                return

    def _publish_cold(self, tick: int, tid: int) -> bool:
        e = self._entries[tid]
        snap = e["replay"]
        leaves = list(snap["state"])
        arrays = {f"c{i}": np.ascontiguousarray(leaf)
                  for i, leaf in enumerate(leaves) if leaf is not None}
        crc = 0
        for name in arrays:
            crc = zlib.crc32(arrays[name].tobytes(), crc)
        meta = {"tenant": int(tid), "tier_format": TIER_FORMAT,
                "t0_us": int(snap["t0_us"]),
                "window_offset": int(snap["window_offset"]),
                "n_spans": int(snap["n_spans"]),
                "n_leaves": len(leaves),
                "none": [i for i, leaf in enumerate(leaves)
                         if leaf is None]}
        key = cache_key({**meta, "crc": crc})
        payload_path, _ = entry_paths(self.cold_dir, key)
        try:
            payload_path.parent.mkdir(parents=True, exist_ok=True)
            # atomic publish FIRST; the host arrays drop only after the
            # rename lands, so a kill anywhere in between leaves the
            # entry warm and intact (tmp leftovers are never read)
            _atomic_publish(payload_path,
                            lambda f: _write_payload(f, arrays, meta))
        except OSError:
            return False
        e["cold_key"] = key
        e["replay"] = meta
        self.warm_state_bytes -= self.slot_nbytes
        self.demotions_cold += 1
        self.events.append({"kind": "demote", "tier": "cold",
                            "tick": int(tick), "tenant": int(tid)})
        return True

    # -- the prefetch lane ------------------------------------------------

    def prefetch(self, tid: int) -> None:
        """Issue the cold-tier read on the async lane (offer-time hook:
        the fetch overlaps this tick's admission/drain/SLO phases and
        the full deferral tick).  Idempotent; a warm or unknown tid is
        a no-op."""
        e = self._entries.get(tid)
        if e is None or e["cold_key"] is None or tid in self._fetching:
            return
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self.prefetch_depth,
                thread_name_prefix="anomod-tier-prefetch")
        self._fetching[tid] = self._pool.submit(
            self._read_cold, e["cold_key"])

    def _read_cold(self, key: str) -> tuple:
        payload_path, _ = entry_paths(self.cold_dir, key)
        try:
            with open(payload_path, "rb") as f:
                data = f.read()
            arrays, meta = _read_payload(data)
        except Exception as exc:
            # a published entry is complete by construction (atomic
            # rename, publish-before-drop) — an unreadable one is real
            # data loss and must fail LOUD, never re-derive silently
            raise RuntimeError(
                f"cold-tier entry {key} unreadable ({exc!r}): the "
                f"publish-before-drop protocol makes this impossible "
                f"short of on-disk corruption or an external delete"
            ) from exc
        return arrays, meta

    # -- promotion --------------------------------------------------------

    def take(self, tick: int, tid: int, deferred: bool = False) -> tuple:
        """Remove and return ``(replay_snap, detector)`` for one
        promoting tenant.  Warm: the snapshot comes straight back.
        Cold: joins the prefetch future (or reads synchronously when
        none was issued — the run-end promote-all path), rebuilding the
        ``get_state`` pytree from the payload columns."""
        e = self._entries.pop(tid)
        tier = "cold" if e["cold_key"] is not None else "warm"
        if tier == "warm":
            self.warm_state_bytes -= self.slot_nbytes
            snap = e["replay"]
        else:
            fut = self._fetching.pop(tid, None)
            if fut is not None:
                self.prefetch_joins += 1
                if fut.done():
                    self.prefetch_hits += 1
                arrays, meta = fut.result()
            else:
                arrays, meta = self._read_cold(e["cold_key"])
            snap = self._snap_from_payload(arrays, meta)
        self.promotions += 1
        self.events.append({"kind": "promote", "tier": tier,
                            "tick": int(tick), "tenant": int(tid),
                            "deferred": bool(deferred)})
        return snap, e["det"]

    def _snap_from_payload(self, arrays: dict, meta: dict) -> dict:
        leaves = [None if i in set(meta["none"])
                  else np.array(arrays[f"c{i}"])
                  for i in range(int(meta["n_leaves"]))]
        return {"state": self._state_cls(*leaves),
                "t0_us": meta["t0_us"],
                "window_offset": meta["window_offset"],
                "n_spans": meta["n_spans"]}

    def miss(self, tick: int, tid: int, n_batches: int,
             n_spans: int) -> None:
        """Count + journal one deterministic cold-promotion deferral."""
        self.misses += 1
        self.events.append({"kind": "miss", "tick": int(tick),
                            "tenant": int(tid),
                            "batches": int(n_batches),
                            "spans": int(n_spans)})

    # -- checkpoint/restore hooks (anomod.serve.supervise) ----------------

    def ckpt_snap(self, tid: int) -> dict:
        """A checkpoint-ready replay snapshot for one tiered tenant.
        Warm: the held snapshot BY REFERENCE — immutable after
        demotion (promotion copies OUT of it through restore_replay,
        never into it), so the checkpoint and the live entry can share
        it.  Cold: a marker naming the content-addressed entry — the
        store is append-only (promotion pops the index entry but never
        unlinks the payload), so the key stays loadable for the
        checkpoint's lifetime."""
        e = self._entries[tid]
        if e["cold_key"] is None:
            return e["replay"]
        return {"__tier_cold__": e["cold_key"]}

    def ckpt_det(self, tid: int):
        return self._entries[tid]["det"]

    def load_cold(self, key: str) -> dict:
        """Synchronously load one cold entry into a replay snapshot —
        the supervised-restore path (recovery is already off the hot
        loop; a blocking read here is the point, not a miss)."""
        arrays, meta = self._read_cold(key)
        return self._snap_from_payload(arrays, meta)

    def discard(self, tid: int) -> None:
        """Drop one entry WITHOUT promotion accounting — the supervised
        restore path, where the checkpoint view supersedes the tier
        entry (the restore rebuilds the tenant RESIDENT and re-executes
        the retained log against that state; a surviving stale entry
        would shadow it at the next gate).  Unknown tid is a no-op."""
        e = self._entries.pop(tid, None)
        if e is not None and e["cold_key"] is None:
            self.warm_state_bytes -= self.slot_nbytes
        self._fetching.pop(tid, None)

    # -- read-side shims --------------------------------------------------

    def state_shim(self, tid: int) -> _TierStateShim:
        """The ``state_digest`` stand-in for a demoted tenant (see
        :class:`_TierStateShim`).  Cold states load from disk ONLY when
        the digest actually reads them (digest-cadence ticks), without
        promoting the entry."""
        e = self._entries[tid]
        if e["cold_key"] is None:
            snap = e["replay"]
            return _TierStateShim(lambda: snap["state"],
                                  snap["window_offset"],
                                  snap["n_spans"])
        meta = e["replay"]
        key = e["cold_key"]

        def _load():
            arrays, m = self._read_cold(key)
            return self._snap_from_payload(arrays, m)["state"]

        return _TierStateShim(_load, meta["window_offset"],
                              meta["n_spans"])

    def drain_events(self) -> List[dict]:
        out, self.events = self.events, []
        return out

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        self._fetching.clear()
