"""The serving engine: virtual-clock tick loop over admission → dynamic
batching → the shared jitted chunk step → per-tenant SLO accounting.

Deterministic by construction (the anomod.recovery pattern): a virtual
clock advances in fixed ticks, arrivals come from a seeded traffic
source, and every admission/shedding/serving decision is pure
bookkeeping — a seeded overload replay is bit-reproducible, and the
whole engine unit-tests without a single wall sleep.  Wall time is
measured (never waited on) around the serving path only, for the
sustained spans/sec number the bench reports.

Each tenant runs the UNCHANGED detector stack: an
``anomod.stream.OnlineDetector`` whose replay plane is a
:class:`anomod.serve.batcher.BucketedStreamReplay` sharing one compiled
chunk step per bucket across the whole fleet (or, with ``mesh``, an
``anomod.parallel.stream.ShardedStreamReplay`` — the pod-sharded plane,
reused wholesale).  Admission→scored latency per micro-batch folds into
per-tenant t-digests (anomod.ops.tdigest — the repo's one sketch path),
so the ServeReport's p50/p99 are sketch-backed, mergeable across tenants
and priorities.

Scale-out (``ANOMOD_SERVE_SHARDS``): the score plane fans out across
tenant-sharded worker threads (anomod.serve.shard) and joins at a
barrier each tick, while admission/drain/shed/SLO bookkeeping stays on
the coordinator — so an N-shard run's states, alerts and decisions are
IDENTICAL to the 1-shard engine on the same seed.  Within a shard the
fused dispatch pipelines (``ANOMOD_SERVE_PIPELINE``): staging of batch
t+1 overlaps batch t's in-flight XLA dispatch, bit-identically.

Online RCA (``ANOMOD_SERVE_RCA``): a tenant's detector firing queues
incremental GNN culprit inference over that tenant's live service graph
(anomod.serve.rca) — budgeted per tick, run on the shard that owns the
tenant, verdicts folded at the barrier in enqueue order; a pure
read-side consumer, so every decision above stays byte-identical with
RCA on or off.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from anomod import obs
from anomod.obs.perf import bubble_fractions as _perf_bubbles
from anomod.ops.tdigest import (TDigest, tdigest_build, tdigest_merge_many,
                                tdigest_quantile)
from anomod.replay import N_FEATS, ReplayConfig
from anomod.schemas import concat_span_batches
from anomod.serve.batcher import (BucketedStreamReplay, BucketRunner,
                                  PooledStreamReplay)
from anomod.serve.queues import (AdmissionController, QueuedBatch,
                                 TenantSpec)

#: t-digest centroid capacity for the latency sketches (compact enough to
#: keep per tenant, accurate to well under a tick at the tails)
_DIGEST_K = 32
#: latency samples buffered per tenant before folding into the digest
_FOLD_EVERY = 256


class VirtualClock:
    """Tick-based deterministic time (no wall sleeps — recovery.py's
    pattern, shared contract with the chaos/recovery controllers)."""

    def __init__(self, tick_s: float = 1.0, t0_s: float = 0.0):
        if tick_s <= 0:
            raise ValueError("tick_s must be positive")
        self.tick_s = float(tick_s)
        self.now_s = float(t0_s)
        self.ticks = 0

    def advance(self) -> float:
        self.now_s += self.tick_s
        self.ticks += 1
        return self.now_s


class _TenantSLO:
    """Per-tenant latency sketch + alert bookkeeping.

    Every fold ALSO merges the freshly-built digest chunk into the
    process registry's ``anomod_serve_admit_to_scored_seconds`` histogram
    (anomod.obs) — the registry's fleet-wide latency sketch is literally
    the fold of these private per-tenant digests, with no double counting
    and no second pass over raw samples."""

    def __init__(self,
                 hist_name: str = "anomod_serve_admit_to_scored_seconds"):
        self.digest: Optional[TDigest] = None
        self._buf: List[float] = []
        self.n_samples = 0
        self.max_latency_s = 0.0
        self._obs_hist = obs.histogram(hist_name)

    def record(self, latency_s: float) -> None:
        self._buf.append(float(latency_s))
        self.n_samples += 1
        self.max_latency_s = max(self.max_latency_s, float(latency_s))
        if len(self._buf) >= _FOLD_EVERY:
            self.fold()

    def fold(self) -> None:
        if not self._buf:
            return
        d = tdigest_build(np.asarray(self._buf, np.float32), k=_DIGEST_K)
        self._obs_hist.merge_digest(d)
        self.digest = d if self.digest is None else \
            tdigest_merge_many([self.digest, d])
        self._buf = []

    def quantile(self, q: float) -> Optional[float]:
        self.fold()
        if self.digest is None or float(self.digest.weight.sum()) <= 0:
            return None
        return float(tdigest_quantile(self.digest, q))


class _LazySLO(dict):
    """Per-tenant SLO sketches created on first recorded sample — the
    registered fleet never materializes a digest row (the tiering PR's
    O(hot-set) registry contract; the report's priority merge walks the
    rows that exist, and ``_merged_quantiles`` of none is None-safe)."""

    def __missing__(self, tid: int) -> _TenantSLO:
        s = self[tid] = _TenantSLO()
        return s


def _merged_quantiles(slos: Sequence[_TenantSLO],
                      qs=(0.5, 0.99)) -> Dict[str, Optional[float]]:
    digests = []
    for s in slos:
        s.fold()
        if s.digest is not None and float(s.digest.weight.sum()) > 0:
            digests.append(s.digest)
    if not digests:
        return {f"p{int(q * 100)}_latency_s": None for q in qs}
    merged = digests[0] if len(digests) == 1 else \
        tdigest_merge_many(digests)
    return {f"p{int(q * 100)}_latency_s":
            round(float(tdigest_quantile(merged, q)), 6) for q in qs}


#: ServeReport fields that legitimately differ across shard counts /
#: pipeline depths on the same seed: wall-clock measurements and lane
#: GROUPING topology (which lanes share a fused stack depends on shard
#: membership; the resulting per-lane bits do not).  The ONE definition
#: of the shard-determinism contract's exclusion list — shared by the
#: parity tests (tests/test_serve.py) and the pre-bench fan-out smoke
#: (scripts/pre_bench_check.py), so the two pins cannot drift apart.
#: ``rca_latency``/``rca_wall_s`` are wall measurements of the RCA runs;
#: the verdict STREAM itself (and every other rca_* field) is pinned
#: identical across shard counts.
SHARD_VARIANT_REPORT_FIELDS = (
    "serve_wall_s", "sustained_spans_per_sec", "compile_s",
    "lane_compile_s", "fused_dispatches", "lanes_by_bucket",
    "lane_pad_waste", "shards", "pipeline", "shard_tenants",
    "shard_spans", "shard_imbalance", "rca_latency", "rca_wall_s",
    # tick-wall decomposition: wall measurements, and the native-staged
    # dispatch count follows the fused-dispatch grouping topology
    "stage_wall_s", "dispatch_wall_s", "fold_wall_s", "score_wall_s",
    "native_staged_dispatches",
    # supervision wall legs: snapshot and recovery time are wall
    # measurements (the decisions they protect are pinned identical)
    "ckpt_wall_s", "recovery_wall_s",
    # elastic topology: how many workers the policy ran at its peak is
    # execution strategy (a policy-off run's peak IS its shard count),
    # and the policy/migration wall is a wall measurement
    "peak_shards", "policy_wall_s",
    # the performance observatory (anomod.obs.perf): lifecycle-event
    # counts follow the fused-dispatch grouping topology, and the
    # fold-wait / overlap-headroom / bubble numbers are wall-clock
    # measurements — consciously VARIANT, never the parity surface
    # (perf_enabled, the config bit, stays canonical)
    "perf_events_recorded", "overlap_headroom_s", "fold_wait_s",
    "bubble_fractions",
    # the deferred-commit seam (ANOMOD_SERVE_ASYNC_COMMIT): how long
    # dispatches were left executing under coordinator work is a wall
    # measurement — consciously VARIANT (async_commit, the config bit,
    # and async_ticks, its config-derived tick count, stay canonical)
    "commit_defer_wall_s",
    # the fleet census observatory (anomod.obs.census): resident-bytes
    # totals follow the execution TOPOLOGY (per-shard pool capacity and
    # scratch grids depend on the shard count and residency), so the
    # byte dict is consciously VARIANT — the hot-set census
    # (census_hot_set) and the census tick count derive from
    # coordinator admission decisions alone and stay CANONICAL; the
    # census wall is a wall measurement (the in-run overhead price)
    "census_resident_bytes", "census_wall_s",
    # the state-tiering plane (ANOMOD_SERVE_TIER_HOT): demotions /
    # promotions / misses are functions of seed+config and stay
    # CANONICAL; whether a cold fetch happened to finish before its
    # one-tick deferral elapsed is wall luck, and the gate+demote wall
    # is a wall measurement — consciously VARIANT
    "tier_prefetch_hidden", "tier_wall_s",
    # the worker plane (ANOMOD_SERVE_WORKER / ANOMOD_SERVE_FOLD):
    # thread-vs-process shard execution and dense-vs-sparse barrier
    # deltas are execution topology, and the fold payload byte count
    # follows that topology — a process-worker report must compare
    # equal to the thread oracle on every decision field
    "worker", "fold", "fold_payload_bytes")


def _runner_stats(r) -> dict:
    """One runner's cumulative book + compile/wall legs as a plain
    dict — the ONE shape shared by the report aggregation and the
    retired-runner retention at elastic scale-down, so the "counts
    cover the WHOLE run" invariant cannot drift when a new leg
    lands in one site but not the other."""
    return {"book": r.book_snapshot(),
            "compile_s": r.compile_s,
            "lane_compile_s": r.lane_compile_s,
            "stage_wall_s": r.stage_wall_s,
            "dispatch_wall_s": r.dispatch_wall_s,
            "fold_wall_s": r.fold_wall_s,
            "score_wall_s": r.score_wall_s}


def _plane_col_gather(work):
    """The ``gather_cols`` backend for one batched COMMIT pass
    (:func:`anomod.stream.score_closed_windows_batched`) over the
    engine's replay planes.

    DEVICE path — every requested plane lives in the SAME runner's
    tenant pool (the engine maps a tenant's replay to its owning
    shard's runner, and one commit pass only ever sees one shard's
    tenants): ONE fused pool gather per scored window
    (:meth:`anomod.replay.TenantStatePool.gather_window`), so only the
    small scored columns materialize to host — never the full
    [SW, F] rows.  HOST path (host-seam replays, or mixed callers):
    per-plane host views, cached across the pass's windows (the plane
    is static during scoring — same snapshot discipline as the
    sequential scorer's one ``agg_plane()`` read)."""
    planes: Dict[int, np.ndarray] = {}

    def gather(items):
        reps = [work[i][0].replay for i, _ in items]
        # anomod-lint: disable=S301 — the one blessed fused-gather exception: slots are only COLLECTED here and handed to pool.gather_window, which owns the always-copy contract
        if reps and all(type(r) is PooledStreamReplay for r in reps) \
                and all(r._runner is reps[0]._runner for r in reps):
            return reps[0]._runner.pool.gather_window(
                [r._slot for r in reps], [c for _, c in items])
        out = np.empty((len(items), reps[0].cfg.n_services, N_FEATS),
                       np.float32)
        for j, (i, c) in enumerate(items):
            pl = planes.get(i)
            if pl is None:
                pl = planes[i] = np.asarray(
                    work[i][0].replay.agg_plane(), np.float32)
            out[j] = pl[:, c]
        return out

    return gather


def onset_eligible(window: int, onset_window: int) -> bool:
    """THE pre-onset-noise eligibility rule, in one place: an alert (or
    an RCA verdict, via its triggering alert) at absolute window ``w``
    is attributable to a fault whose onset falls in ``onset_window`` iff
    ``w >= onset_window`` — the boundary window itself counts (it is the
    earliest window the fault can influence), anything earlier is noise
    and must not score as (negative-latency) detection or as an RCA hit.
    Shared by the golden fault-detection metrics, :meth:`ServeEngine.
    alerts_for` and the RCA hit accounting so the three paths can never
    apply different rules."""
    return window >= onset_window


def onset_eligible_alerts(alerts, onset_window: int) -> list:
    """The alerts that pass :func:`onset_eligible`."""
    return [a for a in alerts if onset_eligible(a.window, onset_window)]


@dataclasses.dataclass
class ServeReport:
    """The serving run's quality/throughput document (JSON-able)."""
    n_tenants: int
    duration_s: float
    ticks: int
    capacity_spans_per_s: float
    offered_spans: int
    admitted_spans: int
    served_spans: int
    shed_spans: int
    shed_fraction: float
    served_batches: int
    peak_backlog_spans: int
    max_backlog: int
    buckets: Tuple[int, ...]
    dispatches_by_width: Dict[int, int]
    fused: bool                                  # lane-stacked dispatch on?
    fused_dispatches: int                        # actual fused dispatches
    lane_buckets: Tuple[int, ...]
    lanes_by_bucket: Dict[int, int]              # fused dispatches per bucket
    lane_pad_waste: float                        # dead-lane fraction
    compile_s: float
    lane_compile_s: float
    native_staging: bool                         # GIL-free C++ scratch pack?
    native_staged_dispatches: int                # fused dispatches so packed
    serve_state: str                             # tenant states: host|device
    stage_wall_s: float                          # host packing wall
    dispatch_wall_s: float                       # executable-issue wall
    fold_wall_s: float                           # delta fold wall (device:
    #                                              scatter-add + barrier)
    score_wall_s: float                          # window-scoring wall
    shards: int                                  # engine-worker shard count
    pipeline: int                                # in-flight dispatch depth
    shard_tenants: Dict[int, int]                # tenants owned per shard
    shard_spans: Dict[int, int]                  # spans scored per shard
    shard_imbalance: float                       # max shard load / mean
    latency: Dict[str, Optional[float]]          # aggregate p50/p99
    per_priority: Dict[int, dict]
    modality_events: Dict[str, int]              # multimodal sidecar volume
    n_alerts: int
    n_tenants_alerted: int
    fault_detection: Optional[dict]
    rca_enabled: bool                            # online RCA plane on?
    n_rca_runs: int                              # alert→culprit inferences
    rca_topk_hits: Dict[int, int]                # k -> fault tenants hit@k
    rca_eligible: int                            # fault tenants w/ verdict
    rca_latency: Dict[str, Optional[float]]      # wall p50/p99 per RCA run
    rca_alert_to_culprit_s: Dict[str, Optional[float]]  # virtual queue delay
    rca_wall_s: float                            # total RCA wall
    supervised: bool                             # checkpoint/recovery on?
    ckpt_every: int                              # snapshot cadence (ticks)
    n_checkpoints: int                           # snapshots taken
    ckpt_wall_s: float                           # snapshot wall
    n_shard_crashes: int                         # tick-barrier failures
    n_respawns: int                              # worker threads respawned
    n_restored_ticks: int                        # slices re-executed
    n_quarantined: int                           # batches dropped after K
    #                                              consecutive kill loops
    n_migrated_tenants: int                      # moved off dead shards
    recovery_wall_s: float                       # restore + re-exec wall
    policy: str                                  # elastic mode: off|auto|
    #                                              script
    n_scale_ups: int                             # executed up episodes
    n_scale_downs: int                           # executed down episodes
    n_rebalances: int                            # executed rebalances
    n_policy_migrations: int                     # tenants moved by policy
    brownout_ticks: int                          # ticks at ladder level>=1
    peak_shards: int                             # max workers the run held
    policy_wall_s: float                         # policy eval + migration
    #                                              wall
    flight_enabled: bool                         # black-box recorder on?
    flight_recorded_ticks: int                   # journal records written
    flight_dropped_ticks: int                    # ring evictions (0 = no
    #                                              loss; never silent)
    perf_enabled: bool                           # dispatch-lifecycle
    #                                              timeline on?
    perf_events_recorded: int                    # lifecycle events taken
    overlap_headroom_s: float                    # fold WAIT legally
    #                                              hideable under next-
    #                                              round staging (upper
    #                                              bound; anomod.obs.perf)
    fold_wait_s: float                           # measured execute WAIT
    #                                              inside the fold leg
    bubble_fractions: Dict[str, float]           # per-leg dead-time shares
    census_enabled: bool                         # fleet census on?
    census_ticks: int                            # census drains taken
    census_hot_set: Dict[str, object]            # hot-set/Zipf census
    #                                              (canonical: admission-
    #                                              derived, shard-invariant)
    census_resident_bytes: Dict[str, object]     # deterministic resident
    #                                              bytes (variant: follows
    #                                              pool/scratch topology)
    census_wall_s: float                         # census drain wall (the
    #                                              in-run overhead price)
    tier_hot: int                                # hot-pool tenant capacity
    #                                              (0 = tiering off)
    n_tier_demotions_warm: int                   # device→host warm demotions
    n_tier_demotions_cold: int                   # warm→disk cold spills
    n_tier_promotions: int                       # tier→device re-admissions
    n_tier_misses: int                           # deterministic one-tick
    #                                              cold-promotion deferrals
    tier_prefetch_hidden: int                    # cold joins whose disk read
    #                                              had already finished
    #                                              (variant: wall telemetry)
    tier_wall_s: float                           # gate + demote-step wall
    async_commit: bool                           # deferred-commit tick on?
    async_ticks: int                             # ticks whose commit
    #                                              deferred past issue
    commit_defer_wall_s: float                   # wall dispatches spent
    #                                              executing under next-tick
    #                                              coordinator work (the
    #                                              hidden fold wait)
    worker: str                                  # shard engine: thread|
    #                                              process (execution
    #                                              topology — variant)
    fold: str                                    # barrier delta mode:
    #                                              dense|sparse (variant)
    fold_payload_bytes: int                      # structural bytes the tick
    #                                              barrier's registry deltas
    #                                              carried (variant: follows
    #                                              worker/fold topology)
    serve_wall_s: float
    sustained_spans_per_sec: float

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["buckets"] = list(self.buckets)
        d["lane_buckets"] = list(self.lane_buckets)
        d["dispatches_by_width"] = {str(k): v for k, v
                                    in self.dispatches_by_width.items()}
        d["lanes_by_bucket"] = {str(k): v for k, v
                                in self.lanes_by_bucket.items()}
        d["per_priority"] = {str(k): v for k, v
                             in self.per_priority.items()}
        d["shard_tenants"] = {str(k): v for k, v
                              in self.shard_tenants.items()}
        d["shard_spans"] = {str(k): v for k, v
                            in self.shard_spans.items()}
        d["rca_topk_hits"] = {str(k): v for k, v
                              in self.rca_topk_hits.items()}
        return d


def serve_plane_cfg(n_services: int = 12, window_s: float = 5.0,
                    n_windows: int = 32) -> ReplayConfig:
    """The serve bench's replay-plane shape — ONE definition shared by
    ``run_power_law`` (and thus ``bench.py --mode serve``, whose
    serve_main passes these defaults) and the pre-bench serve gate
    (``scripts/pre_bench_check.py --mode serve``), so the gate's
    "bucket set compiles" check always covers the plane the capture
    actually runs."""
    return ReplayConfig(n_services=n_services, n_windows=n_windows,
                        window_us=int(window_s * 1e6), chunk_size=4096)


def run_power_law(n_tenants: int = 200, n_services: int = 8,
                  capacity_spans_per_s: float = 20_000.0,
                  overload: float = 1.0, duration_s: float = 120.0,
                  tick_s: float = 1.0, seed: int = 0, alpha: float = 1.2,
                  window_s: float = 5.0, baseline_windows: int = 4,
                  z_threshold: float = 4.0,
                  buckets: Optional[Tuple[int, ...]] = None,
                  max_backlog: Optional[int] = None,
                  fault_tenants: int = 2, score: bool = True,
                  mesh=None, tracer=None, n_windows: int = 32,
                  fuse: Optional[bool] = None,
                  lane_buckets: Optional[Tuple[int, ...]] = None,
                  shards: Optional[int] = None,
                  pipeline: Optional[int] = None,
                  rca: Optional[bool] = None,
                  native: Optional[bool] = None,
                  state: Optional[str] = None,
                  flight: Optional[bool] = None,
                  flight_digest_every: Optional[int] = None,
                  flight_max_ticks: Optional[int] = None,
                  perf: Optional[bool] = None,
                  census: Optional[bool] = None,
                  census_every: Optional[int] = None,
                  chaos: Optional[str] = None,
                  ckpt_every: Optional[int] = None,
                  retries: Optional[int] = None,
                  retry_backoff_s: Optional[float] = None,
                  max_respawns: Optional[int] = None,
                  policy: Optional[str] = None,
                  policy_script: Optional[str] = None,
                  min_shards: Optional[int] = None,
                  max_shards: Optional[int] = None,
                  target_imbalance: Optional[float] = None,
                  cooldown_ticks: Optional[int] = None,
                  async_commit: Optional[bool] = None,
                  native_drain: Optional[str] = None,
                  tier_hot: Optional[int] = None,
                  tier_demote_after: Optional[int] = None,
                  tier_warm_bytes: Optional[int] = None,
                  tier_cold_dir=None,
                  tier_prefetch: Optional[int] = None,
                  worker: Optional[str] = None,
                  fold: Optional[str] = None
                  ) -> Tuple["ServeEngine", ServeReport]:
    """The canonical seeded serve run shared by ``anomod serve`` and
    ``bench.py --mode serve``: a power-law tenant fleet offering
    ``overload``× the engine's capacity, with ``fault_tenants`` busiest
    tenants given a scripted latency fault once calibration is past —
    so one invocation measures sustained throughput, shed behavior AND
    alert latency under load."""
    from anomod.serve.traffic import PowerLawTraffic, TenantFault
    onset_s = (baseline_windows + 2) * window_s
    if duration_s <= onset_s + 2 * window_s:
        fault_tenants = 0                 # too short for a fault phase
    faults = {t: TenantFault("latency", service=1, onset_s=onset_s,
                             factor=10.0)
              for t in range(min(fault_tenants, n_tenants))}
    traffic = PowerLawTraffic(
        n_tenants=n_tenants,
        total_rate_spans_per_s=capacity_spans_per_s * overload,
        alpha=alpha, seed=seed, n_services=n_services, faults=faults)
    cfg = serve_plane_cfg(n_services, window_s, n_windows)
    engine = ServeEngine(traffic.specs, traffic.services, cfg,
                         capacity_spans_per_s=capacity_spans_per_s,
                         tick_s=tick_s, buckets=buckets,
                         max_backlog=max_backlog, score=score,
                         baseline_windows=baseline_windows,
                         z_threshold=z_threshold, mesh=mesh,
                         tracer=tracer, fuse=fuse,
                         lane_buckets=lane_buckets, shards=shards,
                         pipeline=pipeline, rca=rca, native=native,
                         state=state, flight=flight,
                         flight_digest_every=flight_digest_every,
                         flight_max_ticks=flight_max_ticks,
                         perf=perf, census=census,
                         census_every=census_every,
                         chaos=chaos, ckpt_every=ckpt_every,
                         retries=retries,
                         retry_backoff_s=retry_backoff_s,
                         max_respawns=max_respawns, policy=policy,
                         policy_script=policy_script,
                         min_shards=min_shards, max_shards=max_shards,
                         target_imbalance=target_imbalance,
                         cooldown_ticks=cooldown_ticks,
                         async_commit=async_commit,
                         native_drain=native_drain,
                         tier_hot=tier_hot,
                         tier_demote_after=tier_demote_after,
                         tier_warm_bytes=tier_warm_bytes,
                         tier_cold_dir=tier_cold_dir,
                         tier_prefetch=tier_prefetch,
                         worker=worker, fold=fold)
    if engine.flight_recorder is not None:
        # the header's replay contract: `anomod audit replay` re-executes
        # this exact invocation from the journal alone.  Every
        # env-defaulted knob is recorded RESOLVED (what the engine
        # actually served with), never as the raw None the ctor would
        # re-resolve from the REPLAY process's env — otherwise a replay
        # under a different ANOMOD_SERVE_BUCKETS / _MAX_BACKLOG /
        # _FUSE / _RCA would report env drift as plane divergence.
        # ``native`` stays raw on purpose: native-vs-python staging is
        # byte-identical (it cannot move a canonical plane), and a
        # resolved ``True`` would refuse to replay on a box without the
        # toolchain for zero forensic benefit.
        engine.flight_recorder.header["run"] = dict(
            n_tenants=n_tenants, n_services=n_services,
            capacity_spans_per_s=capacity_spans_per_s, overload=overload,
            duration_s=duration_s, tick_s=tick_s, seed=seed, alpha=alpha,
            window_s=window_s, baseline_windows=baseline_windows,
            z_threshold=z_threshold,
            buckets=list(engine.runner.buckets),
            max_backlog=engine.max_backlog, fault_tenants=fault_tenants,
            score=score, n_windows=n_windows, fuse=engine.fuse,
            lane_buckets=list(engine.runner.lane_buckets),
            shards=engine.shards, pipeline=engine.pipeline,
            rca=engine.rca, native=native,
            state=engine.serve_state, flight=True,
            flight_digest_every=engine.flight_recorder.digest_every,
            flight_max_ticks=engine.flight_recorder.max_ticks,
            # the perf plane, RESOLVED: a replay of a perf-on run
            # re-records its timeline (variant tier — the canonical
            # journal is identical either way, the read-side pin)
            perf=engine.perf,
            # the census plane, RESOLVED: a replay of a census-on run
            # re-takes the same deterministic census (the `census`
            # variant stream of a replay is byte-equal to the
            # original's at matching topology — pinned)
            census=engine.census,
            census_every=engine.census_every,
            # the fault-tolerance knobs, RESOLVED: an audit replay of a
            # chaos run re-injects the same script and re-recovers —
            # its canonical journal must equal the original's (the
            # no-score-gap contract makes both equal the fault-free
            # journal)
            chaos=(engine._chaos.script
                   if engine._chaos is not None else ""),
            ckpt_every=engine.ckpt_every, retries=engine.retries,
            retry_backoff_s=engine.retry_backoff_s,
            max_respawns=engine.max_respawns,
            # the elastic-policy knobs, RESOLVED: an audit replay of an
            # elastic run re-evaluates the same policy over the same
            # canonical signals and re-executes the SAME scaling
            # schedule (the episode-determinism pin)
            policy=(engine.policy.mode if engine.policy is not None
                    else "off"),
            policy_script=(engine.policy.script
                           if engine.policy is not None else ""),
            min_shards=(engine.policy.min_shards
                        if engine.policy is not None else None),
            max_shards=(engine.policy.max_shards
                        if engine.policy is not None else None),
            target_imbalance=(engine.policy.target_imbalance
                              if engine.policy is not None else None),
            cooldown_ticks=(engine.policy.cooldown_ticks
                            if engine.policy is not None else None),
            # the deferred-commit seam, RESOLVED: a replay of an
            # async run re-defers and re-commits the same schedule —
            # canonical journal byte-equal to the synchronous
            # engine's (the parity pin), so replaying either mode
            # against either journal matches
            async_commit=engine.async_commit,
            # the state-tiering knobs, RESOLVED: demotions/promotions/
            # misses are functions of these values (warm_bytes and
            # cold_dir decide cold-vs-warm, and a cold promotion's
            # one-tick deferral moves which tick the tenant's canonical
            # fold/score deltas land in), so a replay must serve with
            # the ORIGINAL tiering geometry to reproduce the journal
            tier_hot=engine.tier_hot,
            tier_demote_after=engine.tier_demote_after,
            tier_warm_bytes=engine.tier_warm_bytes,
            tier_cold_dir=(str(engine.tier_cold_dir)
                           if engine.tier_cold_dir is not None else None),
            tier_prefetch=engine.tier_prefetch,
            # ``native_drain`` stays raw — the ``native`` rationale:
            # the columnar/native SFQ drain is byte-identical to the
            # heap (it cannot move a canonical plane), and a resolved
            # "native" would refuse to replay on a toolchain-less box
            # for zero forensic benefit
            native_drain=native_drain,
            # the worker plane, RESOLVED: thread-vs-process shard
            # execution and dense-vs-sparse barrier deltas are
            # byte-parity pinned, so a replay may run either — but the
            # header records what the original actually served with
            # (the forensic record; also what the replay defaults to)
            worker=engine.worker_mode, fold=engine.fold_mode)
    report = engine.run(traffic, duration_s=duration_s)
    return engine, report


class ServeEngine:
    """Multi-tenant serving plane over the streaming detectors."""

    def __init__(self, specs: Sequence[TenantSpec], services: Sequence[str],
                 cfg: Optional[ReplayConfig] = None, t0_us: int = 0,
                 capacity_spans_per_s: float = 20_000.0, tick_s: float = 1.0,
                 buckets: Optional[Tuple[int, ...]] = None,
                 max_backlog: Optional[int] = None,
                 max_tenant_backlog: Optional[int] = None,
                 score: bool = True, baseline_windows: int = 4,
                 z_threshold: float = 4.0, consecutive: int = 1,
                 min_count: float = 5.0, mesh=None, tracer=None,
                 multimodal: bool = False, testbed: Optional[str] = None,
                 fuse: Optional[bool] = None,
                 lane_buckets: Optional[Tuple[int, ...]] = None,
                 shards: Optional[int] = None,
                 pipeline: Optional[int] = None,
                 rca: Optional[bool] = None,
                 rca_buckets: Optional[tuple] = None,
                 rca_topk: Optional[int] = None,
                 rca_budget: Optional[int] = None,
                 rca_windows: Optional[int] = None,
                 native: Optional[bool] = None,
                 state: Optional[str] = None,
                 flight: Optional[bool] = None,
                 flight_digest_every: Optional[int] = None,
                 flight_max_ticks: Optional[int] = None,
                 perf: Optional[bool] = None,
                 census: Optional[bool] = None,
                 census_every: Optional[int] = None,
                 chaos: Optional[object] = None,
                 ckpt_every: Optional[int] = None,
                 retries: Optional[int] = None,
                 retry_backoff_s: Optional[float] = None,
                 max_respawns: Optional[int] = None,
                 policy: Optional[str] = None,
                 policy_script: Optional[str] = None,
                 min_shards: Optional[int] = None,
                 max_shards: Optional[int] = None,
                 target_imbalance: Optional[float] = None,
                 cooldown_ticks: Optional[int] = None,
                 async_commit: Optional[bool] = None,
                 native_drain: Optional[str] = None,
                 tier_hot: Optional[int] = None,
                 tier_demote_after: Optional[int] = None,
                 tier_warm_bytes: Optional[int] = None,
                 tier_cold_dir=None,
                 tier_prefetch: Optional[int] = None,
                 worker: Optional[str] = None,
                 fold: Optional[str] = None):
        from anomod.config import get_config
        from anomod.utils.platform import enable_jit_cache
        if capacity_spans_per_s <= 0:
            raise ValueError("capacity must be positive")
        app_cfg = get_config()
        enable_jit_cache()           # no-op unless ANOMOD_JIT_CACHE is on
        self.specs = list(specs)
        self.services = tuple(services)
        self.cfg = cfg or ReplayConfig(n_services=len(self.services),
                                       chunk_size=4096)
        if self.cfg.n_services != len(self.services):
            raise ValueError("cfg.n_services disagrees with the service "
                             "table")
        self.t0_us = int(t0_us)
        self.capacity_spans_per_s = float(capacity_spans_per_s)
        self.clock = VirtualClock(tick_s)
        self.max_backlog = int(max_backlog if max_backlog is not None
                               else app_cfg.serve_max_backlog)
        self.admission = AdmissionController(
            self.specs, max_backlog=self.max_backlog,
            max_tenant_backlog=max_tenant_backlog,
            drain_engine=native_drain)
        self.score = bool(score)
        self.mesh = mesh
        #: tenant-fused scoring (ANOMOD_SERVE_FUSE): per tick, drained
        #: same-tenant batches coalesce into one staging and same-width
        #: chunks across tenants run as lane-stacked dispatches — pinned
        #: bit-identical on CPU to sequential per-tenant scoring of the
        #: same COALESCED batches (coalescing is the one documented
        #: regrouping vs the unfused per-batch path: docs/SERVING.md).
        #: The mesh plane manages its own sharded dispatch, so fusion
        #: only applies to the bucket-runner plane.
        self.fuse = bool(app_cfg.serve_fuse if fuse is None else fuse)
        self._fused = self.fuse and mesh is None
        #: tenant sharding (ANOMOD_SERVE_SHARDS): the score plane fans
        #: out across worker threads by tenant ownership; admission/
        #: drain/shed/SLO stay on the coordinator, so every decision is
        #: identical to the 1-shard engine on the same seed.  shards=1
        #: (the default) is the exact pre-sharding code path.
        self.shards = int(app_cfg.serve_shards if shards is None
                          else shards)
        if self.shards < 1:
            raise ValueError("shards must be >= 1")
        #: in-flight fused dispatches per runner (ANOMOD_SERVE_PIPELINE):
        #: depth d stages dispatch t+1 while dispatch t's XLA work is in
        #: flight (per-slot pinned scratch; folds in dispatch order, so
        #: any depth is bit-identical).  Applies to the inline 1-shard
        #: fused path AND every shard worker — depth 1 is the exact
        #: synchronous pre-pipelining code path.
        self.pipeline = int(app_cfg.serve_pipeline if pipeline is None
                            else pipeline)
        if self.pipeline < 1:
            raise ValueError("pipeline depth must be >= 1")
        if mesh is not None and self.shards > 1:
            raise ValueError(
                "the mesh plane manages its own sharded dispatch; "
                "run it with shards=1 (ANOMOD_SERVE_SHARDS=1)")
        #: deferred-commit tick (ANOMOD_SERVE_ASYNC_COMMIT): tick t's
        #: fold/score dispatches are ISSUED but not waited on; while
        #: the XLA executes run, the coordinator handles tick t+1's
        #: admission/drain/shed/SLO phases against last-committed
        #: state, and tick t commits at a barrier placed just before
        #: its results are first read.  Every decision input is a
        #: snapshot taken at tick t, so states / alerts / SLO / shed
        #: and the canonical flight journal are byte-identical to the
        #: synchronous engine (=0, the parity oracle) — only walls
        #: move.  The mesh plane manages its own sharded dispatch
        #: (there is no issue/commit seam to split), so the mode
        #: auto-disables there and an explicit request is refused —
        #: the policy/state idiom.
        _async = (app_cfg.serve_async_commit if async_commit is None
                  else bool(async_commit))
        if mesh is not None and _async:
            if async_commit is not None:
                raise ValueError(
                    "the deferred-commit tick splits the bucket-runner "
                    "issue/commit seam; the mesh plane manages its own "
                    "sharded dispatch (ANOMOD_SERVE_ASYNC_COMMIT=0)")
            _async = False
        self.async_commit = bool(_async)
        self._async = self.async_commit
        #: the in-flight deferred tick's snapshotted context (None
        #: when nothing is deferred): every input its commit tail
        #: will read, captured at issue time so the NEXT tick's
        #: admission can never leak into this tick's journal/policy
        self._deferred: Optional[dict] = None
        #: ticks whose commit actually deferred past issue
        self.async_ticks = 0
        #: wall spent with dispatches left executing under coordinator
        #: work before their barrier first read them — the hidden wait
        #: `anomod perf diff` attributes to the ``commit_defer`` leg
        self.commit_defer_wall_s = 0.0
        #: elastic scaling policy (ANOMOD_SERVE_POLICY, anomod.serve.
        #: policy): "off" (the default) is the static engine; "auto"/
        #: "script" evaluate an ElasticPolicy at every tick boundary on
        #: the coordinator and execute scale-up / scale-down /
        #: rebalance / brownout decisions through the live-migration
        #: seams.  Fed ONLY canonical signals, so the scaling schedule
        #: is seed-deterministic (reruns and `anomod audit replay`
        #: reproduce it) and tenant states / alerts / SLO / shed stay
        #: byte-identical to a static run of the same seed.  The mesh
        #: plane keeps state outside the migration seams and the
        #: multimodal sidecar's modality planes have never been
        #: migration-exercised, so the policy auto-disables on both
        #: (an explicit request is refused) — the supervision idiom.
        _policy_mode = (app_cfg.serve_policy if policy is None
                        else str(policy).strip().lower() or "off")
        if _policy_mode not in ("off", "auto", "script"):
            raise ValueError(f"unknown serve policy mode "
                             f"{_policy_mode!r} (off|auto|script)")
        if (mesh is not None or multimodal) and _policy_mode != "off":
            if policy is not None:
                raise ValueError(
                    "the elastic policy migrates tenants through the "
                    "bucket-runner state seams; "
                    + ("the mesh plane manages its own sharded state"
                       if mesh is not None else
                       "the multimodal sidecar planes are not covered "
                       "by the migration seams")
                    + " (ANOMOD_SERVE_POLICY=off)")
            _policy_mode = "off"
        self._elastic = _policy_mode != "off"
        #: elastic engines run the SHARDED machinery at every count
        #: (per-shard registries/runners/workers even at 1 shard), so a
        #: scale-up never has to convert an inline engine mid-run; the
        #: static 1-shard engine keeps the exact inline code path
        self._use_workers = self.shards > 1 or self._elastic
        self.policy = None
        if self._elastic:
            from anomod.serve.policy import ElasticPolicy
            self.policy = ElasticPolicy(
                _policy_mode,
                int(app_cfg.serve_policy_min_shards
                    if min_shards is None else min_shards),
                int(app_cfg.serve_policy_max_shards
                    if max_shards is None else max_shards),
                float(app_cfg.serve_policy_target_imbalance
                      if target_imbalance is None else target_imbalance),
                int(app_cfg.serve_policy_cooldown_ticks
                    if cooldown_ticks is None else cooldown_ticks),
                script=(app_cfg.serve_policy_script
                        if policy_script is None else policy_script))
            if not (self.policy.min_shards <= self.shards
                    <= self.policy.max_shards):
                raise ValueError(
                    f"shards={self.shards} is outside the elastic "
                    f"envelope [{self.policy.min_shards}, "
                    f"{self.policy.max_shards}] "
                    "(ANOMOD_SERVE_POLICY_MIN/MAX_SHARDS)")
        self.policy_wall_s = 0.0
        #: spans resident in the replay states policy migrations moved
        #: (the bench elasticity block's "migration spans" volume)
        self.policy_migrated_spans = 0
        self._peak_shards = self.shards
        self._policy_events: List[dict] = []
        self._policy_prev_chunks: Optional[List[int]] = None
        self._policy_prev_shed = 0
        #: retired shard runners' cumulative books (scale-down keeps
        #: them so the report's canonical dispatch counts — and its
        #: wall legs — still cover the whole run)
        self._retired_runners: List[dict] = []
        #: tenant-state residency (ANOMOD_SERVE_STATE): "device" keeps
        #: each shard's tenant states in its runner's device-resident
        #: pool (lane folds = on-device scatter-adds in dispatch order,
        #: pinned BIT-identical to the host seam); "host" is the
        #: per-tenant numpy seam.  The mesh plane manages its own
        #: sharded state, so the pool cannot apply there: forcing
        #: "device" with a mesh is refused (auto degrades to host).
        _state = state if state is not None else app_cfg.serve_state
        if _state not in ("auto", "host", "device"):
            raise ValueError(f"unknown serve state mode {_state!r} "
                             "(auto|host|device)")
        if mesh is not None:
            if _state == "device":
                raise ValueError(
                    "the mesh plane manages its own sharded state; "
                    "a device state pool cannot apply "
                    "(ANOMOD_SERVE_STATE=host or auto)")
            _state = "host"
        self.serve_state = "device" if _state == "auto" else _state
        #: tenant-state tiering (ANOMOD_SERVE_TIER_HOT > 0; anomod.
        #: serve.tiering): cold tenants demote out of the device pool
        #: into a host warm tier (and past the warm budget, a
        #: content-addressed disk cold tier), re-admitting transparently
        #: on their next drained batch — pool bytes track the HOT set
        #: while the registered fleet scales to millions.  The mesh
        #: plane keeps state outside the snapshot seams, the multimodal
        #: sidecar's modality planes have no demotion copier, and the
        #: deferred-commit tick would demote states with uncommitted
        #: in-flight folds at tick end — tiering auto-disables on all
        #: three (an explicit request is refused): the policy idiom.
        _tier_hot = (app_cfg.serve_tier_hot if tier_hot is None
                     else int(tier_hot))
        if tier_hot is not None and _tier_hot < 0:
            raise ValueError("tier_hot must be >= 0 (0 = tiering off)")
        if _tier_hot > 0 and (mesh is not None or multimodal
                              or self.async_commit):
            if tier_hot is not None:
                raise ValueError(
                    "state tiering demotes tenants through the "
                    "bucket-runner snapshot seams; "
                    + ("the mesh plane manages its own sharded state"
                       if mesh is not None else
                       "the multimodal sidecar planes are not covered "
                       "by the demotion copier" if multimodal else
                       "the deferred-commit tick leaves folds in "
                       "flight at the demotion point")
                    + " (ANOMOD_SERVE_TIER_HOT=0)")
            _tier_hot = 0
        self.tier_hot = int(_tier_hot)
        self.tier_demote_after = int(
            app_cfg.serve_tier_demote_after if tier_demote_after is None
            else tier_demote_after)
        if self.tier_demote_after < 1:
            raise ValueError("tier_demote_after must be >= 1 tick")
        self.tier_warm_bytes = int(
            app_cfg.serve_tier_warm_bytes if tier_warm_bytes is None
            else tier_warm_bytes)
        if self.tier_warm_bytes < 0:
            raise ValueError("tier_warm_bytes must be >= 0")
        _tier_cold = (app_cfg.serve_tier_cold_dir if tier_cold_dir is None
                      else tier_cold_dir)
        self.tier_cold_dir = (Path(_tier_cold).expanduser()
                              if _tier_cold else None)
        self.tier_prefetch = int(app_cfg.serve_tier_prefetch
                                 if tier_prefetch is None
                                 else tier_prefetch)
        if not 1 <= self.tier_prefetch <= 256:
            raise ValueError("tier_prefetch must be in [1, 256]")
        self._tier = None
        #: a cold-promoting tenant's drained batches, parked exactly
        #: one tick (the deterministic tier_miss deferral) — flushed
        #: FIRST at the next tick's scoring gate, in park order
        self._tier_parked: Dict[int, list] = {}
        self.tier_wall_s = 0.0
        if self.tier_hot:
            from anomod.serve.tiering import TierPlane
            self._tier = TierPlane(
                self.tier_hot, self.tier_demote_after,
                self.tier_warm_bytes, self.tier_cold_dir,
                self.tier_prefetch,
                slot_nbytes=self.cfg.sw
                * (N_FEATS + self.cfg.n_hist_buckets) * 4)
        _buckets = (buckets if buckets is not None
                    else app_cfg.serve_buckets)
        self._proc_registry = obs.get_registry()
        #: the performance observatory (ANOMOD_PERF, anomod.obs.perf):
        #: per-shard dispatch-lifecycle recorders ride the runners'
        #: fused submit/retire path (staged / submitted / materialized
        #: / folded / slot-refilled event timestamps), drain at the
        #: tick barrier in shard order (the fold_verdicts idiom), feed
        #: the overlap-bubble analyzer, and ride the flight journal's
        #: ``perf`` VARIANT key.  A pure read-side consumer: every
        #: decision is byte-identical with recording on or off
        #: (pinned).  The mesh plane manages its own dispatch, so the
        #: timeline records nothing there (the runner path is idle).
        self.perf = bool(app_cfg.perf if perf is None else perf)
        self.perf_max_events = int(app_cfg.perf_max_events)
        self.perf_events: list = []      # retained timeline (bounded)
        self.perf_events_recorded = 0
        self.perf_events_dropped = 0
        self.perf_headroom_s = 0.0
        self.perf_wait_s = 0.0
        self._perf_pending: list = []    # drains of retired runners
        self._perf_tick_doc: Optional[dict] = None
        self._perf_recs: list = []
        if self.perf:
            from anomod.obs.perf import PerfRecorder
            self._perf_recs = [PerfRecorder(s)
                               for s in range(self.shards)]
            # metric handles only when the plane is live (the RCA
            # discipline: a perf-off run must not register permanently-
            # zero series)
            self._obs_perf_events = obs.counter(
                "anomod_perf_events_total")
            self._obs_perf_dropped = obs.counter(
                "anomod_perf_dropped_events_total")
            self._obs_fold_wait = obs.counter(
                "anomod_serve_fold_wait_seconds_total")
            self._obs_headroom = obs.counter(
                "anomod_serve_overlap_headroom_seconds_total")
        #: the fleet census observatory (ANOMOD_CENSUS, anomod.obs.
        #: census): every ANOMOD_CENSUS_EVERY-th tick (and always at
        #: run end) the coordinator takes a deterministic resident-
        #: bytes census of every plane (state pools, lane scratch,
        #: admission queues/registries, SLO digests, RCA evidence,
        #: recorder retentions — shapes and container lengths, never
        #: an RSS wall) plus the hot-set/Zipf census, exported as
        #: registry gauges, new ServeReport fields and the flight
        #: journal's ``census`` VARIANT key.  A pure read-side
        #: consumer: every decision is byte-identical with the census
        #: on or off (pinned).
        self.census = bool(app_cfg.census if census is None else census)
        self.census_every = int(app_cfg.census_every
                                if census_every is None else census_every)
        if self.census_every < 1:
            raise ValueError("census_every must be >= 1 tick")
        self._census_tracker = None
        self._census_tick_doc: Optional[dict] = None
        self.census_ticks = 0
        self.census_hot_set: Dict[str, object] = {}
        self.census_resident: Dict[str, object] = {}
        self.census_peak_bytes = 0
        self.census_wall_s = 0.0
        self._census_reconciled = True
        if self.census or self.tier_hot:
            # the tracker also runs under a census-off TIERED engine:
            # its last-served/EWMA bookkeeping is the demotion policy's
            # input (coldest_candidates — the eviction preview promoted
            # to policy); the census DRAIN stays gated on self.census
            from anomod.obs.census import CensusTracker
            self._census_tracker = CensusTracker(
                app_cfg.census_decay_ticks,
                app_cfg.census_coldest_k, self.census_every)
        if self.census:
            # metric handles only when the plane is live (the RCA/perf
            # discipline: a census-off run must not register
            # permanently-zero series)
            self._obs_census = {
                "total": obs.gauge("anomod_census_resident_bytes"),
                "pool": obs.gauge("anomod_census_pool_bytes"),
                "scratch": obs.gauge("anomod_census_scratch_bytes"),
                "admission": obs.gauge("anomod_census_admission_bytes"),
                "slo": obs.gauge("anomod_census_slo_bytes"),
                "rca": obs.gauge("anomod_census_rca_bytes"),
                "recorder": obs.gauge("anomod_census_recorder_bytes"),
                "registered": obs.gauge(
                    "anomod_census_registered_tenants"),
                "resident": obs.gauge("anomod_census_resident_tenants"),
                "hot": obs.gauge("anomod_census_hot_tenants"),
                "occupancy": obs.gauge(
                    "anomod_census_slot_occupancy_fraction"),
            }
            self._obs_census_ticks = obs.counter(
                "anomod_census_ticks_total")
        #: worker execution (ANOMOD_SERVE_WORKER): "thread" (the
        #: default, the byte-parity oracle) keeps shard workers as
        #: threads of this interpreter; "process" moves each shard's
        #: WHOLE scoring plane — detectors, replay states, its
        #: BucketRunner, its metrics registry — into a spawn-context
        #: worker process (anomod.serve.procshard) behind the same
        #: ShardWorker seam, so N shards score on N interpreters
        #: instead of time-slicing one GIL.  Each child executes its
        #: slice through the SAME _score_shard code (a 1-shard
        #: sub-engine over its owned tenants), so states / alerts /
        #: SLO / shed and the canonical flight journal are
        #: byte-identical to the thread engine (pinned).  Planes that
        #: share coordinator memory with the score plane cannot cross
        #: the process boundary — the mesh plane, the multimodal
        #: sidecar, the deferred-commit seam, state tiering's demotion
        #: copier and the perf/census observatories — so process mode
        #: auto-degrades to thread under any of them (an explicit
        #: request is refused): the policy/state idiom.
        _worker = (app_cfg.serve_worker if worker is None
                   else str(worker).strip().lower() or "thread")
        if _worker not in ("thread", "process"):
            raise ValueError(f"unknown serve worker mode {_worker!r} "
                             "(thread|process)")
        if _worker == "process":
            blocker = (
                "the mesh plane manages its own sharded dispatch"
                if mesh is not None else
                "the multimodal sidecar planes share coordinator memory"
                if multimodal else
                "the deferred-commit seam keeps folds in flight inside "
                "one interpreter" if self.async_commit else
                "state tiering's demotion copier reads the pool "
                "in-process" if self.tier_hot else
                "the perf observatory rides the runners in-process"
                if self.perf else
                "the census walks resident planes in-process"
                if self.census else None)
            if blocker is not None:
                if worker is not None:
                    raise ValueError(
                        "process shard workers own their score plane "
                        "in a separate interpreter; " + blocker +
                        " (ANOMOD_SERVE_WORKER=thread)")
                _worker = "thread"
        self.worker_mode = _worker
        self._worker_start_timeout_s = float(
            app_cfg.serve_worker_start_timeout_s)
        #: per-shard chaos fault fired-counts, retained from the last
        #: barrier reply — a respawned worker process resumes its
        #: faults' repeat budgets where the dead one left them (a
        #: one-shot crash fault must not re-trip on recovery
        #: re-execution just because the crash emptied the child)
        self._chaos_fired: Dict[int, list] = {}
        if self.worker_mode == "process":
            # process workers run the sharded machinery at every count
            # (mirrors + command barriers even at 1 shard), exactly the
            # elastic engines' discipline
            self._use_workers = True
        #: tick-barrier fold discipline (ANOMOD_SERVE_FOLD): per-tick
        #: cross-shard merges (registry counter/gauge deltas, t-digest
        #: centroid sets, leg/perf/verdict records) serialize as
        #: "sparse" touched-key deltas (the default — barrier cost
        #: follows ACTIVE tenants, not registered fleet size) or
        #: "dense" full walks (the payload oracle the sparse win is
        #: measured against), combined through a deterministic binary
        #: fold tree in fixed (shard, seq) order either way.  Scrape
        #: output is pinned byte-identical across the two; only the
        #: payload bytes move (counted in fold_payload_bytes).
        _fold = (app_cfg.serve_fold if fold is None
                 else str(fold).strip().lower() or "sparse")
        if _fold not in ("dense", "sparse"):
            raise ValueError(f"unknown serve fold mode {_fold!r} "
                             "(dense|sparse)")
        self.fold_mode = _fold
        #: structural bytes the tick-barrier registry folds shipped
        #: (anomod.obs.registry.delta_nbytes — deterministic, box-
        #: independent accounting, NOT pickle lengths)
        self.fold_payload_bytes = 0
        self._obs_fold_payload = (
            obs.counter("anomod_serve_fold_payload_bytes_total")
            if self._use_workers else None)
        #: the runner recipe a policy-time scale-up rebuilds from (the
        #: same arguments every initial shard runner got)
        self._runner_kw = dict(lane_buckets=lane_buckets,
                               pipeline=self.pipeline,
                               native_stage=native,
                               state=self.serve_state)
        self._buckets_arg = _buckets
        if self._use_workers:
            from anomod.serve.shard import plan_shards
            self.shard_of = plan_shards(self.specs, self.shards,
                                        self.capacity_spans_per_s)
            if self.worker_mode == "process":
                # the runners live IN the worker processes; the
                # coordinator keeps per-shard mirrors serving every
                # runner fact its planes read (flight header buckets,
                # leg walls, policy chunk signals, report stats) from
                # the children's barrier replies.  Registry deltas
                # arrive pre-serialized over the pipe, so there are no
                # coordinator-side shard registries to fold from.
                from anomod.serve.procshard import RunnerMirror
                self._shard_regs = []
                self._runners = [
                    RunnerMirror(self.cfg, _buckets,
                                 lane_buckets=lane_buckets,
                                 native_stage=native,
                                 state=self.serve_state)
                    for _ in range(self.shards)]
            else:
                # each shard owns a full scoring plane: its own runner
                # (own jitted executables + pinned scratch slots)
                # recording into its OWN registry — zero cross-thread
                # contention on the dispatch hot path; the coordinator
                # folds shard registries into the process registry at
                # the tick barrier (obs.Registry.fold_from)
                self._shard_regs = [
                    obs.Registry(enabled=self._proc_registry.enabled)
                    for _ in range(self.shards)]
                owned = [sum(1 for t in self.shard_of.values() if t == s)
                         for s in range(self.shards)]
                # with tiering on, each shard's pool sizes to its share
                # of the HOT capacity, not its registered ownership
                # (demotion returns slots; the pool's doubling growth
                # covers transients between demote steps)
                self._runners = [
                    BucketRunner(self.cfg, _buckets, registry=reg,
                                 pool_slots=max(min(owned[s],
                                                    self.tier_hot)
                                                if self.tier_hot
                                                else owned[s], 1),
                                 perf=(self._perf_recs[s] if self.perf
                                       else None),
                                 **self._runner_kw)
                    for s, reg in enumerate(self._shard_regs)]
            self._fold_state = [dict() for _ in range(self.shards)]
            self.runner = self._runners[0]
        else:
            # the inline engine owns every tenant on shard 0: keep the
            # placement map EMPTY (every read is `.get(tid, 0)`) instead
            # of materializing an O(registered) dict — the tiering PR's
            # O(hot-set) registry contract
            self.shard_of = {}
            self.runner = BucketRunner(self.cfg, _buckets,
                                       lane_buckets=lane_buckets,
                                       pipeline=self.pipeline,
                                       native_stage=native,
                                       state=self.serve_state,
                                       pool_slots=max(
                                           min(len(self.specs),
                                               self.tier_hot)
                                           if self.tier_hot
                                           else len(self.specs), 1),
                                       perf=(self._perf_recs[0]
                                             if self.perf else None))
            self._runners = [self.runner]
        self._workers = None
        #: online RCA (ANOMOD_SERVE_RCA): when a tenant's detector fires
        #: inside a tick, incremental GNN culprit inference runs over
        #: that tenant's live service graph (anomod.serve.rca) on the
        #: shard that OWNS the tenant, verdicts folding at the barrier
        #: in enqueue order — a pure read-side consumer of the alert
        #: stream, so detector states / alerts / admission / SLO / shed
        #: are byte-identical with RCA on or off.
        self.rca = bool(app_cfg.serve_rca if rca is None else rca)
        if self.rca and not self.score:
            raise ValueError("online RCA consumes the detectors' alert "
                             "stream; it needs score=True")
        self.rca_budget = int(app_cfg.serve_rca_budget
                              if rca_budget is None else rca_budget)
        if self.rca_budget < 1:
            raise ValueError("rca_budget must be >= 1 run per tick")
        self._rca_planes: list = []
        self._rca_seen: Dict[int, int] = {}
        self._rca_queue: "collections.deque" = collections.deque()
        self._rca_seq = 0
        self.rca_verdicts: list = []
        self.rca_wall_s = 0.0
        # metric handles only when the plane is live: an RCA-off run
        # must not register permanently-zero RCA series in the scrape
        # journal / exports
        self._rca_slo = None
        if self.rca:
            self._rca_slo = _TenantSLO("anomod_serve_rca_seconds")
            self._obs_rca_queued = obs.counter(
                "anomod_serve_rca_queued_total")
            from anomod.serve.rca import OnlineRCA, RcaRunner
            _rca_buckets = (rca_buckets if rca_buckets is not None
                            else app_cfg.serve_rca_buckets)
            _topk = int(app_cfg.serve_rca_topk if rca_topk is None
                        else rca_topk)
            _windows = int(app_cfg.serve_rca_windows
                           if rca_windows is None else rca_windows)
            # one plane per shard (shard-private runner + registry, the
            # BucketRunner discipline); the inline 1-shard plane records
            # into the process registry directly.  Process workers keep
            # ONE coordinator-resident plane regardless of shard count:
            # evidence buffering is documented coordinator-side (rca.py
            # — buffer content is shard-count-invariant there), which is
            # also what lets the evidence survive a worker-process crash
            # exactly as it survives a thread crash.
            _regs = (self._shard_regs
                     if self._use_workers and self.worker_mode == "thread"
                     else [self._proc_registry])
            #: the RCA-plane recipe a policy-time scale-up rebuilds from
            self._rca_kw = dict(buckets=_rca_buckets, topk=_topk,
                                windows=_windows)
            self._rca_planes = [
                OnlineRCA(self.services, self.cfg.window_us, self.t0_us,
                          RcaRunner(_rca_buckets, registry=reg),
                          topk=_topk, windows=_windows)
                for reg in _regs]
        # tracing is ON by default, gated on the one telemetry switch
        # (ANOMOD_OBS_ENABLED) so "telemetry off" means off end to end;
        # pass an explicit Tracer to force it on regardless
        if tracer is None and obs.get_registry().enabled:
            from anomod.utils.tracing import Tracer
            tracer = Tracer("anomod-serve")
        self.tracer = tracer
        self._det_kw = dict(baseline_windows=baseline_windows,
                            z_threshold=z_threshold,
                            consecutive=consecutive, min_count=min_count)
        # per-tenant detector/replay state, built lazily at first served
        # batch (a fleet of mostly-idle tenants must not pay T dead
        # planes up front)
        self.multimodal = bool(multimodal)
        self.testbed = testbed
        #: pushed log/metric/api events per modality (multimodal mode)
        self.modality_events: Dict[str, int] = {}
        self._tenant_replay: Dict[int, object] = {}
        self._tenant_det: Dict[int, object] = {}
        self._shared_sharded_fn = None
        self._slo: Dict[int, _TenantSLO] = _LazySLO()
        self._credit = 0.0
        #: widest batch ever served — the legitimate overdraw envelope
        #: the per-tick credit clamp must respect (a >budget batch's debt
        #: persists across idle ticks; forgiving it would forge capacity)
        self._max_served_batch = 0
        self.serve_wall_s = 0.0
        #: per-tick serve-wall samples (one float per tick, bounded by
        #: the run's tick count) — the ``raw_wall_s`` sample list the
        #: bench ``perf`` block commits and `anomod perf diff`
        #: bootstraps over; wall clock, never a decision input
        self.tick_walls: List[float] = []
        self.n_spans_served = 0
        # self-scrape plumbing (anomod.obs): cached handles for the tick
        # loop, plus a per-tick registry scrape on the VIRTUAL clock so a
        # seeded run's telemetry timeline is deterministic and exports
        # bin cleanly into detector windows
        self._registry = obs.get_registry()
        self._obs_tick = obs.histogram("anomod_serve_tick_seconds")
        self._obs_ticks = obs.counter("anomod_serve_ticks_total")
        self._obs_tenants = obs.gauge("anomod_serve_active_tenants")
        # one scrape per virtual second (not per tick): ~5 samples per
        # detector window at the default 5 s width — plenty for the
        # self-scrape z statistics — at a fraction of the per-tick cost
        self._scrape_every = max(1, int(round(1.0 / self.clock.tick_s)))
        #: black-box flight recorder (ANOMOD_FLIGHT, anomod.obs.flight):
        #: every tick journals its admission decisions, staged dispatch
        #: plan, alert/RCA digests and (at the ANOMOD_FLIGHT_DIGEST_EVERY
        #: cadence) a crc32 tenant-state digest into a bounded ring — the
        #: deterministic record `anomod audit` replays and bisects
        #: against.  A pure read-side consumer: every decision above is
        #: byte-identical with the recorder on or off.
        self.flight = bool(app_cfg.flight if flight is None else flight)
        self.flight_recorder = None
        self._flight_dump_dir = app_cfg.flight_dump_dir
        self._flight_dumped = False
        if self.flight:
            from anomod.obs.flight import (FlightRecorder, config_snapshot,
                                           versions)
            self.flight_recorder = FlightRecorder(
                {"engine": {
                    "n_tenants": len(self.specs),
                    "n_services": len(self.services),
                    "capacity_spans_per_s": self.capacity_spans_per_s,
                    "tick_s": self.clock.tick_s,
                    "max_backlog": self.max_backlog,
                    "buckets": list(self.runner.buckets),
                    "lane_buckets": list(self.runner.lane_buckets),
                    "shards": self.shards,
                    "pipeline": self.pipeline,
                    "serve_state": self.serve_state,
                    "fused": self._fused,
                    "score": self.score,
                    "rca": self.rca,
                    "native_staging": any(r.native_stage
                                          for r in self._runners),
                    "multimodal": self.multimodal,
                    "policy": (self.policy.mode
                               if self.policy is not None else "off"),
                    "perf": self.perf,
                    "census": self.census,
                    "async_commit": self.async_commit,
                    "tier_hot": self.tier_hot,
                    "drain_engine": self.admission.drain_engine,
                    # worker topology: which execution seam scored the
                    # run (thread|process) and which barrier-fold
                    # discipline shipped its metrics (dense|sparse) —
                    # recorded RESOLVED so `anomod audit replay`
                    # re-executes under the same seams
                    "worker": self.worker_mode,
                    "fold": self.fold_mode,
                 },
                 "config": config_snapshot(),
                 "versions": versions()},
                max_ticks=flight_max_ticks,
                digest_every=flight_digest_every)
            #: the brownout ladder's restore point: level 2 coarsens
            #: the live digest cadence 4x, relaxing back to this
            self._flight_digest_base = self.flight_recorder.digest_every
            self._flight_prev_tot = None
            self._flight_prev_legs = None
            self._flight_alert_seen: Dict[int, int] = {}
            self._flight_alert_total = 0
            self._flight_score_crc = 0
            self._flight_rca_seen = 0
            self._flight_rca_crc = 0
        #: scripted serve-plane fault injection (ANOMOD_SERVE_CHAOS,
        #: anomod.serve.chaos) — off by default; a script string or a
        #: prebuilt ServeChaos aims the paper's fault taxonomy at the
        #: framework itself (worker crashes, score-path exceptions,
        #: stalls, pool-put failures) at deterministic (tick, shard,
        #: phase) points.
        _chaos = app_cfg.serve_chaos if chaos is None else chaos
        if isinstance(_chaos, str):
            if _chaos.strip():
                from anomod.serve.chaos import ServeChaos
                _chaos = ServeChaos(_chaos)
            else:
                _chaos = None
        self._chaos = _chaos
        if self._chaos is not None:
            # a fault aimed at a shard this engine doesn't have can
            # never inject — WARN loud (the never-a-silent-no-op
            # contract), but do not refuse: `anomod audit replay
            # --shards 1` deliberately re-executes a 2-shard chaos
            # journal at 1 shard, where the extra faults are inert and
            # the canonical journal still matches (the no-score-gap
            # contract makes every leg equal fault-free).  The CLI's
            # `anomod serve --chaos` validates the range HARD — a typo
            # there is a user error, not a forensic override.
            reachable = (self.policy.max_shards
                         if self.policy is not None else self.shards)
            bad = sorted({f.shard for f in self._chaos.faults
                          if f.kind != "surge" and f.shard >= reachable})
            if bad:
                import warnings
                warnings.warn(
                    f"chaos script targets shard(s) {bad} but the "
                    f"engine has {reachable} shard(s) (ids 0.."
                    f"{reachable - 1}); those faults will never "
                    "fire", RuntimeWarning, stacklevel=2)
        #: shard supervision (ANOMOD_SERVE_CKPT_EVERY > 0, the default;
        #: anomod.serve.supervise): cadenced tenant-state checkpoints
        #: through the get_state/pool-gather seam + a served-batch
        #: recovery log make any mid-tick shard failure recoverable
        #: with NO score gap — restore, re-execute, byte-identical to
        #: fault-free.  Snapshots are pure reads: a chaos-off
        #: supervised run's decisions are byte-identical to the
        #: unsupervised engine (pinned).  The mesh and multimodal
        #: planes keep state outside the snapshot seams, so supervision
        #: auto-disables there (and an explicit request is refused).
        self.ckpt_every = int(app_cfg.serve_ckpt_every
                              if ckpt_every is None else ckpt_every)
        if self.ckpt_every < 0:
            raise ValueError("ckpt_every must be >= 0 (0 = supervision "
                             "off)")
        if (mesh is not None or self.multimodal) and self.ckpt_every:
            if ckpt_every is not None:
                raise ValueError(
                    "shard supervision cannot checkpoint the "
                    + ("mesh plane's sharded" if mesh is not None
                       else "multimodal sidecar") +
                    " state; run with ckpt_every=0 "
                    "(ANOMOD_SERVE_CKPT_EVERY=0)")
            self.ckpt_every = 0
        self.retries = int(app_cfg.serve_retries if retries is None
                           else retries)
        if self.retries < 1:
            raise ValueError("retries must be >= 1")
        self.retry_backoff_s = float(app_cfg.serve_retry_backoff_s
                                     if retry_backoff_s is None
                                     else retry_backoff_s)
        if self.retry_backoff_s < 0:
            raise ValueError("retry_backoff_s must be >= 0")
        self.max_respawns = int(app_cfg.serve_max_respawns
                                if max_respawns is None else max_respawns)
        if self.max_respawns < 0:
            raise ValueError("max_respawns must be >= 0")
        self._supervisor = None
        if self.ckpt_every:
            from anomod.serve.supervise import ShardSupervisor
            self._supervisor = ShardSupervisor(
                self, ckpt_every=self.ckpt_every, retries=self.retries,
                backoff_s=self.retry_backoff_s,
                max_respawns=self.max_respawns)
        self._last_failures = None

    # -- per-tenant plane construction ------------------------------------

    def _replay_for(self, tenant_id: int):
        got = self._tenant_replay.get(tenant_id)
        if got is None:
            if self.mesh is not None:
                from anomod.parallel.stream import ShardedStreamReplay
                got = ShardedStreamReplay(self.cfg, self.t0_us, self.mesh)
                # every tenant's plane runs the IDENTICAL sharded scan;
                # sharing the first plane's jitted fn object gives the
                # fleet one compile instead of T (a fresh closure per
                # tenant would never hit jax's compile cache, and the
                # T-1 redundant compiles would land inside the measured
                # serving wall)
                if self._shared_sharded_fn is None:
                    self._shared_sharded_fn = got._fn
                else:
                    got._fn = self._shared_sharded_fn
            else:
                runner = self._runners[self.shard_of.get(tenant_id, 0)]
                # first service maps the tenant to its shard's pool slot
                # (device mode); the host seam keeps per-tenant pytrees
                cls = (PooledStreamReplay if runner.pool is not None
                       else BucketedStreamReplay)
                got = cls(self.cfg, self.t0_us, runner)
            self._tenant_replay[tenant_id] = got
        return got

    def _detector_for(self, tenant_id: int):
        got = self._tenant_det.get(tenant_id)
        if got is None:
            if self.multimodal:
                from anomod.stream import MultimodalDetector
                got = MultimodalDetector(self.services, self.cfg,
                                         self.t0_us, testbed=self.testbed,
                                         replay=self._replay_for(tenant_id),
                                         **self._det_kw)
            else:
                from anomod.stream import OnlineDetector
                got = OnlineDetector(self.services, self.cfg, self.t0_us,
                                     replay=self._replay_for(tenant_id),
                                     **self._det_kw)
            self._tenant_det[tenant_id] = got
        return got

    # -- modality sidecar (multimodal mode) -------------------------------

    def offer_modality(self, tenant_id: int, kind: str, batch) -> None:
        """Admit a log/metric/api micro-batch for a tenant.

        Modality planes are per-window host aggregates a fraction the
        span volume — control-plane data.  They bypass the weighted-fair
        span queue and push straight into the tenant's MultimodalDetector
        host planes: a window only CLOSES when a later span is pushed, and
        queued spans can only delay that, so a modality batch admitted at
        arrival is always in place before its window scores.
        """
        if not (self.multimodal and self.score):
            raise ValueError("offer_modality needs multimodal=True and "
                             "score=True")
        det = self._detector_for(tenant_id)
        if kind == "logs":
            n = batch.n_lines
            det.push_logs(batch)
        elif kind == "metrics":
            n = batch.n_samples
            det.push_metrics(batch)
        elif kind == "api":
            n = batch.n_records
            det.push_api(batch)
        else:
            raise ValueError(f"unknown modality kind {kind!r}")
        self.modality_events[kind] = self.modality_events.get(kind, 0) + n

    # -- the state-tiering planes (anomod.serve.tiering) ------------------

    def _tier_gate(self, served: List[QueuedBatch]) -> List[QueuedBatch]:
        """The promotion gate between drain and scoring (synchronous
        tick path only — tiering refuses the deferred-commit engine).
        Returns the list that actually scores this tick: last tick's
        parked batches FIRST in park order (their tenants' prefetches
        join here — the one-tick deferral ending), then this tick's
        drained batches, minus any batch whose tenant is still cold
        (parked + prefetch issued + ONE counted `tier_miss` per
        tenant-tick).  Warm tenants promote synchronously in place."""
        tier = self._tier
        score_list: List[QueuedBatch] = []
        if self._tier_parked:
            parked, self._tier_parked = self._tier_parked, {}
            for tid, batches in parked.items():
                # a supervised restore may have re-installed the tenant
                # from a checkpoint (no longer tiered): its batches
                # still score, the promotion is simply a no-op
                if tid in tier:
                    self._tier_promote(tid, deferred=True)
                score_list.extend(batches)
        fresh = self._tier_parked
        for qb in served:
            tid = qb.tenant_id
            if tid in fresh:
                fresh[tid].append(qb)
            elif tid not in tier:
                score_list.append(qb)
            elif tier.status(tid) == "warm":
                self._tier_promote(tid, deferred=False)
                score_list.append(qb)
            else:
                tier.prefetch(tid)
                fresh[tid] = [qb]
        for tid, batches in fresh.items():
            tier.miss(self.clock.ticks, tid, len(batches),
                      sum(qb.n_spans for qb in batches))
        return score_list

    def _tier_promote(self, tid: int, deferred: bool) -> None:
        """Re-admit one demoted tenant through the official seams: take
        its snapshot from the tier (joining the prefetch future for a
        cold entry), rebuild the pool-resident replay via the
        always-copy restore, and repoint the RETAINED detector at the
        new plane — the ``_move_tenant`` discipline, so re-admission
        cannot shift a scored byte."""
        from anomod.serve.supervise import restore_replay
        snap, det = self._tier.take(self.clock.ticks, tid, deferred)
        rep = self._replay_for(tid)
        restore_replay(rep, snap)
        if det is not None:
            det.replay = rep
            self._tenant_det[tid] = det

    def _tier_demote_step(self) -> None:
        """Decay-driven eviction at tick end: while more than
        ``tier_hot`` tenants are pool-resident, demote the coldest
        residents past ``tier_demote_after`` idle ticks — the census
        ``coldest_candidates`` ordering, the PR-15 eviction preview
        promoted from observed-only to policy.  Tenants with queued
        backlog or parked batches are skipped (a demote would promote
        right back next tick — thrash), so every input is coordinator
        state and the demotion schedule is a pure function of
        seed+config."""
        resident = self._tenant_replay
        n_over = len(resident) - self.tier_hot
        if n_over <= 0:
            return
        from anomod.serve.supervise import snapshot_replay
        tracker = self._census_tracker
        t_idx = self.clock.ticks
        for tid in tracker.coldest_candidates(t_idx, resident):
            idle = t_idx - tracker.last_served[tid]
            if idle < self.tier_demote_after:
                break                  # coldest-first: the rest is hotter
            if (self.admission.tenant_backlog(tid)
                    or tid in self._tier_parked):
                continue
            rep = resident.pop(tid)
            snap = snapshot_replay(rep)
            if hasattr(rep, "release"):
                rep.release()          # hand the pool slot back
            det = self._tenant_det.pop(tid, None)
            self._tier.demote(t_idx, tid, snap, det, idle)
            n_over -= 1
            if n_over <= 0:
                return

    # -- the tick loop ----------------------------------------------------

    def _span(self, name: str, **tags):
        import contextlib
        return (self.tracer.span(name, **tags) if self.tracer is not None
                else contextlib.nullcontext())

    def tick(self, arrivals, modality_arrivals=()) -> List[QueuedBatch]:
        """One virtual tick: admit this tick's arrivals (modality
        sidecar batches first — their windows must be populated before
        any span push can close them), drain up to the tick's capacity
        budget in weighted-fair order, score every drained batch,
        advance the clock.  Returns the served batches.

        Under ANOMOD_SERVE_ASYNC_COMMIT the second half of the tick
        runs the deferred-commit seam instead (``_tick_async_tail``):
        scoring dispatches are issued but not drained, and the
        PREVIOUS tick commits at this tick's barrier — same decisions,
        overlapped walls."""
        t_wall = time.perf_counter()
        now = self.clock.now_s + self.clock.tick_s   # decisions at tick end
        if self._chaos is not None:
            # scripted load surge (the chaos 'surge' kind): a pure
            # function of the tick index, so the amplified arrival
            # stream — and everything downstream of it — is identical
            # on every rerun/replay of the same script, at every shard
            # count, with the elastic policy on or off
            factor = self._chaos.surge_factor(self.clock.ticks)
            if factor > 1:
                arrivals = [(tid, concat_span_batches([spans] * factor))
                            for tid, spans in arrivals]
        if modality_arrivals:
            with self._span("serve.modality"):
                for tenant_id, kind, batch in modality_arrivals:
                    self.offer_modality(tenant_id, kind, batch)
        with self._span("serve.admit"):
            for tenant_id, spans in arrivals:
                # one shared service table per engine: a batch whose ids
                # mean different services would silently corrupt the
                # shared plane rows
                if spans.n_spans and spans.services != self.services:
                    raise ValueError(
                        f"tenant {tenant_id} batch carries a different "
                        "service table than the engine's")
                self.admission.offer(tenant_id, spans, now)
        # capacity credit: unused budget does not bank across idle ticks
        # beyond one tick's worth (no unbounded burst debt)
        budget = self.capacity_spans_per_s * self.clock.tick_s
        self._credit = min(self._credit, 0.0) + budget
        with self._span("serve.drain"):
            served = self.admission.drain(self._credit)
        for qb in served:
            self._credit -= qb.n_spans
        # credit clamp: the residual is physically bounded — at most one
        # tick's unused budget (positive), at most one batch's overdraw
        # (negative) — so anything outside that envelope can only be
        # accumulated float rounding (budget = capacity * tick_s is
        # inexact for most tick widths).  Clamp it, and snap sub-span
        # dust to zero, so a billion-tick run cannot drift phantom
        # capacity or phantom debt into the schedule.  The negative
        # bound uses the widest batch EVER served, not this tick's: a
        # >budget batch's legitimate debt is paid down across several
        # idle ticks, and a floor derived from the (empty) current tick
        # would forgive it mid-repayment.
        for qb in served:
            if qb.n_spans > self._max_served_batch:
                self._max_served_batch = qb.n_spans
        self._credit = min(
            max(self._credit, -max(budget, float(self._max_served_batch))),
            budget)
        if -1e-9 < self._credit < 1e-9:
            self._credit = 0.0
        if self._async:
            # deferred-commit mode: everything above (admission, drain,
            # shed, credit) already ran OVERLAPPED with the previous
            # tick's in-flight XLA work; the tail issues this tick's
            # dispatches and defers their commit to the next barrier
            return self._tick_async_tail(t_wall, now, served)
        # the state-tiering gate (ANOMOD_SERVE_TIER_HOT): any drained
        # tenant the decay plane demoted must be pool-resident before
        # its batches score.  Warm entries re-admit synchronously (a
        # host memcpy through the PR-10 restore seam); cold entries'
        # batches PARK for exactly one tick while the disk fetch runs
        # on the prefetch lane (issued here, joined by the NEXT tick's
        # gate) — a counted, journaled `tier_miss`, never a blocking
        # read in the hot loop.  Only the SCORING list is re-shaped:
        # `served` keeps feeding every admission-time consumer below
        # (SLO, RCA evidence, perf, census, flight, policy), and
        # parked batches score ahead of the next tick's drain in park
        # order, so per-tenant push order — and therefore every final
        # state/alert byte — matches the never-evicted run.
        if self._tier is not None:
            t0 = time.perf_counter()
            with self._span("serve.tier"):
                score_list = self._tier_gate(served)
            self.tier_wall_s += time.perf_counter() - t0
        else:
            score_list = served
        if self._perf_recs:
            # tick-boundary stamp (the workers are quiescent between
            # ticks, so this cross-thread write races nothing): events
            # the dispatch path records below key on this tick index
            for rec_ in self._perf_recs:
                rec_.tick = self.clock.ticks
        if score_list:
            sup = self._supervisor
            if sup is not None:
                # the recovery log must hold this tick's slices BEFORE
                # scoring: a mid-tick shard failure re-executes them.
                # The log holds what SCORES (score_list), not what
                # drained: a parked batch logs at the tick it actually
                # folds, which is the tick a restore must re-execute.
                sup.begin_tick(score_list)
            self._last_failures = None
            try:
                if self._use_workers:
                    with self._span("serve.score_sharded"):
                        self._score_sharded(score_list)
                elif self._fused:
                    with self._span("serve.score_fused"):
                        self._score_fused(score_list)
                else:
                    # ONE unfused definition (chaos injection ordering
                    # included): _score_shard's unfused branch — the
                    # same unification _score_fused got, so original
                    # execution and recovery re-execution can never
                    # inject or score differently
                    self._score_shard(0, score_list)
            except BaseException as e:
                failures = self._last_failures or [(0, e)]
                self._last_failures = None
                if sup is None or not isinstance(e, Exception):
                    # KeyboardInterrupt / SystemExit are the OPERATOR
                    # stopping the run, not a shard fault — recovery
                    # must never absorb them (re-executing ticks after
                    # a Ctrl-C would make the process uninterruptible)
                    raise
                # supervised recovery: respawn + checkpoint restore +
                # deterministic re-execution — the tick completes as if
                # the fault never happened (or degrades loudly:
                # quarantine / migration / propagation)
                with self._span("serve.recover"):
                    sup.recover(failures)
        if self._supervisor is not None:
            self._supervisor.end_tick()
        # per-batch SLO accounting is DEFERRED past scoring in both paths
        # (the latency samples depend only on admission times and the
        # tick clock, so fused and unfused runs record identical values
        # in identical per-tenant order)
        for qb in served:
            self._slo[qb.tenant_id].record(now - qb.enqueued_s)
            self.n_spans_served += qb.n_spans
        if self.rca:
            self._rca_step(now, served)
        # the perf-timeline drain rides INSIDE the measured wall (the
        # bench perf block prices the recorder, never hides it); it
        # runs after the score barrier, so every dispatch of this tick
        # has folded and its record is complete
        self._perf_tick_doc = self._perf_drain() if self.perf else None
        if self._census_tracker is not None:
            # hot-set bookkeeping every tick (O(served)); the full
            # resident-bytes census drains on its cadence, INSIDE the
            # measured wall (the bench census block prices it, never
            # hides it) and after the perf drain so the recorder
            # retentions it counts are this tick's.  The census wall
            # accumulates separately so the bench prices the overhead
            # IN-RUN (census_wall_s / serve_wall_s — the ckpt_wall
            # idiom: exact, immune to this box's A/B leg noise).
            t0 = time.perf_counter()
            self._census_tracker.observe(self.clock.ticks, served)
            self._census_tick_doc = (
                self._census_drain()
                if self.census
                and self._census_tracker.due(self.clock.ticks) else None)
            if self.census:
                self.census_wall_s += time.perf_counter() - t0
            else:
                # the tracker is alive only to feed the tiering decay
                # plane (coldest_candidates): its bookkeeping wall is
                # tiering overhead, never a census price
                self.tier_wall_s += time.perf_counter() - t0
        if self.flight_recorder is not None:
            # the journal entry rides INSIDE the measured wall (the
            # serve_wall_s accumulation below) — the bench's flight
            # overhead leg prices the recorder, never hides it
            self._flight_tick(now, served,
                              time.perf_counter() - t_wall)
        if self.policy is not None:
            # the elastic-policy step runs AFTER this tick's journal
            # record (a scale-down must not remove a runner whose
            # tick-t dispatch deltas have not been journaled yet); its
            # events ride the NEXT record's `scaling` variant key, and
            # its wall lands inside the measured tick wall — the bench
            # elasticity block prices scaling, never hides it
            t0 = time.perf_counter()
            with self._span("serve.policy"):
                self._policy_step(served)
            self.policy_wall_s += time.perf_counter() - t0
        if self._tier is not None:
            # decay-driven demotion rides the tick END — after this
            # tick's journal record (a demoted tenant's tick-t deltas
            # are already journaled; its demote event rides the NEXT
            # record's `tiering` variant key, the scaling-key idiom)
            # and after the policy step (a migration decision saw the
            # live residency map)
            t0 = time.perf_counter()
            with self._span("serve.tier_demote"):
                self._tier_demote_step()
            self.tier_wall_s += time.perf_counter() - t0
        self.clock.advance()
        # telemetry work stays INSIDE the measured wall: the bench's
        # enabled-vs-off overhead number must price the scrape, not
        # hide it
        self._obs_tick.observe(time.perf_counter() - t_wall)
        self._obs_ticks.inc()
        self._obs_tenants.set(len(self._tenant_det)
                              or len(self._tenant_replay))
        if self.clock.ticks % self._scrape_every == 0:
            self._registry.scrape(now_s=now)
        t_tick = time.perf_counter() - t_wall
        self.serve_wall_s += t_tick
        self.tick_walls.append(t_tick)
        return served

    # -- the deferred-commit seam (ANOMOD_SERVE_ASYNC_COMMIT) -------------

    def _tick_async_tail(self, t_wall: float, now: float,
                         served: List[QueuedBatch]) -> List[QueuedBatch]:
        """The deferred-commit second half of one tick.

        Order of operations, and why each placement preserves byte
        parity with the synchronous tick:

        1. SLO accounting moves AHEAD of scoring: the latency samples
           are pure functions of admission times and the tick clock
           (never of scoring results), recorded in the same served
           order — identical values, identical per-tenant sample
           sequence.
        2. THE COMMIT BARRIER (``_commit_deferred``): the PREVIOUS
           tick's in-flight XLA work has been executing under this
           tick's admission/drain/shed/SLO coordinator phases; its
           results are about to be read (folds feed this tick's
           staging), so it commits now, then runs the deferred tick's
           tail (RCA, perf/census drains, flight record, policy)
           against snapshotted inputs.
        3. ISSUE: this tick's fused dispatches stage + submit but do
           NOT drain (``defer=True``); the XLA executes stay in
           flight until the next tick's barrier.  The unfused path
           has no issue/commit seam to split (pushes are synchronous
           host work), so it scores in place and only the tick tail
           defers.
        4. The deferred context snapshots every input the commit tail
           will need — admission totals, backlog, the tick index —
           so the next tick's admission cannot leak into this tick's
           journal or policy view.

        Stage/dispatch-phase faults surface at ISSUE time exactly as
        in the synchronous engine; fold/score/commit-phase faults
        surface one tick later at the barrier, keyed (and recovered)
        at their ORIGIN tick, so chaos scripts and the recovery
        ledger stay deterministic.  Checkpoint ticks force a
        synchronous commit: the supervisor's snapshot must cover this
        tick's folds, or a restore would lose them.
        """
        for qb in served:
            self._slo[qb.tenant_id].record(now - qb.enqueued_s)
            self.n_spans_served += qb.n_spans
        self._commit_deferred()
        if self._perf_recs:
            # tick-boundary stamp, POST-barrier: the workers are
            # quiescent only after the deferred commit has joined
            for rec_ in self._perf_recs:
                rec_.tick = self.clock.ticks
        pending = None
        sup = self._supervisor
        if served:
            if sup is not None:
                # the recovery log must hold this tick's slices BEFORE
                # issue: a barrier-time shard failure re-executes them
                sup.begin_tick(served)
            self._last_failures = None
            try:
                if self._fused:
                    pending = self._dispatch_tick(served)
                elif self._use_workers:
                    with self._span("serve.score_sharded"):
                        self._score_sharded(served)
                else:
                    self._score_shard(0, served)
            except BaseException as e:
                failures = self._last_failures or [(0, e)]
                self._last_failures = None
                if sup is None or not isinstance(e, Exception):
                    # operator interrupts are not shard faults — the
                    # synchronous tick's rule, unchanged
                    raise
                with self._span("serve.recover"):
                    sup.recover(failures)
                # recovery re-executed the tick synchronously (restore
                # + full _score_shard replay): it is already committed
                pending = None
        tot = self.admission.totals()
        t_issue = time.perf_counter()
        self._deferred = {
            "tick": self.clock.ticks,
            "now": now,
            "served": served,
            "pending": pending,
            "tot": tot,
            "backlog": self.admission.backlog_spans,
            "t_issue": t_issue,
            "coord_wall": t_issue - t_wall,
        }
        self.async_ticks += 1
        if sup is not None \
                and (self.clock.ticks + 1) % self.ckpt_every == 0:
            # end_tick() checkpoints on this cadence — force the
            # commit so the snapshot covers this tick's folds (the one
            # tick per ckpt_every that pays the synchronous wait)
            self._commit_deferred()
        if sup is not None:
            sup.end_tick()
        self.clock.advance()
        self._obs_tick.observe(time.perf_counter() - t_wall)
        self._obs_ticks.inc()
        self._obs_tenants.set(len(self._tenant_det)
                              or len(self._tenant_replay))
        if self.clock.ticks % self._scrape_every == 0:
            self._registry.scrape(now_s=now)
        t_tick = time.perf_counter() - t_wall
        self.serve_wall_s += t_tick
        self.tick_walls.append(t_tick)
        return served

    def _commit_deferred(self) -> None:
        """The deferred tick's COMMIT BARRIER (no-op when nothing is
        deferred): drain the in-flight fold/score/commit phases, then
        run the deferred tick's tail — RCA, perf/census drains, flight
        record, elastic policy — against the exact state, and the
        exact snapshotted inputs, the synchronous engine used at that
        tick.  The tail order mirrors the synchronous tick body
        (RCA → perf → census → flight → policy) line for line.  Chaos
        hooks key on the ORIGIN tick, so scripted fold/score/commit
        faults fire — and recover, via the supervisor's origin-keyed
        retry ledger — exactly as scripted even though they surface
        one tick later.  The policy executing here (not at issue)
        keeps the sync ordering guarantee: a scale-down can never
        remove a runner with un-journaled or in-flight work."""
        d = self._deferred
        if d is None:
            return
        self._deferred = None
        t_barrier = time.perf_counter()
        pending = d["pending"]
        if pending is not None and any(pending):
            # the hidden-wait leg: how long the dispatches were left
            # executing under coordinator work before this barrier
            # first read them (`anomod perf diff`'s commit_defer leg)
            self.commit_defer_wall_s += max(0.0,
                                            t_barrier - d["t_issue"])
            if self.perf:
                for r in self._runners:
                    r.mark_deferred(d["t_issue"], t_barrier)
            sup = self._supervisor
            self._last_failures = None
            try:
                if self._use_workers:
                    self._join_commits(pending, d["tick"])
                else:
                    self._commit_shard(0, pending[0], d["tick"])
            except BaseException as e:
                failures = self._last_failures or [(0, e)]
                self._last_failures = None
                if sup is None or not isinstance(e, Exception):
                    raise
                with self._span("serve.recover"):
                    sup.recover(failures, origin_tick=d["tick"])
        now, served = d["now"], d["served"]
        if self.rca:
            self._rca_step(now, served)
        self._perf_tick_doc = self._perf_drain() if self.perf else None
        if self._census_tracker is not None:
            t0 = time.perf_counter()
            self._census_tracker.observe(d["tick"], served)
            self._census_tick_doc = (
                self._census_drain(t_idx=d["tick"])
                if self._census_tracker.due(d["tick"]) else None)
            self.census_wall_s += time.perf_counter() - t0
        if self.flight_recorder is not None:
            self._flight_tick(now, served,
                              d["coord_wall"]
                              + (time.perf_counter() - t_barrier),
                              t_idx=d["tick"], tot=d["tot"])
        if self.policy is not None:
            t0 = time.perf_counter()
            with self._span("serve.policy"):
                self._policy_step(served, tick=d["tick"],
                                  backlog_spans=d["backlog"],
                                  shed_spans=d["tot"].shed_spans)
            self.policy_wall_s += time.perf_counter() - t0

    def _dispatch_tick(self, served: List[QueuedBatch]) -> list:
        """The ISSUE half of one fused tick: stage + submit every
        shard's lane dispatches and return the per-shard pending work
        lists WITHOUT draining — the XLA executes stay in flight until
        the next barrier first reads them.  The sharded path keeps the
        ``_submit_parts`` discipline (per-shard worker threads, shard
        registries folded at the join, first failure re-raised with
        the full failure list parked for the supervisor)."""
        origin = self.clock.ticks
        if not self._use_workers:
            with self._span("serve.issue_tick"):
                return [self._dispatch_shard(0, served, origin)]
        from functools import partial
        parts: List[List[QueuedBatch]] = [[] for _ in range(self.shards)]
        for qb in served:
            parts[self.shard_of[qb.tenant_id]].append(qb)
        self._ensure_workers()
        pending: list = [None] * self.shards

        def _issue(s: int, part: List[QueuedBatch]) -> None:
            pending[s] = self._dispatch_shard(s, part, origin)

        with self._span("serve.issue_tick"):
            submitted = []
            for s, worker in enumerate(self._workers):
                if parts[s]:
                    worker.submit(partial(_issue, s, parts[s]))
                    submitted.append((s, worker))
            failures = []
            for s, worker in submitted:
                try:
                    worker.join()
                except BaseException as e:
                    failures.append((s, e))
        self._fold_shard_registries()
        if failures:
            self._last_failures = failures
            raise failures[0][1]
        return pending

    def _dispatch_shard(self, shard_id: int, served: List[QueuedBatch],
                        origin_tick: Optional[int] = None) -> list:
        """One shard's stage + submit (phases 1-2 of fused scoring)
        with the drain DEFERRED; returns the pending work list
        ``_commit_shard`` completes at the barrier.  Chaos phases
        ``stage`` and ``dispatch`` fire here, at issue time, exactly
        as in the synchronous ``_score_shard``."""
        runner = self._runners[shard_id]
        chaos = self._chaos
        if chaos is not None:
            tick = (self.clock.ticks if origin_tick is None
                    else origin_tick)
            hook = lambda phase: chaos.hit(phase, tick, shard_id)  # noqa: E731
        else:
            hook = None
        if hook is not None:
            hook("stage")
        with self._span("serve.dispatch_shard", shard=shard_id,
                        pipeline=self.pipeline):
            pending = self._stage_pending(served)
            self._dispatch_rounds(pending, runner, chaos_hook=hook,
                                  defer=True)
        return pending

    def _commit_shard(self, shard_id: int, pending: list,
                      origin_tick: int) -> None:
        """One shard's barrier-time completion: drain the deferred
        dispatches (the fold wait the seam hides), then phase 3
        (window scoring).  Chaos phases ``fold`` / ``score`` /
        ``commit`` fire here keyed on the ORIGIN tick — the same
        injection points, tick keys and ordering the synchronous
        ``_score_shard`` gives them."""
        runner = self._runners[shard_id]
        chaos = self._chaos
        if chaos is not None:
            hook = lambda phase: chaos.hit(phase, origin_tick, shard_id)  # noqa: E731
        else:
            hook = None
        try:
            with self._span("serve.commit_shard", shard=shard_id):
                runner.drain_lanes()
        except BaseException:
            # the abort discipline (_dispatch_rounds): a failed commit
            # must not park issued dispatches for a later drain to
            # fold as stale deltas
            runner.abort_lanes()
            raise
        if hook is not None:
            hook("fold")
        self._commit_pending(pending, runner, chaos_hook=hook)
        if hook is not None:
            hook("commit")

    def _join_commits(self, pending: list, origin_tick: int) -> None:
        """Barrier-time sharded commit: each shard with deferred work
        commits on its own worker (the ``_submit_parts`` discipline —
        join all, fold shard registries, park the failure list and
        re-raise the first)."""
        from functools import partial
        self._ensure_workers()
        submitted = []
        for s, worker in enumerate(self._workers):
            if s < len(pending) and pending[s]:
                worker.submit(partial(self._commit_shard, s,
                                      pending[s], origin_tick))
                submitted.append((s, worker))
        failures = []
        for s, worker in submitted:
            try:
                worker.join()
            except BaseException as e:
                failures.append((s, e))
        self._fold_shard_registries()
        if failures:
            self._last_failures = failures
            raise failures[0][1]

    def _rca_step(self, now: float, served: List[QueuedBatch]) -> None:
        """One tick's RCA pass: evidence buffering on the COORDINATOR
        (shard-count-invariant content), then the alert→culprit pass;
        both inside the measured tick wall — RCA rides the serve SLO.
        Pruning floors at each tenant's OLDEST queued alert window, so
        a budget-delayed run still finds its full evidence window in
        the buffer (the determinism contract's "delayed run scores the
        same evidence" clause).  THIS tick's new alerts enqueue BEFORE
        the floor is computed: an alert fired across a traffic gap
        longer than the evidence window would otherwise have its
        pre-gap evidence pruned by the same tick's buffering, before
        its run sees it (the enqueue is _rca_seen-guarded, so
        _rca_tick's own enqueue pass below stays a no-op for these).
        Brownout level >= 1 (the elastic policy's degradation ladder)
        tightens the per-tick RCA budget to one run — the item set and
        verdict CONTENT are budget-invariant (the PR-6 pin); only the
        virtual scoring tick moves."""
        self._rca_enqueue(now)
        floor: Dict[int, int] = {}
        for _, tid, w, _ in self._rca_queue:
            floor[tid] = min(floor.get(tid, w), w)
        for qb in served:
            plane = self._rca_planes[
                self.shard_of.get(qb.tenant_id, 0)
                if len(self._rca_planes) > 1 else 0]
            plane.buffer(qb.tenant_id, qb.spans,
                         keep_window=floor.get(qb.tenant_id))
        self._rca_tick(now, budget=(
            1 if self.policy is not None
            and self.policy.brownout_level >= 1 else None))

    def _score_fused(self, served: List[QueuedBatch]) -> None:
        """Tenant-fused scoring of one tick's drained batches.

        Three phases, each pinned bit-identical to the sequential path:

        1. COALESCE (host): same-tenant batches drained this tick
           concatenate in arrival order into ONE staging per tenant —
           one roll, one split plan, one edge pass instead of per batch.
        2. STACK + DISPATCH: per chunk ROUND (a tenant's own chunks must
           apply in order), same-width staged chunks across tenants run
           as lane-stacked fused dispatches (``runner.run_lanes``), lane
           counts padded to the fixed lane-bucket set.  Tenant states
           gather/scatter through the StreamReplay ``get_state`` /
           ``set_state`` seam; dead pad lanes pass through untouched.
        3. COMMIT (host): per tenant, the detector's post-replay half
           (``note_pushed``) scores newly closed windows exactly as a
           sequential push of the coalesced batch would.

        One definition with the sharded path: this IS ``_score_shard``
        on shard 0 (same phases, same chaos injection points), so the
        inline and sharded engines can never drift apart.
        """
        self._score_shard(0, served)

    def _dispatch_rounds(self, pending: list, runner,
                         chaos_hook=None, defer: bool = False) -> None:
        """Phase 2 of fused scoring (STACK + DISPATCH), shared by the
        inline and sharded paths: per chunk round, same-width staged
        chunks lane-stack into fused dispatches through the runner's
        submit/drain path.  At pipeline depth 1 every dispatch retires
        immediately after issue (the exact synchronous fold order);
        depth > 1 stages round r+1's scratch while round r's XLA
        dispatch is still in flight, folding deltas in dispatch order at
        retire (bit-identical at any depth), drained before window
        scoring.  With the device state pool the retire fold is an
        on-device scatter-add — the replay planes ride the submit path
        at EVERY depth so per-tenant host states never materialize in
        the hot loop."""
        try:
            rnd = 0
            while True:
                groups: Dict[int, List[int]] = {}
                for i, (_, _, _, _, plan) in enumerate(pending):
                    if rnd < len(plan):
                        groups.setdefault(plan[rnd][0], []).append(i)
                if not groups:
                    break
                for width in sorted(groups):
                    runner.submit_lanes(
                        width, [(pending[i][1], pending[i][4][rnd][1])
                                for i in groups[width]])
                rnd += 1
            if chaos_hook is not None:
                # the DISPATCH injection point: submits issued, up to
                # pipeline-1 dispatches in flight — a fault here
                # exercises the abort path below with live in-flight
                # work, the nastiest partial-tick state
                chaos_hook("dispatch")
            if not defer:
                runner.drain_lanes()     # tick-end barrier: folds land
            # defer=True (the async-commit issue path) leaves the
            # in-flight dispatches for _commit_shard's barrier drain;
            # the abort discipline below still owns the failure path
        except BaseException:
            # a failed tick must not park its issued dispatches in the
            # runner: a LATER tick's drain would fold the aborted
            # tick's stale deltas into tenant states with no error
            runner.abort_lanes()
            raise

    def _stage_pending(self, served: List[QueuedBatch]) -> list:
        """Phase 1 of fused scoring (COALESCE + plan), shared by the
        inline and sharded paths: same-tenant batches concatenate in
        arrival order into one staging; returns the ordered
        ``(det, replay, n_spans, w_ret, plan)`` work list."""
        per_tenant: Dict[int, List[QueuedBatch]] = {}
        for qb in served:
            per_tenant.setdefault(qb.tenant_id, []).append(qb)
        pending = []
        for tid, qbs in per_tenant.items():
            batch = qbs[0].spans if len(qbs) == 1 else \
                concat_span_batches([qb.spans for qb in qbs])
            if self.score:
                det = self._detector_for(tid)
                replay = det.replay
            else:
                det = None
                replay = self._replay_for(tid)
            t0 = time.perf_counter()
            rb = det.replay_batch(batch) if det is not None else batch
            w_ret, plan = replay.plan_push(rb)
            if det is not None:
                det.push_wall_s += time.perf_counter() - t0
            pending.append((det, replay, batch.n_spans, w_ret, plan))
        return pending

    def _commit_pending(self, pending: list, runner,
                        chaos_hook=None) -> None:
        """Phase 3 of fused scoring (COMMIT), shared by the inline and
        sharded paths: per tenant, the detector's post-replay half
        scores newly closed windows exactly as a sequential push would —
        with every batch-scorable tenant's window scoring VECTORIZED
        into one pass per closed window
        (anomod.stream.score_closed_windows_batched: the sequential
        scorer's own z core with a leading tenant axis, byte-identical
        alerts/streaks/CUSUM — pinned), fed by one fused device-pool
        gather that materializes only the scored columns.  Modality and
        edge-attributing detectors keep the per-tenant sequential path.
        The wall lands in the ``score`` leg of the serve
        decomposition."""
        from anomod.stream import score_closed_windows_batched
        t0 = time.perf_counter()
        work = []
        for det, replay, n_in, w_ret, plan in pending:
            if det is None:
                continue
            if det.batch_scorable:
                through = det.note_bookkeep(n_in, w_ret)
                rng = (det.scoring_window_range(through)
                       if through is not None else None)
                if rng is not None:
                    work.append((det, rng[0], rng[1]))
            else:
                det.note_pushed(n_in, w_ret)
        if chaos_hook is not None:
            # the SCORE injection point: replay folds committed and
            # window bookkeeping advanced, batched scoring not yet run
            chaos_hook("score")
        if work:
            score_closed_windows_batched(work, _plane_col_gather(work))
        dt = time.perf_counter() - t0
        runner.score_wall_s += dt
        runner._obs_score_s.inc(dt)

    # -- the performance observatory (anomod.obs.perf) --------------------

    def _perf_drain(self) -> dict:
        """Tick-barrier drain of the per-shard dispatch-lifecycle
        recorders: fold in (shard, seq) order, run the overlap-bubble
        analyzer, accumulate the run totals, retain the events
        (bounded — evictions counted, never silent) and return the
        journal-shaped doc the flight record's ``perf`` variant key
        carries — or None when no flight recorder will consume it
        (the rounded event copies would be pure dead allocation inside
        the measured wall)."""
        from anomod.obs.perf import (analyze_events, fold_perf_records,
                                     round_events)
        parts = [self._perf_pending] \
            + [r.drain() for r in self._perf_recs]
        self._perf_pending = []
        events = fold_perf_records(parts)
        stats = analyze_events(events, self.pipeline)
        n = len(events)
        self.perf_events_recorded += n
        self.perf_headroom_s += stats["headroom_s"]
        self.perf_wait_s += stats["wait_s"]
        if n:
            self._obs_perf_events.inc(n)
            self._obs_fold_wait.inc(stats["wait_s"])
            self._obs_headroom.inc(stats["headroom_s"])
        self.perf_events.extend(events)
        over = len(self.perf_events) - self.perf_max_events
        if over > 0:
            del self.perf_events[:over]
            self.perf_events_dropped += over
            self._obs_perf_dropped.inc(over)
        if self.flight_recorder is None:
            return None
        return {"events": round_events(events),
                "headroom_s": round(stats["headroom_s"], 6),
                "wait_s": round(stats["wait_s"], 6)}

    # -- the fleet census observatory (anomod.obs.census) -----------------

    def _census_drain(self, t_idx: Optional[int] = None) -> dict:
        """One tick-barrier census: the deterministic resident-bytes
        walk over every plane (shapes and container lengths only — the
        workers are quiescent at the barrier, so the per-shard pool/
        scratch reads race nothing), the hot-set/Zipf doc, the
        registry gauges, and the journal-shaped record the flight
        ``census`` variant key carries.  A pure read of engine state:
        no clocks, no RNG, no mutation of any decision plane.  The
        deferred-commit barrier passes ``t_idx`` (the ORIGIN tick —
        the live clock has already advanced by barrier time); the
        synchronous tick reads the clock."""
        from anomod.obs.census import collect_resident_bytes
        if t_idx is None:
            t_idx = self.clock.ticks
        planes, by_plane, total, reconciled = \
            collect_resident_bytes(self)
        tracker = self._census_tracker
        hot = tracker.hot_doc(t_idx, len(self.specs),
                              list(self._tenant_replay))
        self.census_ticks += 1
        self._census_reconciled = self._census_reconciled and reconciled
        self.census_peak_bytes = max(self.census_peak_bytes, total)
        self.census_hot_set = hot
        self.census_resident = {
            "total": total, "peak_total": self.census_peak_bytes,
            "by_plane": by_plane,
            "pool_reconciled": self._census_reconciled}
        g = self._obs_census
        g["total"].set(total)
        for plane in ("pool", "scratch", "admission", "slo", "rca"):
            g[plane].set(by_plane.get(plane, 0))
        g["recorder"].set(by_plane.get("flight", 0)
                          + by_plane.get("perf", 0))
        g["registered"].set(len(self.specs))
        g["resident"].set(hot["resident"])
        g["hot"].set(hot["hot_by_decay"].get(
            str(min(tracker.decay_ticks)), 0))
        g["occupancy"].set(hot["occupancy_vs_registered"])
        self._obs_census_ticks.inc()
        return {"tick": t_idx, "planes": planes,
                "total_bytes": total, "pool_reconciled": reconciled,
                "hot": hot}

    # -- the black-box flight recorder (anomod.obs.flight) ----------------

    def _flight_tick(self, now: float, served: List[QueuedBatch],
                     tick_wall_s: float, final: bool = False,
                     t_idx: Optional[int] = None,
                     tot=None) -> None:
        """Journal one tick into the flight recorder.

        The CANONICAL planes hold only seed-determined decisions (the
        parity surface `anomod audit diff` bisects): the admission
        deltas + a crc32 over the served decision set in drain order,
        the staged-chunk counts per width (``stage_plan`` is the one
        staging definition, so the counts are identical at every shard
        count / pipeline depth / residency), the active-plane census +
        the cadenced tenant-state digest, and running digests of the
        alert and RCA-verdict streams.  The VARIANT keys (``walls`` /
        ``topology``) carry the tick's five-leg wall deltas and the
        per-shard leg records, folded at the tick barrier in shard
        order (the ``fold_verdicts`` idiom — every runner's book is
        quiescent here, after the barrier).  ``final=True`` is the
        run-end settlement record: finish() alerts and budget-deferred
        RCA verdicts land in it, and a state digest is forced so every
        journal ends on a full-state parity anchor.

        The deferred-commit barrier passes ``t_idx`` and ``tot``
        snapshots taken at the ORIGIN tick (by barrier time the next
        tick's admission has already mutated the live totals and the
        clock has advanced); the synchronous tick reads them live —
        identical values, so the canonical journal is
        async-invariant."""
        from anomod.obs.flight import crc_text, state_digest
        from anomod.serve.shard import fold_leg_records
        fr = self.flight_recorder
        if t_idx is None:
            t_idx = self.clock.ticks
        if tot is None:
            tot = self.admission.totals()
        prev = self._flight_prev_tot

        def delta(field):
            return getattr(tot, field) - (getattr(prev, field)
                                          if prev is not None else 0)

        crc = 0
        for qb in served:
            crc = crc_text(f"{qb.tenant_id}:{qb.seq}:{qb.n_spans}:"
                           f"{qb.priority}:{qb.enqueued_s!r}", crc)
        admission = {"offered": delta("offered_spans"),
                     "admitted": delta("admitted_spans"),
                     "served": delta("served_spans"),
                     "shed": delta("shed_spans"),
                     "evicted": delta("evicted_batches"),
                     "served_batches": delta("served_batches"),
                     "digest": crc}
        self._flight_prev_tot = tot
        legs = [r.leg_walls() for r in self._runners]
        prev_legs = self._flight_prev_legs or [{} for _ in legs]
        if len(prev_legs) < len(legs):
            # an elastic scale-up appended runners since the last
            # record: the new runners' whole books are this tick's
            # delta (a truncating zip would silently drop their chunks
            # from the canonical dispatch plane)
            prev_legs = prev_legs + [{}] * (len(legs) - len(prev_legs))
        by_width: Dict[int, int] = {}
        chunks = 0
        shard_legs = []
        stage_s = dispatch_s = fold_s = score_s = 0.0
        fused_d = native_staged = 0
        for s, (leg, pleg) in enumerate(zip(legs, prev_legs)):
            pw = pleg.get("by_width", {})
            for w, n in leg["by_width"].items():
                dn = n - pw.get(w, 0)
                if dn:
                    by_width[w] = by_width.get(w, 0) + dn
            dchunks = leg["chunks"] - pleg.get("chunks", 0)
            dstage = leg["stage_s"] - pleg.get("stage_s", 0.0)
            ddisp = leg["dispatch_s"] - pleg.get("dispatch_s", 0.0)
            dfold = leg["fold_s"] - pleg.get("fold_s", 0.0)
            dscore = leg["score_s"] - pleg.get("score_s", 0.0)
            dfused = leg["fused"] - pleg.get("fused", 0)
            dnative = leg["native_staged"] - pleg.get("native_staged", 0)
            chunks += dchunks
            stage_s += dstage
            dispatch_s += ddisp
            fold_s += dfold
            score_s += dscore
            fused_d += dfused
            native_staged += dnative
            shard_legs.append({"shard": s, "chunks": dchunks,
                               "fused": dfused,
                               "native_staged": dnative,
                               "stage_s": round(dstage, 6),
                               "dispatch_s": round(ddisp, 6),
                               "fold_s": round(dfold, 6),
                               "score_s": round(dscore, 6)})
        self._flight_prev_legs = legs
        # the fold plane covers the WHOLE fleet's states: pool-resident
        # replays plus (under tiering) the demoted set, read through
        # the tier's digest shims — warm snapshots by reference, cold
        # entries loaded from disk on digest ticks only.  The merged
        # map is built ONLY when the digest actually runs, so the
        # per-tick cost stays O(resident).
        do_digest = final or fr.digest_tick(t_idx)
        reps = self._tenant_replay
        n_states = len(reps)
        if self._tier is not None and len(self._tier):
            n_states += len(self._tier)
            if do_digest:
                reps = dict(reps)
                for tid_ in self._tier.tids():
                    reps[tid_] = self._tier.state_shim(tid_)
        if do_digest and self.worker_mode == "process":
            # the states live in the children: each ships per-tenant
            # (tid, crc, len) fragments, folded here in global sorted
            # tenant order via crc32_combine — bit-equal to the
            # state_digest walk a thread engine runs (the journal
            # parity anchor survives the process boundary)
            from anomod.obs.flight import fold_digest_parts
            parts = []
            if self._workers is not None:
                for w in self._workers:
                    if not w.alive:
                        continue
                    try:
                        parts.extend(w.call({"op": "digest"})["parts"])
                    except RuntimeError:
                        continue
            digest = fold_digest_parts(parts)
        else:
            digest = state_digest(reps) if do_digest else None
        fold = {"tenants": n_states, "state_digest": digest}
        new_alerts = 0
        crc = self._flight_score_crc
        for tid in sorted(self._tenant_det):
            alerts = getattr(self._tenant_det[tid], "alerts", ())
            seen = self._flight_alert_seen.get(tid, 0)
            for a in alerts[seen:]:
                crc = crc_text(
                    f"{tid}:{a.window}:{a.service}:{a.service_name}:"
                    f"{a.score!r}:{a.z_latency!r}:{a.z_error!r}:"
                    f"{a.z_drop!r}:{a.z_drop_cum!r}:{a.evidence}", crc)
                new_alerts += 1
            self._flight_alert_seen[tid] = len(alerts)
        self._flight_score_crc = crc
        self._flight_alert_total += new_alerts
        score = {"alerts": new_alerts,
                 "alerts_total": self._flight_alert_total,
                 "digest": crc}
        new_verdicts = self.rca_verdicts[self._flight_rca_seen:]
        crc = self._flight_rca_crc
        for v in new_verdicts:
            crc = crc_text(repr(v.to_dict()), crc)
        self._flight_rca_seen = len(self.rca_verdicts)
        self._flight_rca_crc = crc
        rca = {"verdicts": len(new_verdicts),
               "verdicts_total": self._flight_rca_seen,
               "digest": crc}
        rec = {
            "tick": t_idx, "now_s": now,
            "admission": admission,
            "dispatch": {"chunks": chunks,
                         "by_width": {str(w): by_width[w]
                                      for w in sorted(by_width)}},
            "fold": fold, "score": score, "rca": rca,
            "walls": {"tick_s": round(tick_wall_s, 6),
                      "stage_s": round(stage_s, 6),
                      "dispatch_s": round(dispatch_s, 6),
                      "fold_s": round(fold_s, 6),
                      "score_s": round(score_s, 6),
                      "other_s": round(max(0.0, tick_wall_s - stage_s
                                           - dispatch_s - fold_s
                                           - score_s), 6)},
            "topology": {"fused_dispatches": fused_d,
                         "native_staged": native_staged,
                         "shard_legs": fold_leg_records(shard_legs)},
        }
        # recovery events ride the journal's VARIANT tier (the
        # "recovery" key is in FLIGHT_VARIANT_KEYS): what crashed,
        # respawned, quarantined or migrated this tick is forensic
        # topology — the canonical planes above must stay equal to a
        # fault-free run's (the no-score-gap pin), so they never carry
        # recovery marks.  The key is ALWAYS present (usually empty) so
        # every record carries every tier — the self-describing-shape
        # contract the variant-key tests pin.
        rec["recovery"] = (self._supervisor.drain_events()
                           if self._supervisor is not None else [])
        # elastic-policy decisions ride the VARIANT tier too (the
        # "scaling" key in FLIGHT_VARIANT_KEYS): WHAT scaled, when, and
        # which tenants moved is execution topology — the canonical
        # planes stay equal to a static run's (the elastic no-score-gap
        # pin), so scaling marks never touch them.  Always present
        # (usually empty), the recovery-key contract.
        scaling, self._policy_events = self._policy_events, []
        rec["scaling"] = scaling
        # the performance observatory's tick timeline rides the VARIANT
        # tier too (the "perf" key in FLIGHT_VARIANT_KEYS): pure
        # wall-clock event timestamps + the overlap-headroom bound —
        # never the parity surface.  ALWAYS present (empty when the
        # plane is off) — the every-record-carries-every-tier contract.
        perf_doc, self._perf_tick_doc = self._perf_tick_doc, None
        rec["perf"] = perf_doc if perf_doc is not None else \
            {"events": [], "headroom_s": 0.0, "wait_s": 0.0}
        # the fleet census rides the VARIANT tier too (the "census"
        # key in FLIGHT_VARIANT_KEYS): per-shard pool/scratch bytes
        # follow the execution topology, so the key is excluded from
        # the canonical surface — but unlike walls/perf its content is
        # wall-free, so the census stream is byte-equal across
        # same-seed reruns of one topology (pinned).  ALWAYS present
        # (empty off-cadence or with the census off) — the
        # every-record-carries-every-tier contract.
        census_doc, self._census_tick_doc = self._census_tick_doc, None
        rec["census"] = census_doc if census_doc is not None else \
            {"planes": [], "hot": {}}
        # the state-tiering plane rides the VARIANT tier too (the
        # "tiering" key in FLIGHT_VARIANT_KEYS): demote/promote/miss
        # events are wall-free functions of seed+config — byte-equal
        # across same-config reruns (pinned), excluded from the
        # canonical surface only because a `tier_miss` legitimately
        # moves WHICH tick a deferred tenant's fold/score deltas land
        # in vs the never-evicted journal.  Demotions ride the record
        # AFTER their tick (the step runs post-journal — the
        # scaling-key placement); promotions/misses ride their own
        # tick's.  ALWAYS present (empty with tiering off) — the
        # every-record-carries-every-tier contract.
        rec["tiering"] = (self._tier.drain_events()
                          if self._tier is not None else [])
        if final:
            rec["final"] = True
        fr.record(rec)
        # alert-triggered forensic bundle (ANOMOD_FLIGHT_DUMP_DIR): the
        # first tick that raises a new alert publishes ONE ring+scrape+
        # trace bundle — once per run, so a noisy fleet cannot turn the
        # dump dir into a write amplifier
        if (self._flight_dump_dir is not None and new_alerts
                and not self._flight_dumped):
            self._flight_dumped = True
            from pathlib import Path as _P
            fr.forensic(
                _P(self._flight_dump_dir)
                / f"flight_forensic_tick{t_idx:06d}.json",
                registry=self._registry, tracer=self.tracer,
                reason=f"{new_alerts} new alert(s) at tick {t_idx}")

    # -- the sharded (scale-out) score path -------------------------------

    def _make_worker(self, s: int):
        """One shard worker of the engine's configured kind — the ONE
        construction point the engine, the supervisor's respawn path
        and the elastic policy's scale edges all route through, so a
        process-mode engine can never accidentally respawn a thread."""
        if self.worker_mode == "process":
            from anomod.serve.procshard import ProcShardWorker
            return ProcShardWorker(
                s, self._procshard_init(s),
                start_timeout_s=self._worker_start_timeout_s)
        from anomod.serve.shard import ShardWorker
        return ShardWorker(s)

    def _procshard_init(self, s: int) -> dict:
        """The picklable init payload for shard ``s``'s worker process:
        every knob the child's 1-shard sub-engine needs, passed
        RESOLVED from this engine's values (never re-read from the
        child's env — the child must not drift onto a different
        configuration than the engine that spawned it)."""
        owned = [spec for spec in self.specs
                 if self.shard_of.get(spec.tenant_id, 0) == s]
        chaos_script = None
        if self._chaos is not None:
            chaos_script = getattr(self._chaos, "script", None)
        return {"shard_id": s,
                "specs": owned,
                "services": self.services,
                "cfg": self.cfg,
                "t0_us": self.t0_us,
                "capacity_spans_per_s": self.capacity_spans_per_s,
                "tick_s": self.clock.tick_s,
                "buckets": tuple(self._runners[s].buckets),
                "lane_buckets": tuple(self._runners[s].lane_buckets),
                "max_backlog": self.max_backlog,
                "score": self.score,
                "fuse": self.fuse,
                "pipeline": self.pipeline,
                "native": bool(self._runners[s].native_stage),
                "state": self.serve_state,
                "det_kw": dict(self._det_kw),
                "registry_enabled": bool(self._proc_registry.enabled),
                "chaos_script": chaos_script,
                "chaos_fired": self._chaos_fired.get(s)}

    def _ensure_workers(self) -> None:
        if self._workers is None:
            self._workers = [self._make_worker(s)
                             for s in range(self.shards)]
            return
        if all(w.alive for w in self._workers):
            return
        errs = []
        if self.worker_mode == "process":
            # replace ONLY the dead children: a live worker process
            # holds its shard's tenant states — closing it to respawn a
            # sibling would destroy healthy state.  (A respawned child
            # starts EMPTY: the supervisor's checkpoint/replay path
            # restores it; an unsupervised process engine loses the
            # dead shard's states, exactly like a real process crash
            # without checkpoints — docs/SERVING.md.)
            for s, w in enumerate(self._workers):
                if not w.alive:
                    try:
                        w.close()
                    except BaseException as e:  # noqa: BLE001
                        errs.append(e)
                    self._workers[s] = self._make_worker(s)
        else:
            for w in self._workers:   # no leaked threads on respawn
                try:
                    w.close()
                except BaseException as e:  # noqa: BLE001
                    errs.append(e)
            self._workers = [self._make_worker(s)
                             for s in range(self.shards)]
        if errs:
            # close() re-raises a deferred (never-joined) task
            # error; every sibling still closed before it surfaces
            raise errs[0]

    def close(self) -> None:
        """Stop the shard worker threads (idempotent; the engine remains
        usable — the next sharded tick respawns them).  Every worker
        closes before a deferred task error propagates (the join_all
        discipline).  A close with an uncommitted deferred tick ABORTS
        it (the _dispatch_rounds discipline): in-flight dispatches must
        never park in the runners for a later drain to fold as stale
        deltas — run() always commits before closing, so this only
        fires on direct tick()+close() API use."""
        if self._deferred is not None:
            self._deferred = None
            for r in self._runners:
                r.abort_lanes()
        if self._tier is not None:
            self._tier.close()         # join/park the prefetch lane
        if self._workers is not None:
            errs = []
            for w in self._workers:
                try:
                    w.close()
                except BaseException as e:      # noqa: BLE001
                    errs.append(e)
            self._workers = None
            if errs:
                raise errs[0]

    def _score_sharded(self, served: List[QueuedBatch]) -> None:
        """Fan one tick's drained batches out to the shard workers by
        tenant ownership and join at the barrier.

        Each worker scores only tenants it owns — detectors, replay
        states, the shard's BucketRunner (pipelined: up to
        ``pipeline - 1`` fused dispatches in flight while the next
        stages) and its metrics registry are all shard-private, so the
        score path takes no cross-shard lock.  Per-tenant results are
        bit-identical to the 1-shard engine: the same coalesced batches
        stage the same chunk plans, lane deltas are bit-equal to
        single-lane dispatches regardless of which lanes share a stack,
        and folds apply in round order per tenant.  After the barrier
        the coordinator folds each shard registry into the process
        registry (counter deltas + shard-labeled gauges)."""
        parts: List[List[QueuedBatch]] = [[] for _ in range(self.shards)]
        for qb in served:
            parts[self.shard_of[qb.tenant_id]].append(qb)
        self._ensure_workers()
        failures = (self._submit_parts_proc(parts)
                    if self.worker_mode == "process"
                    else self._submit_parts(parts))
        if failures:
            # attribution for the supervisor (which shards failed);
            # unsupervised engines keep the historical contract — the
            # barrier completed, registries folded, first error raises
            self._last_failures = failures
            raise failures[0][1]

    def _submit_parts(self, parts: List[List[QueuedBatch]],
                      origin_tick: Optional[int] = None) -> list:
        """Fan per-shard slices out to the workers and join at the
        barrier.  The barrier COMPLETES before anything propagates
        (raising at the first failed join would desynchronize sibling
        done-events — the join_all contract) and the shard registries
        fold either way (counters fold by delta, so folding what the
        shards did record is correct whether or not the tick
        succeeded).  Returns ``[(shard_id, exc), ...]`` in shard
        order."""
        from functools import partial
        submitted = []
        for s, worker in enumerate(self._workers):
            if parts[s]:
                worker.submit(partial(self._score_shard, s, parts[s],
                                      origin_tick))
                submitted.append((s, worker))
        failures = []
        for s, worker in submitted:
            try:
                worker.join()
            except BaseException as e:    # noqa: BLE001 — re-raised
                failures.append((s, e))
        self._fold_shard_registries()
        return failures

    def _submit_parts_proc(self, parts: List[List[QueuedBatch]],
                           origin_tick: Optional[int] = None) -> list:
        """The process-worker barrier: fan per-shard slices out as
        ``score`` commands (all sends complete before any recv — the
        children overlap), then drain the replies in shard order.
        Every reply folds its mirror/alert/registry payloads whether or
        not the slice succeeded (counters the child DID record are
        correct either way — the _submit_parts contract), and a shipped
        error reconstructs into the same exception surface the thread
        worker raises at join().  Returns ``[(shard_id, exc), ...]``
        in shard order."""
        from anomod.serve.procshard import rebuild_exc
        tick = (self.clock.ticks if origin_tick is None else origin_tick)
        submitted = []
        for s, worker in enumerate(self._workers):
            if parts[s]:
                try:
                    worker.send({"op": "score", "served": parts[s],
                                 "origin_tick": tick,
                                 "fold": self.fold_mode})
                    submitted.append((s, worker, None))
                except BaseException as e:      # noqa: BLE001
                    submitted.append((s, worker, e))
        failures = []
        deltas = []
        for s, worker, send_err in submitted:
            if send_err is not None:
                failures.append((s, send_err))
                continue
            try:
                rep = worker.recv()
            except BaseException as e:          # noqa: BLE001
                failures.append((s, e))
                continue
            self._apply_shard_reply(s, rep)
            if rep.get("reg_delta") is not None:
                deltas.append((s, rep["reg_delta"]))
            if rep.get("error") is not None:
                failures.append((s, rebuild_exc(rep["error"])))
        self._fold_shard_registries(deltas=deltas)
        return failures

    def _apply_shard_reply(self, s: int, rep: dict) -> None:
        """Fold one child reply's coordinator-mirror payloads: the
        runner's cumulative book/walls, newly materialized tenant
        planes, the alert suffix protocol, and the shard's chaos
        fired-counts (respawn budget continuity).  Registry deltas are
        NOT applied here — the caller batches them through the fold
        tree (_fold_shard_registries) so payload accounting and combine
        order stay one code path."""
        from anomod.serve.procshard import DetMirror
        if "book" in rep:
            self._runners[s].apply(rep)
        for tid in rep.get("resident_new", ()):
            if tid not in self._tenant_replay:
                # residency stub: the states live in the child; the
                # coordinator only needs the resident SET (census off
                # and tiering off in process mode — nothing walks the
                # values)
                self._tenant_replay[tid] = None
        for tid in rep.get("det_new", ()):
            if tid not in self._tenant_det:
                self._tenant_det[tid] = DetMirror()
        for tid, base, new in rep.get("alerts", ()):
            det = self._tenant_det.get(tid)
            if det is None:
                det = self._tenant_det[tid] = DetMirror()
            del det.alerts[base:]
            det.alerts.extend(new)
        if rep.get("chaos_fired") is not None:
            self._chaos_fired[s] = list(rep["chaos_fired"])

    def _fold_shard_registries(self, final: bool = False,
                               shards: Optional[List[int]] = None,
                               deltas: Optional[list] = None) -> None:
        """The tick barrier's registry merge, one code path for both
        worker kinds: collect per-shard ``(shard, delta)`` payloads —
        snapshotted locally from the shard registries (thread mode) or
        handed in pre-serialized off the pipe (process mode) — combine
        them through the deterministic binary fold tree in fixed
        (shard, seq) order, apply to the process registry, and account
        the structural payload bytes (the sparse-vs-dense win
        criterion: exact and box-independent)."""
        from anomod.obs.registry import delta_nbytes
        from anomod.serve.shard import fold_tree
        if deltas is None:
            idx = range(self.shards) if shards is None else shards
            deltas = []
            for s in idx:
                d = self._shard_regs[s].delta_snapshot(
                    self._fold_state[s], mode=self.fold_mode,
                    final=final)
                deltas.append((s, d))
        parts = [[(s, d)] for s, d in deltas if d is not None]
        merged = fold_tree(parts, lambda a, b: a + b)
        if not merged:
            return
        nbytes = 0
        for s, d in merged:
            self._proc_registry.apply_delta(d, shard=str(s))
            nbytes += delta_nbytes(d)
        self.fold_payload_bytes += nbytes
        if self._obs_fold_payload is not None and nbytes:
            self._obs_fold_payload.inc(nbytes)

    # -- the supervisor's process-mode seams (supervise.py routes here
    # -- when worker_mode == "process"; states live in the children) ------

    def _snapshot_tenants_proc(self) -> dict:
        """Checkpoint gather over the pipes: each child runs the SAME
        snapshot_replay/snapshot_detector seams locally and ships
        ``tid -> (replay_snap, det_snap)``; a dead child's tenants are
        simply absent (their state died with it)."""
        tenants: dict = {}
        if self._workers is None:
            return tenants
        for w in self._workers:
            if not w.alive:
                continue
            try:
                rep = w.call({"op": "snapshot"})
            except RuntimeError:
                continue
            tenants.update(rep["tenants"])
        return tenants

    def _drop_shard_proc(self, s: int) -> None:
        """Restore teardown half, process flavor: clear the
        coordinator's resident stubs/alert mirrors for shard ``s`` and
        tell the child (when one is listening — a freshly respawned
        child is already empty) to drop its planes."""
        for tid in [t for t in list(self._tenant_replay)
                    if self.shard_of.get(t, 0) == s]:
            self._tenant_replay.pop(tid, None)
            self._tenant_det.pop(tid, None)
        if self._workers is not None and self._workers[s].alive:
            try:
                self._workers[s].call({"op": "drop"})
            except RuntimeError:
                pass                 # died on the way out: child gone

    def _restore_book(self, s: int, book: dict) -> None:
        """Install a checkpoint's runner book on shard ``s`` — the
        coordinator mirror AND (process mode) the child's live runner,
        so re-executed slices advance from checkpoint counts in both
        places (the double-count guard must hold where the dispatches
        actually happen)."""
        self._runners[s].book_restore(book)
        if (self.worker_mode == "process" and self._workers is not None
                and self._workers[s].alive):
            try:
                self._workers[s].call({"op": "book_restore",
                                       "book": book})
            except RuntimeError:
                pass

    def _install_tenant_proc(self, tid: int, snap: tuple) -> None:
        """Reinstall one checkpointed tenant into its owning child and
        rewind the coordinator's alert mirror to the checkpoint view
        (restore_detector rewinds the real alert list the same way in
        thread mode)."""
        from anomod.serve.procshard import DetMirror
        rep_snap, det_snap = snap
        s = self.shard_of.get(tid, 0)
        self._ensure_workers()
        rep = self._workers[s].call({"op": "install_tenant", "tid": tid,
                                     "replay": rep_snap,
                                     "det": det_snap})
        self._apply_shard_reply(s, rep)
        self._tenant_replay.setdefault(tid, None)
        if det_snap is not None:
            det = self._tenant_det.get(tid)
            if det is None:
                det = self._tenant_det[tid] = DetMirror()
            det.alerts[:] = list(det_snap.get("alerts", ()))

    def _exec_slice_proc(self, s: int, slice_: list, tick: int) -> None:
        """Supervised re-execution of one logged slice inside shard
        ``s``'s child — the chaos injector keys on ``origin_tick``
        exactly as the thread path does, and a shipped failure raises
        here so the recovery loop charges the slice."""
        from anomod.serve.procshard import rebuild_exc
        w = self._workers[s]
        w.send({"op": "score", "served": slice_, "origin_tick": tick,
                "fold": self.fold_mode})
        rep = w.recv()
        self._apply_shard_reply(s, rep)
        if rep.get("reg_delta") is not None:
            self._fold_shard_registries(deltas=[(s, rep["reg_delta"])])
        if rep.get("error") is not None:
            raise rebuild_exc(rep["error"])

    def _score_shard(self, shard_id: int, served: List[QueuedBatch],
                     origin_tick: Optional[int] = None) -> None:
        """One shard's slice of one tick's served batches — on that
        shard's worker thread in the sharded engine, inline on the
        1-shard fused engine, and during supervised recovery the
        re-execution entry point (``origin_tick`` then names the tick
        the slice was drained on, which is what the chaos injector keys
        on — a re-execution of an older slice must not re-trip a fault
        scripted for the current tick).

        Fused: coalesce + plan (identical at every shard count), then
        pipelined lane-stacked dispatches through the shard's runner
        (``submit_lanes`` — readback and state folds defer behind the
        in-flight window), drained before window scoring.  Unfused: one
        detector/replay push per batch, in served order."""
        runner = self._runners[shard_id]
        chaos = self._chaos
        if chaos is not None:
            tick = self.clock.ticks if origin_tick is None else origin_tick
            hook = lambda phase: chaos.hit(phase, tick, shard_id)  # noqa: E731
        else:
            hook = None
        if hook is not None:
            hook("stage")
        if self._fused:
            # the shard/pipeline tags ride the span into the chrome
            # export's args, and the span opens ON the worker thread —
            # so a sharded trace's Perfetto lanes group by shard
            # instead of collapsing onto the coordinator's lane
            with self._span("serve.score_shard", shard=shard_id,
                            pipeline=self.pipeline):
                pending = self._stage_pending(served)
                self._dispatch_rounds(pending, runner, chaos_hook=hook)
                if hook is not None:
                    hook("fold")
                self._commit_pending(pending, runner, chaos_hook=hook)
            if hook is not None:
                hook("commit")
        else:
            # the unfused path has no phase structure, but every
            # scripted fault must still FIRE somewhere (a silently
            # never-injected fault reads as "the engine survived"):
            # the remaining phases collapse onto the slice's two real
            # boundaries — dispatch before the pushes, fold/score/
            # commit after them (post-mutation, the harder case)
            if hook is not None:
                hook("dispatch")
            for qb in served:
                with self._span("serve.score"):
                    if self.score:
                        self._detector_for(qb.tenant_id).push(qb.spans)
                    else:
                        self._replay_for(qb.tenant_id).push(qb.spans)
            if hook is not None:
                hook("fold")
                hook("score")
                hook("commit")

    # -- the elastic-policy plane (anomod.serve.policy) --------------------

    def _policy_step(self, served: List[QueuedBatch],
                     tick: Optional[int] = None,
                     backlog_spans: Optional[int] = None,
                     shed_spans: Optional[int] = None) -> None:
        """One tick-boundary policy evaluation on the coordinator:
        fold this tick's CANONICAL signals into the policy EWMAs,
        collect its decisions, execute them through the live-migration
        seams, and journal what actually happened.  Every input is a
        function of seed+config (served spans, staged-chunk books,
        backlog, shed — never a wall clock), so the whole scaling
        schedule replays from the flight header.  The deferred-commit
        barrier passes ``tick`` / ``backlog_spans`` / ``shed_spans``
        snapshots taken at the ORIGIN tick (by barrier time the next
        tick's admission has already mutated the live values); the
        synchronous tick reads them live — identical numbers, so the
        scaling schedule is async-invariant."""
        from anomod.serve.policy import TickSignals
        if tick is None:
            tick = self.clock.ticks
        if backlog_spans is None:
            backlog_spans = self.admission.backlog_spans
        if shed_spans is None:
            shed_spans = self.admission.totals().shed_spans
        served_by_tenant: Dict[int, int] = {}
        for qb in served:
            served_by_tenant[qb.tenant_id] = \
                served_by_tenant.get(qb.tenant_id, 0) + qb.n_spans
        chunks = [r.n_dispatches for r in self._runners]
        prev = self._policy_prev_chunks
        if prev is None:
            prev = [0] * len(chunks)
        elif len(prev) != len(chunks):
            prev = (prev + [0] * len(chunks))[:len(chunks)]
        self.policy.observe(TickSignals(
            tick=tick, served_by_tenant=served_by_tenant,
            per_shard_chunks=[c - p for c, p in zip(chunks, prev)],
            backlog_spans=backlog_spans,
            max_backlog=self.max_backlog,
            shed_delta=shed_spans - self._policy_prev_shed,
            budget_spans=self.capacity_spans_per_s
            * self.clock.tick_s))
        self._policy_prev_shed = shed_spans
        topology_changed = False
        for d in self.policy.decide(tick, self.shards):
            topology_changed |= self._execute_decision(d, tick)
        if topology_changed and self._supervisor is not None:
            # the recovery log must never span a topology change: the
            # checkpoint's per-runner books and tenant placements are
            # indexed by the CURRENT shard set, so every scaling action
            # ends on a fresh baseline
            self._supervisor.note_topology_change()
        self._policy_prev_chunks = [r.n_dispatches
                                    for r in self._runners]
        if self.flight_recorder is None and self._policy_events:
            # no journal to drain into: the counters/report carry the
            # story, and the event list must not grow with a
            # flight-off run's episode count
            self._policy_events.clear()

    def _execute_decision(self, d: dict, tick: int) -> bool:
        """Execute one policy decision against the live envelope;
        returns whether the shard topology changed.  A decision the
        envelope refuses (scripted ``up`` at the ceiling) is journaled
        as skipped — never silently dropped, never counted."""
        pol = self.policy
        act = d["action"]
        if act == "up":
            if self.shards >= pol.max_shards:
                self._policy_events.append(
                    {"kind": "scale_up", "tick": tick,
                     "skipped": f"at max_shards={pol.max_shards}"})
                return False
            moved = self._scale_up()
            self._peak_shards = max(self._peak_shards, self.shards)
            self._policy_events.append(
                {"kind": "scale_up", "tick": tick,
                 "from": self.shards - 1, "to": self.shards,
                 "tenants": len(moved), "moved": moved})
            pol.note_executed("up", tick, migrated=len(moved),
                              shards=self.shards)
            return True
        if act == "down":
            if self.shards <= pol.min_shards:
                self._policy_events.append(
                    {"kind": "scale_down", "tick": tick,
                     "skipped": f"at min_shards={pol.min_shards}"})
                return False
            moved = self._scale_down()
            self._policy_events.append(
                {"kind": "scale_down", "tick": tick,
                 "from": self.shards + 1, "to": self.shards,
                 "tenants": len(moved), "moved": moved})
            pol.note_executed("down", tick, migrated=len(moved),
                              shards=self.shards)
            return True
        if act == "rebalance":
            from anomod.serve.policy import plan_rebalance
            dead = (self._supervisor.dead_shards
                    if self._supervisor is not None else ())
            moves = plan_rebalance(self.shard_of, self.shards,
                                   self.specs, pol.rate_ewma,
                                   self.capacity_spans_per_s,
                                   int(d.get("k", 1)), dead=dead)
            if not moves:
                pol.note_noop(tick)
                self._policy_events.append(
                    {"kind": "rebalance", "tick": tick,
                     "skipped": "already balanced"})
                return False
            imb_before = pol.imbalance()
            for tid, dst in moves:
                self._move_tenant(tid, dst)
            self._policy_events.append(
                {"kind": "rebalance", "tick": tick,
                 "tenants": len(moves), "moved": [t for t, _ in moves],
                 "imbalance_ewma": round(imb_before, 4)})
            pol.note_executed("rebalance", tick, migrated=len(moves))
            return True
        # brownout: degrade (or restore) the auxiliary planes — RCA
        # budget at level >= 1 (applied at the _rca_tick call site),
        # flight digest cadence at level >= 2 (applied here)
        level = max(0, min(int(d.get("level", 1)),
                           self._policy_max_brownout()))
        prev = pol.brownout_level
        if level == prev:
            # a redundant scripted step is journaled like any other
            # clamped decision — an auditor must be able to tell
            # "evaluated, already there" from "never executed"
            self._policy_events.append(
                {"kind": "brownout", "tick": tick,
                 "skipped": f"already at level {prev}"})
            return False
        self._apply_brownout(level)
        self._policy_events.append(
            {"kind": "brownout", "tick": tick, "from": prev,
             "to": level})
        pol.note_executed("brownout", tick, level=level)
        return False

    def _policy_max_brownout(self) -> int:
        from anomod.serve.policy import MAX_BROWNOUT_LEVEL
        return MAX_BROWNOUT_LEVEL

    def _apply_brownout(self, level: int) -> None:
        fr = self.flight_recorder
        if fr is not None:
            fr.digest_every = (self._flight_digest_base * 4
                               if level >= 2
                               else self._flight_digest_base)

    def _scale_up(self) -> List[int]:
        """Grow the shard set by one worker and migrate the rendezvous
        DELTA — only tenants the new candidate wins under the grown
        set move (minimal disruption: everything else keeps its owner,
        so the migration bill is ~1/(n+1) of the fleet, not a full
        reshuffle).  Returns the moved tenant ids."""
        from functools import partial

        from anomod.serve.shard import rendezvous_shard
        s = self.shards
        moved = [tid for tid in sorted(self.shard_of)
                 if rendezvous_shard(tid, s + 1) == s]
        if self.worker_mode == "process":
            # the new shard's runner lives in its child; the
            # coordinator grows a mirror cloned from shard 0's
            # resolved static facts (perf/RCA planes never branch:
            # perf is refused in process mode and RCA keeps its one
            # coordinator-resident plane)
            from anomod.serve.procshard import RunnerMirror
            m0 = self._runners[0]
            self._runners.append(RunnerMirror(
                self.cfg, m0.buckets, lane_buckets=m0.lane_buckets,
                native_stage=m0.native_stage, state=m0.state_mode))
            self._fold_state.append(dict())
            self.shards = s + 1
            if self._workers is not None:
                w = self._make_worker(s)
                self._workers.append(w)
                # warm the new child's compile grid inside the measured
                # tick wall (scaling is real work the bench elasticity
                # block prices), off the coordinator thread
                rep = w.call({"op": "warm"})
                self._apply_shard_reply(s, rep)
            for tid in moved:
                self._move_tenant(tid, s)
            return moved
        reg = obs.Registry(enabled=self._proc_registry.enabled)
        prec = None
        if self.perf:
            from anomod.obs.perf import PerfRecorder
            prec = PerfRecorder(s)
            prec.tick = self.clock.ticks
            self._perf_recs.append(prec)
        runner = BucketRunner(self.cfg, self._buckets_arg, registry=reg,
                              pool_slots=max(len(moved), 1), perf=prec,
                              **self._runner_kw)
        self._shard_regs.append(reg)
        self._runners.append(runner)
        self._fold_state.append(dict())
        if self.rca:
            from anomod.serve.rca import OnlineRCA, RcaRunner
            self._rca_planes.append(OnlineRCA(
                self.services, self.cfg.window_us, self.t0_us,
                RcaRunner(self._rca_kw["buckets"], registry=reg),
                topk=self._rca_kw["topk"],
                windows=self._rca_kw["windows"]))
        self.shards = s + 1
        if self._workers is not None:
            self._workers.append(self._make_worker(s))
            # warm the new runner's compile grid on its own worker —
            # inside the measured tick wall (scaling is real work the
            # bench elasticity block prices), off the serving threads
            self._workers[s].submit(partial(self._warm_shard, s))
            self._workers[s].join()
        else:
            self._warm_shard(s)
        for tid in moved:
            self._move_tenant(tid, s)
        return moved

    def _scale_down(self) -> List[int]:
        """Drain the highest shard through the live-migration seam and
        retire its worker.  The victim is ALWAYS the tail id, so the
        candidate set stays ``range(shards)`` and the rendezvous key
        stays the one placement definition; its tenants re-place by
        rendezvous over the shrunk set — exactly the tenants whose
        owner changed, nobody else moves.  The victim's cumulative
        book/walls are retained so the report still covers the whole
        run, and its registry takes a final drain fold.  Returns the
        moved tenant ids."""
        from anomod.serve.shard import rendezvous_shard
        s = self.shards - 1
        dead = (self._supervisor.dead_shards
                if self._supervisor is not None else set())
        candidates = [x for x in range(s) if x not in dead]
        moved = sorted(tid for tid, sh in self.shard_of.items()
                       if sh == s)
        for tid in moved:
            self._move_tenant(
                tid, rendezvous_shard(tid, s, candidates=candidates))
        errs = []
        if self.worker_mode == "process" and self._workers is not None:
            # drain the dying child's registry BEFORE retiring it —
            # after close there is no pipe left to ask
            w = self._workers[s]
            if w.alive:
                try:
                    rep = w.call({"op": "reg_delta",
                                  "fold": self.fold_mode, "final": True})
                    if rep.get("delta") is not None:
                        self._fold_shard_registries(
                            deltas=[(s, rep["delta"])], final=True)
                except RuntimeError:
                    pass                      # crashed mid-drain: close
        if self._workers is not None:
            try:
                self._workers.pop().close()
            except BaseException as e:        # noqa: BLE001 — re-raised
                errs.append(e)
        if self.worker_mode != "process":
            self._proc_registry.fold_from(self._shard_regs[s],
                                          self._fold_state[s],
                                          shard=str(s), final=True)
        self._retired_runners.append(_runner_stats(self._runners[s]))
        if self.perf and len(self._perf_recs) > s:
            # the victim's undrained lifecycle events fold into the
            # next tick's drain (the retained-book discipline: the
            # timeline covers the whole run, not the final topology)
            self._perf_pending.extend(self._perf_recs.pop().drain())
        self._runners.pop()
        if self._shard_regs:                  # empty in process mode
            self._shard_regs.pop()
        self._fold_state.pop()
        if self.rca and len(self._rca_planes) > s:
            self._rca_planes.pop()
        if self._supervisor is not None:
            self._supervisor.dead_shards.discard(s)
        self.shards = s
        if errs:
            raise errs[0]
        return moved

    def _move_tenant(self, tid: int, dst: int) -> None:
        """Live-migrate one tenant between shards through the official
        state seams: gather (always-copy) via ``snapshot_replay``,
        reinstall on the new owner via ``restore_replay``, repoint the
        detector's replay plane, and carry the RCA evidence buffers.
        Tenant bits are placement-invariant (the PR-5/8 pins), so the
        move cannot shift a single scored byte."""
        src = self.shard_of.get(tid, 0)
        if src == dst:
            return
        if self.worker_mode == "process":
            # gather/reinstall over the pipes, through the SAME
            # snapshot seams (supervise.snapshot_replay/restore_replay
            # run inside the children): take from the src child, put
            # into the dst child.  The coordinator's resident stubs
            # and alert mirrors carry over unchanged — alerts already
            # mirrored, and the dst child re-anchors its ship base at
            # install time.
            self.shard_of[tid] = dst
            if self._workers is not None:
                self._ensure_workers()
                taken = self._workers[src].call(
                    {"op": "take_tenant", "tid": tid})
                snap = taken.get("snap")
                if snap is not None:
                    rep_snap, det_snap = snap
                    self.policy_migrated_spans += int(rep_snap["n_spans"])
                    put = self._workers[dst].call(
                        {"op": "put_tenant", "tid": tid,
                         "replay": rep_snap, "det": det_snap})
                    self._apply_shard_reply(dst, put)
            return
        rep = self._tenant_replay.pop(tid, None)
        self.shard_of[tid] = dst
        if rep is not None:
            from anomod.serve.supervise import (restore_replay,
                                                snapshot_replay)
            snap = snapshot_replay(rep)
            self.policy_migrated_spans += int(snap["n_spans"])
            if hasattr(rep, "release"):
                rep.release()            # hand the pool slot back
            new_rep = self._replay_for(tid)
            restore_replay(new_rep, snap)
            det = self._tenant_det.get(tid)
            if det is not None:
                det.replay = new_rep
        if self.rca and len(self._rca_planes) > max(src, dst):
            self._rca_planes[src].move_tenant_evidence(
                self._rca_planes[dst], tid)

    # -- the online alert→culprit pass (anomod.serve.rca) -----------------

    def _rca_enqueue(self, now: float) -> None:
        """Queue one RCA item per (tenant, batch of new alerts) — the
        ``_rca_seen`` high-water mark makes repeated calls within a
        tick no-ops, so the tick path may enqueue early (ahead of
        evidence-buffer pruning) without double-queuing."""
        for tid in sorted(self._tenant_det):
            det = self._tenant_det[tid]
            n = len(det.alerts)
            seen = self._rca_seen.get(tid, 0)
            if n > seen:
                w = max(a.window for a in det.alerts[seen:])
                self._rca_queue.append((self._rca_seq, tid, w, now))
                self._rca_seq += 1
                self._obs_rca_queued.inc()
                self._rca_seen[tid] = n

    def _rca_tick(self, now: float, budget: Optional[int] = None) -> None:
        """Enqueue one item per (tenant, tick with new alerts), keyed by
        the NEWEST new alert window — the verdict's evidence lookback
        reaches BACK from its anchor, so anchoring at the newest window
        covers every alert of the batch (a min anchor would exclude a
        same-batch later-window alert from the evidence, and a pre-onset
        noise alert sharing the batch with the first real fault alert
        would mis-anchor the verdict before the onset).  Then drain up
        to ``budget`` items (default: the per-tick ``rca_budget``) —
        inline on the 1-shard engine, on the owning shard workers
        otherwise, verdicts folding at the barrier in enqueue order
        either way.  A tenant that keeps alerting while earlier items
        still queue gets a NEW item per tick-batch of alerts (never
        absorbed into a stale one), so the item set — and therefore the
        verdict stream — is identical at any budget; the budget moves
        only ``scored_s``."""
        self._rca_enqueue(now)
        if not self._rca_queue:
            return
        burst = min(budget if budget is not None else self.rca_budget,
                    len(self._rca_queue))
        items = [self._rca_queue.popleft() for _ in range(burst)]
        with self._span("serve.rca"):
            if self._use_workers and self.worker_mode == "process":
                # process mode keeps ONE coordinator-resident plane
                # (evidence is buffered coordinator-side, rca.py's
                # shard-count-invariant contract) — the mirrors'
                # alert lists feed it exactly like thread detectors
                folded = []
                self._rca_run_items(self._rca_planes[0], items, folded,
                                    now)
            elif self._use_workers:
                from anomod.serve.shard import fold_verdicts, join_all
                parts: List[list] = [[] for _ in range(self.shards)]
                for it in items:
                    parts[self.shard_of[it[1]]].append(it)
                self._ensure_workers()
                from functools import partial
                results: List[list] = [[] for _ in range(self.shards)]
                submitted = []
                for s, worker in enumerate(self._workers):
                    if parts[s]:
                        worker.submit(partial(self._rca_shard, s, parts[s],
                                              results[s], now))
                        submitted.append(worker)
                join_all(submitted)
                folded = fold_verdicts(results)
            else:
                folded = []
                self._rca_run_items(self._rca_planes[0], items, folded,
                                    now)
        for _, verdict, wall in folded:
            self.rca_verdicts.append(verdict)
            self._rca_slo.record(wall)
            self.rca_wall_s += wall

    def _rca_run_items(self, plane, items: list, out: list,
                       now: float) -> None:
        for seq, tid, w, enq in items:
            det = self._tenant_det.get(tid)
            alerts = det.alerts if det is not None else []
            verdict, wall = plane.run(tid, w, alerts, enqueued_s=enq,
                                      scored_s=now)
            out.append((seq, verdict, wall))

    def _rca_shard(self, shard_id: int, items: list, out: list,
                   now: float) -> None:
        self._rca_run_items(self._rca_planes[shard_id], items, out, now)

    def run(self, traffic, duration_s: float,
            warm: bool = True) -> "ServeReport":
        """Drive the engine from a traffic source for ``duration_s``
        virtual seconds, then close every tenant's last window."""
        if warm and self.mesh is None:
            if self._use_workers and self.worker_mode == "process":
                # the thread discipline, over the pipe: shard 0 warms
                # first and alone (with ANOMOD_JIT_CACHE on it
                # populates the persistent cache for the siblings),
                # then the rest overlap — all sends complete before
                # any recv.  Replies carry each child's compile walls
                # into the coordinator mirrors.
                from anomod.serve.procshard import rebuild_exc
                self._ensure_workers()
                reps: List[Optional[dict]] = [None] * self.shards
                self._workers[0].send({"op": "warm"})
                reps[0] = self._workers[0].recv()
                for s in range(1, self.shards):
                    self._workers[s].send({"op": "warm"})
                for s in range(1, self.shards):
                    reps[s] = self._workers[s].recv()
                for s, rep in enumerate(reps):
                    self._apply_shard_reply(s, rep)
                for rep in reps:
                    if rep.get("error") is not None:
                        raise rebuild_exc(rep["error"])
                if self.rca:
                    # the single coordinator-resident plane (process
                    # mode keeps RCA evidence out of the children)
                    self._rca_planes[0].runner.warm()
            elif self._use_workers:
                # warm shard 0 FIRST, alone: with ANOMOD_JIT_CACHE on
                # it populates the persistent cache, so the remaining
                # shards' identical-HLO grids (warmed in parallel on
                # their own workers next) are cache reads instead of N
                # concurrent compilers thrashing the host — compiles
                # stay outside the measured wall either way
                from functools import partial

                from anomod.serve.shard import join_all
                self._ensure_workers()
                self._workers[0].submit(partial(self._warm_shard, 0))
                self._workers[0].join()
                for s in range(1, self.shards):
                    self._workers[s].submit(partial(self._warm_shard, s))
                join_all(self._workers[1:])
            else:
                self.runner.warm()               # compiles outside the wall
                if self._fused:
                    self.runner.warm_lanes()
                if self.rca:
                    self._rca_planes[0].runner.warm()
        n_ticks = max(int(round(duration_s / self.clock.tick_s)), 1)
        mod_src = getattr(traffic, "modality_arrivals", None) \
            if self.multimodal else None
        with self._span("serve.run"):
            for _ in range(n_ticks):
                lo = self.clock.now_s
                hi = lo + self.clock.tick_s
                self.tick(traffic.arrivals(lo, hi),
                          mod_src(lo, hi) if mod_src is not None else ())
        if self._deferred is not None:
            # the run-end barrier: the last tick's deferred commit must
            # land before finish() reads any tenant state (its wall
            # joins the serve wall — the seam hides waits, never drops
            # them)
            t0 = time.perf_counter()
            self._commit_deferred()
            self.serve_wall_s += time.perf_counter() - t0
        t_wall = time.perf_counter()
        if self._tier is not None:
            # run-end tier settlement: batches whose one-tick cold
            # deferral crossed the run end still score (through the
            # NORMAL per-tick scoring paths, in park order), and every
            # tiered tenant promotes back to residency — finish() must
            # close the whole fleet's last windows, the report counts
            # the whole fleet's alerts, and the settlement record's
            # forced digest anchors FULL state.  Sorted promotion order
            # keeps the event stream deterministic; the events land in
            # the settlement record's `tiering` key below.
            if self._tier_parked:
                parked, self._tier_parked = self._tier_parked, {}
                leftovers: List[QueuedBatch] = []
                for tid, batches in parked.items():
                    if tid in self._tier:
                        self._tier_promote(tid, deferred=True)
                    leftovers.extend(batches)
                if leftovers:
                    sup = self._supervisor
                    if sup is not None:
                        sup.begin_tick(leftovers)
                    if self._use_workers:
                        self._score_sharded(leftovers)
                    elif self._fused:
                        self._score_fused(leftovers)
                    else:
                        self._score_shard(0, leftovers)
                    if sup is not None:
                        sup.end_tick()
            for tid in sorted(self._tier.tids()):
                self._tier_promote(tid, deferred=False)
        if self.score:
            if self._use_workers and self.worker_mode == "process":
                # the detectors live in the children: fan the finish
                # out over the pipes; replies carry the closing
                # windows' alerts (and registry deltas) back
                self._finish_proc()
            else:
                for det in self._tenant_det.values():
                    det.finish()
        if self.rca:
            # end-of-run settlement: alerts raised by finish() (the last
            # window closing) still get culprits, and anything the
            # per-tick budget deferred drains now — every alert of the
            # run is answered before the report
            self._rca_tick(self.clock.now_s, budget=len(self._tenant_det)
                           + len(self._rca_queue) + 1)
            while self._rca_queue:
                self._rca_tick(self.clock.now_s,
                               budget=len(self._rca_queue))
        self.serve_wall_s += time.perf_counter() - t_wall
        if self.perf:
            # settle any lifecycle events the final drain window left
            # (and feed the settlement record's perf key below)
            self._perf_tick_doc = self._perf_drain()
        if self.census and self._census_tracker is not None:
            # run-end settlement census (the forced-digest idiom):
            # every census-on run ends on a full resident-bytes +
            # hot-set anchor regardless of the cadence, feeding the
            # report fields and the settlement record's census key
            t0 = time.perf_counter()
            self._census_tick_doc = self._census_drain()
            self.census_wall_s += time.perf_counter() - t0
        if self.flight_recorder is not None:
            # run-end settlement record: finish() alerts + drained RCA
            # verdicts land here, and the forced state digest gives every
            # journal a full end-state parity anchor regardless of the
            # per-tick digest cadence
            self._flight_tick(self.clock.now_s, [],
                              time.perf_counter() - t_wall, final=True)
        if self._use_workers:
            # run-end registry fold: shard histograms (lane counts
            # etc.) DRAIN through the Histogram.merge_digest seam — the
            # same way the per-tenant SLO digests already join; drain
            # semantics make a re-run() engine fold its new data only
            if self.worker_mode == "process":
                self._final_fold_proc()
            else:
                self._fold_shard_registries(final=True)
            self.close()
        return self.report(traffic=traffic)

    def _finish_proc(self) -> None:
        """Fan ``Detector.finish()`` out to the shard children.

        A dead (crashed, unsupervised) child is skipped: its
        detectors died with it, exactly like a thread-mode engine
        whose state was lost would have nothing to finish — the
        documented unsupervised-crash degradation.
        """
        if self._workers is None:
            return
        sent = []
        for s, w in enumerate(self._workers):
            if not w.alive:
                continue
            try:
                w.send({"op": "finish", "fold": self.fold_mode})
                sent.append((s, w))
            except RuntimeError:
                continue
        from anomod.serve.procshard import rebuild_exc
        deltas, first_err = [], None
        for s, w in sent:
            try:
                rep = w.recv()
            except RuntimeError:
                continue
            self._apply_shard_reply(s, rep)
            if rep.get("reg_delta") is not None:
                deltas.append((s, rep["reg_delta"]))
            if rep.get("error") is not None and first_err is None:
                first_err = rebuild_exc(rep["error"])
        self._fold_shard_registries(deltas=deltas)
        if first_err is not None:
            raise first_err

    def _final_fold_proc(self) -> None:
        """Run-end registry drain over the pipes (final=True folds)."""
        if self._workers is None:
            return
        deltas = []
        for s, w in enumerate(self._workers):
            if not w.alive:
                continue
            try:
                rep = w.call({"op": "reg_delta", "fold": self.fold_mode,
                              "final": True})
            except RuntimeError:
                continue
            if rep.get("delta") is not None:
                deltas.append((s, rep["delta"]))
        self._fold_shard_registries(deltas=deltas, final=True)

    def _warm_shard(self, shard_id: int) -> None:
        runner = self._runners[shard_id]
        runner.warm()
        if self._fused:
            runner.warm_lanes()
        if self.rca:
            self._rca_planes[shard_id].runner.warm()

    # -- reporting --------------------------------------------------------

    def alerts_for(self, tenant_id: int,
                   onset_window: Optional[int] = None):
        """A tenant's alert stream; ``onset_window`` filters it through
        the ONE pre-onset-noise eligibility rule (:func:`onset_eligible`
        — shared with the golden fault-detection metrics and the RCA hit
        accounting, so report consumers cannot apply a different rule)."""
        det = self._tenant_det.get(tenant_id)
        alerts = list(det.alerts) if det is not None else []
        if onset_window is not None:
            alerts = onset_eligible_alerts(alerts, onset_window)
        return alerts

    def _fault_detection(self, traffic) -> Optional[dict]:
        faults = getattr(traffic, "faults", None)
        if not faults:
            return None
        win_s = self.cfg.window_us / 1e6
        lat = []
        hits = 0
        for tid, fault in sorted(faults.items()):
            det = self._tenant_det.get(tid)
            onset_w = int(fault.onset_s // win_s)
            fw = None
            if det is not None:
                # only alerts AT or AFTER the onset can be the fault
                # (onset_eligible — the shared pre-onset-noise rule): a
                # pre-onset noise alert on the culprit service must not
                # count as (negative-latency) detection
                ws = [a.window
                      for a in onset_eligible_alerts(det.alerts, onset_w)
                      if a.service_name == self.services[fault.service]]
                fw = min(ws) if ws else None
            if fw is not None:
                hits += 1
                lat.append(fw - onset_w)
        return {
            "n_fault_tenants": len(faults),
            "n_detected": hits,
            "median_alert_latency_windows":
                (float(np.median(lat)) if lat else None),
        }

    def _rca_hits(self, traffic) -> Tuple[Dict[int, int], int]:
        """Top-k hit counts against the traffic script's injected-fault
        ground truth: per fault tenant, its FIRST onset-eligible verdict
        (triggering alert at/after the onset window — the same
        :func:`onset_eligible` rule the golden fault-detection metrics
        apply) is checked for the culprit in its top-1/3/5.  With
        ``serve_rca_topk`` (or the service table) below 5 the ranking is
        shorter than k and hit@k degrades to hit@len — a conservative
        UNDERSTATEMENT, never an overstatement."""
        faults = getattr(traffic, "faults", None) \
            if traffic is not None else None
        hits = {1: 0, 3: 0, 5: 0}
        eligible = 0
        if not (self.rca and faults):
            return hits, eligible
        win_s = self.cfg.window_us / 1e6
        by_tenant: Dict[int, list] = {}
        for v in self.rca_verdicts:
            by_tenant.setdefault(v.tenant_id, []).append(v)
        for tid, fault in sorted(faults.items()):
            onset_w = int(fault.onset_s // win_s)
            vs = [v for v in by_tenant.get(tid, ())
                  if onset_eligible(v.alert_window, onset_w)]
            if not vs:
                continue
            eligible += 1
            first = min(vs, key=lambda v: (v.alert_window, v.scored_s))
            culprit = self.services[fault.service]
            for k in hits:
                if culprit in first.services[:k]:
                    hits[k] += 1
        return hits, eligible

    def report(self, traffic=None) -> ServeReport:
        tot = self.admission.totals()
        shed_fraction = (tot.shed_spans / tot.offered_spans
                         if tot.offered_spans else 0.0)
        per_pri = {}
        # walk the SLO rows that exist (the lazy map holds only
        # ever-served tenants), never the registered fleet — a
        # spec-driven walk would materialize O(registered) digest rows
        # right here
        pri_slos: Dict[int, List[_TenantSLO]] = {}
        for tid, slo in self._slo.items():
            pri_slos.setdefault(
                self.admission.specs.priority_of(tid), []).append(slo)
        for pri, c in sorted(self.admission.per_priority().items()):
            per_pri[pri] = {
                "offered_spans": c.offered_spans,
                "served_spans": c.served_spans,
                "shed_spans": c.shed_spans,
                "shed_fraction": (c.shed_spans / c.offered_spans
                                  if c.offered_spans else 0.0),
                **_merged_quantiles(pri_slos.get(pri, ())),
            }
        n_alerts = sum(len(d.alerts) for d in self._tenant_det.values())
        n_alerted = sum(1 for d in self._tenant_det.values() if d.alerts)
        # runner stats aggregate across the shard runners (the 1-shard
        # list is just [self.runner]); counts are identical to the
        # 1-shard engine's except lane GROUPING stats (fused_dispatches,
        # lanes_by_bucket, pad waste), which legitimately depend on how
        # many tenants share a shard's stack
        disp_by_width: Dict[int, int] = {}
        lanes_by_bucket: Dict[int, int] = {}
        staged_lanes = live_lanes = fused_dispatches = 0
        compile_s = lane_compile_s = 0.0
        native_staged = 0
        stage_wall = dispatch_wall = fold_wall = score_wall = 0.0
        # live runners + the books/walls of runners an elastic
        # scale-down retired: the canonical dispatch counts (and the
        # wall legs) must cover the WHOLE run, not just the final
        # topology
        stats = [_runner_stats(r) for r in self._runners] \
            + self._retired_runners
        for st in stats:
            book = st["book"]
            for w, n in book["dispatches_by_width"].items():
                disp_by_width[w] = disp_by_width.get(w, 0) + n
            for b, n in book["lanes_by_bucket"].items():
                lanes_by_bucket[b] = lanes_by_bucket.get(b, 0) + n
            staged_lanes += book["staged_lanes"]
            live_lanes += book["live_lanes"]
            fused_dispatches += book["fused_dispatches"]
            native_staged += book["native_staged"]
            compile_s += st["compile_s"]
            lane_compile_s += st["lane_compile_s"]
            stage_wall += st["stage_wall_s"]
            dispatch_wall += st["dispatch_wall_s"]
            fold_wall += st["fold_wall_s"]
            score_wall += st["score_wall_s"]
        shard_tenants: Dict[int, int] = {s: 0 for s in range(self.shards)}
        shard_spans: Dict[int, int] = {s: 0 for s in range(self.shards)}
        # the inline engine's placement map is empty (everyone defaults
        # to shard 0): count the unplaced arithmetically, walk only the
        # placed — never the registered fleet
        shard_tenants[0] += len(self.specs) - len(self.shard_of)
        for tid, sh in self.shard_of.items():
            shard_tenants[sh] += 1
        for tid, c in self.admission.counters.items():
            # only ever-offered tenants hold a counter row (the lazy
            # map): a [] walk over specs would materialize O(registered)
            shard_spans[self.shard_of.get(tid, 0)] += c.served_spans
        total_shard_spans = sum(shard_spans.values())
        shard_imbalance = (max(shard_spans.values())
                           / (total_shard_spans / self.shards)
                           if total_shard_spans else 1.0)
        rca_hits, rca_eligible = self._rca_hits(traffic)
        delays = [v.scored_s - v.enqueued_s for v in self.rca_verdicts]
        rca_delay = {
            q: (round(float(np.quantile(delays, p)), 6) if delays
                else None)
            for q, p in (("p50_s", 0.5), ("p99_s", 0.99))}
        rca_lat = {}
        for q, p in (("p50_s", 0.5), ("p99_s", 0.99)):
            got = self._rca_slo.quantile(p) \
                if self._rca_slo is not None else None
            rca_lat[q] = round(got, 6) if got is not None else None
        return ServeReport(
            n_tenants=len(self.specs),
            duration_s=round(self.clock.now_s, 6),
            ticks=self.clock.ticks,
            capacity_spans_per_s=self.capacity_spans_per_s,
            offered_spans=tot.offered_spans,
            admitted_spans=tot.admitted_spans,
            served_spans=tot.served_spans,
            shed_spans=tot.shed_spans,
            shed_fraction=round(shed_fraction, 6),
            served_batches=tot.served_batches,
            peak_backlog_spans=self.admission.peak_backlog_spans,
            max_backlog=self.admission.max_backlog,
            buckets=self.runner.buckets,
            dispatches_by_width=disp_by_width,
            fused=self._fused,
            fused_dispatches=fused_dispatches,
            lane_buckets=self.runner.lane_buckets,
            lanes_by_bucket=lanes_by_bucket,
            lane_pad_waste=round(1.0 - live_lanes / staged_lanes
                                 if staged_lanes else 0.0, 6),
            compile_s=round(compile_s, 4),
            lane_compile_s=round(lane_compile_s, 4),
            native_staging=any(r.native_stage for r in self._runners),
            native_staged_dispatches=native_staged,
            serve_state=self.serve_state,
            stage_wall_s=round(stage_wall, 4),
            dispatch_wall_s=round(dispatch_wall, 4),
            fold_wall_s=round(fold_wall, 4),
            score_wall_s=round(score_wall, 4),
            shards=self.shards,
            pipeline=self.pipeline,
            shard_tenants=shard_tenants,
            shard_spans=shard_spans,
            shard_imbalance=round(shard_imbalance, 6),
            latency=_merged_quantiles(list(self._slo.values())),
            per_priority=per_pri,
            modality_events=dict(self.modality_events),
            n_alerts=n_alerts,
            n_tenants_alerted=n_alerted,
            fault_detection=self._fault_detection(traffic),
            rca_enabled=self.rca,
            n_rca_runs=len(self.rca_verdicts),
            rca_topk_hits=rca_hits,
            rca_eligible=rca_eligible,
            rca_latency=rca_lat,
            rca_alert_to_culprit_s=rca_delay,
            rca_wall_s=round(self.rca_wall_s, 4),
            supervised=self._supervisor is not None,
            ckpt_every=self.ckpt_every,
            n_checkpoints=(self._supervisor.n_checkpoints
                           if self._supervisor is not None else 0),
            ckpt_wall_s=round(self._supervisor.ckpt_wall_s
                              if self._supervisor is not None else 0.0,
                              4),
            n_shard_crashes=(self._supervisor.n_crashes
                             if self._supervisor is not None else 0),
            n_respawns=(self._supervisor.n_respawns
                        if self._supervisor is not None else 0),
            n_restored_ticks=(self._supervisor.n_restored_ticks
                              if self._supervisor is not None else 0),
            n_quarantined=(self._supervisor.n_quarantined
                           if self._supervisor is not None else 0),
            n_migrated_tenants=(self._supervisor.n_migrated
                                if self._supervisor is not None else 0),
            recovery_wall_s=round(self._supervisor.recovery_wall_s
                                  if self._supervisor is not None
                                  else 0.0, 4),
            policy=(self.policy.mode if self.policy is not None
                    else "off"),
            n_scale_ups=(self.policy.n_scale_ups
                         if self.policy is not None else 0),
            n_scale_downs=(self.policy.n_scale_downs
                           if self.policy is not None else 0),
            n_rebalances=(self.policy.n_rebalances
                          if self.policy is not None else 0),
            n_policy_migrations=(self.policy.n_migrated
                                 if self.policy is not None else 0),
            brownout_ticks=(self.policy.brownout_ticks
                            if self.policy is not None else 0),
            peak_shards=max(self._peak_shards, self.shards),
            policy_wall_s=round(self.policy_wall_s, 4),
            flight_enabled=self.flight,
            flight_recorded_ticks=(self.flight_recorder.n_recorded
                                   if self.flight_recorder is not None
                                   else 0),
            flight_dropped_ticks=(self.flight_recorder.n_dropped
                                  if self.flight_recorder is not None
                                  else 0),
            perf_enabled=self.perf,
            perf_events_recorded=self.perf_events_recorded,
            overlap_headroom_s=round(self.perf_headroom_s, 6),
            fold_wait_s=round(self.perf_wait_s, 6),
            bubble_fractions=(_perf_bubbles(
                self.perf_wait_s, self.perf_headroom_s, fold_wall,
                self.serve_wall_s) if self.perf else {}),
            census_enabled=self.census,
            census_ticks=self.census_ticks,
            census_hot_set=dict(self.census_hot_set),
            census_resident_bytes=dict(self.census_resident),
            census_wall_s=round(self.census_wall_s, 4),
            tier_hot=self.tier_hot,
            n_tier_demotions_warm=(self._tier.demotions_warm
                                   if self._tier is not None else 0),
            n_tier_demotions_cold=(self._tier.demotions_cold
                                   if self._tier is not None else 0),
            n_tier_promotions=(self._tier.promotions
                               if self._tier is not None else 0),
            n_tier_misses=(self._tier.misses
                           if self._tier is not None else 0),
            tier_prefetch_hidden=(self._tier.prefetch_hits
                                  if self._tier is not None else 0),
            tier_wall_s=round(self.tier_wall_s, 4),
            async_commit=self.async_commit,
            async_ticks=self.async_ticks,
            commit_defer_wall_s=round(self.commit_defer_wall_s, 6),
            worker=self.worker_mode,
            fold=self.fold_mode,
            fold_payload_bytes=self.fold_payload_bytes,
            serve_wall_s=round(self.serve_wall_s, 4),
            sustained_spans_per_sec=round(
                self.n_spans_served / max(self.serve_wall_s, 1e-9), 1),
        )
