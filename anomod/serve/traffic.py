"""Seeded multi-tenant traffic: the synthetic stand-in for "millions of
users" hitting the serving plane.

Per-tenant offered rates follow a power law (rate of the r-th busiest
tenant ∝ (r+1)^-alpha — the skewed/heavy-head tenant distribution real
multi-tenant systems show; cf. the Sparse-Allreduce power-law framing in
PAPERS.md), normalized so the fleet's total offered rate is exactly what
the caller asked for.  Priorities cycle through the rate ranking so every
class spans the whole rate range (the overload tests need busy AND quiet
tenants in each class).

Arrival counts per (tenant, tick) are Poisson draws from per-tenant
``np.random.default_rng((seed, tenant_id))`` streams: fully deterministic
given (seed, tick schedule), independent across tenants, and stable under
adding/removing OTHER tenants.  Span payloads are cheap vectorized
synthetics over a shared service table — lognormal latencies with a
per-service scale, a small error floor, and an optional per-tenant FAULT
(latency inflation or an error burst on one culprit service after an
onset) so detection latency under load is measurable end to end.

No wall clocks anywhere: callers drive ``arrivals(t_lo_s, t_hi_s)`` from
the engine's virtual clock (the anomod.recovery pattern).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from anomod.schemas import SpanBatch, take_spans
from anomod.serve.queues import TenantSpec


@dataclasses.dataclass(frozen=True)
class TenantFault:
    """A scripted per-tenant anomaly (the serving-plane analog of the
    synth generator's fault effects)."""
    kind: str                  # "latency" | "error"
    service: int               # culprit service id
    onset_s: float             # virtual time the effect activates
    factor: float = 8.0        # latency multiplier / error-rate boost


class PowerLawTraffic:
    """Seeded power-law tenant fleet emitting span micro-batches."""

    def __init__(self, n_tenants: int, total_rate_spans_per_s: float,
                 alpha: float = 1.2, seed: int = 0, n_services: int = 8,
                 n_priorities: int = 3,
                 faults: Optional[Dict[int, TenantFault]] = None,
                 t0_us: int = 0, batch_cap: int = 512):
        if n_tenants < 1:
            raise ValueError("need >= 1 tenant")
        if total_rate_spans_per_s <= 0:
            raise ValueError("total rate must be positive")
        if batch_cap < 1:
            raise ValueError("batch_cap must be >= 1 span")
        # a feed arrives as bounded collector flushes, not one tick-wide
        # slab — capping the micro-batch keeps admission decisions at
        # flush granularity (a busy tenant sheds its EXCESS, not its
        # whole tick)
        self.batch_cap = int(batch_cap)
        self.n_services = int(n_services)
        self.services: Tuple[str, ...] = tuple(
            f"svc{i:02d}" for i in range(self.n_services))
        self.t0_us = int(t0_us)
        self.seed = int(seed)
        self.faults = dict(faults or {})
        shares = (1.0 + np.arange(n_tenants)) ** -float(alpha)
        shares /= shares.sum()
        self.specs: List[TenantSpec] = [
            TenantSpec(tenant_id=t, name=f"tenant{t:04d}",
                       priority=t % n_priorities,
                       rate_spans_per_s=float(total_rate_spans_per_s
                                              * shares[t]))
            for t in range(n_tenants)]
        self._rngs = {t.tenant_id: np.random.default_rng(
            (self.seed, t.tenant_id)) for t in self.specs}
        # per-tenant service mix + latency scale: deterministic from the
        # tenant id, NOT drawn from the arrival stream (arrival draws must
        # depend only on the tick schedule)
        self._svc_p: Dict[int, np.ndarray] = {}
        self._lat_scale: Dict[int, np.ndarray] = {}
        for t in self.specs:
            mix_rng = np.random.default_rng((self.seed, t.tenant_id, 7))
            p = mix_rng.dirichlet(np.full(self.n_services, 2.0))
            self._svc_p[t.tenant_id] = p
            self._lat_scale[t.tenant_id] = mix_rng.uniform(
                800.0, 6000.0, self.n_services)

    def arrivals(self, t_lo_s: float,
                 t_hi_s: float) -> List[Tuple[int, SpanBatch]]:
        """Per-tenant micro-batches arriving in [t_lo_s, t_hi_s)."""
        out: List[Tuple[int, SpanBatch]] = []
        dt = t_hi_s - t_lo_s
        for spec in self.specs:
            rng = self._rngs[spec.tenant_id]
            n = int(rng.poisson(spec.rate_spans_per_s * dt))
            if n == 0:
                continue
            batch = self._make_spans(spec, rng, n, t_lo_s, t_hi_s)
            for lo in range(0, n, self.batch_cap):
                out.append((spec.tenant_id,
                            take_spans(batch,
                                       slice(lo, min(lo + self.batch_cap,
                                                     n)))))
        return out

    def _make_spans(self, spec: TenantSpec, rng: np.random.Generator,
                    n: int, t_lo_s: float, t_hi_s: float) -> SpanBatch:
        svc = rng.choice(self.n_services, size=n,
                         p=self._svc_p[spec.tenant_id]).astype(np.int32)
        start = self.t0_us + np.sort(rng.integers(
            int(t_lo_s * 1e6), int(t_hi_s * 1e6), n)).astype(np.int64)
        scale = self._lat_scale[spec.tenant_id][svc]
        dur = (scale * rng.lognormal(0.0, 0.35, n)).astype(np.int64)
        err = rng.random(n) < 0.01
        fault = self.faults.get(spec.tenant_id)
        if fault is not None and t_lo_s >= fault.onset_s:
            hit = svc == fault.service
            if fault.kind == "latency":
                dur = np.where(hit, (dur * fault.factor).astype(np.int64),
                               dur)
            elif fault.kind == "error":
                err = err | (hit & (rng.random(n)
                                    < min(0.95, 0.1 * fault.factor)))
            else:
                raise ValueError(f"unknown fault kind {fault.kind!r}")
        return SpanBatch(
            trace=(rng.integers(0, 64, n)).astype(np.int32),
            parent=np.full(n, -1, np.int32),
            service=svc,
            endpoint=np.zeros(n, np.int32),
            start_us=start,
            duration_us=np.maximum(dur, 1),
            is_error=err.astype(np.bool_),
            status=np.where(err, 500, 200).astype(np.int16),
            kind=np.zeros(n, np.int8),
            services=self.services,
            endpoints=("ep",),
            trace_ids=tuple(f"t{i:02d}" for i in range(64)),
        ).validate()


class ScriptedTraffic:
    """Replay pre-built per-tenant SpanBatches on the virtual clock —
    the parity harness's traffic source (same spans into the serving
    plane as into the sequential per-tenant baselines).

    ``streams`` maps tenant_id -> arrival-ordered SpanBatch; each
    ``arrivals`` call slices every stream to [t_lo_s, t_hi_s) relative
    to ``t0_us`` (absolute span timestamps, same convention as
    anomod.stream.stream_experiment's slicing).  ``experiments``
    (optional, tenant_id -> Experiment) additionally feeds the tenants'
    log/metric/api planes through ``modality_arrivals`` — the multimodal
    serving analog of stream_experiment_multimodal's one-clock slicing.
    """

    def __init__(self, streams: Dict[int, SpanBatch],
                 specs: Sequence[TenantSpec], t0_us: int,
                 experiments: Optional[Dict[int, object]] = None):
        self.specs = list(specs)
        self.t0_us = int(t0_us)
        ids = {s.tenant_id for s in self.specs}
        if set(streams) - ids:
            raise ValueError("streams for unknown tenant ids: "
                             f"{sorted(set(streams) - ids)}")
        self.streams = {
            t: take_spans(b, np.argsort(b.start_us, kind="stable"))
            for t, b in streams.items()}
        self.experiments = dict(experiments or {})

    def end_s(self) -> float:
        """Last span's arrival, in virtual seconds past t0."""
        ends = [float(b.start_us.max()) for b in self.streams.values()
                if b.n_spans]
        return (max(ends) - self.t0_us) / 1e6 if ends else 0.0

    def arrivals(self, t_lo_s: float,
                 t_hi_s: float) -> List[Tuple[int, SpanBatch]]:
        lo = self.t0_us + int(t_lo_s * 1e6)
        hi = self.t0_us + int(t_hi_s * 1e6)
        out = []
        for tid in sorted(self.streams):
            b = self.streams[tid]
            m = (b.start_us >= lo) & (b.start_us < hi)
            if m.any():
                out.append((tid, take_spans(b, m)))
        return out

    def modality_arrivals(self, t_lo_s: float, t_hi_s: float) -> List[tuple]:
        """(tenant_id, kind, batch) log/metric/api slices for the tick —
        the same second-resolution slicing stream_experiment_multimodal
        drives, on the serving clock."""
        from anomod.stream import _take_nt
        lo = self.t0_us / 1e6 + t_lo_s
        hi = self.t0_us / 1e6 + t_hi_s
        out: List[tuple] = []
        for tid in sorted(self.experiments):
            exp = self.experiments[tid]
            for kind, b, n in (("logs", exp.logs,
                                getattr(exp.logs, "n_lines", 0)),
                               ("metrics", exp.metrics,
                                getattr(exp.metrics, "n_samples", 0)),
                               ("api", exp.api,
                                getattr(exp.api, "n_records", 0))):
                if b is None or not n:
                    continue
                m = (b.t_s >= lo) & (b.t_s < hi)
                if m.any():
                    out.append((tid, kind, _take_nt(b, m)))
        return out
