"""Shard supervision: deterministic checkpoint/restore and no-score-gap
recovery for the serving plane.

The reference system's value proposition is staying collectable while
faults are injected under it (SURVEY §5 — the self-healing layer's
force-delete-and-respawn, modeled in ``anomod.recovery``); this module
gives the serve plane the same property.  Three pieces:

- **Checkpoint** (``ANOMOD_SERVE_CKPT_EVERY``, the flight-digest
  cadence idiom): every Nth tick the supervisor snapshots each shard's
  tenants — replay state through the ``get_state``/pool-gather seam
  (pinned byte-exact across residencies) plus the detector's host
  bookkeeping — and each runner's dispatch-count book.  Between
  checkpoints the coordinator retains every tick's served-batch slices
  (it owns admission, so the slices ARE the re-execution input): the
  admission-plane bookkeeping that makes a tick re-executable.
- **Recovery**: a shard failure at the tick barrier triggers restore
  (drop the shard's suspect planes, reinstall the snapshot through
  ``set_state``) + deterministic RE-execution of the retained slices,
  including the failed tick's — on the respawned worker when the
  thread died.  Scoring is a pure function of (state, slices) at every
  shard count / pipeline depth / residency (the PR-5/8 parity pins),
  so the recovered run's states, alerts, SLO and shed are
  BYTE-identical to a fault-free run of the same seed: the
  "no score gap" contract, verified by equal canonical flight
  journals (``anomod audit diff``).
- **Degradation**: a slice that kills its shard ``ANOMOD_SERVE_RETRIES``
  consecutive times is QUARANTINED (dropped from the log, counted,
  journaled — never retried forever); a shard whose worker dies past
  ``ANOMOD_SERVE_MAX_RESPAWNS`` is declared DEAD and its tenants
  MIGRATE to the survivors through the same ``set_state`` seam — the
  first real step of the elastic-tenancy roadmap item.

Everything the supervisor does on the happy path is a pure read
(snapshots) or host bookkeeping (the log), so a chaos-off supervised
run's decisions are byte-identical to the unsupervised engine —
pinned in tests/test_serve_supervise.py.
"""

from __future__ import annotations

import copy
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from anomod import obs

__all__ = ["ShardSupervisor", "snapshot_replay", "restore_replay",
           "snapshot_detector", "restore_detector"]


# -- tenant snapshot/restore through the official state seams ------------

def snapshot_replay(rep) -> dict:
    """One tenant replay plane's full restorable state: the
    ``get_state`` pytree owned-by-the-checkpoint plus the ring
    bookkeeping ``plan_push`` advances.  A pool-backed replay's gather
    is ALWAYS a copy (the :meth:`anomod.replay.TenantStatePool.gather`
    contract), so its pytree is taken as-is; the host seam hands its
    LIVE arrays and must be copied here — re-copying the pool gather
    too would double the checkpoint's memcpy bill at fleet size."""
    from anomod.serve.batcher import PooledStreamReplay
    st = rep.get_state()
    if not isinstance(rep, PooledStreamReplay):
        st = type(st)(*[None if x is None else np.array(x)
                        for x in st])
    return {"state": st,
            "t0_us": rep.t0_us,
            "window_offset": rep.window_offset,
            "n_spans": rep.n_spans}


def restore_replay(rep, snap: dict) -> None:
    """Install a :func:`snapshot_replay` into a FRESH plane.  The host
    seam's ``set_state`` installs references and the fold mutates
    through them — sharing with the checkpoint would corrupt it for
    the next restore, so the arrays are copied on the way in.  A pool
    put SCATTERS into the pool's own planes (the snapshot is never
    aliased), so the pooled path skips the extra copy — the same
    asymmetry as :func:`snapshot_replay`, restore side."""
    from anomod.serve.batcher import PooledStreamReplay
    rep.t0_us = snap["t0_us"]
    rep.window_offset = snap["window_offset"]
    rep.n_spans = snap["n_spans"]
    st = snap["state"]
    if not isinstance(rep, PooledStreamReplay):
        st = type(st)(*[None if x is None else np.array(x)
                        for x in st])
    rep.set_state(st)


def _copy_state_val(v):
    """Structured copy for detector host state: arrays and containers
    copy (folds mutate them in place), scalars and RECORD objects
    (dataclass instances — Alert etc., append-only emission records the
    detector never mutates after creation) share by reference.  A
    generic ``copy.deepcopy`` of the same graph walks ~60 objects per
    detector and dominated the checkpoint wall at fleet size; anything
    this function does not recognize still falls back to deepcopy, so
    an unknown mutable type degrades to slow-but-safe."""
    if isinstance(v, np.ndarray):
        return v.copy()
    if v is None or isinstance(v, (int, float, bool, str, bytes,
                                   frozenset)):
        return v
    if isinstance(v, tuple):
        return tuple(_copy_state_val(x) for x in v)
    if isinstance(v, list):
        return [_copy_state_val(x) for x in v]
    if isinstance(v, dict):
        return {k: _copy_state_val(x) for k, x in v.items()}
    if isinstance(v, set):
        return set(v)
    import dataclasses as _dc
    if _dc.is_dataclass(v) and not isinstance(v, type) \
            and v.__dataclass_params__.frozen:
        return v                      # an immutable record, shareable
    return copy.deepcopy(v)


def snapshot_detector(det) -> dict:
    """The detector's host bookkeeping (alerts, streaks, CUSUM,
    calibration, edge/pair accumulators — everything but the replay
    plane, which snapshots separately through its own seam)."""
    return {k: _copy_state_val(v) for k, v in det.__dict__.items()
            if k != "replay"}


def restore_detector(det, snap: dict) -> None:
    det.__dict__.update({k: _copy_state_val(v)
                         for k, v in snap.items()})


class _ReplayFailed(Exception):
    """Internal: a recovery re-execution failed at one log slice."""

    def __init__(self, tick: int, exc: BaseException):
        super().__init__(f"re-execution failed at tick {tick}: {exc}")
        self.tick = tick
        self.exc = exc


class _Checkpoint:
    __slots__ = ("tick", "tenants", "books")

    def __init__(self, tick: int, tenants: dict, books: list):
        self.tick = tick
        self.tenants = tenants          # tid -> (replay_snap, det_snap)
        self.books = books              # per-runner book_snapshot()


class ShardSupervisor:
    """Owns the checkpoint cadence, the recovery log, the retry/
    quarantine policy and the dead-shard migration path for one
    :class:`~anomod.serve.engine.ServeEngine`."""

    def __init__(self, engine, ckpt_every: int, retries: int,
                 backoff_s: float, max_respawns: int, sleep_fn=None):
        if ckpt_every < 1:
            raise ValueError("supervision needs ckpt_every >= 1 "
                             "(0 disables it at the engine)")
        self.engine = engine
        self.ckpt_every = int(ckpt_every)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.max_respawns = int(max_respawns)
        #: the respawn-backoff clock, injectable so supervised
        #: campaigns are wall-free under test (a fake sleep records the
        #: schedule instead of parking the coordinator).  Backoff is
        #: wall-side supervision policy either way: the replayed
        #: DECISIONS stay pinned byte-identical at any sleep_fn.
        self._sleep = sleep_fn if sleep_fn is not None else time.sleep
        self._ckpt: Optional[_Checkpoint] = None
        #: (tick, served) since the last checkpoint — the re-execution
        #: input; batches are immutable, so retention is reference-cheap
        self._log: List[Tuple[int, list]] = []
        self._quarantined_seqs: set = set()
        #: consecutive recovery failures per (shard, origin tick) slice
        self._fail_counts: Dict[Tuple[int, int], int] = {}
        self._respawns: Dict[int, int] = {}
        self.dead_shards: set = set()
        #: recovery events for the flight journal's VARIANT tier
        #: (drained per tick by the engine; canonical planes untouched)
        self._events: List[dict] = []
        self.n_checkpoints = 0
        self.n_crashes = 0
        self.n_respawns = 0
        self.n_restored_ticks = 0
        self.n_quarantined = 0
        self.quarantined_spans = 0
        self.n_migrated = 0
        self.ckpt_wall_s = 0.0
        self.recovery_wall_s = 0.0
        self._obs_ckpt = obs.counter("anomod_serve_ckpt_total")
        self._obs_ckpt_s = obs.counter("anomod_serve_ckpt_seconds_total")
        self._obs_crashes = obs.counter(
            "anomod_serve_shard_crashes_total")
        self._obs_respawns = obs.counter(
            "anomod_serve_shard_respawns_total")
        self._obs_restored = obs.counter(
            "anomod_serve_restored_ticks_total")
        self._obs_quarantined = obs.counter(
            "anomod_serve_quarantined_batches_total")
        self._obs_migrated = obs.counter(
            "anomod_serve_migrated_tenants_total")
        self._obs_recovery_s = obs.counter(
            "anomod_serve_recovery_seconds_total")

    # -- the per-tick protocol (engine.tick drives this) ------------------

    def begin_tick(self, served: list) -> None:
        """Log this tick's served batches BEFORE scoring runs — the
        failed tick's slices must already be in the log when recovery
        re-executes it.  The baseline checkpoint is taken lazily here
        (post-warm, pre-first-scoring: empty tenants, the runners'
        warmed-but-unserved books)."""
        if self._ckpt is None:
            self._checkpoint()
        self._log.append((self.engine.clock.ticks, served))

    def end_tick(self) -> None:
        """Checkpoint at the cadence (the flight-digest tick rule:
        0-based tick t checkpoints when ``(t + 1) % every == 0``),
        AFTER the tick's scoring committed."""
        if (self.engine.clock.ticks + 1) % self.ckpt_every == 0:
            self._checkpoint()
        if self.engine.flight_recorder is None and self._events:
            # no journal to drain into: the counters/report carry the
            # recovery story, and an unbounded event list must not grow
            # with a flight-off run's crash count
            self._events.clear()

    def drain_events(self) -> List[dict]:
        ev, self._events = self._events, []
        return ev

    def note_topology_change(self) -> None:
        """The elastic policy changed the shard set (scale-up/down or
        a rebalance migration): take a fresh baseline checkpoint NOW.
        The checkpoint's per-runner books and tenant placements index
        the current topology, and the recovery log is the re-execution
        input against exactly that checkpoint — letting the log span a
        scale boundary would re-execute slices against books that no
        longer line up with the runner list."""
        self._checkpoint()

    # -- checkpointing -----------------------------------------------------

    def _checkpoint(self) -> None:
        t0 = time.perf_counter()
        eng = self.engine
        tenants = {}
        if getattr(eng, "worker_mode", "thread") == "process":
            # the states live in the children: each ships its tenants'
            # (replay_snap, det_snap) pairs through the SAME snapshot
            # seams, run child-side
            tenants = eng._snapshot_tenants_proc()
        else:
            for tid, rep in eng._tenant_replay.items():
                det = eng._tenant_det.get(tid)
                tenants[tid] = (snapshot_replay(rep),
                                snapshot_detector(det)
                                if det is not None else None)
        tier = getattr(eng, "_tier", None)
        if tier is not None:
            # demoted tenants are fleet state too: a tenant demoted
            # before this checkpoint and promoted (then scored) after
            # it must restore from ITS state, not re-derive from zero.
            # Warm snapshots ride by reference (immutable after
            # demotion), cold entries by content-address key (the
            # store is append-only); the detector's host bookkeeping
            # is COPIED — it mutates again the moment the tenant
            # promotes and scores
            for tid in tier.tids():
                det = tier.ckpt_det(tid)
                tenants[tid] = (tier.ckpt_snap(tid),
                                snapshot_detector(det)
                                if det is not None else None)
        books = [r.book_snapshot() for r in eng._runners]
        self._ckpt = _Checkpoint(eng.clock.ticks, tenants, books)
        self._log = []
        self.n_checkpoints += 1
        self._obs_ckpt.inc()
        dt = time.perf_counter() - t0
        self.ckpt_wall_s += dt
        self._obs_ckpt_s.inc(dt)

    # -- recovery ----------------------------------------------------------

    def recover(self, failures: List[Tuple[int, BaseException]],
                origin_tick: Optional[int] = None) -> None:
        """Recover every shard that failed this tick's barrier.  Raises
        (the original error) only when recovery is impossible: retry +
        quarantine exhausted AND no surviving shard to migrate to.
        The deferred-commit barrier passes ``origin_tick`` (a
        barrier-time failure belongs to the tick that ISSUED the work,
        one tick behind the clock) so the retry ledger charges the
        slice that actually failed."""
        t0 = time.perf_counter()
        try:
            for shard_id, exc in failures:
                if not isinstance(exc, Exception):
                    raise exc     # operator interrupt, never a fault
                self._recover_shard(shard_id, exc,
                                    origin_tick=origin_tick)
        finally:
            dt = time.perf_counter() - t0
            self.recovery_wall_s += dt
            self._obs_recovery_s.inc(dt)

    def _recover_shard(self, s: int, exc: BaseException,
                       origin_tick: Optional[int] = None) -> None:
        eng = self.engine
        tick = eng.clock.ticks
        self.n_crashes += 1
        self._obs_crashes.inc()
        event = {"kind": "recovered", "tick": tick, "shard": s,
                 "error": f"{type(exc).__name__}: {exc}",
                 "attempts": 0, "respawns": 0, "restored_ticks": 0,
                 "quarantined": 0}
        # the live failure is attempt 1 against the slice that actually
        # failed — the current tick's, unless the migration path hands
        # in an older origin tick (charging the current tick instead
        # would quarantine an innocent slice one real failure early)
        fail_key = (s, tick if origin_tick is None else origin_tick)
        self._fail_counts[fail_key] = \
            self._fail_counts.get(fail_key, 0) + 1
        last = exc
        attempt = 0
        while True:
            if self._worker_dead_past_budget(s):
                # the shard is dead past its respawn budget — migrate
                # its tenants to the survivors (or give up loudly when
                # there are none) BEFORE any quarantine decision: a
                # fault that follows the SHARD runs clean on the new
                # owners (no score gap), and a fault that follows the
                # BATCH still quarantines inside the migration replay
                self._migrate_dead_shard(s, last)
                return
            if self._fail_counts.get(fail_key, 0) >= self.retries:
                event["quarantined"] += self._quarantine(s, fail_key[1])
            if self.backoff_s > 0:
                self._sleep(min(self.backoff_s * (2 ** attempt), 5.0))
            self._respawn_worker(s, event)
            try:
                restored = self._restore_and_replay(s, event)
            except _ReplayFailed as rf:
                attempt += 1
                last = rf.exc
                fail_key = (s, rf.tick)
                self._fail_counts[fail_key] = \
                    self._fail_counts.get(fail_key, 0) + 1
                continue
            event["attempts"] = attempt + 1
            event["restored_ticks"] = restored
            self._events.append(event)
            # the incident is OVER: every slice (including the one that
            # failed) just executed clean, so its failure streak is
            # broken — quarantine counts CONSECUTIVE failures, and a
            # stale count would let a later unrelated incident
            # quarantine a recovered slice one real failure early
            self._fail_counts = {k: v for k, v in
                                 self._fail_counts.items() if k[0] != s}
            return

    def _worker_dead_past_budget(self, s: int) -> bool:
        eng = self.engine
        return (eng._workers is not None
                and not eng._workers[s].alive
                and self._respawns.get(s, 0) >= self.max_respawns)

    def _respawn_worker(self, s: int, event: dict) -> None:
        """Respawn shard ``s``'s worker thread if it died (the budget
        was already checked by the recovery loop).  The inline engine
        (no worker threads) has nothing to respawn."""
        eng = self.engine
        if eng._workers is None:
            return
        w = eng._workers[s]
        if w.alive:
            return
        w.close()                    # dead worker: joins immediately
        # the engine picks the worker kind (ShardWorker thread or
        # ProcShardWorker child process); a fresh process child starts
        # EMPTY — _restore_and_replay reinstalls the checkpoint into it
        eng._workers[s] = eng._make_worker(s)
        self._respawns[s] = self._respawns.get(s, 0) + 1
        self.n_respawns += 1
        self._obs_respawns.inc()
        event["respawns"] += 1

    def _drop_shard_planes(self, s: int) -> None:
        """Discard shard ``s``'s (suspect, possibly mid-fold) tenant
        planes and any parked dispatches — the restore's teardown
        half."""
        eng = self.engine
        if getattr(eng, "worker_mode", "thread") == "process":
            eng._drop_shard_proc(s)
            return
        for tid in [t for t, r in list(eng._tenant_replay.items())
                    if eng.shard_of.get(t, 0) == s]:
            rep = eng._tenant_replay.pop(tid)
            eng._tenant_det.pop(tid, None)
            if hasattr(rep, "release"):
                rep.release()        # hand the pool slot back
        eng._runners[s].abort_lanes()

    def _install_tenant(self, tid: int, snap: tuple) -> None:
        """Recreate one tenant's planes on its (current) owning shard
        and install the checkpoint snapshot through the state seams."""
        eng = self.engine
        if getattr(eng, "worker_mode", "thread") == "process":
            # reinstall into the owning CHILD over the pipe — the same
            # restore seams, run where the state lives
            eng._install_tenant_proc(tid, snap)
            return
        rep_snap, det_snap = snap
        tier = getattr(eng, "_tier", None)
        if tier is not None:
            # the checkpoint view supersedes any live tier entry
            # (demoted before OR after the snapshot): the restore
            # rebuilds the tenant RESIDENT and the re-executed log
            # advances that state — a stale entry left behind would
            # shadow it at the tenant's next scoring gate
            tier.discard(tid)
            if "__tier_cold__" in rep_snap:
                rep_snap = tier.load_cold(rep_snap["__tier_cold__"])
        rep = eng._replay_for(tid)
        restore_replay(rep, rep_snap)
        if det_snap is not None:
            det = eng._detector_for(tid)
            restore_detector(det, det_snap)

    def _restore_and_replay(self, s: int, event: Optional[dict] = None
                            ) -> int:
        """Restore shard ``s`` to the checkpoint and re-execute its
        retained slices (oldest first, quarantined batches excluded).
        Returns the number of slices re-executed; raises
        :class:`_ReplayFailed` naming the slice that failed."""
        eng = self.engine
        ck = self._ckpt
        self._drop_shard_planes(s)
        eng._restore_book(s, ck.books[s])
        for tid, snap in ck.tenants.items():
            if eng.shard_of.get(tid, 0) == s:
                self._install_tenant(tid, snap)
        restored = 0
        for tick, served in self._log:
            slice_ = [qb for qb in served
                      if eng.shard_of.get(qb.tenant_id, 0) == s
                      and qb.seq not in self._quarantined_seqs]
            if not slice_:
                continue
            # the respawn is SETUP, outside the try: a thread-creation
            # failure is infrastructure, not attributable to the slice,
            # and must propagate raw instead of charging the slice's
            # quarantine budget for an error its content didn't cause
            self._ensure_worker_alive(s, event)
            try:
                self._exec_slice(s, slice_, tick)
            except Exception as e:       # interrupts propagate raw
                raise _ReplayFailed(tick, e)
            restored += 1
        self.n_restored_ticks += restored
        self._obs_restored.inc(restored)
        return restored

    def _ensure_worker_alive(self, s: int,
                             event: Optional[dict] = None) -> None:
        """Respawn shard ``s``'s worker if its thread is dead — a
        migration can re-execute on a shard whose own barrier failure
        is still queued behind this one (submitting to a dead thread
        would wait forever), and a mid-replay kill leaves the thread
        dead for the next slice.  The respawn lands in the caller's
        recovery ``event`` (the journaled incident must not
        under-report what happened) and is counted like any other;
        every failure path from here returns to a budget-checked
        loop, so this cannot respawn unboundedly."""
        eng = self.engine
        if eng._workers is not None and not eng._workers[s].alive:
            self._respawn_worker(
                s, event if event is not None else {"respawns": 0})

    def _exec_slice(self, s: int, slice_: list, tick: int) -> None:
        """Re-execute one logged slice on shard ``s`` — on its worker
        thread when workers exist (so a killing fault dies where it
        would live, and XLA dispatch runs where it normally does),
        inline on the 1-shard engine.  An exception here is the
        SLICE's failure (the task raised); callers charge it to the
        slice's quarantine budget — setup errors belong in
        :meth:`_ensure_worker_alive`, before the attributable zone."""
        eng = self.engine
        if eng._workers is not None \
                and getattr(eng._workers[s], "kind", "thread") == "process":
            eng._exec_slice_proc(s, slice_, tick)
        elif eng._workers is not None:
            from functools import partial
            w = eng._workers[s]
            w.submit(partial(eng._score_shard, s, slice_, tick))
            w.join()
        else:
            eng._score_shard(s, slice_, tick)

    def _quarantine(self, s: int, tick: int) -> int:
        """Drop shard ``s``'s slice of origin ``tick`` from the log —
        the batch set that has now failed ``retries`` consecutive
        recovery attempts.  Counted per batch, never silent."""
        eng = self.engine
        dropped = spans = 0
        for t, served in self._log:
            if t != tick:
                continue
            for qb in served:
                if eng.shard_of.get(qb.tenant_id, 0) == s \
                        and qb.seq not in self._quarantined_seqs:
                    self._quarantined_seqs.add(qb.seq)
                    self.quarantined_spans += qb.n_spans
                    spans += qb.n_spans
                    dropped += 1
        self.n_quarantined += dropped
        self._obs_quarantined.inc(dropped)
        self._events.append({"kind": "quarantine", "tick": tick,
                             "shard": s, "batches": dropped,
                             "spans": spans})
        return dropped

    # -- dead-shard migration (the elastic-tenancy seam) -------------------

    def _migrate_dead_shard(self, s: int,
                            last: BaseException) -> None:
        """Shard ``s`` is dead past its respawn budget: move every
        tenant it owns to the surviving shards through the ``set_state``
        seam — checkpoint state in, retained slices re-executed on the
        new owners — and route all future work away from it.  Tenant
        bits are shard-placement-invariant (the PR-5 contract), so a
        clean migration keeps the no-score-gap parity."""
        eng = self.engine
        tick = eng.clock.ticks
        survivors = [x for x in range(eng.shards)
                     if x != s and x not in self.dead_shards]
        if not survivors:
            raise last
        self.dead_shards.add(s)
        moved = sorted(t for t, sh in eng.shard_of.items() if sh == s)
        self._drop_shard_planes(s)
        eng._restore_book(s, self._ckpt.books[s])
        # park a fresh idle worker in the dead slot so the engine's
        # all-alive respawn check stays quiet; it never receives work
        if eng._workers is not None:
            eng._workers[s].close()
            eng._workers[s] = eng._make_worker(s)
        # rendezvous over the survivors (the SAME key definition as
        # initial placement — shard.rendezvous_shard): deterministic in
        # (tenant, survivor set) alone, so a replay of the same chaos
        # script migrates identically
        from anomod.serve.shard import rendezvous_shard
        for tid in moved:
            eng.shard_of[tid] = rendezvous_shard(tid, eng.shards,
                                                 candidates=survivors)
            self.n_migrated += 1
            self._obs_migrated.inc()
        # the RCA evidence buffers ride on the owning shard's plane
        if eng.rca and len(eng._rca_planes) > 1:
            src = eng._rca_planes[s]
            for tid in moved:
                src.move_tenant_evidence(
                    eng._rca_planes[eng.shard_of[tid]], tid)
        for tid in moved:
            snap = self._ckpt.tenants.get(tid)
            if snap is not None:
                self._install_tenant(tid, snap)
        moved_set = set(moved)
        mig_event = {"kind": "migrate", "tick": tick, "shard": s,
                     "to": survivors, "tenants": len(moved),
                     "respawns": 0,
                     "error": f"{type(last).__name__}: {last}"}
        #: targets whose nested recovery already replayed the WHOLE log
        #: (shard_of is updated, so their restore included the migrated
        #: tenants' every slice) — the outer walk must skip them, or
        #: each later slice would fold twice and silently diverge
        recovered: set = set()
        outer_counts: Dict[int, int] = {}
        for t, served in self._log:
            by_shard: Dict[int, list] = {}
            for qb in served:
                if qb.tenant_id in moved_set \
                        and qb.seq not in self._quarantined_seqs \
                        and eng.shard_of[qb.tenant_id] not in recovered:
                    by_shard.setdefault(
                        eng.shard_of[qb.tenant_id], []).append(qb)
            for tgt in sorted(by_shard):
                self._ensure_worker_alive(tgt, mig_event)
                try:
                    self._exec_slice(tgt, by_shard[tgt], t)
                except Exception as e2:      # interrupts propagate raw
                    # the fault followed the BATCH onto the new shard:
                    # quarantine the slice and recover the target
                    # through the normal path — a poison batch must not
                    # take the survivor down with the dead shard
                    for qb in by_shard[tgt]:
                        self._quarantined_seqs.add(qb.seq)
                        self.quarantined_spans += qb.n_spans
                    self.n_quarantined += len(by_shard[tgt])
                    self._obs_quarantined.inc(len(by_shard[tgt]))
                    self._events.append(
                        {"kind": "quarantine", "tick": t, "shard": tgt,
                         "batches": len(by_shard[tgt]),
                         "spans": sum(qb.n_spans for qb in by_shard[tgt]),
                         "during": "migration"})
                    # the nested recovery restores tgt from checkpoint
                    # and replays the WHOLE log: the outer walk's
                    # increments for tgt are superseded, not additional
                    # (the report's n_restored_ticks — and therefore
                    # mttr_ticks — must not inflate; the registry
                    # counter stays a monotone count of slices
                    # EXECUTED during recovery)
                    self.n_restored_ticks -= outer_counts.pop(tgt, 0)
                    self._recover_shard(tgt, e2, origin_tick=t)
                    recovered.add(tgt)
                    continue
                self.n_restored_ticks += 1
                outer_counts[tgt] = outer_counts.get(tgt, 0) + 1
                self._obs_restored.inc()
        self._events.append(mig_event)
