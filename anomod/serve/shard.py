"""Tenant sharding for the serving plane: deterministic partition +
engine worker threads.

The scale-out model (``ANOMOD_SERVE_SHARDS``): the virtual-clock tick's
CONTROL plane — admission, weighted-fair drain, shedding, SLO sample
collection — stays on the coordinator thread (it is integer/float
bookkeeping, microseconds per tick, and keeping it single-threaded is
what makes every admission/shed decision identical to the 1-shard engine
by construction).  The SCORE plane — staging, lane-stacked XLA
dispatches, window scoring, per-tenant detector state — is where the
tick wall actually goes, and it partitions cleanly by tenant: each shard
worker owns its tenants' ``BucketedStreamReplay``/``OnlineDetector``
states and its own :class:`~anomod.serve.batcher.BucketRunner` (own
jitted executables, own pinned scratch, own per-shard metrics registry)
END TO END, so the score path needs no cross-shard locking at all.  The
tick fans served batches out by tenant ownership and joins at a barrier
before SLO accounting — alerts, SLO digests and shed decisions are
deterministic per seed and identical at every shard count.

Partitioning is rendezvous hashing (highest-random-weight: tenant t goes
to ``argmax_s crc32(f"{t}/{s}")``) — stable under shard-count changes
for most tenants, independent of spec order — followed by a
LOAD-BALANCE pass over the tenants' seeded offered rates: power-law
fleets (PAPERS.md, *Sparse Allreduce*) concentrate most of the span
volume in a few head tenants, and a pure hash regularly pins two of
them to one shard.  The pass greedily moves the heaviest movable tenant
from the most- to the least-loaded shard while that strictly shrinks
the span-rate spread, so the head tenants end up spread across shards
while the hash keeps the long tail stable.  Everything is derived from
``(tenant_id, rate)`` alone — the same specs always produce the same
plan.
"""

from __future__ import annotations

import queue
import threading
import zlib
from typing import Dict, List, Optional, Sequence

from anomod.serve.queues import TenantSpec


def _fmix32(h: int) -> int:
    """MurmurHash3's 32-bit avalanche finalizer.  crc32 alone is
    XOR-LINEAR: two keys differing only in the shard suffix differ by a
    near-constant XOR, so comparing raw crc32 scores across shards
    clumps — runs of ~80 CONSECUTIVE tenant ids all prefer the same
    shard (measured: the 1→2 delta set over tenants 0..79 was empty,
    which would make a small fleet's first scale-up a placement
    no-op).  The multiply/shift mix destroys that linear structure
    while staying process- and hash-seed-stable."""
    h &= 0xFFFFFFFF
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def rendezvous_shard(tenant_id: int, n_shards: int,
                     candidates: Optional[Sequence[int]] = None) -> int:
    """Highest-random-weight shard for one tenant (crc32 + the
    :func:`_fmix32` avalanche — stable across processes and Python hash
    seeds).  ``candidates`` restricts the draw to a subset of shard ids
    (the dead-shard migration and elastic scale-down cases: the ONE key
    definition must serve initial placement, recovery migration and
    policy-time scaling alike, or they could silently disagree)."""
    pool = range(n_shards) if candidates is None else candidates
    best, best_score = -1, -1
    for s in pool:
        score = _fmix32(zlib.crc32(f"{tenant_id}/{s}".encode()))
        if score > best_score:
            best, best_score = s, score
    if best < 0:
        raise ValueError("rendezvous needs at least one candidate shard")
    return best


def served_rate_model(specs: Sequence[TenantSpec],
                      capacity_spans_per_s: float) -> Dict[int, float]:
    """Expected SERVED spans/s per tenant under weighted-fair overload.

    Offered rate is the wrong balance weight once the fleet overloads:
    shedding is priority-ordered, so a bronze head tenant's spans mostly
    shed while a gold tenant's mostly serve — and the shard barrier
    waits on *scored* work, not offered work.  Under SFQ saturation each
    backlogged tenant's served rate is proportional to its weight, so
    the fleet splits as ``served_t = min(rate_t, w_t * K)`` with K set
    by capacity: ``sum_t min(rate_t, w_t * K) = C`` (demand-limited
    tenants serve their whole offer, the rest split the remainder by
    weight).  K solves by bisection; with capacity >= offered load the
    model degrades to the offered rates exactly.
    """
    rates = {s.tenant_id: max(float(s.rate_spans_per_s), 0.0)
             for s in specs}
    total = sum(rates.values())
    if total <= 0 or capacity_spans_per_s >= total:
        return rates
    ws = {s.tenant_id: s.effective_weight() for s in specs}
    lo, hi = 0.0, max(r / w for r, w in
                      ((rates[t], ws[t]) for t in rates) if w > 0)
    for _ in range(60):
        k = 0.5 * (lo + hi)
        if sum(min(rates[t], ws[t] * k) for t in rates) \
                < capacity_spans_per_s:
            lo = k
        else:
            hi = k
    k = 0.5 * (lo + hi)
    return {t: min(rates[t], ws[t] * k) for t in rates}


def plan_shards(specs: Sequence[TenantSpec], n_shards: int,
                capacity_spans_per_s: float = 0.0) -> Dict[int, int]:
    """tenant_id -> shard for the whole fleet: rendezvous base + the
    greedy rate-balance pass described in the module docstring.

    ``capacity_spans_per_s`` (when positive and below the offered load)
    switches the balance weights from offered to expected-served rates
    (:func:`served_rate_model`) — the barrier waits on scored spans, so
    that is the load to equalize.  Deterministic in the arguments alone;
    every tenant is assigned; with ``n_shards == 1`` everything maps to
    shard 0.
    """
    if n_shards < 1:
        raise ValueError("n_shards must be >= 1")
    assign = {s.tenant_id: rendezvous_shard(s.tenant_id, n_shards)
              for s in specs}
    if n_shards == 1 or len(specs) <= 1:
        return assign
    # expected-served weights (offered rates when capacity is ample or
    # unknown); an all-zero fleet (scripted traffic with no rate hints)
    # balances by tenant count instead
    w = served_rate_model(specs, capacity_spans_per_s) \
        if capacity_spans_per_s > 0 else \
        {s.tenant_id: max(float(s.rate_spans_per_s), 0.0) for s in specs}
    if sum(w.values()) <= 0:
        w = {t: 1.0 for t in w}
    loads = [0.0] * n_shards
    members: List[List[int]] = [[] for _ in range(n_shards)]
    for s in specs:
        loads[assign[s.tenant_id]] += w[s.tenant_id]
        members[assign[s.tenant_id]].append(s.tenant_id)
    # every accepted move strictly decreases the load variance
    # (condition below implies wt < loads[hi] - loads[lo]), so the loop
    # terminates; the iteration cap is a belt for float dust.  Donors
    # are tried in descending load order — a shard whose whole load is
    # one indivisible head tenant is optimal already and must not stop
    # the rest of the fleet from leveling.
    for _ in range(8 * len(specs)):
        lo = min(range(n_shards), key=lambda i: (loads[i], i))
        moved = False
        for hi in sorted(range(n_shards), key=lambda i: (-loads[i], i)):
            if hi == lo or loads[hi] <= loads[lo]:
                break
            # heaviest first (ties broken by tenant id for
            # determinism): moving a head tenant off the hot shard is
            # the whole point
            for tid in sorted(members[hi], key=lambda t: (-w[t], t)):
                wt = w[tid]
                if max(loads[hi] - wt, loads[lo] + wt) \
                        < loads[hi] - 1e-12:
                    members[hi].remove(tid)
                    members[lo].append(tid)
                    loads[hi] -= wt
                    loads[lo] += wt
                    assign[tid] = lo
                    moved = True
                    break
            if moved:
                break
        if not moved:
            break
    return assign


def fold_verdicts(parts: Sequence[Sequence[tuple]]) -> List[tuple]:
    """Barrier fold of per-shard RCA results: each shard worker appends
    ``(seq, verdict, wall_s)`` tuples for the tenants it owns; merging
    on ``seq`` (the coordinator's enqueue order) makes the folded stream
    IDENTICAL to the 1-shard engine's — the RCA half of the shard
    determinism contract (wall_s legitimately varies; the verdicts carry
    no wall fields, so byte-comparison holds)."""
    out = [item for part in parts for item in part]
    out.sort(key=lambda item: item[0])
    return out


def fold_leg_records(legs: Sequence[dict]) -> List[dict]:
    """Barrier fold of per-shard flight-journal leg records: each
    shard's runner contributes one ``{"shard": s, ...}`` wall/dispatch
    delta for the tick; merging on the shard id makes the journaled
    order deterministic regardless of which worker finished first — the
    :func:`fold_verdicts` idiom, flight-recorder half (the leg contents
    are wall-clock/topology and ride the journal's VARIANT tier; only
    their ORDER is part of the record's determinism)."""
    out = [dict(leg) for leg in legs]
    out.sort(key=lambda leg: leg["shard"])
    return out


def fold_tree(parts: Sequence, combine) -> object:
    """Deterministic binary fold tree over per-shard barrier payloads.

    ``parts`` arrive in fixed shard order (the caller's contract) and
    pair off bottom-up — ``((s0, s1), (s2, s3))`` — so the combine
    schedule is a function of the part COUNT alone, never of which
    worker finished first: the reduction is reproducible at any shard
    count and any completion order, the fold_verdicts/fold_from idiom
    lifted to an O(log n)-depth tree (the Sparse Allreduce shape,
    PAPERS.md).  ``combine`` must be associative over adjacent parts;
    an empty sequence folds to None."""
    items = list(parts)
    if not items:
        return None
    while len(items) > 1:
        paired = [combine(items[i], items[i + 1])
                  for i in range(0, len(items) - 1, 2)]
        if len(items) % 2:
            paired.append(items[-1])
        items = paired
    return items[0]


def join_all(workers) -> None:
    """Barrier over submitted workers that COMPLETES before any error
    propagates: raising at the first failed join would leave sibling
    tasks running, and the next submit would desynchronize their
    done-events (a later join could observe the old task's completion).
    Re-raises the first collected error after every join returned."""
    errs = []
    for w in workers:
        try:
            w.join()
        except BaseException as e:           # noqa: BLE001 — re-raised
            errs.append(e)
    if errs:
        raise errs[0]


class ShardWorker:
    """One persistent engine worker thread.

    The coordinator submits ONE closure per tick (the shard's slice of
    the served batches) and joins at the barrier; the worker executes it
    against state only this shard ever touches.  Exceptions propagate to
    the coordinator at join() — a failed shard must fail the tick, not
    silently drop its tenants' scoring.

    This submit/join/close/``alive`` surface IS the worker seam: the
    engine, the supervisor's respawn path and the elastic policy's
    scale edges drive every worker kind through it.
    :class:`anomod.serve.procshard.ProcShardWorker` presents the same
    four members over a spawn-context worker PROCESS (submit takes a
    picklable command dict instead of a closure — a process cannot
    share the engine's memory, so the engine hands it data, not code).
    """

    def __init__(self, shard_id: int, name: str = "anomod-serve-shard"):
        self.shard_id = shard_id
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._exc: BaseException | None = None
        self._dying = False
        self._thread = threading.Thread(
            target=self._loop, name=f"{name}-{shard_id}", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            fn = self._q.get()
            if fn is None:
                return
            die = False
            try:
                fn()
            except BaseException as e:       # noqa: BLE001 — re-raised at join
                self._exc = e
                # an injected worker CRASH (anomod.serve.chaos, duck-
                # typed so this module stays import-free) reports its
                # error at the barrier like any failure, then the
                # thread itself dies — respawning is the supervisor's
                # job, exactly like the paper's force-delete-and-respawn.
                # ``_dying`` flips BEFORE the done event: the joiner
                # wakes strictly after ``alive`` reads False, so a
                # respawn check can never race the thread's last
                # instructions and submit to a queue nobody drains.
                die = bool(getattr(e, "kills_worker", False))
                if die:
                    self._dying = True
            finally:
                self._done.set()
            if die:
                return

    def submit(self, fn) -> None:
        """Queue one task; pair every submit with a :meth:`join`."""
        self._done.clear()
        self._q.put(fn)

    def join(self) -> None:
        """Barrier: wait for the submitted task; re-raise its error."""
        self._done.wait()
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise exc

    def close(self) -> None:
        """Stop the worker thread and settle its books.

        A worker still parked mid-task past the join timeout cannot be
        force-killed in-process — but abandoning it SILENTLY hid two
        failure modes: the hang itself (now counted,
        ``anomod_serve_shard_close_timeout_total``, and warned) and any
        task error nobody joined (now re-raised here instead of dying
        with the thread)."""
        self._q.put(None)
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            import warnings

            from anomod import obs
            obs.counter("anomod_serve_shard_close_timeout_total").inc()
            warnings.warn(
                f"shard worker {self.shard_id} still running 5 s after "
                "close(); abandoning the daemon thread (its task error, "
                "if any, will be lost)", RuntimeWarning, stacklevel=2)
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise exc

    @property
    def alive(self) -> bool:
        return self._thread.is_alive() and not self._dying
