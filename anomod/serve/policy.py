"""ElasticPolicy: signal-fed autoscaling for the serving plane.

PR 10 shipped the MECHANISM of elastic tenancy — supervised migration of
live tenants between shards through the ``get_state``/``set_state``
seams with no score gap.  This module is the POLICY half: an
:class:`ElasticPolicy` the coordinator evaluates at every tick boundary
(``ANOMOD_SERVE_POLICY=auto|script``), emitting scale-up / scale-down /
rebalance / brownout decisions that the engine executes through the
same seams at POLICY time instead of failure time.

**Determinism contract.**  Every input is canonical — a function of
seed+config alone: per-tenant served-span counts (the admission plane's
drain decisions), per-shard staged-chunk counts (the canonical
dispatch book of :meth:`anomod.serve.batcher.BucketRunner.leg_walls`,
whose WALL fields are deliberately never read — a wall-fed policy could
not replay), backlog depth, and the shed delta.  EWMAs update once per
virtual tick (the "quantized to virtual ticks" rule), so the whole
decision stream is a pure function of the seed: a rerun, an ``anomod
audit replay``, and the original run all produce the SAME scaling
schedule.  And because admission/drain/shed stay on the coordinator and
tenant bits are placement-invariant (the PR-5/8/10 pins), an elastic
run's states, alerts, SLO and shed are byte-identical to a STATIC run
of the same seed with the policy off.

**Hysteresis & cooldown.**  Scale-up needs the backlog-ratio EWMA above
:data:`UP_BACKLOG_RATIO` for :data:`SUSTAIN_TICKS` consecutive ticks;
scale-down needs it below :data:`DOWN_BACKLOG_RATIO` as long — and the
two thresholds are far apart, so the policy cannot flap between them.
``ANOMOD_SERVE_POLICY_COOLDOWN_TICKS`` spaces EXECUTED decisions.

**Brownout ladder.**  Sustained pressure at the
``ANOMOD_SERVE_POLICY_MAX_SHARDS`` ceiling degrades auxiliary planes
BEFORE tenants shed, one rung per cooldown: level 1 tightens the
online-RCA budget to one run per tick, level 2 additionally coarsens
the flight-recorder state-digest cadence 4×.  Pressure falling below
:data:`BROWNOUT_LO_RATIO` relaxes the ladder in REVERSE order (digest
cadence first, RCA budget last).  The ladder never touches admission:
shedding stays the admission controller's decision, byte-identical to
the static run.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

from anomod import obs
from anomod.config import validate_policy_script
from anomod.serve.queues import TenantSpec
from anomod.serve.shard import served_rate_model

__all__ = ["ElasticPolicy", "TickSignals", "plan_rebalance",
           "ALPHA", "SUSTAIN_TICKS", "UP_BACKLOG_RATIO",
           "DOWN_BACKLOG_RATIO", "BROWNOUT_HI_RATIO",
           "BROWNOUT_LO_RATIO", "MAX_BROWNOUT_LEVEL"]

#: EWMA smoothing for every policy signal (per virtual tick): heavy
#: enough that one spiky tick cannot trigger an episode, light enough
#: that a real surge registers within SUSTAIN_TICKS
ALPHA = 0.5
#: consecutive ticks a threshold must hold before a decision fires —
#: the time half of the hysteresis contract (the level half is the
#: UP/DOWN threshold gap)
SUSTAIN_TICKS = 2
#: pressure EWMA (max of backlog-fill ratio and budget-normalized shed
#: rate, see TickSignals.pressure) above which the fleet is overloaded:
#: scale up (or climb the brownout ladder at the shard ceiling)
UP_BACKLOG_RATIO = 0.5
#: pressure EWMA below which the fleet is idle enough to scale down
#: (far from UP_BACKLOG_RATIO on purpose — no flapping band)
DOWN_BACKLOG_RATIO = 0.05
#: pressure past this at the shard ceiling climbs the brownout ladder
BROWNOUT_HI_RATIO = 0.85
#: pressure below this relaxes the brownout ladder one rung
BROWNOUT_LO_RATIO = 0.3
#: the ladder's top rung (1 = RCA budget, 2 = + flight digest cadence)
MAX_BROWNOUT_LEVEL = 2


@dataclasses.dataclass
class TickSignals:
    """One tick's canonical policy inputs, assembled by the coordinator
    at the tick boundary.  Everything here is seed-determined — the
    audit-replay contract's precondition."""
    tick: int                        #: 0-based virtual tick index
    served_by_tenant: Dict[int, int]  #: spans drained per tenant
    per_shard_chunks: Sequence[int]  #: staged-chunk deltas per shard
    #: (leg_walls' canonical dispatch book — never its wall fields)
    backlog_spans: int
    max_backlog: int
    shed_delta: int                  #: spans shed this tick
    budget_spans: float              #: capacity * tick_s (the drain
    #: budget — what shed/backlog normalize against)

    def pressure(self) -> float:
        """The tick's overload pressure in [0, ~1+]: the max of the
        backlog-fill ratio and the shed rate normalized by the drain
        budget (clamped to 1).  Backlog alone oscillates with drain
        quantization — a whole retained backlog can drain in one tick
        while shedding continues — so the shed term is what keeps the
        signal steady through a sustained surge."""
        ratio = (self.backlog_spans / self.max_backlog
                 if self.max_backlog else 0.0)
        shed = (min(1.0, self.shed_delta / self.budget_spans)
                if self.budget_spans > 0 else 0.0)
        return max(ratio, shed)


def plan_rebalance(shard_of: Dict[int, int], n_shards: int,
                   specs: Sequence[TenantSpec],
                   live_rates: Dict[int, float],
                   capacity_spans_per_s: float,
                   k: int, dead: Sequence[int] = ()) -> List[tuple]:
    """The rebalance pass: up to ``k`` ``(tenant_id, dst_shard)`` moves
    of the hottest tenants off the most-loaded shard.

    The weights are :func:`anomod.serve.shard.served_rate_model` over
    the LIVE served-rate EWMAs (not the static spec rates — the skew
    being fixed is the one the traffic actually produced), solved
    against capacity exactly like initial placement.  Greedy and
    strictly improving: each move goes from the currently most- to the
    currently least-loaded shard and must shrink the load spread, so a
    balanced fleet yields an empty plan.  ``dead`` shards (past their
    respawn budget, PR-10) are never chosen as a destination — an idle
    shard that is idle because it is DEAD is not spare capacity.
    Deterministic in the arguments alone (ties break on tenant/shard
    id)."""
    if n_shards < 2 or k < 1:
        return []
    pool = [i for i in range(n_shards) if i not in set(dead)]
    if len(pool) < 2:
        return []
    live_specs = [dataclasses.replace(
        s, rate_spans_per_s=float(live_rates.get(s.tenant_id, 0.0)))
        for s in specs]
    w = served_rate_model(live_specs, capacity_spans_per_s)
    loads = [0.0] * n_shards
    members: List[List[int]] = [[] for _ in range(n_shards)]
    for s in specs:
        sh = shard_of.get(s.tenant_id, 0)
        loads[sh] += w.get(s.tenant_id, 0.0)
        members[sh].append(s.tenant_id)
    moves: List[tuple] = []
    for _ in range(k):
        hi = max(pool, key=lambda i: (loads[i], -i))
        lo = min(pool, key=lambda i: (loads[i], i))
        if hi == lo or loads[hi] <= loads[lo]:
            break
        moved = False
        for tid in sorted(members[hi],
                          key=lambda t: (-w.get(t, 0.0), t)):
            wt = w.get(tid, 0.0)
            if wt <= 0:
                break
            if max(loads[hi] - wt, loads[lo] + wt) < loads[hi] - 1e-12:
                members[hi].remove(tid)
                members[lo].append(tid)
                loads[hi] -= wt
                loads[lo] += wt
                moves.append((tid, lo))
                moved = True
                break
        if not moved:
            break
    return moves


class ElasticPolicy:
    """The coordinator's tick-boundary scaling brain.

    ``mode`` is the validated ``ANOMOD_SERVE_POLICY`` value: ``auto``
    decides from the signal EWMAs (hysteresis + cooldown), ``script``
    replays a fixed ``ANOMOD_SERVE_POLICY_SCRIPT`` schedule (the
    episode-determinism probe; min/max clamps still apply at
    execution).  The engine owns EXECUTION — this class only observes
    canonical signals and emits decision dicts."""

    def __init__(self, mode: str, min_shards: int, max_shards: int,
                 target_imbalance: float, cooldown_ticks: int,
                 script: str = ""):
        if mode not in ("auto", "script"):
            raise ValueError(f"unknown policy mode {mode!r} "
                             "(auto|script; off = no policy object)")
        self.mode = mode
        self.min_shards = int(min_shards)
        self.max_shards = int(max_shards)
        if not 1 <= self.min_shards <= self.max_shards:
            raise ValueError(
                f"policy shard envelope must satisfy 1 <= min <= max, "
                f"got [{self.min_shards}, {self.max_shards}]")
        self.target_imbalance = float(target_imbalance)
        if self.target_imbalance < 1.0:
            raise ValueError("target imbalance must be >= 1.0")
        self.cooldown_ticks = int(cooldown_ticks)
        if self.cooldown_ticks < 1:
            raise ValueError("cooldown must be >= 1 tick")
        self.script = str(script).strip()
        self._script_actions = validate_policy_script(self.script)
        if mode == "script" and not self._script_actions:
            raise ValueError(
                "ANOMOD_SERVE_POLICY=script needs a non-empty "
                "ANOMOD_SERVE_POLICY_SCRIPT (an empty scripted policy "
                "is a misconfiguration, not a quiet static run)")
        #: per-tenant served-rate EWMA (spans per tick) — the live-rate
        #: input of the rebalance plan
        self.rate_ewma: Dict[int, float] = {}
        #: per-shard staged-chunk EWMA (the leg_walls dispatch book) —
        #: the imbalance signal's numerator
        self.chunk_ewma: List[float] = []
        self.pressure_ewma = 0.0
        self.brownout_level = 0
        self._up_streak = 0
        self._down_streak = 0
        self._hot_streak = 0
        self._cool_streak = 0
        self._last_scale_tick: Optional[int] = None
        self._last_brownout_tick: Optional[int] = None
        #: pacing stamp for rebalance ATTEMPTS that turned out to be
        #: no-ops — separate from the executed-decision cooldown, so a
        #: fleet whose imbalance cannot improve (one unsplittable hot
        #: tenant) never delays a genuinely needed scale-up
        self._last_rebalance_try: Optional[int] = None
        self.n_scale_ups = 0
        self.n_scale_downs = 0
        self.n_rebalances = 0
        self.n_migrated = 0
        self.brownout_ticks = 0
        self._obs_ups = obs.counter("anomod_serve_policy_scale_ups_total")
        self._obs_downs = obs.counter(
            "anomod_serve_policy_scale_downs_total")
        self._obs_rebal = obs.counter(
            "anomod_serve_policy_rebalances_total")
        self._obs_migrated = obs.counter(
            "anomod_serve_policy_migrated_tenants_total")
        self._obs_level = obs.gauge("anomod_serve_policy_brownout_level")
        self._obs_ratio = obs.gauge(
            "anomod_serve_policy_pressure_ewma")
        self._obs_shards = obs.gauge("anomod_serve_policy_shards")

    # -- signal fold (once per virtual tick — the quantization rule) ----

    def observe(self, sig: TickSignals) -> None:
        self.pressure_ewma += ALPHA * (sig.pressure()
                                       - self.pressure_ewma)
        self._obs_ratio.set(self.pressure_ewma)
        # decay every known tenant, then fold this tick's served spans:
        # an idle tenant's rate must decay toward zero or one historic
        # burst would pin it "hot" forever
        for tid in self.rate_ewma:
            self.rate_ewma[tid] *= (1.0 - ALPHA)
        for tid in sorted(sig.served_by_tenant):
            self.rate_ewma[tid] = self.rate_ewma.get(tid, 0.0) \
                + ALPHA * sig.served_by_tenant[tid]
        chunks = list(sig.per_shard_chunks)
        if len(self.chunk_ewma) != len(chunks):
            # topology changed since last tick: new shards start cold,
            # retired shards drop off the end (the engine always grows/
            # shrinks at the tail, so indexes stay aligned)
            self.chunk_ewma = (self.chunk_ewma + [0.0] * len(chunks)
                               )[:len(chunks)]
        for i, c in enumerate(chunks):
            self.chunk_ewma[i] += ALPHA * (c - self.chunk_ewma[i])
        # streak bookkeeping (the SUSTAIN half of the hysteresis)
        self._up_streak = self._up_streak + 1 \
            if self.pressure_ewma > UP_BACKLOG_RATIO else 0
        self._down_streak = self._down_streak + 1 \
            if self.pressure_ewma < DOWN_BACKLOG_RATIO else 0
        self._hot_streak = self._hot_streak + 1 \
            if self.pressure_ewma > BROWNOUT_HI_RATIO else 0
        self._cool_streak = self._cool_streak + 1 \
            if self.pressure_ewma < BROWNOUT_LO_RATIO else 0
        if self.brownout_level:
            self.brownout_ticks += 1

    def imbalance(self) -> float:
        """max/mean of the per-shard chunk EWMAs (1.0 when unloaded or
        single-shard) — the rebalance trigger."""
        if len(self.chunk_ewma) < 2:
            return 1.0
        mean = sum(self.chunk_ewma) / len(self.chunk_ewma)
        return max(self.chunk_ewma) / mean if mean > 0 else 1.0

    # -- decisions ------------------------------------------------------

    def _cooldown_ok(self, tick: int) -> bool:
        return (self._last_scale_tick is None
                or tick - self._last_scale_tick >= self.cooldown_ticks)

    def _brownout_ok(self, tick: int) -> bool:
        return (self._last_brownout_tick is None
                or tick - self._last_brownout_tick >= self.cooldown_ticks)

    def _rebalance_ok(self, tick: int) -> bool:
        return (self._last_rebalance_try is None
                or tick - self._last_rebalance_try >= self.cooldown_ticks)

    def decide(self, tick: int, shards: int) -> List[dict]:
        """The tick's decision list (usually empty; at most one scaling
        action plus at most one brownout step).  ``observe`` must have
        folded this tick's signals first.  Decisions carry only intent —
        the engine clamps against the live envelope and journals what
        actually executed."""
        if self.mode == "script":
            return [dict(a) for a in self._script_actions
                    if a["tick"] == tick]
        out: List[dict] = []
        if self._up_streak >= SUSTAIN_TICKS and self._cooldown_ok(tick):
            if shards < self.max_shards:
                out.append({"action": "up", "tick": tick})
            elif self._hot_streak >= SUSTAIN_TICKS \
                    and self.brownout_level < MAX_BROWNOUT_LEVEL \
                    and self._brownout_ok(tick):
                out.append({"action": "brownout", "tick": tick,
                            "level": self.brownout_level + 1})
        elif self._down_streak >= SUSTAIN_TICKS:
            # relax the ladder BEFORE shrinking the fleet (reverse
            # degradation order: restore observability first)
            if self.brownout_level > 0 and self._brownout_ok(tick):
                out.append({"action": "brownout", "tick": tick,
                            "level": self.brownout_level - 1})
            elif shards > self.min_shards and self._cooldown_ok(tick):
                out.append({"action": "down", "tick": tick})
        elif self.brownout_level > 0 \
                and self._cool_streak >= SUSTAIN_TICKS \
                and self._brownout_ok(tick):
            out.append({"action": "brownout", "tick": tick,
                        "level": self.brownout_level - 1})
        if not out and shards > 1 \
                and self.imbalance() > self.target_imbalance \
                and self._cooldown_ok(tick) and self._rebalance_ok(tick):
            out.append({"action": "rebalance", "tick": tick, "k": 1})
        return out

    # -- execution bookkeeping (the engine reports back) ---------------

    def note_executed(self, action: str, tick: int,
                      migrated: int = 0, level: int = 0,
                      shards: int = 0) -> None:
        """Record an action the engine actually EXECUTED (clamped or
        skipped decisions never reach here): counters, cooldown stamps
        and the brownout level all key off execution, so a decision the
        envelope refused cannot burn the cooldown."""
        self.n_migrated += migrated
        self._obs_migrated.inc(migrated)
        if action == "up":
            self.n_scale_ups += 1
            self._obs_ups.inc()
            self._last_scale_tick = tick
        elif action == "down":
            self.n_scale_downs += 1
            self._obs_downs.inc()
            self._last_scale_tick = tick
        elif action == "rebalance":
            self.n_rebalances += 1
            self._obs_rebal.inc()
            self._last_scale_tick = tick
            self._last_rebalance_try = tick
        elif action == "brownout":
            self.brownout_level = level
            self._obs_level.set(level)
            self._last_brownout_tick = tick
        if shards:
            self._obs_shards.set(shards)

    def note_noop(self, tick: int) -> None:
        """Stamp the REBALANCE-attempt pacing for a decision the engine
        evaluated but had nothing to do (an already-balanced or
        unimprovable rebalance): without the stamp the auto policy
        would re-emit the same no-op every tick until the signal moved.
        Deliberately NOT the executed-decision cooldown — a no-op must
        never delay a genuinely needed scale-up/down (the cooldown
        spaces EXECUTED decisions, the documented contract)."""
        self._last_rebalance_try = tick
