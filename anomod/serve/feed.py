"""Live telemetry feed: streaming observability endpoints into the tick.

The reference's dataset is collected from LIVE interfaces — Prometheus
``query_range``, Jaeger REST — and PR-era ``anomod.io.live`` ships the
one-shot batch collectors.  This module is the streaming half: a
:class:`LiveFeed` traffic source that drives ``ServeEngine.run`` from
those same interfaces by watermark-tailed incremental polling, one poll
sweep per virtual tick.

Three design rules keep live runs as auditable as everything else:

- **Walls are measured, never consulted.**  The only wall-clock read is
  ONE anchor (``t0_wall_s``) captured at construction and recorded in
  the wire journal.  Every poll window is a pure function of (anchor,
  virtual tick bounds, watermarks from previous responses), and every
  collected sample is re-stamped onto the virtual clock through the
  explicit bridge ``t_virt = t_wall - t0_wall + lag`` — the lag budget
  (``ANOMOD_SERVE_FEED_LAG_S``) keeps the feed asking only for data old
  enough to be complete, and a straggler landing behind the current
  tick is clamped forward to the tick's open edge (gap-fill, counted on
  ``anomod_feed_gaps_total``).
- **Every response is journaled.**  The transport seam records each
  HTTP response the feed consumes, in sequence
  (:class:`RecordingTransport` → ``ANOMOD_FEED_JOURNAL``, atomic
  publish); :class:`ReplayTransport` re-serves the journal, so a live
  run and its replay execute the SAME response sequence and therefore
  produce byte-identical states/alerts/SLO/shed and equal canonical
  flight journals (``anomod audit diff``).
- **Deterministic corpus windowing.**  The metric→span synthesis
  (:func:`anomod.obs.selfscrape.spans_from_metrics`) is stateful across
  a corpus (first-difference + early-sample scale normalization), so
  the feed re-runs it over the WHOLE accumulated row corpus each tick
  and emits only the spans landing in the tick's window — the emitted
  sequence is a pure function of the response sequence, never of how
  the corpus was chunked.

Sources (any subset):

- ``scrape_url`` — a Prometheus text-exposition endpoint, fetched whole
  each tick and stamped at the tick's open edge.  Pointing this at the
  framework's OWN ``/metrics`` (anomod.obs.http) is the dogfood closed
  loop: ``anomod serve --from-live self``.
- ``prom_url`` + ``prom_queries`` — ``query_range`` polls through
  :meth:`anomod.io.live.PrometheusClient.query_range_since`.
- ``jaeger_url`` — per-service trace polls through
  :meth:`anomod.io.live.JaegerClient.traces_since`; spans map straight
  onto the span IR with virtualized start times.
"""

from __future__ import annotations

import json
import time
import urllib.parse
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from anomod.io.live import HttpTransport, TransportError
from anomod.obs.registry import get_registry, render_labels
from anomod.serve.queues import TenantSpec

#: wire-journal document format (bumped on schema change; load refuses
#: mismatches the way the flight journal does)
FEED_WIRE_FORMAT = 1

#: bounded trace-id table for synthesized feed spans (the PowerLaw idiom)
_TRACE_IDS = tuple(f"t{i:02x}" for i in range(64))


# ---------------------------------------------------------------------------
# Prometheus text-exposition parsing (the scrape read side)
# ---------------------------------------------------------------------------

def _unescape_label_value(raw: str) -> str:
    """Inverse of :func:`anomod.obs.export.escape_label_value`: ``\\\\``,
    ``\\"`` and ``\\n`` back to their characters; an unknown escape
    keeps the backslash literally (the exposition grammar's behavior)."""
    out: List[str] = []
    i, n = 0, len(raw)
    while i < n:
        c = raw[i]
        if c == "\\" and i + 1 < n:
            nxt = raw[i + 1]
            if nxt == "\\":
                out.append("\\")
            elif nxt == '"':
                out.append('"')
            elif nxt == "n":
                out.append("\n")
            else:
                out.append(c)
                out.append(nxt)
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_label_block(line: str, start: int) -> Tuple[Dict[str, str], int]:
    """Parse ``k="v",...}`` starting just past the ``{``; returns the
    label dict and the index just past the closing ``}``."""
    labels: Dict[str, str] = {}
    i, n = start, len(line)
    while i < n:
        while i < n and line[i] in ", \t":
            i += 1
        if i < n and line[i] == "}":
            return labels, i + 1
        eq = line.find("=", i)
        if eq < 0 or eq + 1 >= n or line[eq + 1] != '"':
            raise ValueError(f"malformed label block: {line!r}")
        key = line[i:eq].strip()
        j = eq + 2
        buf: List[str] = []
        while j < n:
            c = line[j]
            if c == "\\" and j + 1 < n:
                buf.append(c)
                buf.append(line[j + 1])
                j += 2
                continue
            if c == '"':
                break
            buf.append(c)
            j += 1
        if j >= n:
            raise ValueError(f"unterminated label value: {line!r}")
        labels[key] = _unescape_label_value("".join(buf))
        i = j + 1
    raise ValueError(f"unterminated label block: {line!r}")


def parse_prometheus_text(text: str) -> List[Tuple[str, str, float]]:
    """Exposition-format text -> ``(sample_name, labels_str, value)``
    rows, with ``labels_str`` the registry's canonical UNESCAPED
    rendering (:func:`anomod.obs.registry.render_labels`) so a scrape of
    the framework's own endpoint round-trips exactly to its registry
    journal rows — the adversarial-label pin in tests/test_feed.py.

    Comment/blank lines and unparseable sample values are skipped (the
    reference collectors' tolerance); a structurally broken label block
    raises, because silently dropping half a scrape is how divergence
    hides."""
    rows: List[Tuple[str, str, float]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        brace = line.find("{")
        sp = line.find(" ")
        if brace >= 0 and (sp < 0 or brace < sp):
            name = line[:brace]
            labels, end = _parse_label_block(line, brace + 1)
            rest = line[end:].strip()
        else:
            name, _, rest = line.partition(" ")
            labels = {}
        val_tok = rest.split()[0] if rest.split() else ""
        try:
            value = float(val_tok)
        except ValueError:
            continue
        rows.append((name, render_labels(labels), value))
    return rows


# ---------------------------------------------------------------------------
# The wire journal + its two transports
# ---------------------------------------------------------------------------

def _norm(doc) -> object:
    """JSON-normalize a params/payload value so live-recorded and
    journal-loaded copies compare equal (tuples->lists, int/float unify
    through the JSON number grammar)."""
    return json.loads(json.dumps(doc, sort_keys=True))


def _url_path(url: str) -> str:
    """Host/port-free request identity: replay must match a journal
    recorded against a different (ephemeral) port."""
    return urllib.parse.urlparse(url).path


class RecordingTransport:
    """Transport seam that records every successful response, in
    sequence, while delegating to a real :class:`HttpTransport` (whose
    retry/backoff policy is unchanged — only the FINAL response of a
    retried request is journaled, which is the one the feed consumed)."""

    def __init__(self, inner: Optional[HttpTransport] = None):
        self.inner = inner if inner is not None else HttpTransport()
        self.entries: List[dict] = []

    def _record(self, kind: str, url: str, payload, params, body) -> None:
        self.entries.append({
            "kind": kind, "path": _url_path(url),
            "params": _norm(params if params is not None else {}),
            "payload": _norm(payload) if payload is not None else None,
            "body": _norm(body) if kind == "json" else body,
        })

    def request_json(self, url: str, payload: Optional[dict] = None,
                     params: Optional[dict] = None):
        doc = self.inner.request_json(url, payload=payload, params=params)
        self._record("json", url, payload, params, doc)
        return doc

    def request_text(self, url: str, params: Optional[dict] = None) -> str:
        text = self.inner.request_text(url, params=params)
        self._record("text", url, None, params, text)
        return text


class ReplayTransport:
    """Re-serve a recorded wire journal, strictly in sequence.

    Every request must match the next journal entry on (kind, URL path,
    params, payload) — host and port are NOT part of the identity, so a
    journal recorded against an ephemeral dogfood port replays anywhere.
    A mismatch or an exhausted journal raises :class:`TransportError`:
    a replay that would silently serve the wrong response is worse than
    one that fails loudly."""

    def __init__(self, entries: Sequence[dict]):
        self.entries = list(entries)
        self._next = 0

    def _take(self, kind: str, url: str, payload, params):
        if self._next >= len(self.entries):
            raise TransportError(
                f"feed journal exhausted: no entry for {kind} "
                f"{_url_path(url)} (served {self._next})")
        entry = self.entries[self._next]
        want = {"kind": kind, "path": _url_path(url),
                "params": _norm(params if params is not None else {}),
                "payload": _norm(payload) if payload is not None else None}
        got = {k: entry.get(k) for k in want}
        if want != got:
            raise TransportError(
                f"feed journal divergence at entry {self._next}: "
                f"request {want} != recorded {got}")
        self._next += 1
        return entry["body"]

    def request_json(self, url: str, payload: Optional[dict] = None,
                     params: Optional[dict] = None):
        return self._take("json", url, payload, params)

    def request_text(self, url: str, params: Optional[dict] = None) -> str:
        return self._take("text", url, None, params)

    @property
    def n_served(self) -> int:
        return self._next


def dump_feed_journal(path, header: dict, entries: Sequence[dict]) -> Path:
    """Atomic publish (the io/cache idiom, via the flight journal's one
    writer) of the wire-journal document."""
    from anomod.obs.flight import _atomic_write_json
    return _atomic_write_json(path, {
        "feed_format": FEED_WIRE_FORMAT, "header": dict(header),
        "entries": list(entries)})


def load_feed_journal(path) -> dict:
    """Load a wire journal; fails loud on a non-feed document."""
    doc = json.loads(Path(path).read_text())
    if not isinstance(doc, dict) or "entries" not in doc \
            or doc.get("feed_format") != FEED_WIRE_FORMAT:
        raise ValueError(f"not a feed wire journal (format "
                         f"{FEED_WIRE_FORMAT}): {path}")
    return doc


# ---------------------------------------------------------------------------
# The feed itself
# ---------------------------------------------------------------------------

class LiveFeed:
    """Engine traffic source polling live observability endpoints.

    Implements the engine's duck-typed surface (``arrivals(lo, hi)`` /
    ``modality_arrivals`` / ``specs``): each ``arrivals`` call runs one
    poll sweep over the configured sources, folds fresh data into the
    virtual-stamped corpus, and returns the per-tenant span batches
    whose virtual start times land in ``[lo, hi)``.

    Tenant/service identity: every collected stream carries a source
    token (the metric subsystem for scrape/Prometheus rows, the service
    name for Jaeger spans); tokens map to the fixed tenant/service slots
    in first-seen order, clipped to the declared fleet size — the
    selfscrape subsystem mapping, extended to live sources.  The fleet
    shape is declared up front (``n_tenants`` / ``n_services``) because
    the engine needs its spec table at construction, before the first
    poll can discover anything.
    """

    def __init__(self, scrape_url: Optional[str] = None,
                 prom_url: Optional[str] = None,
                 prom_queries: Sequence[str] = (),
                 jaeger_url: Optional[str] = None,
                 n_tenants: int = 8, n_services: int = 8,
                 lag_s: Optional[float] = None,
                 step: str = "15s",
                 transport=None,
                 t0_wall_s: Optional[float] = None):
        if not (scrape_url or prom_url or jaeger_url):
            raise ValueError("LiveFeed needs at least one source "
                             "(scrape_url, prom_url or jaeger_url)")
        if prom_url and not prom_queries:
            raise ValueError("prom_url needs prom_queries")
        if n_tenants < 1 or n_services < 1:
            raise ValueError("n_tenants and n_services must be >= 1")
        from anomod.config import get_config
        cfg = get_config()
        self.scrape_url = scrape_url
        self.prom_url = prom_url
        self.prom_queries = tuple(prom_queries)
        self.jaeger_url = jaeger_url
        self.n_tenants = int(n_tenants)
        self.lag_s = float(cfg.serve_feed_lag_s if lag_s is None
                           else lag_s)
        self.step = str(step)
        self.transport = transport if transport is not None \
            else RecordingTransport()
        # THE one wall-clock read that feeds decisions — and only via
        # the journal: recorded in the header, reused verbatim on replay
        # anomod-lint: disable=D101 — the live anchor IS a wall read by definition; it lands in the wire-journal header and replay reuses it verbatim, so decisions stay functions of the journal
        self.t0_wall_s = float(time.time() if t0_wall_s is None
                               else t0_wall_s)
        self.services: Tuple[str, ...] = tuple(
            f"live{i:02d}" for i in range(int(n_services)))
        self.specs: List[TenantSpec] = [
            TenantSpec(tenant_id=t, name=f"feed{t:04d}", priority=t % 3,
                       rate_spans_per_s=100.0)
            for t in range(self.n_tenants)]
        self.faults: Dict[int, object] = {}
        # source clients share the (recording or replay) transport
        self._prom = None
        if prom_url:
            from anomod.io.live import PrometheusClient
            self._prom = PrometheusClient(prom_url,
                                          transport=self.transport)
        self._jaeger = None
        if jaeger_url:
            from anomod.io.live import JaegerClient
            self._jaeger = JaegerClient(jaeger_url,
                                        transport=self.transport)
        # watermarks (virtual-bridge state; all derived from responses)
        self._prom_marks: Dict[str, float] = {
            q: self.t0_wall_s - self.lag_s for q in self.prom_queries}
        self._jaeger_services: Optional[List[str]] = None
        self._jaeger_marks: Dict[str, int] = {}
        # corpora (grow monotonically; re-windowed each tick)
        self._mrows: List[Tuple[float, str, str, float]] = []
        self._jspans: List[Tuple[int, str, str, int, bool]] = []
        self._emitted_us = -1      # high-water mark of emitted windows
        # token -> first-seen slot index (tenant AND service identity)
        self._tokens: Dict[str, int] = {}
        self._endpoints: Dict[str, int] = {}
        # feed telemetry (variant plane: measured, never decisive)
        reg = get_registry()
        self._obs_polls = reg.counter("anomod_feed_polls_total")
        self._obs_samples = reg.counter("anomod_feed_samples_total")
        self._obs_spans = reg.counter("anomod_feed_spans_total")
        self._obs_gaps = reg.counter("anomod_feed_gaps_total")
        self._obs_lag = reg.histogram("anomod_feed_lag_s")
        self.n_polls = 0
        self.n_samples = 0
        self.n_spans = 0
        self.n_gaps = 0

    # -- construction from a wire journal (replay mode) --------------------

    @classmethod
    def from_journal(cls, journal, n_tenants: Optional[int] = None,
                     n_services: Optional[int] = None,
                     lag_s: Optional[float] = None) -> "LiveFeed":
        """Rebuild the feed a journal records: same sources, same
        anchor, same lag — served by a :class:`ReplayTransport`, so the
        run needs no network and reproduces the live run's planes
        byte-for-byte."""
        doc = journal if isinstance(journal, dict) \
            else load_feed_journal(journal)
        h = doc.get("header", {})
        return cls(
            scrape_url=h.get("scrape_url") or None,
            prom_url=h.get("prom_url") or None,
            prom_queries=tuple(h.get("prom_queries") or ()),
            jaeger_url=h.get("jaeger_url") or None,
            n_tenants=int(h["n_tenants"] if n_tenants is None
                          else n_tenants),
            n_services=int(h["n_services"] if n_services is None
                           else n_services),
            lag_s=float(h["lag_s"] if lag_s is None else lag_s),
            step=str(h.get("step", "15s")),
            transport=ReplayTransport(doc.get("entries", ())),
            t0_wall_s=float(h["t0_wall_s"]))

    def header(self) -> dict:
        """The wire journal's header: everything replay needs to re-run
        this feed's exact request sequence."""
        return {"scrape_url": self.scrape_url or "",
                "prom_url": self.prom_url or "",
                "prom_queries": list(self.prom_queries),
                "jaeger_url": self.jaeger_url or "",
                "n_tenants": self.n_tenants,
                "n_services": len(self.services),
                "lag_s": self.lag_s, "step": self.step,
                "t0_wall_s": self.t0_wall_s}

    def journal_entries(self) -> List[dict]:
        return list(getattr(self.transport, "entries", ()))

    def dump_journal(self, path) -> Path:
        return dump_feed_journal(path, self.header(),
                                 self.journal_entries())

    # -- the poll sweep ----------------------------------------------------

    def _bridge(self, t_wall_s: float, lo: float) -> float:
        """Wall -> virtual: anchor-relative shift plus the lag budget;
        stragglers clamp forward to the tick's open edge (gap-fill)."""
        t_virt = t_wall_s - self.t0_wall_s + self.lag_s
        self._obs_lag.observe(max(self.lag_s, 0.0))
        if t_virt < lo:
            self.n_gaps += 1
            self._obs_gaps.inc()
            return lo
        return t_virt

    def _poll(self, lo: float, hi: float) -> None:
        # wall-side poll ceiling: a pure function of (anchor, virtual
        # tick edge, lag) — never the local clock, so replay issues the
        # byte-same request parameters
        w_hi = self.t0_wall_s + max(hi - self.lag_s, 0.0)
        if self.scrape_url is not None:
            text = self.transport.request_text(self.scrape_url)
            self.n_polls += 1
            self._obs_polls.inc()
            # scrape rows stamp at the tick's open edge under the same
            # lag budget the bridge applies, so the lag histogram sees
            # the effective ingest lag here too
            self._obs_lag.observe(max(self.lag_s, 0.0))
            rows = parse_prometheus_text(text)
            for name, labels_str, value in rows:
                # whole-endpoint scrapes are point-in-time: stamped at
                # the tick's open edge (pure virtual, no bridge)
                self._mrows.append((lo, name, labels_str, value))
            self.n_samples += len(rows)
            self._obs_samples.inc(len(rows))
        if self._prom is not None:
            for q in self.prom_queries:
                fresh, mark = self._prom.query_range_since(
                    q, self._prom_marks[q], w_hi, step=self.step)
                self._prom_marks[q] = mark
                self.n_polls += 1
                self._obs_polls.inc()
                for ts, val, labels in fresh:
                    name = labels.get("__name__") or q
                    lab = render_labels({k: v for k, v in labels.items()
                                         if k != "__name__"})
                    self._mrows.append(
                        (self._bridge(ts, lo), name, lab, val))
                self.n_samples += len(fresh)
                self._obs_samples.inc(len(fresh))
        if self._jaeger is not None:
            if self._jaeger_services is None:
                self._jaeger_services = sorted(self._jaeger.services())
                mark0 = int((self.t0_wall_s - self.lag_s) * 1e6)
                self._jaeger_marks = {s: mark0
                                      for s in self._jaeger_services}
            for svc in self._jaeger_services:
                fresh, mark = self._jaeger.traces_since(
                    svc, self._jaeger_marks[svc], int(w_hi * 1e6))
                self._jaeger_marks[svc] = mark
                self.n_polls += 1
                self._obs_polls.inc()
                n_here = 0
                for tr in fresh:
                    for sp in tr.get("spans") or []:
                        start_wall_s = float(sp.get("startTime", 0)) / 1e6
                        t_virt = self._bridge(start_wall_s, lo)
                        self._jspans.append((
                            int(round(t_virt * 1e6)), str(svc),
                            str(sp.get("operationName") or "op"),
                            max(int(sp.get("duration", 0)), 1),
                            bool(any(
                                t.get("key") == "error"
                                and str(t.get("value")).lower() == "true"
                                for t in sp.get("tags") or ()))))
                        n_here += 1
                self.n_samples += n_here
                self._obs_samples.inc(n_here)

    # -- window synthesis --------------------------------------------------

    def _token_slot(self, token: str) -> int:
        got = self._tokens.get(token)
        if got is None:
            got = len(self._tokens)
            self._tokens[token] = got
        return got

    def _metric_window(self, lo_us: int,
                       hi_us: int) -> List[Tuple[int, str, str, int, bool]]:
        """Re-synthesize spans over the whole metric corpus, keep the
        window — see the module docstring's determinism rule."""
        if not self._mrows:
            return []
        from anomod.obs.export import rows_to_metric_batch
        from anomod.obs.selfscrape import spans_from_metrics
        spans = spans_from_metrics(rows_to_metric_batch(self._mrows))
        if spans.n_spans == 0:
            return []
        m = (spans.start_us >= lo_us) & (spans.start_us < hi_us)
        out = []
        for i in np.nonzero(m)[0]:
            out.append((int(spans.start_us[i]),
                        spans.services[int(spans.service[i])],
                        spans.endpoints[int(spans.endpoint[i])],
                        max(int(spans.duration_us[i]), 1),
                        bool(spans.is_error[i])))
        return out

    def arrivals(self, t_lo_s: float,
                 t_hi_s: float) -> List[Tuple[int, "object"]]:
        from anomod.schemas import KIND_LOCAL, SpanBatch
        self._poll(t_lo_s, t_hi_s)
        lo_us = int(round(t_lo_s * 1e6))
        hi_us = int(round(t_hi_s * 1e6))
        rows = self._metric_window(lo_us, hi_us)
        rows += [r for r in self._jspans
                 if lo_us <= r[0] < hi_us and r[0] > self._emitted_us]
        self._emitted_us = max(self._emitted_us, hi_us - 1)
        if not rows:
            return []
        n_svc = len(self.services)
        by_tenant: Dict[int, List[Tuple[int, int, int, int, bool]]] = {}
        for start_us, token, endpoint, dur_us, is_err in rows:
            slot = self._token_slot(token)
            ep = self._endpoints.setdefault(endpoint,
                                            len(self._endpoints))
            tenant = min(slot, self.n_tenants - 1)
            by_tenant.setdefault(tenant, []).append(
                (start_us, min(slot, n_svc - 1), ep, dur_us, is_err))
        endpoints = tuple(self._endpoints)
        out: List[Tuple[int, SpanBatch]] = []
        for tenant in sorted(by_tenant):
            rs = sorted(by_tenant[tenant])
            n = len(rs)
            batch = SpanBatch(
                trace=(np.arange(n) % len(_TRACE_IDS)).astype(np.int32),
                parent=np.full(n, -1, np.int32),
                service=np.asarray([r[1] for r in rs], np.int32),
                endpoint=np.asarray([r[2] for r in rs], np.int32),
                start_us=np.asarray([r[0] for r in rs], np.int64),
                duration_us=np.asarray([r[3] for r in rs], np.int64),
                is_error=np.asarray([r[4] for r in rs], np.bool_),
                status=np.where(np.asarray([r[4] for r in rs]), 500,
                                200).astype(np.int16),
                kind=np.full(n, KIND_LOCAL, np.int8),
                services=self.services, endpoints=endpoints,
                trace_ids=_TRACE_IDS).validate()
            out.append((tenant, batch))
            self.n_spans += n
            self._obs_spans.inc(n)
        return out

    def modality_arrivals(self, t_lo_s: float, t_hi_s: float) -> List[tuple]:
        """No live log/api planes yet — the surface exists so the engine's
        multimodal path can drive a feed without a hasattr special case."""
        return []


# ---------------------------------------------------------------------------
# The canonical feed run (the run_power_law twin for live sources)
# ---------------------------------------------------------------------------

def run_live_feed(scrape_url: Optional[str] = None,
                  prom_url: Optional[str] = None,
                  prom_queries: Sequence[str] = (),
                  jaeger_url: Optional[str] = None,
                  replay=None,
                  n_tenants: Optional[int] = None,
                  n_services: Optional[int] = None,
                  capacity_spans_per_s: float = 2000.0,
                  duration_s: float = 20.0, tick_s: float = 1.0,
                  lag_s: Optional[float] = None,
                  window_s: float = 5.0, baseline_windows: int = 4,
                  z_threshold: float = 4.0,
                  buckets: Optional[Tuple[int, ...]] = None,
                  lane_buckets: Optional[Tuple[int, ...]] = None,
                  max_backlog: Optional[int] = None,
                  score: bool = True, n_windows: int = 32,
                  fuse: Optional[bool] = None,
                  shards: Optional[int] = None,
                  pipeline: Optional[int] = None,
                  flight: Optional[bool] = None,
                  flight_digest_every: Optional[int] = None,
                  flight_max_ticks: Optional[int] = None,
                  journal=None):
    """Drive one live (or journal-replayed) feed run.

    The ``run_power_law`` twin for live sources: builds the feed, runs
    the engine for ``duration_s`` virtual seconds, writes the flight
    header's replay contract (``traffic="live_feed"`` + the wire-journal
    path, so ``anomod audit replay`` reconstructs the run through
    :class:`ReplayTransport`), and — when ``journal`` (or
    ``ANOMOD_FEED_JOURNAL``) names a path on a LIVE run — publishes the
    wire journal atomically at the end.

    Returns ``(engine, report, feed)``.
    """
    from anomod.config import get_config
    from anomod.serve.engine import ServeEngine, serve_plane_cfg
    cfg = get_config()
    journal_path = cfg.feed_journal if journal is None else Path(journal)
    if replay is not None:
        # None passes through so the wire-journal HEADER sizes the fleet:
        # a replay engine plane mis-sized vs the live run would diverge
        # at the fold digest (sw = n_services * n_windows), not error
        feed = LiveFeed.from_journal(replay, n_tenants=n_tenants,
                                     n_services=n_services, lag_s=lag_s)
        journal_path = None          # a replay never re-records itself
    else:
        feed = LiveFeed(scrape_url=scrape_url, prom_url=prom_url,
                        prom_queries=prom_queries, jaeger_url=jaeger_url,
                        n_tenants=8 if n_tenants is None else n_tenants,
                        n_services=8 if n_services is None else n_services,
                        lag_s=lag_s)
    plane_cfg = serve_plane_cfg(len(feed.services), window_s, n_windows)
    engine = ServeEngine(feed.specs, feed.services, plane_cfg,
                         capacity_spans_per_s=capacity_spans_per_s,
                         tick_s=tick_s, buckets=buckets,
                         lane_buckets=lane_buckets,
                         max_backlog=max_backlog, score=score,
                         baseline_windows=baseline_windows,
                         z_threshold=z_threshold, fuse=fuse,
                         shards=shards, pipeline=pipeline,
                         flight=flight,
                         flight_digest_every=flight_digest_every,
                         flight_max_ticks=flight_max_ticks)
    if engine.flight_recorder is not None:
        # the feed run's replay contract: `anomod audit replay` re-runs
        # this invocation through the WIRE journal (the response
        # sequence is the ground truth a live run can be reproduced
        # from), so the journal path and the resolved feed knobs are
        # what the header must carry
        engine.flight_recorder.header["run"] = dict(
            traffic="live_feed",
            feed_journal=str(journal_path) if journal_path else "",
            n_tenants=feed.n_tenants, n_services=len(feed.services),
            capacity_spans_per_s=capacity_spans_per_s,
            duration_s=duration_s, tick_s=tick_s,
            lag_s=feed.lag_s, window_s=window_s,
            baseline_windows=baseline_windows, z_threshold=z_threshold,
            buckets=list(engine.runner.buckets),
            lane_buckets=list(engine.runner.lane_buckets),
            max_backlog=engine.max_backlog, score=score,
            n_windows=n_windows, fuse=engine.fuse, shards=engine.shards,
            pipeline=engine.pipeline, flight=True,
            flight_digest_every=engine.flight_recorder.digest_every,
            flight_max_ticks=engine.flight_recorder.max_ticks)
    report = engine.run(feed, duration_s=duration_s)
    if journal_path is not None:
        feed.dump_journal(journal_path)
    return engine, report, feed
